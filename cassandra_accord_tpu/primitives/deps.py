"""Dependency sets: the CSR key<->txn and range<->txn multimaps.

Capability parity with ``accord.primitives.KeyDeps/RangeDeps/Deps``
(KeyDeps.java:150-439, RangeDeps.java:74-85, Deps.java:59-120) and their underlying
``RelationMultiMap`` engine (RelationMultiMap.java:40-1108).  The reference stores a
CSR (compressed sparse row) bidirectional multimap in primitive int arrays; we keep the
same layout in numpy int32 arrays — deliberately, because these offsets+indices arrays
ARE the host<->device interchange format: a KeyDeps can be shipped to the TPU data
plane (ops.deps_kernels) without reshaping.

Semantics preserved:
- keys and txn_ids are sorted & de-duplicated; per-key postings lists are sorted
  txn-index lists;
- ``invert()`` lazily builds the txn->keys view;
- ``merge`` is an n-way linear union (LinearMerger semantics);
- ``slice(ranges)`` restricts to keys covered by ranges, dropping unreferenced txns;
- ``without`` filters txn ids (used when removing redundant/committed deps).
"""
from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..utils.invariants import check_argument, check_state
from .keys import Key, Keys, Range, Ranges, RoutingKey, RoutingKeys
from .timestamp import Timestamp, TxnId

_EMPTY_I32 = np.zeros(0, dtype=np.int32)


class KeyDeps:
    """CSR bidirectional multimap RoutingKey <-> TxnId."""

    __slots__ = ("keys", "txn_ids", "offsets", "indices", "_inverted")

    def __init__(self, keys: RoutingKeys, txn_ids: Tuple[TxnId, ...],
                 offsets: np.ndarray, indices: np.ndarray):
        self.keys = keys
        self.txn_ids = txn_ids
        self.offsets = offsets      # int32[len(keys)+1]
        self.indices = indices      # int32[nnz] — indexes into txn_ids
        self._inverted: Optional[Tuple[np.ndarray, np.ndarray]] = None

    # -- construction -------------------------------------------------------
    NONE: "KeyDeps"

    @staticmethod
    def of(mapping: Dict[RoutingKey, Iterable[TxnId]]) -> "KeyDeps":
        b = KeyDepsBuilder()
        for k, tids in mapping.items():
            for t in tids:
                b.add(k, t)
        return b.build()

    # -- size / membership --------------------------------------------------
    def is_empty(self) -> bool:
        return len(self.txn_ids) == 0

    def txn_id_count(self) -> int:
        return len(self.txn_ids)

    def key_count(self) -> int:
        return len(self.keys)

    def contains(self, txn_id: TxnId) -> bool:
        i = bisect_left(self.txn_ids, txn_id)
        return i < len(self.txn_ids) and self.txn_ids[i] == txn_id

    def max_txn_id(self) -> Optional[TxnId]:
        return self.txn_ids[-1] if self.txn_ids else None

    # -- per-key access -----------------------------------------------------
    def txn_ids_for(self, key: RoutingKey) -> List[TxnId]:
        ki = self.keys.index_of(key)
        if ki < 0:
            return []
        lo, hi = int(self.offsets[ki]), int(self.offsets[ki + 1])
        return [self.txn_ids[int(i)] for i in self.indices[lo:hi]]

    def for_each_key(self, fn: Callable[[RoutingKey, List[TxnId]], None]) -> None:
        for ki, k in enumerate(self.keys):
            lo, hi = int(self.offsets[ki]), int(self.offsets[ki + 1])
            fn(k, [self.txn_ids[int(i)] for i in self.indices[lo:hi]])

    # -- per-txn access (lazy inversion, KeyDeps.invert semantics) ----------
    def _invert(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._inverted is None:
            nnz = len(self.indices)
            counts = np.zeros(len(self.txn_ids) + 1, dtype=np.int32)
            for i in self.indices:
                counts[int(i) + 1] += 1
            t_offsets = np.cumsum(counts, dtype=np.int32)
            t_indices = np.zeros(nnz, dtype=np.int32)
            cursor = t_offsets[:-1].copy()
            for ki in range(len(self.keys)):
                for p in range(int(self.offsets[ki]), int(self.offsets[ki + 1])):
                    t = int(self.indices[p])
                    t_indices[cursor[t]] = ki
                    cursor[t] += 1
            self._inverted = (t_offsets, t_indices)
        return self._inverted

    def participants(self, txn_id: TxnId) -> RoutingKeys:
        ti = bisect_left(self.txn_ids, txn_id)
        if ti >= len(self.txn_ids) or self.txn_ids[ti] != txn_id:
            return RoutingKeys.empty()
        t_offsets, t_indices = self._invert()
        lo, hi = int(t_offsets[ti]), int(t_offsets[ti + 1])
        return RoutingKeys(tuple(self.keys[int(ki)] for ki in t_indices[lo:hi]))

    def for_each_unique_txn_id(self, fn: Callable[[TxnId], None]) -> None:
        for t in self.txn_ids:
            fn(t)

    # -- algebra ------------------------------------------------------------
    def slice(self, ranges: Ranges) -> "KeyDeps":
        keep = [ki for ki, k in enumerate(self.keys) if ranges.contains(k)]
        if len(keep) == len(self.keys):
            return self
        return self._select_keys(keep)

    def intersecting(self, keys_or_ranges) -> "KeyDeps":
        if isinstance(keys_or_ranges, Ranges):
            return self.slice(keys_or_ranges)
        keep = [ki for ki, k in enumerate(self.keys) if keys_or_ranges.contains(k)]
        return self._select_keys(keep)

    def _select_keys(self, keep: List[int]) -> "KeyDeps":
        if not keep:
            return KeyDeps.NONE
        new_keys = RoutingKeys(tuple(self.keys[ki] for ki in keep))
        # gather postings, remap txn indices to the referenced subset
        referenced: Set[int] = set()
        postings: List[np.ndarray] = []
        for ki in keep:
            seg = self.indices[int(self.offsets[ki]):int(self.offsets[ki + 1])]
            postings.append(seg)
            referenced.update(int(i) for i in seg)
        old_order = sorted(referenced)
        remap = {old: new for new, old in enumerate(old_order)}
        new_txn_ids = tuple(self.txn_ids[i] for i in old_order)
        offsets = np.zeros(len(keep) + 1, dtype=np.int32)
        chunks: List[np.ndarray] = []
        for i, seg in enumerate(postings):
            offsets[i + 1] = offsets[i] + len(seg)
            chunks.append(np.array([remap[int(x)] for x in seg], dtype=np.int32))
        indices = np.concatenate(chunks) if chunks else _EMPTY_I32
        return KeyDeps(new_keys, new_txn_ids, offsets, indices)

    def without(self, predicate: Callable[[TxnId], bool]) -> "KeyDeps":
        """Remove txn ids matching predicate."""
        drop = {i for i, t in enumerate(self.txn_ids) if predicate(t)}
        if not drop:
            return self
        keep_t = [i for i in range(len(self.txn_ids)) if i not in drop]
        remap = {old: new for new, old in enumerate(keep_t)}
        new_txn_ids = tuple(self.txn_ids[i] for i in keep_t)
        new_key_idx: List[int] = []
        offsets = [0]
        indices: List[int] = []
        for ki in range(len(self.keys)):
            seg = [remap[int(i)] for i in
                   self.indices[int(self.offsets[ki]):int(self.offsets[ki + 1])]
                   if int(i) not in drop]
            if seg:
                new_key_idx.append(ki)
                indices.extend(seg)
                offsets.append(len(indices))
        new_keys = RoutingKeys(tuple(self.keys[ki] for ki in new_key_idx))
        return KeyDeps(new_keys, new_txn_ids,
                       np.array(offsets, dtype=np.int32),
                       np.array(indices, dtype=np.int32) if indices else _EMPTY_I32)

    @staticmethod
    def merge(many: Sequence["KeyDeps"]) -> "KeyDeps":
        many = [m for m in many if m is not None and not m.is_empty()]
        if not many:
            return KeyDeps.NONE
        if len(many) == 1:
            return many[0]
        b = KeyDepsBuilder()
        for m in many:
            for ki, k in enumerate(m.keys):
                lo, hi = int(m.offsets[ki]), int(m.offsets[ki + 1])
                for i in m.indices[lo:hi]:
                    b.add(k, m.txn_ids[int(i)])
        return b.build()

    def with_merged(self, other: "KeyDeps") -> "KeyDeps":
        return KeyDeps.merge([self, other])

    # -- equality -----------------------------------------------------------
    def __eq__(self, other) -> bool:
        return (isinstance(other, KeyDeps)
                and self.keys == other.keys
                and self.txn_ids == other.txn_ids
                and np.array_equal(self.offsets, other.offsets)
                and np.array_equal(self.indices, other.indices))

    def __hash__(self):
        return hash((self.keys, self.txn_ids))

    def __repr__(self) -> str:
        parts = []
        for ki, k in enumerate(self.keys):
            lo, hi = int(self.offsets[ki]), int(self.offsets[ki + 1])
            tids = ",".join(repr(self.txn_ids[int(i)]) for i in self.indices[lo:hi])
            parts.append(f"{k}:[{tids}]")
        return "KeyDeps{" + ", ".join(parts) + "}"


KeyDeps.NONE = KeyDeps(RoutingKeys.empty(), (), np.zeros(1, dtype=np.int32), _EMPTY_I32)


class KeyDepsBuilder:
    __slots__ = ("_map",)

    def __init__(self):
        self._map: Dict[RoutingKey, Set[TxnId]] = {}

    def add(self, key: RoutingKey, txn_id: TxnId) -> "KeyDepsBuilder":
        self._map.setdefault(key, set()).add(txn_id)
        return self

    def is_empty(self) -> bool:
        return not self._map

    def build(self) -> KeyDeps:
        if not self._map:
            return KeyDeps.NONE
        keys = RoutingKeys.of(self._map.keys())
        all_tids = sorted({t for s in self._map.values() for t in s})
        tid_index = {t: i for i, t in enumerate(all_tids)}
        offsets = np.zeros(len(keys) + 1, dtype=np.int32)
        indices: List[int] = []
        for i, k in enumerate(keys):
            seg = sorted(self._map[k])
            indices.extend(tid_index[t] for t in seg)
            offsets[i + 1] = len(indices)
        return KeyDeps(keys, tuple(all_tids),
                       offsets, np.array(indices, dtype=np.int32) if indices else _EMPTY_I32)


class RangeDeps:
    """CSR bidirectional multimap Range <-> TxnId with stabbing queries.

    Parity: RangeDeps.java:74-85 — its SearchableRangeList
    (CheckpointIntervalArrayBuilder.java:33-1133) is realised here as a
    sorted-start + prefix-max-end interval index: a stab at ``key`` binary
    searches the candidates with ``start <= key`` and walks back only until
    the running max end drops to ``key`` — the checkpoint that makes stabbing
    sub-linear instead of a full scan (the TPU overlap-join kernel in ``ops``
    remains the fast path for BATCHED queries)."""

    __slots__ = ("ranges", "txn_ids", "offsets", "indices", "_by_txn",
                 "_starts", "_max_end")

    def __init__(self, ranges: Tuple[Range, ...], txn_ids: Tuple[TxnId, ...],
                 offsets: np.ndarray, indices: np.ndarray):
        self.ranges = ranges        # sorted by (start, end); may overlap each other
        self.txn_ids = txn_ids
        self.offsets = offsets
        self.indices = indices
        self._by_txn = None         # lazy inversion (participants)
        self._starts = None         # lazy interval index (starts list)
        self._max_end = None        # prefix max of range ends

    def _interval_index(self):
        if self._starts is None:
            self._starts = [r.start for r in self.ranges]
            best = None
            max_end = []
            for r in self.ranges:
                best = r.end if best is None or r.end > best else best
                max_end.append(best)
            self._max_end = max_end
        return self._starts, self._max_end

    def _stab(self, key) -> Set[int]:
        """Range positions whose half-open interval contains ``key``."""
        starts, max_end = self._interval_index()
        out: Set[int] = set()
        i = bisect_right(starts, key) - 1
        while i >= 0:
            if not key < max_end[i]:
                break                       # nothing earlier can reach key
            if self.ranges[i].contains(key):
                out.add(i)
            i -= 1
        return out

    def _overlaps(self, target: "Range") -> Set[int]:
        """Range positions intersecting ``target``."""
        starts, max_end = self._interval_index()
        out: Set[int] = set()
        i = bisect_left(starts, target.end) - 1
        while i >= 0:
            if not target.start < max_end[i]:
                break
            if self.ranges[i].intersects(target):
                out.add(i)
            i -= 1
        return out

    NONE: "RangeDeps"

    @staticmethod
    def of(mapping: Dict[Range, Iterable[TxnId]]) -> "RangeDeps":
        b = RangeDepsBuilder()
        for r, tids in mapping.items():
            for t in tids:
                b.add(r, t)
        return b.build()

    def is_empty(self) -> bool:
        return len(self.txn_ids) == 0

    def txn_id_count(self) -> int:
        return len(self.txn_ids)

    def contains(self, txn_id: TxnId) -> bool:
        i = bisect_left(self.txn_ids, txn_id)
        return i < len(self.txn_ids) and self.txn_ids[i] == txn_id

    # -- stabbing queries (via the interval index) ---------------------------
    def for_each_intersecting_key(self, key: RoutingKey, fn: Callable[[TxnId], None]) -> None:
        seen: Set[int] = set()
        for ri in sorted(self._stab(key)):
            for i in self.indices[int(self.offsets[ri]):int(self.offsets[ri + 1])]:
                if int(i) not in seen:
                    seen.add(int(i))
                    fn(self.txn_ids[int(i)])

    def intersecting_txn_ids(self, target) -> List[TxnId]:
        """TxnIds whose range intersects target (a Range, Ranges, or key)."""
        if isinstance(target, Range):
            hits = self._overlaps(target)
        elif isinstance(target, Ranges):
            hits: Set[int] = set()
            for rng in target:
                hits |= self._overlaps(rng)
        else:  # key
            hits = self._stab(target)
        out: Set[int] = set()
        for ri in hits:
            out.update(int(i) for i in
                       self.indices[int(self.offsets[ri]):int(self.offsets[ri + 1])])
        return sorted(self.txn_ids[i] for i in out)

    def participants(self, txn_id: TxnId) -> Ranges:
        ti = bisect_left(self.txn_ids, txn_id)
        if ti >= len(self.txn_ids) or self.txn_ids[ti] != txn_id:
            return Ranges.EMPTY
        if self._by_txn is None:
            # one-pass lazy inversion (KeyDeps.invert semantics): per-call
            # linear scans are quadratic across a WaitingOn initialise
            m: Dict[int, List[Range]] = {}
            for ri, r in enumerate(self.ranges):
                for i in self.indices[int(self.offsets[ri]):int(self.offsets[ri + 1])]:
                    m.setdefault(int(i), []).append(r)
            self._by_txn = {i: Ranges.of(*rs) for i, rs in m.items()}
        return self._by_txn.get(ti, Ranges.EMPTY)

    # -- algebra ------------------------------------------------------------
    def slice(self, covering: Ranges) -> "RangeDeps":
        if self.is_empty():
            return self
        b = RangeDepsBuilder()
        for ri, r in enumerate(self.ranges):
            for c in covering:
                x = r.intersection(c)
                if x is not None:
                    for i in self.indices[int(self.offsets[ri]):int(self.offsets[ri + 1])]:
                        b.add(x, self.txn_ids[int(i)])
        return b.build()

    def without(self, predicate: Callable[[TxnId], bool]) -> "RangeDeps":
        if self.is_empty():
            return self
        b = RangeDepsBuilder()
        for ri, r in enumerate(self.ranges):
            for i in self.indices[int(self.offsets[ri]):int(self.offsets[ri + 1])]:
                t = self.txn_ids[int(i)]
                if not predicate(t):
                    b.add(r, t)
        return b.build()

    @staticmethod
    def merge(many: Sequence["RangeDeps"]) -> "RangeDeps":
        many = [m for m in many if m is not None and not m.is_empty()]
        if not many:
            return RangeDeps.NONE
        if len(many) == 1:
            return many[0]
        b = RangeDepsBuilder()
        for m in many:
            for ri, r in enumerate(m.ranges):
                for i in m.indices[int(m.offsets[ri]):int(m.offsets[ri + 1])]:
                    b.add(r, m.txn_ids[int(i)])
        return b.build()

    def __eq__(self, other) -> bool:
        return (isinstance(other, RangeDeps)
                and self.ranges == other.ranges
                and self.txn_ids == other.txn_ids
                and np.array_equal(self.offsets, other.offsets)
                and np.array_equal(self.indices, other.indices))

    def __hash__(self):
        return hash((self.ranges, self.txn_ids))

    def __repr__(self) -> str:
        parts = []
        for ri, r in enumerate(self.ranges):
            tids = ",".join(repr(self.txn_ids[int(i)]) for i in
                            self.indices[int(self.offsets[ri]):int(self.offsets[ri + 1])])
            parts.append(f"{r}:[{tids}]")
        return "RangeDeps{" + ", ".join(parts) + "}"


RangeDeps.NONE = RangeDeps((), (), np.zeros(1, dtype=np.int32), _EMPTY_I32)


class RangeDepsBuilder:
    __slots__ = ("_map",)

    def __init__(self):
        self._map: Dict[Range, Set[TxnId]] = {}

    def add(self, rng: Range, txn_id: TxnId) -> "RangeDepsBuilder":
        self._map.setdefault(rng, set()).add(txn_id)
        return self

    def is_empty(self) -> bool:
        return not self._map

    def build(self) -> RangeDeps:
        if not self._map:
            return RangeDeps.NONE
        ranges = tuple(sorted(self._map.keys()))
        all_tids = sorted({t for s in self._map.values() for t in s})
        tid_index = {t: i for i, t in enumerate(all_tids)}
        offsets = np.zeros(len(ranges) + 1, dtype=np.int32)
        indices: List[int] = []
        for i, r in enumerate(ranges):
            seg = sorted(self._map[r])
            indices.extend(tid_index[t] for t in seg)
            offsets[i + 1] = len(indices)
        return RangeDeps(ranges, tuple(all_tids), offsets,
                         np.array(indices, dtype=np.int32) if indices else _EMPTY_I32)


class Deps:
    """Triple of key deps (CFK-managed), range deps, and direct key deps (key txns
    whose execution CommandsForKey does NOT manage, e.g. key sync points)
    — Deps.java:59-120."""

    __slots__ = ("key_deps", "range_deps", "direct_key_deps", "_memo")

    def __init__(self, key_deps: KeyDeps = None, range_deps: RangeDeps = None,
                 direct_key_deps: KeyDeps = None):
        self.key_deps = key_deps if key_deps is not None else KeyDeps.NONE
        self.range_deps = range_deps if range_deps is not None else RangeDeps.NONE
        self.direct_key_deps = direct_key_deps if direct_key_deps is not None else KeyDeps.NONE
        # lazy derived-answer cache (never on the wire — codec _SKIP_SLOTS):
        # Deps is immutable after construction, and the hot protocol scans
        # (WaitingOn init, recovery evidence, the auditor) re-ask txn_ids()
        # and participants() for the same object repeatedly — the re-derived
        # sorted unions were a measured slice of per-commit wall cost.
        # Cached values are shared: CALLERS MUST NOT MUTATE them.
        self._memo = None

    NONE: "Deps"

    def is_empty(self) -> bool:
        return (self.key_deps.is_empty() and self.range_deps.is_empty()
                and self.direct_key_deps.is_empty())

    def txn_id_count(self) -> int:
        return len(self.txn_ids())

    def txn_ids(self) -> List[TxnId]:
        memo = self._memo
        if memo is None:
            memo = self._memo = {}
        cached = memo.get("txn_ids")
        if cached is None:
            out: Set[TxnId] = set(self.key_deps.txn_ids)
            out.update(self.range_deps.txn_ids)
            out.update(self.direct_key_deps.txn_ids)
            cached = memo["txn_ids"] = sorted(out)
        return cached

    def contains(self, txn_id: TxnId) -> bool:
        return (self.key_deps.contains(txn_id) or self.range_deps.contains(txn_id)
                or self.direct_key_deps.contains(txn_id))

    def max_txn_id(self) -> Optional[TxnId]:
        tids = self.txn_ids()
        return tids[-1] if tids else None

    def participants(self, txn_id: TxnId):
        """Union footprint of a dependency (keys + ranges).  Memoized per
        dep (immutable object, hot on the WaitingOn-init path); callers
        treat the result as read-only."""
        memo = self._memo
        if memo is None:
            memo = self._memo = {}
        cached = memo.get(txn_id)
        if cached is None:
            keys = self.key_deps.participants(txn_id).union(
                self.direct_key_deps.participants(txn_id))
            cached = memo[txn_id] = (keys, self.range_deps.participants(txn_id))
        return cached

    def slice(self, covering: Ranges) -> "Deps":
        return Deps(self.key_deps.slice(covering),
                    self.range_deps.slice(covering),
                    self.direct_key_deps.slice(covering))

    def without(self, predicate: Callable[[TxnId], bool]) -> "Deps":
        return Deps(self.key_deps.without(predicate),
                    self.range_deps.without(predicate),
                    self.direct_key_deps.without(predicate))

    @staticmethod
    def merge(many: Sequence["Deps"]) -> "Deps":
        many = [m for m in many if m is not None]
        return Deps(KeyDeps.merge([m.key_deps for m in many]),
                    RangeDeps.merge([m.range_deps for m in many]),
                    KeyDeps.merge([m.direct_key_deps for m in many]))

    def with_merged(self, other: "Deps") -> "Deps":
        return Deps.merge([self, other])

    def __eq__(self, other) -> bool:
        return (isinstance(other, Deps)
                and self.key_deps == other.key_deps
                and self.range_deps == other.range_deps
                and self.direct_key_deps == other.direct_key_deps)

    def __hash__(self):
        return hash((self.key_deps, self.range_deps, self.direct_key_deps))

    def __repr__(self) -> str:
        return f"Deps{{{self.key_deps!r}, {self.range_deps!r}, direct={self.direct_key_deps!r}}}"


Deps.NONE = Deps()


class DepsBuilder:
    """Routes each (seekable, txnId) add by domain and execution management
    (Deps.java:80-106): key txns managed by CommandsForKey go to key_deps; key txns
    NOT managed (key-domain sync points) to direct_key_deps; range txns to range_deps."""

    __slots__ = ("_keys", "_direct", "_ranges")

    def __init__(self):
        self._keys = KeyDepsBuilder()
        self._direct = KeyDepsBuilder()
        self._ranges = RangeDepsBuilder()

    def add(self, seekable, txn_id: TxnId) -> "DepsBuilder":
        if isinstance(seekable, Range):
            self._ranges.add(seekable, txn_id)
        else:
            from ..local.cfk import manages_execution
            if manages_execution(txn_id):
                self._keys.add(seekable, txn_id)
            else:
                self._direct.add(seekable, txn_id)
        return self

    def build(self) -> Deps:
        return Deps(self._keys.build(), self._ranges.build(), self._direct.build())
