"""Hybrid logical clock timestamps, transaction ids, and ballots.

Capability parity with ``accord.primitives.Timestamp/TxnId/Ballot``
(Timestamp.java:27-118, TxnId.java:84-150, Ballot.java).  The reference packs
48-bit epoch + 64-bit HLC + 16-bit flags + node id into two longs; here the fields are
kept unpacked (Python ints are arbitrary precision) but the *ordering and merge
semantics are identical*: total order on (epoch, hlc, flags, node), ``merge_max``
retains MERGE_FLAGS from both operands, and TxnId identity-flags encode
``Txn.Kind`` (3 bits) and ``Routable.Domain`` (1 bit).

For the TPU data plane a TxnId is exchanged with device code as a packed int64 pair via
``pack64``/``unpack64`` — the same two-word layout the reference uses, so device-side
sorts/compares agree with host-side ordering.
"""
from __future__ import annotations

import enum
from typing import Optional, Tuple

from ..utils.invariants import check_argument

MAX_EPOCH = (1 << 48) - 1
MAX_HLC = (1 << 63) - 1
MAX_FLAGS = (1 << 16) - 1
REJECTED_FLAG = 0x8000
MERGE_FLAGS = 0x8000
MAX_NODE = (1 << 32) - 1


class Domain(enum.IntEnum):
    """Routable.Domain — whether a txn's footprint is keys or ranges."""
    KEY = 0
    RANGE = 1


class TxnKind(enum.IntEnum):
    """Txn.Kind (Txn.java:53-113) with the same witness matrix (Txn.java:221-262)."""
    READ = 0
    WRITE = 1
    EPHEMERAL_READ = 2
    SYNC_POINT = 3
    EXCLUSIVE_SYNC_POINT = 4
    LOCAL_ONLY = 5

    # -- classification -----------------------------------------------------
    @property
    def is_write(self) -> bool:
        return self is TxnKind.WRITE

    @property
    def is_read(self) -> bool:
        return self is TxnKind.READ

    @property
    def is_local(self) -> bool:
        return self is TxnKind.LOCAL_ONLY

    @property
    def is_durable(self) -> bool:
        return self is not TxnKind.EPHEMERAL_READ

    @property
    def is_globally_visible(self) -> bool:
        return self in (TxnKind.READ, TxnKind.WRITE,
                        TxnKind.SYNC_POINT, TxnKind.EXCLUSIVE_SYNC_POINT)

    @property
    def is_sync_point(self) -> bool:
        return self in (TxnKind.SYNC_POINT, TxnKind.EXCLUSIVE_SYNC_POINT)

    @property
    def awaits_only_deps(self) -> bool:
        """ExclusiveSyncPoint / EphemeralRead execute only after their deps and have
        no logical executeAt (Txn.java:209-213)."""
        return self in (TxnKind.EXCLUSIVE_SYNC_POINT, TxnKind.EPHEMERAL_READ)

    # -- witness matrix (Txn.java:221-262) ----------------------------------
    def witnesses(self, other: "TxnKind") -> bool:
        """Does a txn of this kind take a dependency on conflicting txns of ``other``?"""
        if self in (TxnKind.READ, TxnKind.EPHEMERAL_READ):
            return other is TxnKind.WRITE                                   # Ws
        if self in (TxnKind.WRITE, TxnKind.SYNC_POINT):
            return other in (TxnKind.READ, TxnKind.WRITE)                   # RsOrWs
        if self is TxnKind.EXCLUSIVE_SYNC_POINT:
            return other.is_globally_visible                                # AnyGloballyVisible
        return False

    def witnessed_by(self, other: "TxnKind") -> bool:
        """Inverse direction (Txn.java witnessedBy): which kinds witness this kind?"""
        if self is TxnKind.EPHEMERAL_READ:
            return False                                                    # Nothing
        if self is TxnKind.READ:
            return other in (TxnKind.WRITE, TxnKind.SYNC_POINT,
                             TxnKind.EXCLUSIVE_SYNC_POINT)                  # WsOrSyncPoints
        if self is TxnKind.WRITE:
            return other.is_globally_visible                                # AnyGloballyVisible
        if self in (TxnKind.SYNC_POINT, TxnKind.EXCLUSIVE_SYNC_POINT):
            return other is TxnKind.EXCLUSIVE_SYNC_POINT                    # ExclusiveSyncPoints
        return False

    @property
    def short_name(self) -> str:
        return {TxnKind.READ: "R", TxnKind.WRITE: "W", TxnKind.EPHEMERAL_READ: "E",
                TxnKind.SYNC_POINT: "S", TxnKind.EXCLUSIVE_SYNC_POINT: "X",
                TxnKind.LOCAL_ONLY: "L"}[self]


class Timestamp:
    """Totally-ordered HLC timestamp: (epoch, hlc, flags, node)."""

    __slots__ = ("epoch", "hlc", "flags", "node", "_k", "_h")

    def __init__(self, epoch: int, hlc: int, node: int, flags: int = 0):
        check_argument(0 <= epoch <= MAX_EPOCH, "epoch out of range: %s", epoch)
        check_argument(hlc >= 0, "hlc must be >= 0: %s", hlc)
        check_argument(0 <= flags <= MAX_FLAGS, "flags out of range: %s", flags)
        self.epoch = epoch
        self.hlc = hlc
        self.flags = flags
        self.node = node
        # immutable; the comparison key is on every protocol hot path, and
        # the hash rides every deps-set / listener-set / dict operation —
        # both are precomputed once (timestamps hash millions of times per
        # burn; re-hashing the tuple per call was a measured wall slice)
        self._k = (epoch, hlc, flags, node)
        self._h = hash(self._k)

    # -- constants ----------------------------------------------------------
    NONE: "Timestamp"
    MAX: "Timestamp"

    @staticmethod
    def min_for_epoch(epoch: int) -> "Timestamp":
        return Timestamp(epoch, 0, 0, 0)

    @staticmethod
    def max_for_epoch(epoch: int) -> "Timestamp":
        return Timestamp(epoch, MAX_HLC, MAX_NODE, MAX_FLAGS)

    # -- ordering -----------------------------------------------------------
    def _key(self) -> Tuple[int, int, int, int]:
        return self._k

    def __lt__(self, other: "Timestamp") -> bool:
        return self._k < other._k

    def __le__(self, other: "Timestamp") -> bool:
        return self._k <= other._k

    def __gt__(self, other: "Timestamp") -> bool:
        return self._k > other._k

    def __ge__(self, other: "Timestamp") -> bool:
        return self._k >= other._k

    def __eq__(self, other) -> bool:
        return isinstance(other, Timestamp) and self._k == other._k

    def __hash__(self) -> int:
        return self._h

    def compare_to(self, other: "Timestamp") -> int:
        a, b = self._k, other._k
        return -1 if a < b else (1 if a > b else 0)

    def __wire_rebuild__(self) -> None:
        """Recompute derived caches after slot-wise decode (maelstrom codec
        skips them on the wire)."""
        self._k = (self.epoch, self.hlc, self.flags, self.node)
        self._h = hash(self._k)

    # -- flags --------------------------------------------------------------
    @property
    def is_rejected(self) -> bool:
        return bool(self.flags & REJECTED_FLAG)

    def with_rejected(self) -> "Timestamp":
        return self.__class__._rebuild(self, self.flags | REJECTED_FLAG)

    @classmethod
    def _rebuild(cls, src: "Timestamp", flags: int) -> "Timestamp":
        return Timestamp(src.epoch, src.hlc, src.node, flags)

    # -- merge (Timestamp.mergeMax semantics) --------------------------------
    def merge_max(self, other: "Timestamp") -> "Timestamp":
        """max(self, other) but retaining MERGE_FLAGS from both operands."""
        bigger = self if self >= other else other
        merged_flags = bigger.flags | ((self.flags | other.flags) & MERGE_FLAGS)
        if merged_flags == bigger.flags:
            return bigger
        return bigger.__class__._rebuild(bigger, merged_flags)

    # -- device interchange --------------------------------------------------
    def pack64(self) -> Tuple[int, int]:
        """(msb, lsb) two-word packing matching the reference layout
        (Timestamp.java:40-45): msb = epoch<<16 | hlc>>48 ; lsb = hlc<<16 | flags.
        node rides separately in device tables (int32 column)."""
        return ((self.epoch << 16) | (self.hlc >> 48),
                ((self.hlc & ((1 << 48) - 1)) << 16) | self.flags)

    @staticmethod
    def unpack64(msb: int, lsb: int, node: int) -> "Timestamp":
        epoch = msb >> 16
        hlc = ((msb & 0xFFFF) << 48) | (lsb >> 16)
        return Timestamp(epoch, hlc, node, lsb & 0xFFFF)

    # TPU lane layout: five non-negative int32 lanes whose lexicographic order
    # equals the host total order (epoch, hlc, flags, node).  int32 keeps the
    # device plane free of x64 mode; bounds are checked here at the boundary.
    LANE_BOUNDS = ((1 << 31) - 1, (1 << 31) - 1, (1 << 31) - 1,
                   (1 << 16) - 1, (1 << 31) - 1)

    def pack_lanes(self) -> Tuple[int, int, int, int, int]:
        """(epoch, hlc>>31, hlc&0x7FFFFFFF, flags, node) — the device-table
        row for this timestamp (see ops.graph_state)."""
        check_argument(self.epoch < (1 << 31), "epoch exceeds device bound: %s", self.epoch)
        check_argument(self.hlc < (1 << 62), "hlc exceeds device bound: %s", self.hlc)
        check_argument(0 <= self.node < (1 << 31), "node exceeds device bound: %s", self.node)
        return (self.epoch, self.hlc >> 31, self.hlc & 0x7FFFFFFF,
                self.flags, self.node)

    @staticmethod
    def unpack_lanes(lanes) -> "Timestamp":
        epoch, hlc_hi, hlc_lo, flags, node = (int(x) for x in lanes)
        return Timestamp(epoch, (hlc_hi << 31) | hlc_lo, node, flags)

    def __repr__(self) -> str:
        r = "(R)" if self.is_rejected else ""
        return f"[{self.epoch},{self.hlc},{self.node}]{r}"


Timestamp.NONE = Timestamp(0, 0, 0, 0)
Timestamp.MAX = Timestamp(MAX_EPOCH, MAX_HLC, MAX_NODE, MAX_FLAGS)

# identity-flag layout for TxnId (TxnId.java:132-150): kind in 3 bits, domain in 1 bit
_KIND_SHIFT = 2
_DOMAIN_SHIFT = 1


class TxnId(Timestamp):
    """A Timestamp whose identity flags carry (Txn.Kind, Routable.Domain)."""

    __slots__ = ("_kind_c",)

    def __init__(self, epoch: int, hlc: int, node: int,
                 kind: TxnKind = TxnKind.WRITE, domain: Domain = Domain.KEY,
                 extra_flags: int = 0):
        flags = (extra_flags & ~0x1E) | (int(kind) << _KIND_SHIFT) | (int(domain) << _DOMAIN_SHIFT)
        super().__init__(epoch, hlc, node, flags)
        self._kind_c = TxnKind((flags >> _KIND_SHIFT) & 0x7)

    @property
    def kind(self) -> TxnKind:
        return self._kind_c

    def __wire_rebuild__(self) -> None:
        super().__wire_rebuild__()
        self._kind_c = TxnKind((self.flags >> _KIND_SHIFT) & 0x7)

    @property
    def domain(self) -> Domain:
        return Domain((self.flags >> _DOMAIN_SHIFT) & 0x1)

    @property
    def is_write(self) -> bool:
        return self.kind.is_write

    @property
    def is_read(self) -> bool:
        return self.kind.is_read

    @property
    def is_visible(self) -> bool:
        return self.kind.is_globally_visible

    @property
    def is_sync_point(self) -> bool:
        return self.kind.is_sync_point

    @property
    def awaits_only_deps(self) -> bool:
        return self.kind.awaits_only_deps

    def witnesses(self, other: "TxnId | TxnKind") -> bool:
        other_kind = other.kind if isinstance(other, TxnId) else other
        return self.kind.witnesses(other_kind)

    def witnessed_by(self, other: "TxnId | TxnKind") -> bool:
        other_kind = other.kind if isinstance(other, TxnId) else other
        return self.kind.witnessed_by(other_kind)

    @classmethod
    def _rebuild(cls, src: "TxnId", flags: int) -> "TxnId":
        t = TxnId.__new__(TxnId)
        Timestamp.__init__(t, src.epoch, src.hlc, src.node, flags)
        t._kind_c = TxnKind((flags >> _KIND_SHIFT) & 0x7)
        return t

    @staticmethod
    def from_timestamp(ts: Timestamp, kind: TxnKind, domain: Domain = Domain.KEY) -> "TxnId":
        return TxnId(ts.epoch, ts.hlc, ts.node, kind, domain)

    def as_timestamp(self) -> Timestamp:
        return Timestamp(self.epoch, self.hlc, self.node, self.flags)

    def __repr__(self) -> str:
        return (f"[{self.epoch},{self.hlc},{self.node}]"
                f"{self.kind.short_name}{'r' if self.domain is Domain.RANGE else 'k'}")


class Ballot(Timestamp):
    """Paxos-style promise token (Ballot.java)."""

    __slots__ = ()

    ZERO: "Ballot"
    MAX: "Ballot"

    @classmethod
    def _rebuild(cls, src: "Ballot", flags: int) -> "Ballot":
        b = Ballot.__new__(Ballot)
        Timestamp.__init__(b, src.epoch, src.hlc, src.node, flags)
        return b

    @staticmethod
    def from_timestamp(ts: Timestamp) -> "Ballot":
        b = Ballot.__new__(Ballot)
        Timestamp.__init__(b, ts.epoch, ts.hlc, ts.node, ts.flags)
        return b


Ballot.ZERO = Ballot(0, 0, 0, 0)
Ballot.MAX = Ballot.from_timestamp(Timestamp.MAX)
