from .timestamp import Ballot, Domain, Timestamp, TxnId, TxnKind
from .keys import IntKey, Key, Keys, Range, Ranges, RoutingKey, RoutingKeys, SentinelKey
from .route import Route, Unseekables
from .deps import Deps, DepsBuilder, KeyDeps, KeyDepsBuilder, RangeDeps, RangeDepsBuilder
from .txn import PartialTxn, Seekables, Txn, Writes
from .sync_point import SyncPoint

__all__ = [
    "Ballot", "Domain", "Timestamp", "TxnId", "TxnKind",
    "IntKey", "Key", "Keys", "Range", "Ranges", "RoutingKey", "RoutingKeys", "SentinelKey",
    "Route", "Unseekables",
    "Deps", "DepsBuilder", "KeyDeps", "KeyDepsBuilder", "RangeDeps", "RangeDepsBuilder",
    "PartialTxn", "Seekables", "Txn", "Writes", "SyncPoint",
]
