"""LatestDeps — phase-aware, per-range dependency evidence for recovery.

Capability parity with ``accord.primitives.LatestDeps`` (LatestDeps.java:1-433):
a recovering coordinator must NOT flat-union deps reported by replicas in
different phases.  A replica that holds STABLE deps for a range holds the
decided set — unioning another replica's freshly-calculated deps on top can
add txns that execute after us (waits that can never be satisfied), and
mixing two Accept-phase proposals from different ballots resurrects a
superseded proposal.  Instead, every RecoverOk carries a per-range map of
(phase, ballot, coordinated deps, local deps); merging selects, per range,
the highest phase (ballot-breaking ties within the Accept phase) and only
unions local deps while the phase still permits it.

Per-range entries (BeginRecovery.java:95-121 construction):
- coordinated: the coordinator-supplied deps the replica holds (accepted or
  committed partialDeps) — authoritative for its phase;
- local: the replica's freshly calculated deps (only while the command has
  no committed/decided deps).

Extraction:
- ``merge_proposal``  — deps for a recovery re-proposal (Accept round):
  PROPOSED ranges use the max-ballot coordinated deps; UNKNOWN ranges union
  the local calculations (LatestDeps.java:341-351).
- ``merge_commit``    — deps for a commit at ``execute_at``: KNOWN/COMMITTED
  ranges use coordinated deps; on the fast path (executeAt == txnId) other
  ranges may substitute the union of coordinated+local deps (equivalent to
  what the original coordinator would have committed); everything else is
  reported insufficient, for the caller to fetch via GetDeps
  (LatestDeps.java:353-383, Recover.java:384-400).
"""
from __future__ import annotations

import enum
from typing import Callable, List, NamedTuple, Optional, Tuple

from ..utils.interval_map import ReducingIntervalMap
from .deps import Deps
from .keys import Range, Ranges
from .timestamp import Ballot, Timestamp, TxnId


class KnownDeps(enum.IntEnum):
    """How far a range's deps knowledge has progressed (Status.KnownDeps)."""
    UNKNOWN = 0     # pre-accept only: local calculation is the best we have
    PROPOSED = 1    # Accept phase: coordinated deps at a ballot
    COMMITTED = 2   # executeAt agreed with these deps
    KNOWN = 3       # Stable+: deps final


class LatestEntry(NamedTuple):
    known: KnownDeps
    ballot: Ballot
    coordinated: Optional[Deps]
    locals_: Tuple[Deps, ...]       # unmerged local calculations (deferred)

    @staticmethod
    def reduce(a: "LatestEntry", b: "LatestEntry") -> "LatestEntry":
        """Pick the higher phase (ballot tie-break within the Accept phase —
        Phase.tieBreakWithBallot); union locals only while phase permits."""
        c = int(a.known) - int(b.known)
        if c == 0 and a.known is KnownDeps.PROPOSED:
            c = a.ballot.compare_to(b.ballot)
        if c < 0:
            a, b = b, a
        if a.known <= KnownDeps.PROPOSED:
            return a._replace(locals_=a.locals_ + b.locals_)
        return a


class LatestDeps:
    """Per-range LatestEntry map (None outside any reported range)."""

    __slots__ = ("map",)

    def __init__(self, imap: Optional[ReducingIntervalMap] = None):
        self.map = imap if imap is not None else ReducingIntervalMap()

    @staticmethod
    def create(ranges: Ranges, known: KnownDeps, ballot: Ballot,
               coordinated: Optional[Deps], local: Optional[Deps]) -> "LatestDeps":
        if not len(ranges):
            return LatestDeps()
        entry = LatestEntry(known, ballot, coordinated,
                            (local,) if local is not None else ())
        pairs = [(r.start, r.end) for r in ranges]
        return LatestDeps(ReducingIntervalMap.of_ranges(pairs, entry))

    def merge(self, other: "LatestDeps") -> "LatestDeps":
        return LatestDeps(self.map.merge(other.map, LatestEntry.reduce))

    @staticmethod
    def merge_all(many) -> "LatestDeps":
        out = LatestDeps()
        for d in many:
            if d is not None:
                out = out.merge(d)
        return out

    # -- extraction -----------------------------------------------------------
    def _fold(self, per_entry: Callable[[Ranges, LatestEntry, List[Deps]], None]
              ) -> List[Deps]:
        parts: List[Deps] = []

        def visit(value, lo, hi, _acc):
            if value is not None and lo is not None and hi is not None:
                per_entry(Ranges.of(Range(lo, hi)), value, parts)
            return _acc

        self.map.foldl_intervals(visit, None)
        return parts

    def merge_proposal(self) -> Deps:
        """Deps for a recovery re-proposal (forProposal, LatestDeps.java:341)."""
        def per_entry(rngs: Ranges, e: LatestEntry, parts: List[Deps]):
            if e.known is KnownDeps.PROPOSED:
                if e.coordinated is not None:
                    parts.append(e.coordinated.slice(rngs))
            elif e.known is KnownDeps.UNKNOWN:
                parts.extend(d.slice(rngs) for d in e.locals_)
            else:
                # commit-grade deps cannot feed a proposal; recovery resumes
                # at stabilise for these ranges instead
                if e.coordinated is not None:
                    parts.append(e.coordinated.slice(rngs))
        return Deps.merge(self._fold(per_entry))

    def merge_commit(self, txn_id: TxnId, execute_at: Timestamp
                     ) -> Tuple[Deps, Ranges]:
        """(deps, sufficient_for) for committing at ``execute_at``
        (forCommit, LatestDeps.java:353-383)."""
        use_local = execute_at == txn_id.as_timestamp()
        sufficient: List[Range] = []

        def per_entry(rngs: Ranges, e: LatestEntry, parts: List[Deps]):
            if e.known in (KnownDeps.KNOWN, KnownDeps.COMMITTED):
                sufficient.extend(rngs)
                if e.coordinated is not None:
                    parts.append(e.coordinated.slice(rngs))
            elif e.known is KnownDeps.PROPOSED:
                # an interrupted commit: on the fast path the accepted deps
                # plus each reply's local calculation equal what the original
                # coordinator would have committed
                if use_local:
                    sufficient.extend(rngs)
                    if e.coordinated is not None:
                        parts.append(e.coordinated.slice(rngs))
                    parts.extend(d.slice(rngs) for d in e.locals_)
            else:
                if use_local:
                    sufficient.extend(rngs)
                    parts.extend(d.slice(rngs) for d in e.locals_)

        parts = self._fold(per_entry)
        return Deps.merge(parts), Ranges.of(*sufficient)

    def __repr__(self) -> str:
        parts: List[str] = []
        self.map.foldl_intervals(
            lambda v, lo, hi, _a: parts.append(f"[{lo},{hi})={v.known.name}")
            if v is not None else None, None)
        return f"LatestDeps({', '.join(parts)})"
