"""Sync point result handle.

Capability parity with ``accord.primitives.SyncPoint`` (SyncPoint.java): the handle a
coordinated sync point resolves with — its TxnId, the route it covers, and the
dependency set it waited on.  Consumers (Barrier, Bootstrap, durability rounds) use it
to know *which* transactions are guaranteed applied/witnessed once the sync point is.
"""
from __future__ import annotations

from .deps import Deps
from .route import Route
from .timestamp import TxnId


class SyncPoint:
    __slots__ = ("txn_id", "route", "deps", "execute_at")

    def __init__(self, txn_id: TxnId, route: Route, deps: Deps, execute_at=None):
        self.txn_id = txn_id
        self.route = route
        self.deps = deps
        # agreed executeAt (may exceed txn_id on the slow path): consumers that
        # re-disseminate the fence (fetch streaming) must use THIS
        self.execute_at = execute_at if execute_at is not None \
            else txn_id.as_timestamp()

    @property
    def keys_or_ranges(self):
        return self.route.participants()

    def __repr__(self) -> str:
        return f"SyncPoint({self.txn_id!r}, {self.route!r})"
