"""Transaction bodies, partial slices, and applied write sets.

Capability parity with ``accord.primitives.Txn/PartialTxn/Writes``
(Txn.java:53-422, PartialTxn.java, Writes.java): a Txn = Kind + Seekables (keys or
ranges) + Read + optional Update + Query; default execution helpers turn read Data into
Writes and a client Result.  ``Writes`` carries the applied write-set through the Apply
phase with an idempotent ``apply`` chain.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Optional, Union

from ..utils import async_ as au
from ..utils.invariants import check_argument, check_state
from .keys import Keys, Range, Ranges, RoutingKey
from .route import Route
from .timestamp import Domain, Timestamp, TxnId, TxnKind

if TYPE_CHECKING:
    from ..api.interfaces import Data, Query, Read, Result, Update

Seekables = Union[Keys, Ranges]


class Txn:
    """Immutable transaction body (Txn.java:53-113)."""

    __slots__ = ("kind", "keys", "read", "update", "query")

    def __init__(self, kind: TxnKind, keys: Seekables, read: "Read",
                 update: Optional["Update"] = None, query: Optional["Query"] = None):
        self.kind = kind
        self.keys = keys
        self.read = read
        self.update = update
        self.query = query

    # -- constructors -------------------------------------------------------
    @staticmethod
    def of(keys: Seekables, read: "Read", update: Optional["Update"] = None,
           query: Optional["Query"] = None) -> "Txn":
        kind = TxnKind.WRITE if update is not None else TxnKind.READ
        return Txn(kind, keys, read, update, query)

    @staticmethod
    def empty(kind: TxnKind, keys_or_ranges: Seekables) -> "Txn":
        from ..impl.noop_execution import NOOP_QUERY, NoopRead
        return Txn(kind, keys_or_ranges, NoopRead(keys_or_ranges), None, NOOP_QUERY)

    # -- domain -------------------------------------------------------------
    @property
    def domain(self) -> Domain:
        return Domain.RANGE if isinstance(self.keys, Ranges) else Domain.KEY

    def is_write(self) -> bool:
        return self.kind.is_write

    # -- routing ------------------------------------------------------------
    def routing_keys(self):
        if isinstance(self.keys, Ranges):
            return self.keys
        return self.keys.to_routing_keys()

    def home_key(self) -> RoutingKey:
        """Deterministic home-key pick: first routing key / range start
        (reference picks via Node.computeRoute / trySortedArraysToRoute)."""
        if isinstance(self.keys, Ranges):
            return self.keys[0].start
        return self.keys[0].to_routing()

    def to_route(self, home_key: Optional[RoutingKey] = None) -> Route:
        hk = home_key if home_key is not None else self.home_key()
        if isinstance(self.keys, Ranges):
            return Route.for_ranges(hk, self.keys)
        return Route.for_keys(hk, self.keys.to_routing_keys())

    # -- slicing (PartialTxn semantics) -------------------------------------
    def slice(self, ranges: Ranges, include_query: bool) -> "PartialTxn":
        if isinstance(self.keys, Ranges):
            keys = self.keys.intersection(ranges)
        else:
            keys = self.keys.slice(ranges)
        return PartialTxn(
            self.kind, keys,
            self.read.slice(ranges) if self.read is not None else None,
            self.update.slice(ranges) if self.update is not None else None,
            self.query if include_query else None,
        )

    def intersects(self, ranges: Ranges) -> bool:
        return ranges.intersects(self.keys) if isinstance(self.keys, Keys) \
            else self.keys.intersects(ranges)

    # -- execution helpers (Txn.java:395-422) --------------------------------
    def read_chain(self, safe_store, execute_at: Timestamp, read_scope,
                   data_store=None) -> "au.AsyncChain":
        """Execute the read hook for every key in scope; merge Data.

        ``data_store`` overrides the store view (e.g. an exclusive-snapshot
        wrapper when serving a read from an already-applied copy)."""
        chains = []
        if data_store is None:
            data_store = safe_store.data_store()
        read_keys = self.read.keys()
        for key in read_scope:
            if read_keys is not None and not isinstance(read_keys, Ranges) \
                    and not read_keys.contains(key):
                continue  # only keys the Read declares (write-only keys are skipped)
            chains.append(self.read.read(key, safe_store, execute_at, data_store))
        if not chains:
            return au.done(None)

        def merge_all(datas):
            merged = None
            for d in datas:
                if d is None:
                    continue
                if isinstance(d, str):
                    # per-key sentinel from the data store ("obsolete" on a
                    # stale-marked key): the whole store read reports it
                    return d
                merged = d if merged is None else merged.merge(d)
            return merged

        return au.all_of(chains).map(merge_all)

    def execute(self, txn_id: TxnId, execute_at: Timestamp, data: Optional["Data"]) -> "Writes":
        if self.update is None:
            return Writes(txn_id, execute_at, Keys.empty() if not isinstance(self.keys, Ranges) else self.keys, None)
        write = self.update.apply(execute_at, data)
        return Writes(txn_id, execute_at, self.update.keys(), write)

    def result(self, txn_id: TxnId, execute_at: Timestamp, data: Optional["Data"]) -> "Result":
        if self.query is None:
            from ..impl.noop_execution import NOOP_RESULT
            return NOOP_RESULT
        return self.query.compute(txn_id, execute_at, self.keys, data, self.read, self.update)

    def __repr__(self) -> str:
        return f"Txn({self.kind.short_name}, {self.keys!r})"


class PartialTxn(Txn):
    """A Txn sliced to one replica's covered ranges (PartialTxn.java)."""

    __slots__ = ()

    def covers(self, unseekables) -> bool:
        if isinstance(self.keys, Ranges):
            return all(self.keys.intersects(u) if isinstance(u, Range) else self.keys.contains(u)
                       for u in unseekables)
        covered = {k.to_routing() for k in self.keys}
        return all(u in covered for u in unseekables)

    def reconstitute_or_none(self, route: Route) -> Optional[Txn]:
        if route.is_full and self.covers(route.participants()):
            return Txn(self.kind, self.keys, self.read, self.update, self.query)
        return None

    def with_merged(self, other: "PartialTxn") -> "PartialTxn":
        if other is None:
            return self
        keys = self.keys.union(other.keys)
        read = self.read.merge(other.read) if self.read is not None and other.read is not None \
            else (self.read or other.read)
        update = self.update.merge(other.update) if self.update is not None and other.update is not None \
            else (self.update or other.update)
        return PartialTxn(self.kind, keys, read, update, self.query or other.query)


class Writes:
    """Applied write-set (Writes.java): (txnId, executeAt, keys, write)."""

    __slots__ = ("txn_id", "execute_at", "keys", "write", "_rk")

    def __init__(self, txn_id: TxnId, execute_at: Timestamp, keys, write):
        self.txn_id = txn_id
        self.execute_at = execute_at
        self.keys = keys
        self.write = write
        # lazy routing-key-set cache (commands._written_routing_keys); never
        # on the wire (codec _SKIP_SLOTS) — rebuilt on first use post-decode
        self._rk = None

    def is_empty(self) -> bool:
        return self.write is None

    def apply_to(self, safe_store, apply_ranges: Ranges) -> "au.AsyncChain":
        """Apply writes for keys within ``apply_ranges``; returns chain of done."""
        if self.write is None:
            return au.done(None)
        chains = []
        store = safe_store.data_store()
        for key in self.keys:
            if apply_ranges.contains(key.to_routing() if hasattr(key, "to_routing") else key):
                chains.append(self.write.apply(store, key, self.execute_at))
        if not chains:
            return au.done(None)
        return au.all_of(chains).map(lambda _: None)

    def slice(self, ranges: Ranges) -> "Writes":
        if isinstance(self.keys, Ranges):
            return Writes(self.txn_id, self.execute_at, self.keys.intersection(ranges), self.write)
        return Writes(self.txn_id, self.execute_at, self.keys.slice(ranges), self.write)

    def merge(self, other: Optional["Writes"]) -> "Writes":
        """Union of two per-shard slices of the same txn's writes."""
        if other is None or other.write is None:
            return self
        if self.write is None:
            return other
        keys = self.keys.union(other.keys)
        write = self.write if self.write is other.write \
            else self.write.merge(other.write)
        return Writes(self.txn_id, self.execute_at, keys, write)

    def __repr__(self) -> str:
        return f"Writes({self.txn_id!r}@{self.execute_at!r}, {self.keys!r})"
