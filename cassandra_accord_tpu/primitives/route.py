"""Routing projections of a transaction's footprint.

Capability parity with the reference Route family (Route.java, KeyRoute.java,
RangeRoute.java, RoutingKeys.java, Participants.java): an *unseekable* projection of a
txn's keys/ranges used to address messages to shards, carrying a designated **homeKey**
— the key whose shard owns progress/recovery duty for the txn.

Simplification vs the reference: one ``Route`` class parameterized by domain, holding
either RoutingKeys or Ranges plus ``home_key`` and a ``full`` flag (whether this route
covers the txn's entire footprint, vs a partial slice held by one replica).
"""
from __future__ import annotations

from typing import Optional, Union

from ..utils.invariants import check_argument, check_state
from .keys import Range, Ranges, RoutingKey, RoutingKeys
from .timestamp import Domain

Unseekables = Union[RoutingKeys, Ranges]


class Route:
    __slots__ = ("home_key", "unseekables", "full", "covering")

    def __init__(self, home_key: RoutingKey, unseekables: Unseekables, full: bool = True,
                 covering: Optional[Ranges] = None):
        check_argument(home_key is not None, "route requires a homeKey")
        self.home_key = home_key
        self.unseekables = unseekables
        self.full = full
        # for partial routes: the ranges this route was sliced to (reference
        # PartialRoute.covering) — what the route is authoritative for
        self.covering = covering

    # -- constructors -------------------------------------------------------
    @staticmethod
    def for_keys(home_key: RoutingKey, keys: RoutingKeys) -> "Route":
        if not keys.contains(home_key):
            keys = keys.union(RoutingKeys.of([home_key]))
        return Route(home_key, keys, full=True)

    @staticmethod
    def for_ranges(home_key: RoutingKey, ranges: Ranges) -> "Route":
        return Route(home_key, ranges, full=True)

    # -- domain -------------------------------------------------------------
    @property
    def domain(self) -> Domain:
        return Domain.RANGE if isinstance(self.unseekables, Ranges) else Domain.KEY

    @property
    def is_full(self) -> bool:
        return self.full

    # -- participants -------------------------------------------------------
    def participants(self) -> Unseekables:
        return self.unseekables

    def covers(self, ranges: Ranges) -> bool:
        """Is this route authoritative for all of ``ranges``? A full route covers
        everything; a partial route covers exactly the ranges it was sliced to."""
        if self.full:
            return True
        return self.covering is not None and self.covering.contains_all(ranges)

    def intersects(self, ranges: Ranges) -> bool:
        return self.unseekables.intersects(ranges) if len(self.unseekables) else False

    def contains(self, key: RoutingKey) -> bool:
        return self.unseekables.contains(key)

    # -- slicing ------------------------------------------------------------
    def slice(self, ranges: Ranges) -> "Route":
        if isinstance(self.unseekables, Ranges):
            sliced = self.unseekables.intersection(ranges)
        else:
            sliced = self.unseekables.slice(ranges)
        covering = ranges if (self.full or self.covering is None) \
            else self.covering.intersection(ranges)
        return Route(self.home_key, sliced, full=False, covering=covering)

    def union(self, other: "Route") -> "Route":
        check_state(self.home_key == other.home_key, "cannot union routes with different homeKeys")
        a, b = self.unseekables, other.unseekables
        # mixed domains (a key-backed partial meeting the range-backed real
        # route): lift keys to their unit covering ranges — unioning the raw
        # containers would corrupt the route
        if isinstance(a, Ranges) and isinstance(b, RoutingKeys):
            b = b.to_ranges()
        elif isinstance(a, RoutingKeys) and isinstance(b, Ranges):
            a = a.to_ranges()
        u = a.union(b)
        full = self.full or other.full
        covering = None
        if not full and self.covering is not None and other.covering is not None:
            covering = self.covering.union(other.covering)
        return Route(self.home_key, u, full=full, covering=covering)

    def with_home_key(self) -> "Route":
        if isinstance(self.unseekables, RoutingKeys) and not self.unseekables.contains(self.home_key):
            return Route(self.home_key, self.unseekables.union(RoutingKeys.of([self.home_key])), self.full)
        return self

    def home_key_only(self) -> "Route":
        """A partial route claiming only the home key — in the SAME domain as
        this route: a range-domain txn's home-only route must stay
        range-backed, or a later CheckStatusOk.merge unioning it with the
        real route mixes keys into ranges and corrupts the route."""
        if isinstance(self.unseekables, Ranges):
            only = RoutingKeys.of([self.home_key]).to_ranges()
            return Route(self.home_key, only, full=False)
        return Route(self.home_key, RoutingKeys.of([self.home_key]), full=False)

    def is_empty(self) -> bool:
        return self.unseekables.is_empty()

    def __eq__(self, other) -> bool:
        return (isinstance(other, Route) and self.home_key == other.home_key
                and self.unseekables == other.unseekables and self.full == other.full)

    def __hash__(self):
        return hash((self.home_key, self.unseekables, self.full))

    def __repr__(self) -> str:
        tag = "Full" if self.full else "Partial"
        return f"{tag}Route(home={self.home_key}, {self.unseekables!r})"
