"""Keys, ranges and their sorted-set algebra.

Capability parity with ``accord.primitives`` ``AbstractKeys``/``AbstractRanges``/
``Routables``/``Range`` (AbstractRanges.java:1-788, Routables.java:1-434,
Range.java:1-451): immutable sorted sets of keys/ranges supporting union,
intersection, slicing by ranges, and containment — the footprint algebra every phase of
the protocol runs on.  The reference supports four start/end inclusivity variants; here
ranges are uniformly half-open ``[start, end)`` (the variant Cassandra token ranges
reduce to), which simplifies the device-side interval tables without losing
expressiveness: an embedding can always map its own bounds onto half-open routing
tokens.

Keys are modelled as objects with a total order given by ``token()``; the concrete
``IntKey`` (prefix, value) mirrors the reference test harness's ``PrefixedIntHashKey``
prefix-sharded integer keys and is what the simulation harness and Maelstrom adapter
use.  ``SentinelKey`` provides per-prefix ±infinity bounds for full-prefix ranges.
"""
from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

from ..utils.invariants import check_argument, check_state


class RoutingKey:
    """Base: totally ordered, hashable by ``token()``."""

    __slots__ = ()

    def token(self) -> tuple:
        raise NotImplementedError

    def __lt__(self, other: "RoutingKey") -> bool:
        return self.token() < other.token()

    def __le__(self, other: "RoutingKey") -> bool:
        return self.token() <= other.token()

    def __gt__(self, other: "RoutingKey") -> bool:
        return self.token() > other.token()

    def __ge__(self, other: "RoutingKey") -> bool:
        return self.token() >= other.token()

    def __eq__(self, other) -> bool:
        return isinstance(other, RoutingKey) and self.token() == other.token()

    def __hash__(self) -> int:
        return hash(self.token())

    def to_routing(self) -> "RoutingKey":
        """The routing projection of this key (identity for pure routing keys)."""
        return self


class Key(RoutingKey):
    """A seekable user key (storage-addressable). Subclasses add payload addressing."""

    __slots__ = ()


class IntKey(Key):
    """(prefix, value) integer key; prefix is the shard-space partition, matching the
    reference harness's PrefixedIntHashKey (BurnTest.java:278-286)."""

    __slots__ = ("prefix", "value", "_tk")

    def __init__(self, value: int, prefix: int = 0):
        self.prefix = prefix
        self.value = value
        # token tuple cache: ordering/hashing allocated a fresh tuple per
        # compare, millions of times per burn (wire decode leaves this None
        # — codec _SKIP_SLOTS — and it lazily rebuilds)
        self._tk = (prefix, 0, value)

    def token(self) -> tuple:
        tk = self._tk
        if tk is None:
            tk = self._tk = (self.prefix, 0, self.value)
        return tk

    def __repr__(self) -> str:
        return f"{self.prefix}:{self.value}" if self.prefix else f"k{self.value}"


class SentinelKey(RoutingKey):
    """Per-prefix -inf / +inf bound for constructing full-prefix ranges."""

    __slots__ = ("prefix", "is_max")

    def __init__(self, prefix: int, is_max: bool):
        self.prefix = prefix
        self.is_max = is_max

    def token(self) -> tuple:
        return (self.prefix, 1 if self.is_max else -1, 0)

    @staticmethod
    def min(prefix: int = 0) -> "SentinelKey":
        return SentinelKey(prefix, False)

    @staticmethod
    def max(prefix: int = 0) -> "SentinelKey":
        return SentinelKey(prefix, True)

    def __repr__(self) -> str:
        return f"{self.prefix}:{'+inf' if self.is_max else '-inf'}"


class Range:
    """Half-open key range [start, end)."""

    __slots__ = ("start", "end")

    def __init__(self, start: RoutingKey, end: RoutingKey):
        check_argument(start < end, "empty range %s..%s", start, end)
        self.start = start
        self.end = end

    @staticmethod
    def of(start: RoutingKey, end: RoutingKey) -> "Range":
        return Range(start, end)

    @staticmethod
    def full_prefix(prefix: int) -> "Range":
        return Range(SentinelKey.min(prefix), SentinelKey.max(prefix))

    def contains(self, key: RoutingKey) -> bool:
        return self.start <= key < self.end

    def contains_range(self, that: "Range") -> bool:
        return self.start <= that.start and that.end <= self.end

    def intersects(self, that: "Range") -> bool:
        return self.start < that.end and that.start < self.end

    def intersection(self, that: "Range") -> Optional["Range"]:
        s = self.start if self.start >= that.start else that.start
        e = self.end if self.end <= that.end else that.end
        return Range(s, e) if s < e else None

    def compare_key(self, key: RoutingKey) -> int:
        """-1 if range is entirely before key, 0 if contains, 1 if entirely after."""
        if self.end <= key:
            return -1
        if self.start > key:
            return 1
        return 0

    def _key(self) -> tuple:
        return (self.start.token(), self.end.token())

    def __lt__(self, other: "Range") -> bool:
        return self._key() < other._key()

    def __eq__(self, other) -> bool:
        return isinstance(other, Range) and self._key() == other._key()

    def __hash__(self) -> int:
        return hash(self._key())

    def __repr__(self) -> str:
        return f"[{self.start},{self.end})"


class _SortedSet:
    """Shared machinery for Keys / RoutingKeys / Ranges wrappers."""

    __slots__ = ("_items",)

    def __init__(self, items: tuple):
        self._items = items

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self) -> Iterator:
        return iter(self._items)

    def __getitem__(self, i):
        return self._items[i]

    def __bool__(self) -> bool:
        return bool(self._items)

    def is_empty(self) -> bool:
        return not self._items

    def __eq__(self, other) -> bool:
        return type(self) is type(other) and self._items == other._items

    def __hash__(self) -> int:
        return hash((type(self).__name__, self._items))


class AbstractKeys(_SortedSet):
    """Sorted, de-duplicated immutable set of keys (AbstractKeys.java semantics)."""

    __slots__ = ()

    @classmethod
    def of(cls, keys: Iterable[RoutingKey]):
        return cls(tuple(sorted(set(keys))))

    @classmethod
    def empty(cls):
        return cls(())

    def contains(self, key: RoutingKey) -> bool:
        i = bisect_left(self._items, key)
        return i < len(self._items) and self._items[i] == key

    def index_of(self, key: RoutingKey) -> int:
        """Index if present, else -(insertion_point)-1 (reference convention)."""
        i = bisect_left(self._items, key)
        if i < len(self._items) and self._items[i] == key:
            return i
        return -i - 1

    def union(self, that: "AbstractKeys") -> "AbstractKeys":
        if not that._items:
            return self
        if not self._items:
            return type(self)(that._items) if type(that) is not type(self) else that
        return type(self)(tuple(_merge_sorted_unique(self._items, that._items)))

    def intersecting(self, that) -> "AbstractKeys":
        """Keys of self that fall in ``that`` (Keys or Ranges)."""
        if isinstance(that, Ranges):
            return self.slice(that)
        out = [k for k in self._items if that.contains(k)]
        return type(self)(tuple(out))

    def without(self, that) -> "AbstractKeys":
        return type(self)(tuple(k for k in self._items if not that.contains(k)))

    def slice(self, ranges: "Ranges") -> "AbstractKeys":
        """Subset of keys covered by ranges — O(|ranges| * log |keys|)."""
        out: List[RoutingKey] = []
        for r in ranges:
            lo = bisect_left(self._items, r.start)
            hi = bisect_left(self._items, r.end)
            out.extend(self._items[lo:hi])
        return type(self)(tuple(out))

    def intersects(self, that) -> bool:
        if isinstance(that, Ranges):
            return any(not self._empty_slice(r) for r in that)
        i = j = 0
        a, b = self._items, that._items
        while i < len(a) and j < len(b):
            if a[i] == b[j]:
                return True
            if a[i] < b[j]:
                i += 1
            else:
                j += 1
        return False

    def _empty_slice(self, r: Range) -> bool:
        lo = bisect_left(self._items, r.start)
        return lo >= len(self._items) or not r.contains(self._items[lo])

    def foldl(self, fn, accumulate):
        acc = accumulate
        for k in self._items:
            acc = fn(k, acc)
        return acc

    def to_ranges(self) -> "Ranges":
        """Minimal covering Ranges: one unit range per key (key..key-successor).
        Since keys are tokens, use [k, k'] half-open via a zero-width successor trick:
        represent as [k, next) where next sorts immediately after k."""
        return Ranges.of(*[Range(k, _Successor(k)) for k in self._items])

    def __repr__(self) -> str:
        return "{" + ",".join(map(repr, self._items)) + "}"


class _Successor(RoutingKey):
    """A routing key sorting immediately after its base (used for key→range lift)."""

    __slots__ = ("base",)

    def __init__(self, base: RoutingKey):
        self.base = base

    def token(self) -> tuple:
        return self.base.token() + (1,)

    def __repr__(self) -> str:
        return f"{self.base}^"


class Keys(AbstractKeys):
    """Seekable key set (a txn's data footprint)."""

    __slots__ = ()

    def to_routing_keys(self) -> "RoutingKeys":
        return RoutingKeys.of(k.to_routing() for k in self._items)


class RoutingKeys(AbstractKeys):
    """Unseekable routing-key set (a txn's routing footprint)."""

    __slots__ = ()


class Ranges(_SortedSet):
    """Sorted, de-overlapped immutable set of ranges (AbstractRanges semantics)."""

    __slots__ = ()

    @classmethod
    def of(cls, *ranges: Range) -> "Ranges":
        return cls(_normalize_ranges(ranges))

    @classmethod
    def of_list(cls, ranges: Sequence[Range]) -> "Ranges":
        return cls(_normalize_ranges(ranges))

    EMPTY: "Ranges"

    @classmethod
    def empty(cls) -> "Ranges":
        return cls(())

    # -- queries ------------------------------------------------------------
    def contains(self, key: RoutingKey) -> bool:
        return self._index_containing(key) >= 0

    def _index_containing(self, key: RoutingKey) -> int:
        lo, hi = 0, len(self._items) - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            c = self._items[mid].compare_key(key)
            if c == 0:
                return mid
            if c < 0:
                lo = mid + 1
            else:
                hi = mid - 1
        return -1

    def contains_all(self, that) -> bool:
        if isinstance(that, Ranges):
            return all(self._covers(r) for r in that)
        return all(self.contains(k) for k in that)

    def _covers(self, r: Range) -> bool:
        # because ranges are coalesced, r is covered iff one range contains it
        for mine in self._items:
            if mine.contains_range(r):
                return True
            if mine.start >= r.end:
                break
        return False

    def intersects(self, that) -> bool:
        if isinstance(that, Ranges):
            i = j = 0
            while i < len(self._items) and j < len(that._items):
                a, b = self._items[i], that._items[j]
                if a.intersects(b):
                    return True
                if a.end <= b.start:
                    i += 1
                else:
                    j += 1
            return False
        if isinstance(that, Range):
            return any(r.intersects(that) for r in self._items)
        return any(self.contains(k) for k in that)

    # -- algebra ------------------------------------------------------------
    def union(self, that: "Ranges") -> "Ranges":
        if not that._items:
            return self
        if not self._items:
            return that
        return Ranges(_normalize_ranges(self._items + that._items))

    def intersection(self, that: "Ranges") -> "Ranges":
        out: List[Range] = []
        i = j = 0
        while i < len(self._items) and j < len(that._items):
            a, b = self._items[i], that._items[j]
            x = a.intersection(b)
            if x is not None:
                out.append(x)
            if a.end <= b.end:
                i += 1
            else:
                j += 1
        return Ranges(tuple(out))

    def without(self, that: "Ranges") -> "Ranges":
        """Set difference self \\ that."""
        out: List[Range] = []
        for r in self._items:
            pieces = [r]
            for b in that._items:
                nxt: List[Range] = []
                for p in pieces:
                    if not p.intersects(b):
                        nxt.append(p)
                        continue
                    if p.start < b.start:
                        nxt.append(Range(p.start, b.start))
                    if b.end < p.end:
                        nxt.append(Range(b.end, p.end))
                pieces = nxt
            out.extend(pieces)
        return Ranges(_normalize_ranges(out))

    def slice(self, covering: "Ranges") -> "Ranges":
        return self.intersection(covering)

    def __repr__(self) -> str:
        return "{" + ",".join(map(repr, self._items)) + "}"


Ranges.EMPTY = Ranges(())


def _merge_sorted_unique(a: Sequence, b: Sequence) -> Iterator:
    i = j = 0
    while i < len(a) and j < len(b):
        if a[i] == b[j]:
            yield a[i]
            i += 1
            j += 1
        elif a[i] < b[j]:
            yield a[i]
            i += 1
        else:
            yield b[j]
            j += 1
    yield from a[i:]
    yield from b[j:]


def _normalize_ranges(ranges: Sequence[Range]) -> tuple:
    """Sort and coalesce overlapping/adjacent ranges."""
    if not ranges:
        return ()
    rs = sorted(ranges)
    out: List[Range] = [rs[0]]
    for r in rs[1:]:
        last = out[-1]
        if r.start <= last.end:
            if r.end > last.end:
                out[-1] = Range(last.start, r.end)
        else:
            out.append(r)
    return tuple(out)
