"""protocol_batch — the columnar protocol engine (ROADMAP item 1).

Struct-of-arrays **TxnBatch** mirrors of command-store hot state (status
codes, executeAt/ballot lanes, key-set offsets, deps row pointers as
parallel numpy arrays) plus a per-store **BatchEngine** that computes the
protocol's per-txn scans — waiting-graph release fan-out, frontier-init
dependency classification, progress-log settlement scans — as vectorized
passes over all in-flight txns instead of per-txn Python attribute chases.

DESIGN CONTRACT (the on-vs-off byte-identity proof, tests/
test_protocol_batch.py): the engine NEVER changes a protocol decision,
a message, an RNG draw, or a scheduling point.  Every vectorized pass is
either (a) a pure read answering bit-identically to the scalar code it
replaces, or (b) an *exact-skip prefilter*: it may only skip scalar work
it can PROVE is a no-op (no mutation, no observation, no fault-in), and
falls back to the scalar path whenever the mirror cannot prove it.  A
same-seed hostile burn with ``columnar=on`` vs ``off`` is therefore
byte-identical by construction — the knob buys wall-clock, never
trajectory.

Knob: ``LocalConfig.columnar`` / ``ACCORD_COLUMNAR`` in {auto, on, off}
(auto resolves to on — numpy is always present; off keeps every legacy
code path untouched).  The burn CLI exposes ``--columnar``; bench.py's
``protocol_ramp`` stage measures the commits/s-vs-concurrency curve both
ways.
"""
from .columns import ENGAGE_FLOOR, TS_ORDER_LANES, TxnBatch, pack_order_lanes
from .engine import BatchEngine, columnar_enabled, make_engine

__all__ = ["TxnBatch", "BatchEngine", "make_engine", "columnar_enabled",
           "pack_order_lanes", "TS_ORDER_LANES", "ENGAGE_FLOOR"]
