"""TxnBatch — struct-of-arrays columnar layout over command-store hot state.

One row per resident command; parallel numpy columns carry the fields the
protocol's hot scans read:

- ``tid``      [C,3] int64 — TxnId order lanes (see ``pack_order_lanes``);
- ``ea``       [C,3] int64 — executeAt order lanes (valid iff HAS_EA flag);
- ``ballot``   [C,3] int64 — promised-ballot lanes as of the last recorded
                             transition (layout/ingress attribution only —
                             ballots can move WITHOUT a status transition
                             (recovery promises), so decisions never read
                             this column);
- ``status``   [C]   int16 — SaveStatus ordinal;
- ``flags``    [C]   uint8 — TRUNCATED / AWAITS_ONLY / HAS_EA /
                             PRE_COMMITTED / IS_WRITE bits;
- ``waiting``  [C]   int32 — WaitingOn frontier width (deps row pointer
                             count; informational — release decisions read
                             the live WaitingOn, never this column);
- key-set CSR: per-row key-slot column lists (``key_rows``), the offsets
  half of the ragged flat-cols + offsets + txn-rows ``ConsultBatch``
  ingress contract (device_service/batch.py) that
  ``to_consult_batch`` packs into pow2-bucketed batch shapes.

Order-lane packing: a Timestamp orders by (epoch, hlc, flags, node).
Three int64 lanes — (epoch, hlc, flags<<32|node) — compare lexicographically
in exactly that order (epoch <= 2^48, hlc <= 2^63-1, flags <= 2^16,
node <= 2^32-1 all fit), so numpy lane compares agree bit-for-bit with
``Timestamp.__lt__``.

Capacity grows in power-of-two buckets (the same shape discipline as the
device service) so steady-state mirrors never re-allocate per txn.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..local.status import Status
from ..primitives.timestamp import Timestamp, TxnId

TS_ORDER_LANES = 3

# flags bits
F_TRUNCATED = 1 << 0      # save_status.is_truncated
F_AWAITS_ONLY = 1 << 1    # txn_id.kind.awaits_only_deps (sync points / eph reads)
F_HAS_EA = 1 << 2         # execute_at is not None
F_PRE_COMMITTED = 1 << 3  # has_been(Status.PRE_COMMITTED)
F_IS_WRITE = 1 << 4       # txn_id.is_write

_MIN_CAP = 64

# vectorization engagement floor: below this many rows the scalar loops
# beat the batched passes' fixed cost (microbenchmarked crossover; the
# release/frontier masks win 5-10x from ~2x this size up).  Shared by every
# engagement site (notify_listeners, initialise_waiting_on, _poll_in_store).
ENGAGE_FLOOR = 16


def pack_order_lanes(ts: Timestamp) -> Tuple[int, int, int]:
    """The 3-lane int64 order key of a Timestamp/TxnId/Ballot: lexicographic
    compare over the lanes == the host total order (epoch, hlc, flags, node)."""
    return (ts.epoch, ts.hlc, (ts.flags << 32) | ts.node)


def lanes_lt(a: np.ndarray, b_lanes: Tuple[int, int, int]) -> np.ndarray:
    """Vector ``a[i] < b`` over [N,3] order-lane rows (lexicographic)."""
    b0, b1, b2 = b_lanes
    return (a[:, 0] < b0) | ((a[:, 0] == b0) & (
        (a[:, 1] < b1) | ((a[:, 1] == b1) & (a[:, 2] < b2))))


def lanes_le(a: np.ndarray, b_lanes: Tuple[int, int, int]) -> np.ndarray:
    """Vector ``a[i] <= b`` (lexicographic)."""
    b0, b1, b2 = b_lanes
    return (a[:, 0] < b0) | ((a[:, 0] == b0) & (
        (a[:, 1] < b1) | ((a[:, 1] == b1) & (a[:, 2] <= b2))))


class TxnBatch:
    """The SoA mirror of one store's resident commands."""

    __slots__ = ("cap", "slot_of", "free", "tid", "ea", "ballot", "status",
                 "flags", "kind", "waiting", "key_rows")

    def __init__(self, cap: int = _MIN_CAP):
        self.cap = cap
        self.slot_of: Dict[TxnId, int] = {}
        self.free: List[int] = list(range(cap - 1, -1, -1))
        self.tid = np.zeros((cap, TS_ORDER_LANES), dtype=np.int64)
        self.ea = np.zeros((cap, TS_ORDER_LANES), dtype=np.int64)
        self.ballot = np.zeros((cap, TS_ORDER_LANES), dtype=np.int64)
        self.status = np.zeros((cap,), dtype=np.int16)
        self.flags = np.zeros((cap,), dtype=np.uint8)
        self.kind = np.zeros((cap,), dtype=np.int8)
        self.waiting = np.zeros((cap,), dtype=np.int32)
        # deps/key row pointers: per-row key-slot column list (CSR rows for
        # the ConsultBatch ingress; plain lists — they are rebuilt per
        # registration, not per query)
        self.key_rows: Dict[int, Tuple[int, ...]] = {}

    def __len__(self) -> int:
        return len(self.slot_of)

    # -- growth --------------------------------------------------------------
    def _grow(self) -> None:
        new_cap = self.cap * 2
        for name in ("tid", "ea", "ballot"):
            arr = getattr(self, name)
            wide = np.zeros((new_cap, TS_ORDER_LANES), dtype=np.int64)
            wide[: self.cap] = arr
            setattr(self, name, wide)
        for name, dt in (("status", np.int16), ("flags", np.uint8),
                         ("kind", np.int8), ("waiting", np.int32)):
            arr = getattr(self, name)
            wide = np.zeros((new_cap,), dtype=dt)
            wide[: self.cap] = arr
            setattr(self, name, wide)
        self.free.extend(range(new_cap - 1, self.cap - 1, -1))
        self.cap = new_cap

    # -- row lifecycle -------------------------------------------------------
    def ensure(self, txn_id: TxnId) -> int:
        row = self.slot_of.get(txn_id)
        if row is not None:
            return row
        if not self.free:
            self._grow()
        row = self.free.pop()
        self.slot_of[txn_id] = row
        self.tid[row] = pack_order_lanes(txn_id)
        self.ea[row] = 0
        self.ballot[row] = 0
        self.status[row] = 0
        self.waiting[row] = 0
        self.kind[row] = int(txn_id.kind)
        flags = 0
        if txn_id.kind.awaits_only_deps:
            flags |= F_AWAITS_ONLY
        if txn_id.is_write:
            flags |= F_IS_WRITE
        self.flags[row] = flags
        return row

    def update_from(self, cmd) -> int:
        """Refresh a command's row from its live state (the transition choke
        point).  Pure mirror write: reads only fields the transition already
        settled."""
        row = self.ensure(cmd.txn_id)
        ss = cmd.save_status
        self.status[row] = ss.ordinal
        flags = int(self.flags[row]) & (F_AWAITS_ONLY | F_IS_WRITE)
        if ss.is_truncated:
            flags |= F_TRUNCATED
        if ss.has_been(Status.PRE_COMMITTED):
            flags |= F_PRE_COMMITTED
        if cmd.execute_at is not None:
            flags |= F_HAS_EA
            self.ea[row] = pack_order_lanes(cmd.execute_at)
        self.flags[row] = flags
        self.ballot[row] = pack_order_lanes(cmd.promised)
        w = cmd.waiting_on
        self.waiting[row] = len(w.waiting) if w is not None else 0
        return row

    def drop(self, txn_id: TxnId) -> None:
        row = self.slot_of.pop(txn_id, None)
        if row is not None:
            self.status[row] = 0
            self.flags[row] = 0
            self.waiting[row] = 0
            self.key_rows.pop(row, None)
            self.free.append(row)

    def set_keys(self, txn_id: TxnId, key_slots: Sequence[int]) -> None:
        """Record the row's key-set (slot columns) for the ConsultBatch
        ingress bridge."""
        row = self.ensure(txn_id)
        self.key_rows[row] = tuple(key_slots)

    def note_waiting(self, txn_id: TxnId, n: int) -> None:
        row = self.slot_of.get(txn_id)
        if row is not None:
            self.waiting[row] = n

    # -- gathers -------------------------------------------------------------
    def rows_for(self, ids: Sequence[TxnId]) -> Tuple[np.ndarray, np.ndarray]:
        """(row index array, known mask) for ``ids``; unknown ids get row 0
        with known=False (callers must mask)."""
        get = self.slot_of.get
        rows = np.fromiter((get(t, -1) for t in ids), dtype=np.int64,
                           count=len(ids))
        known = rows >= 0
        if not known.all():
            rows = np.where(known, rows, 0)
        return rows, known

    def status_of(self, ids: Sequence[TxnId]) -> Tuple[np.ndarray, np.ndarray]:
        """(SaveStatus ordinal array, known mask) — one vectorized gather for
        a monitored-id scan (the progress-log settlement pass)."""
        rows, known = self.rows_for(ids)
        return self.status[rows], known

    # -- the ConsultBatch ingress bridge -------------------------------------
    def to_consult_batch(self, ids: Sequence[TxnId],
                         before_lanes: Sequence[Tuple[int, ...]],
                         kind_codes: Sequence[int],
                         row_cap: Optional[int] = None,
                         flat_cap: Optional[int] = None):
        """Pack the given rows' key sets + query bounds into the device
        service's ragged ``ConsultBatch`` (flat cols + row offsets + txn
        rows, pow2 buckets) — the ingress contract of device_service/batch.py,
        with the per-row ``txn_rows`` attribution lanes populated from this
        mirror's TxnId columns (the field the batch format reserved for the
        columnar protocol batches)."""
        from ..device_service.batch import build_batch
        row_cols: List[Tuple[int, ...]] = []
        txn_lanes: List[Optional[Tuple[int, ...]]] = []
        for tid in ids:
            row = self.slot_of.get(tid)
            row_cols.append(self.key_rows.get(row, ()) if row is not None
                            else ())
            # the canonical device-table row layout (Timestamp.pack_lanes)
            txn_lanes.append(tid.pack_lanes())
        return build_batch(row_cols, before_lanes, kind_codes,
                           txn_lanes=txn_lanes, row_cap=row_cap,
                           flat_cap=flat_cap)
