"""BatchEngine — per-store vectorized passes over the TxnBatch mirror.

Every pass obeys the exact-skip contract (package doc): it either answers a
pure read bit-identically, or skips scalar work it can PROVE is a no-op,
falling back to the scalar path whenever the mirror cannot prove it.  The
proofs are local and documented per method; tests/test_protocol_batch.py
property-checks each one against the scalar code, and the hostile-burn
on-vs-off byte-identity test seals the whole engine end to end.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..local.status import SaveStatus, Status
from ..primitives.timestamp import TxnId, TxnKind
from .columns import (ENGAGE_FLOOR, F_AWAITS_ONLY, F_HAS_EA, F_PRE_COMMITTED,
                      F_TRUNCATED, TxnBatch, lanes_lt, pack_order_lanes)

_APPLIED_ORD = SaveStatus.APPLIED.ordinal
_PRE_APPLIED_ORD = SaveStatus.PRE_APPLIED.ordinal
_INVALIDATED = SaveStatus.INVALIDATED
_STABLE_ORD = SaveStatus.STABLE.ordinal


def columnar_enabled(config) -> bool:
    """Resolve the ``columnar`` knob: auto|on -> True, off -> False."""
    mode = getattr(config, "columnar", "auto") if config is not None else "auto"
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"columnar must be auto|on|off, got {mode!r}")
    return mode != "off"


def make_engine(store) -> Optional["BatchEngine"]:
    """Build a store's engine per its node's config knob (None = off: every
    legacy code path stays untouched)."""
    config = getattr(store.node, "config", None)
    return BatchEngine(store) if columnar_enabled(config) else None


class BatchEngine:
    """One per CommandStore (constructed with it, dies with it — restart
    incarnations start a fresh mirror, like the resolver)."""

    __slots__ = ("store", "batch", "stats", "_key_slots")

    def __init__(self, store):
        self.store = store
        self.batch = TxnBatch()
        # key -> slot column for the ConsultBatch ingress (first-witness
        # order, like the device resolver's slot allocator)
        self._key_slots: Dict[object, int] = {}
        # wall-plane effectiveness counters (deterministic given a trajectory;
        # surfaced in burn stats as columnar_* keys)
        self.stats: Dict[str, int] = {
            "release_scans": 0,        # batched listener fan-outs taken
            "release_skipped": 0,      # scalar waiter visits proven no-op
            "release_visited": 0,      # scalar waiter visits still taken
            "poll_scans": 0,           # vectorized progress-log gathers
            "poll_fast": 0,            # monitored ids settled from the mirror
            "frontier_scans": 0,       # vectorized still-blocks gathers
            "frontier_fast": 0,        # deps answered from the mirror
            "ingress_windows": 0,      # delivery windows fed to the resolver
            "ingress_rows": 0,         # declared deps queries across them
            "exec_release_scans": 0,   # frontier release-tick partitions
            "exec_release_fast": 0,    # parked ids answered from the mirror
        }

    # -- mirror maintenance (fed from the transition choke points) -----------
    def note_transition(self, cmd) -> None:
        self.batch.update_from(cmd)

    def note_fault_in(self, cmd) -> None:
        """A cache-miss reload made an evicted command resident again."""
        self.batch.update_from(cmd)

    def note_waiting(self, cmd) -> None:
        w = cmd.waiting_on
        self.batch.note_waiting(cmd.txn_id, len(w.waiting) if w is not None
                                else 0)

    def key_slot(self, rk) -> int:
        slot = self._key_slots.get(rk)
        if slot is None:
            slot = self._key_slots[rk] = len(self._key_slots)
        return slot

    def note_keys(self, txn_id: TxnId, key_slots: Sequence[int]) -> None:
        self.batch.set_keys(txn_id, key_slots)

    def drop(self, txn_id: TxnId) -> None:
        """The command left residency (evict / GC erase)."""
        self.batch.drop(txn_id)

    # -- waiting-graph release fan-out (notify_listeners) ---------------------
    def release_skip_mask(self, dep, listener_ids: List[TxnId]):
        """The batched ``remove_waiting`` fan-out prefilter: given a dep that
        just changed and the waiter ids listening on it, return a boolean
        skip mask (True = the scalar ``update_dependency_and_maybe_execute``
        call is PROVABLY a no-op and may be skipped), or None when no skip is
        possible (the caller runs the scalar loop for everyone).

        Proof of the skip (mirrors commands._still_blocks +
        _maybe_defer_execute_at_least exactly):

        - the dep is live here (not READ-kind, not cold, not terminal) — in
          every such state ``_still_blocks`` returns True for a waiter
          unless the dep is PRE_COMMITTED with effective executeAt >= the
          waiter's executeAt;
        - a skipped waiter is NOT awaits-only-deps (so ``_maybe_defer``
          cannot mutate it) and its mirror row is STABLE/PRE_APPLIED with a
          known executeAt (set at that transition; nothing mutates it after
          PRE_COMMITTED without a transition);
        - therefore the scalar call would read state and return without any
          mutation, observation, RNG draw, or fault-in.

        The caller must re-validate the dep snapshot between scalar visits
        (``release_snapshot``): a cascade can advance the dep mid-fan-out,
        at which point the remaining skips are no longer proven.
        """
        kind = dep.txn_id.kind
        if kind is TxnKind.READ:
            return None    # read deps never block: everyone may unblock
        ss = dep.save_status
        if ss is _INVALIDATED or ss.is_truncated \
                or ss.ordinal >= _APPLIED_ORD:
            return None    # terminal: everyone may unblock
        if dep.txn_id in self.store.cold:
            return None    # answered from the cold set: everyone may unblock
        batch = self.batch
        rows, known = batch.rows_for(listener_ids)
        flags = batch.flags[rows]
        status = batch.status[rows]
        # provable skip requires: known row, not awaits-only, executeAt
        # recorded at a STABLE/PRE_APPLIED transition (fresh by construction)
        eligible = known & ((flags & F_AWAITS_ONLY) == 0) \
            & ((flags & F_HAS_EA) != 0) \
            & ((status == _STABLE_ORD) | (status == _PRE_APPLIED_ORD))
        if not dep.has_been(Status.PRE_COMMITTED):
            # dep undecided: _still_blocks is True for every non-awaits
            # waiter and _maybe_defer no-ops (it also gates on PRE_COMMITTED)
            skip = eligible
        else:
            dep_ea = dep.effective_execute_at()
            if dep_ea is None:
                skip = eligible
            else:
                # _still_blocks unblocks when dep_ea >= waiter_ea, so the
                # PROVEN-blocked set is waiter_ea STRICTLY greater
                from .columns import lanes_le
                skip = eligible & ~lanes_le(batch.ea[rows],
                                            pack_order_lanes(dep_ea))
        self.stats["release_scans"] += 1
        n_skip = int(skip.sum())
        self.stats["release_skipped"] += n_skip
        self.stats["release_visited"] += len(listener_ids) - n_skip
        return skip if n_skip else None

    @staticmethod
    def release_snapshot(dep) -> tuple:
        """The dep fields the skip proof depends on; compared between scalar
        visits — any change invalidates the remaining skips."""
        return (dep.save_status, dep.execute_at, dep.execute_at_least)

    # -- frontier-driven execution (the exec_deferred release tick) -----------
    def exec_deferred_partition(self, ids: List[TxnId]
                                ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Partition a frontier release tick's parked ids in ONE gather over
        the mirror's status column: (known, stable) masks.  ``known & ~
        stable`` rows are RESIDENT commands whose SaveStatus provably moved
        past STABLE — the release task may discard them without the scalar
        ``get_if_exists`` + status check (that visit reads two fields and
        returns; skipping it cannot change the trajectory).  ``~known`` rows
        (never mirrored, or evicted — residency tracking follows eviction in
        both directions) MUST take the scalar path: for them ``get_if_
        exists`` can fault in, which is an observable store event the skip
        contract is not allowed to elide.  Returns None below the
        engagement floor (scalar loop wins there)."""
        if len(ids) < ENGAGE_FLOOR:
            return None
        rows, known = self.batch.rows_for(ids)
        stable = known & (self.batch.status[rows] == _STABLE_ORD)
        self.stats["exec_release_scans"] += 1
        self.stats["exec_release_fast"] += int((known & ~stable).sum())
        return known, stable

    # -- frontier-init dependency classification (initialise_waiting_on) ------
    def still_blocks_mask(self, dep_ids: List[TxnId], execute_at,
                          awaits_only: bool):
        """Vectorized ``_still_blocks`` for the frontier-init scan: returns
        (blocks, decided) bool arrays — ``decided[i]`` True where the mirror
        PROVES the scalar answer is ``blocks[i]``; undecided entries must
        take the scalar path (unknown row, cold candidates, READ kinds,
        deferred sync points).

        Exactness: _still_blocks(dep) answers
        - False for READ kinds (decided host-side by the caller),
        - False for cold ids (left undecided here: cold membership is a set
          probe the caller already pays),
        - False for terminal rows (mirror status is exact at every
          transition),
        - for PRE_COMMITTED rows (non-awaits-only waiter): False iff
          effective executeAt >= ours.  The mirror carries the TRANSITION
          executeAt; ``execute_at_least`` deferrals move the effective value
          WITHOUT a transition, so rows flagged AWAITS_ONLY (the only kind
          that defers) are left undecided,
        - True otherwise (unwitnessed rows are NOT decided: absence from
          the mirror cannot distinguish never-witnessed from untracked).
        """
        batch = self.batch
        n = len(dep_ids)
        rows, known = batch.rows_for(dep_ids)
        flags = batch.flags[rows]
        status_arr = batch.status[rows]
        truncated = (flags & F_TRUNCATED) != 0
        terminal = truncated | (status_arr == _INVALIDATED.ordinal) \
            | (status_arr >= _APPLIED_ORD)
        blocks = np.ones(n, dtype=bool)
        decided = known & terminal
        blocks[decided] = False
        # READ deps never block (the MVCC read-dep rule is the FIRST scalar
        # check, ahead of every state read): mirrored READ rows decide False;
        # unmirrored ones stay undecided — their scalar call is one kind
        # check, already cheap
        is_read = known & (batch.kind[rows] == int(TxnKind.READ))
        blocks[is_read] = False
        decided = decided | is_read
        if not awaits_only and execute_at is not None:
            pre = known & ~terminal & ~is_read \
                & ((flags & F_PRE_COMMITTED) != 0) \
                & ((flags & F_HAS_EA) != 0) & ((flags & F_AWAITS_ONLY) == 0)
            if pre.any():
                ge = ~lanes_lt(batch.ea[rows], pack_order_lanes(execute_at))
                unblocked = pre & ge
                blocks[unblocked] = False
                decided = decided | pre
        self.stats["frontier_scans"] += 1
        self.stats["frontier_fast"] += int(decided.sum())
        return blocks, decided

    # -- progress-log settlement scan (_poll_in_store) ------------------------
    def settled_partition(self, ids: List[TxnId]) \
            -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """One vectorized gather over a monitored-id list: returns
        (done, outcome_known, resident) bool arrays where

        - ``resident[i]``: the mirror holds a fresh row (the command is in
          ``store.commands`` — ``store.lookup`` would be a pure dict hit, so
          skipping it skips no fault-in);
        - ``done[i]``: save_status ordinal >= APPLIED (the poll's ``_done``
          branch);
        - ``outcome_known[i]``: ordinal >= PRE_APPLIED (the poll's
          skip-recovery branch).

        Non-resident ids MUST take the scalar path — their lookup may fault
        evicted state in, and that load is observable store state.
        """
        status_arr, resident = self.batch.status_of(ids)
        done = resident & (status_arr >= _APPLIED_ORD)
        outcome_known = resident & (status_arr >= _PRE_APPLIED_ORD)
        self.stats["poll_scans"] += 1
        self.stats["poll_fast"] += int(outcome_known.sum())
        return done, outcome_known, resident

    def resolved_partition(self, ids: List[TxnId]) \
            -> Tuple[np.ndarray, np.ndarray]:
        """(resolved, resident) for the blocking-monitor map: resolved ==
        progress_log._locally_resolved (APPLIED+ / INVALIDATED / truncated),
        proven only for resident rows."""
        batch = self.batch
        rows, resident = batch.rows_for(ids)
        status_arr = batch.status[rows]
        flags = batch.flags[rows]
        resolved = resident & ((status_arr >= _APPLIED_ORD)
                               | (status_arr == _INVALIDATED.ordinal)
                               | ((flags & F_TRUNCATED) != 0))
        return resolved, resident

    # -- the ConsultBatch ingress bridge --------------------------------------
    def consult_ingress(self, specs, key_slot_of) -> object:
        """Pack a delivery window's declared deps queries (resolver
        QuerySpecs) into ONE ragged ConsultBatch in the device service's
        ingress layout, with the querying TxnIds in the (previously
        reserved) ``txn_rows`` attribution lanes.  Used by the batched
        ingress tests and the ramp bench's layout assertions; the live
        device path consumes the same layout through the service's window
        packing."""
        ids, before_lanes, kinds = [], [], []
        for spec in specs:
            ids.append(spec.by)
            bound = spec.before if spec.before is not None else spec.by
            before_lanes.append(bound.pack_lanes())
            kinds.append(int(spec.by.kind))
            row = self.batch.slot_of.get(spec.by)
            if row is None or row not in self.batch.key_rows:
                self.batch.set_keys(spec.by, [key_slot_of(k)
                                              for k in spec.keys])
        return self.batch.to_consult_batch(ids, before_lanes, kinds)
