"""Barriers over keys/ranges.

Capability parity with ``accord.coordinate.Barrier`` (Barrier.java:56-313):

- LOCAL: resolves once SOME transaction covering the scope, from the requested epoch
  or later, has locally applied — giving a local happens-after point.  If such a txn
  has already applied locally the barrier is immediate; otherwise an inclusive sync
  point is coordinated and awaited locally.
- GLOBAL_ASYNC: coordinates an inclusive sync point, resolving once it is stable
  (its dependency set is fixed); application proceeds in the background.
- GLOBAL_SYNC: as above, but resolves only once the sync point has applied at a
  quorum of every shard.

Resolves with the SyncPoint handle (or the local witness TxnId for the fast local
path, mirroring Barrier.java's BarrierTxn result).
"""
from __future__ import annotations

from typing import TYPE_CHECKING

from ..api.interfaces import BarrierType
from ..local.status import SaveStatus
from ..primitives.keys import Ranges
from ..primitives.timestamp import TxnId
from ..primitives.txn import Seekables
from ..utils import async_ as au
from . import sync_point as sp

if TYPE_CHECKING:
    from ..local.node import Node


def barrier(node: "Node", seekables: Seekables, min_epoch: int,
            barrier_type: BarrierType) -> au.AsyncResult:
    """Coordinate a barrier (Barrier.barrier).  Awaits ``min_epoch`` before
    coordinating so the sync point's TxnId (and hence its dependency set) is
    allocated at or after the requested epoch (Barrier.java withEpoch)."""
    result = au.settable()

    if barrier_type.is_global:
        def start_global(_v, f):
            if f is not None:
                result.set_failure(f)
                return
            inner = sp.coordinate_inclusive(
                node, seekables, blocking=barrier_type.wait_on_global_application)
            inner.add_listener(lambda v, f2: result.set_failure(f2) if f2 is not None
                               else result.set_success(v))

        node.with_epoch(min_epoch).begin(start_global)
        return result

    # LOCAL: fast path — some covering txn already applied locally at >= epoch
    witness = _find_local_witness(node, seekables, min_epoch)
    if witness is not None:
        result.set_success(witness)
        return result

    # slow path: coordinate an inclusive sync point, then await ITS local apply
    def start_local(_v, f):
        if f is not None:
            result.set_failure(f)
            return
        inner = sp.coordinate_inclusive(node, seekables, blocking=False)

        def on_sync_point(sync_point, failure):
            if failure is not None:
                result.set_failure(failure)
                return
            _await_local_apply(node, sync_point, result)

        inner.add_listener(on_sync_point)

    node.with_epoch(min_epoch).begin(start_local)
    return result


def _find_local_witness(node: "Node", seekables: Seekables, min_epoch: int):
    """An already-locally-applied txn covering the whole scope at >= min_epoch
    (Barrier.java's existing-txn fast path).  Scope must fall within one store."""
    unseekables = seekables if isinstance(seekables, Ranges) \
        else seekables.to_routing_keys()
    for store in node.command_stores.all_stores():
        ranges = store.current_ranges()
        if not ranges.contains_all(unseekables):
            continue
        # the covering txn we're looking for is applied — exactly the class
        # the cache-miss plane evicts; fault the cold set in for the scan
        for cold_id in list(store.cold):
            store.lookup(cold_id)
        best: TxnId = None
        for txn_id, command in store.commands.items():
            if command.save_status.ordinal < SaveStatus.APPLIED.ordinal \
                    or command.save_status.is_truncated \
                    or command.save_status is SaveStatus.INVALIDATED:
                continue
            if command.execute_at is None or command.execute_at.epoch < min_epoch:
                continue
            if command.route is None:
                continue
            parts = command.route.participants()
            covers = parts.contains_all(unseekables) if isinstance(parts, Ranges) \
                else (not isinstance(unseekables, Ranges)
                      and all(parts.contains(k) for k in unseekables))
            if covers and (best is None or txn_id > best):
                best = txn_id
        if best is not None:
            return best
    return None


def _await_local_apply(node: "Node", sync_point, result: au.Settable) -> None:
    """Resolve ``result`` with the sync point once it has applied in every
    intersecting LOCAL store."""
    from ..messages.txn_messages import await_applied_local
    txn_id = sync_point.txn_id

    def consume(outcome, failure):
        if failure is not None:
            result.set_failure(failure)
        elif outcome == "nack":
            from .errors import Invalidated
            result.set_failure(Invalidated(txn_id, "barrier sync point invalidated"))
        else:
            result.set_success(sync_point)

    await_applied_local(node, txn_id, sync_point.route, txn_id.epoch,
                        txn_id.epoch).begin(consume)
