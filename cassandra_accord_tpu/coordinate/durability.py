"""Durability coordination rounds.

Capability parity with ``accord.coordinate`` CoordinateShardDurable /
CoordinateGloballyDurable (both files; SURVEY §2.5):

- shard round: coordinate an exclusive sync point over (a sub-range of) one shard;
  the sync point itself resolves at quorum-applied, but the durability watermark
  is only broadcast once **every** replica of the covered ranges has acknowledged
  application (``WaitUntilApplied`` to all nodes; CoordinateShardDurable.java uses
  an AppliedTracker whose per-shard waitingOn is ``shard.rf()``, not a quorum).
  Only then is ``SetShardDurable`` sent, so the watermark a replica adopts
  unconditionally proves *all-replica* application — replicas may then truncate
  outcomes below it without risk of dropping a still-needed write.
- global round: ``QueryDurableBefore`` from a quorum of all nodes, MAX-merge the
  replies (DurableBefore.merge semantics, QueryDurableBefore.java:51) and
  disseminate the merged map via ``SetGloballyDurable``.  No promotion happens
  here: universal durability is only ever derived from the all-replica apply
  acknowledgement in the shard round (CommandStore.markShardDurable sets both
  majority and universal to the sync id, CommandStore.java:520-528).
"""
from __future__ import annotations

from typing import TYPE_CHECKING, List

from ..local.durability import DurableBefore
from ..messages.base import Callback, TxnRequest
from ..messages.durability_messages import (DurableBeforeReply, QueryDurableBefore,
                                            SetGloballyDurable, SetShardDurable)
from ..messages.txn_messages import ApplyOk, WaitUntilApplied
from ..primitives.keys import Ranges
from ..utils import async_ as au
from .errors import Exhausted

if TYPE_CHECKING:
    from ..local.node import Node


def coordinate_shard_durable(node: "Node", ranges: Ranges) -> au.AsyncResult:
    """Exclusive sync point over ``ranges``; once ALL replicas of the covered
    ranges ack ``WaitUntilApplied``, broadcast ``SetShardDurable``.  Resolves
    with the SyncPoint (CoordinateShardDurable.java)."""
    result = au.settable()
    inner = node.sync_point(ranges, exclusive=True, blocking=True)

    def on_sync_point(sync_point, failure):
        if failure is not None:
            result.set_failure(failure)
            return
        participants = sync_point.route.participants()
        scope = participants if isinstance(participants, Ranges) else ranges
        _await_all_applied(node, sync_point, scope, result)

    inner.add_listener(on_sync_point)
    return result


def _await_all_applied(node: "Node", sync_point, scope: Ranges,
                       result: au.Settable) -> None:
    """Send WaitUntilApplied to EVERY replica of ``scope``; only when all have
    acked is the durability watermark broadcast.  A single unreachable replica
    fails the round (the scheduling layer retries on the next cycle) — this is
    what makes the SetShardDurable watermark safe to adopt unconditionally."""
    txn_id = sync_point.txn_id
    topologies = node.topology.precise_epochs(scope, txn_id.epoch, txn_id.epoch)
    targets = sorted(topologies.nodes())
    if not targets:
        result.set_success(sync_point)
        return
    state = {"pending": set(targets), "done": False}

    def complete() -> None:
        state["done"] = True
        for to in targets:
            node.send(to, SetShardDurable(txn_id, scope))
        result.set_success(sync_point)

    class AllAppliedCallback(Callback):
        def on_success(self, from_node: int, reply) -> None:
            if state["done"]:
                return
            if not isinstance(reply, ApplyOk):
                # e.g. ReadNack("invalidated"): not a durable apply ack
                self.on_failure(from_node, RuntimeError(f"bad reply {reply!r}"))
                return
            state["pending"].discard(from_node)
            if not state["pending"]:
                complete()

        def on_failure(self, from_node: int, failure: BaseException) -> None:
            if state["done"]:
                return
            state["done"] = True
            result.set_failure(Exhausted(
                txn_id, f"all-replica apply ack (node {from_node}: {failure})"))

    callback = AllAppliedCallback()
    for to in targets:
        req_scope = TxnRequest.compute_scope(to, topologies, sync_point.route)
        if req_scope is None:
            state["pending"].discard(to)
            continue
        wait_for = TxnRequest.compute_wait_for_epoch(to, topologies)
        node.send(to, WaitUntilApplied(txn_id, req_scope, wait_for), callback)
    if not state["pending"] and not state["done"]:
        complete()


def coordinate_globally_durable(node: "Node") -> au.AsyncResult:
    """Query DurableBefore from every node; at a quorum, MAX-merge and
    disseminate the merged map (CoordinateGloballyDurable.java:70-79 —
    no majority→universal promotion)."""
    result = au.settable()
    topology = node.topology.current()
    all_nodes = sorted(topology.nodes())
    replies: List[DurableBefore] = []
    state = {"done": False, "acks": 0, "fails": 0}
    quorum = len(all_nodes) // 2 + 1

    class QueryCallback(Callback):
        def on_success(self, from_node: int, reply) -> None:
            if state["done"] or not isinstance(reply, DurableBeforeReply):
                return
            replies.append(reply.durable_before)
            state["acks"] += 1
            if state["acks"] >= quorum:
                state["done"] = True
                _finish()

        def on_failure(self, from_node: int, failure: BaseException) -> None:
            if state["done"]:
                return
            state["fails"] += 1
            if len(all_nodes) - state["fails"] < quorum:
                state["done"] = True
                result.set_failure(Exhausted(None, "query durable before"))

    def _finish():
        # max-merge: each node's map only ever contains watermarks proved by a
        # completed shard round (majority = quorum-applied sync point past,
        # universal = all-replica-applied), so the pointwise max of any set of
        # maps is itself proved; dissemination spreads the strongest knowledge.
        merged = DurableBefore.EMPTY
        for db in replies:
            merged = merged.merge(db)
        for to in all_nodes:
            node.send(to, SetGloballyDurable(merged))
        result.set_success(merged)

    callback = QueryCallback()
    for to in all_nodes:
        node.send(to, QueryDurableBefore(), callback)
    return result
