"""Durability coordination rounds.

Capability parity with ``accord.coordinate`` CoordinateShardDurable /
CoordinateGloballyDurable (both files; SURVEY §2.5):

- shard round: coordinate an exclusive sync point over (a sub-range of) one shard;
  once it has applied at a quorum, everything in its dependency past is
  majority-durable — broadcast ``SetShardDurable`` so every replica advances its
  DurableBefore/RedundantBefore and can truncate.
- global round: ``QueryDurableBefore`` from a quorum of all nodes, min-merge the
  replies (what EVERYONE agrees is majority-durable is universally durable),
  broadcast ``SetGloballyDurable``.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, List

from ..local.durability import DurableBefore, DurableEntry
from ..messages.base import Callback
from ..messages.durability_messages import (DurableBeforeReply, QueryDurableBefore,
                                            SetGloballyDurable, SetShardDurable)
from ..primitives.keys import Ranges
from ..utils import async_ as au
from .errors import Exhausted

if TYPE_CHECKING:
    from ..local.node import Node


def coordinate_shard_durable(node: "Node", ranges: Ranges) -> au.AsyncResult:
    """Exclusive sync point over ``ranges``; on quorum-applied, SetShardDurable
    to every replica of those ranges.  Resolves with the SyncPoint."""
    result = au.settable()
    inner = node.sync_point(ranges, exclusive=True, blocking=True)

    def on_sync_point(sync_point, failure):
        if failure is not None:
            result.set_failure(failure)
            return
        participants = sync_point.route.participants()
        scope = participants if isinstance(participants, Ranges) else ranges
        topology = node.topology.current()
        for to in topology.nodes_for(scope):
            node.send(to, SetShardDurable(sync_point.txn_id, scope))
        result.set_success(sync_point)

    inner.add_listener(on_sync_point)
    return result


def coordinate_globally_durable(node: "Node") -> au.AsyncResult:
    """Query DurableBefore from every node; at a quorum, min-merge and
    broadcast SetGloballyDurable (upgrading majority -> universal)."""
    result = au.settable()
    topology = node.topology.current()
    all_nodes = sorted(topology.nodes())
    replies: List[DurableBefore] = []
    state = {"done": False, "acks": 0, "fails": 0}
    quorum = len(all_nodes) // 2 + 1

    class QueryCallback(Callback):
        def on_success(self, from_node: int, reply) -> None:
            if state["done"] or not isinstance(reply, DurableBeforeReply):
                return
            replies.append(reply.durable_before)
            state["acks"] += 1
            if state["acks"] >= quorum:
                state["done"] = True
                _finish()

        def on_failure(self, from_node: int, failure: BaseException) -> None:
            if state["done"]:
                return
            state["fails"] += 1
            if len(all_nodes) - state["fails"] < quorum:
                state["done"] = True
                result.set_failure(Exhausted(None, "query durable before"))

    def _finish():
        # min-merge: only what EVERY reporting node holds majority-durable can
        # be called universal; a quorum suffices because majority durability is
        # itself a quorum property (DurableBefore min/max semantics)
        merged = replies[0]
        for db in replies[1:]:
            merged = merged.merge_min(db)
        # lift the agreed majority watermark to universal
        lifted = DurableBefore(merged.map.map_values(
            lambda e: DurableEntry(e.majority_before, e.majority_before)))
        for to in all_nodes:
            node.send(to, SetGloballyDurable(lifted))
        result.set_success(lifted)

    callback = QueryCallback()
    for to in all_nodes:
        node.send(to, QueryDurableBefore(), callback)
    return result
