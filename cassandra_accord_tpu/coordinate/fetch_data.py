"""Fetch missing knowledge about a txn from its peers and apply it locally.

Capability parity with ``accord.coordinate.FetchData`` / ``CheckShards``
(FetchData.java:1-255, CheckShards.java): gather ``CheckStatusOk`` from a quorum of
every shard the target participants intersect, merge the replies, and propagate the
merged knowledge into the local stores (the reference's Propagate).  The merged view
is also handed to the caller — MaybeRecover uses it both as its progress probe and
to reconstitute the txn body before escalating to full recovery.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Callable, Optional

from ..messages.base import Callback, TxnRequest
from ..messages.status_messages import CheckStatus, CheckStatusOk
from ..primitives.route import Route
from ..primitives.timestamp import TxnId
from ..utils import async_ as au
from .errors import Exhausted
from .tracking import QuorumTracker, RequestStatus

if TYPE_CHECKING:
    from ..local.node import Node


def check_status_quorum(node: "Node", txn_id: TxnId, route: Route,
                        include_info: bool = True) -> au.AsyncResult:
    """CheckStatus at a quorum of every intersecting shard; resolves with the
    merged CheckStatusOk.

    Gated on ``node.with_epoch(txn_id.epoch)`` (FetchData.java wraps the
    probe in ``node.withEpoch(srcEpoch, ...)``): a replica can learn of a
    blocked txn through deps/inform traffic BEFORE its config service has
    delivered the txn's epoch — under elastic membership the progress log
    then probes an epoch the local topology manager cannot slice yet, and
    ``precise_epochs`` throws instead of waiting.  When the epoch is already
    known ``with_epoch`` completes synchronously, so the gated path is
    byte-identical to the ungated one on every established trajectory.
    """
    result = au.settable()
    node.with_epoch(txn_id.epoch).begin(
        lambda _v, f: result.set_failure(f) if f is not None
        else _check_status_quorum(node, txn_id, route, include_info, result))
    return result


def _check_status_quorum(node: "Node", txn_id: TxnId, route: Route,
                         include_info: bool, result) -> None:
    topologies = node.topology.precise_epochs(route, txn_id.epoch, txn_id.epoch)
    tracker = QuorumTracker(topologies)
    merged: dict = {"ok": None}

    class StatusCallback(Callback):
        done = False

        def on_success(self, from_node: int, reply) -> None:
            if self.done:
                return
            if isinstance(reply, CheckStatusOk):
                merged["ok"] = reply if merged["ok"] is None else merged["ok"].merge(reply)
            if tracker.record_success(from_node) is RequestStatus.SUCCESS:
                self.done = True
                result.set_success(merged["ok"])

        def on_failure(self, from_node: int, failure: BaseException) -> None:
            if self.done:
                return
            if tracker.record_failure(from_node) is RequestStatus.FAILED:
                self.done = True
                result.set_failure(Exhausted(txn_id, "check-status quorum unreachable"))

    callback = StatusCallback()
    for to in tracker.nodes():
        scope = TxnRequest.compute_scope(to, topologies, route)
        if scope is None:
            continue
        node.send(to, CheckStatus(txn_id, scope,
                                  TxnRequest.compute_wait_for_epoch(to, topologies),
                                  include_info=include_info), callback)


def fetch_data(node: "Node", txn_id: TxnId, route: Route) -> au.AsyncResult:
    """Fetch whatever the cluster knows about ``txn_id`` over ``route``, apply it
    locally (Known upgrade), and resolve with the merged CheckStatusOk."""
    result = au.settable()

    def on_checked(merged: Optional[CheckStatusOk], failure):
        if failure is not None:
            result.set_failure(failure)
            return
        if merged is not None:
            target_route = merged.route if merged.route is not None else route
            merged.route = target_route
            # apply as a first-class LOCAL request (serializable, typed,
            # replayable — Propagate.java), and settle only when the
            # per-store application chain settles: every fetch_data listener
            # relies on the fetched knowledge being applied locally when it
            # fires (with delayed stores the application defers — settling
            # success immediately would leave the progress log checking
            # pre-propagation state and spuriously escalating to recovery).
            # Processed directly — NOT via node.receive, whose catch-all
            # would swallow an application failure and let the result settle
            # success over un-applied knowledge.
            from ..messages.base import LOCAL_NO_REPLY
            from ..messages.status_messages import Propagate
            try:
                applied = Propagate(txn_id, merged).process(
                    node, node.id, LOCAL_NO_REPLY)
            except BaseException as e:  # noqa: BLE001
                result.set_failure(e)
                return
            applied.add_listener(
                lambda _v, f: result.set_failure(f) if f is not None
                else result.set_success(merged))
            return
        result.set_success(merged)

    check_status_quorum(node, txn_id, route, include_info=True) \
        .to_chain().begin(on_checked)
    return result
