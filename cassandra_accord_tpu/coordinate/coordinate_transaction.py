"""The coordinator-side transaction pipeline.

Capability parity with ``accord.coordinate`` CoordinateTransaction / CoordinatePreAccept
/ Propose / Stabilise / ExecuteTxn / PersistTxn (CoordinateTransaction.java:50-113,
CoordinatePreAccept.java:51-164, Propose.java:1-234, CoordinationAdapter.java:48-331):

  PreAccept round (FastPathTracker)
    fast path:  witnessedAt == txnId at a fast-path quorum of every shard
                -> executeAt = txnId, deps = merge of fast-path-voting replicas' deps
                -> Execute (Stable+Read fused)
    slow path:  executeAt = mergeMax(witnessedAt); Propose (Accept round, ballot 0)
                -> deps at executeAt from AcceptOks -> Stabilise+Execute
  Execute:      Stable(+Read) to one replica per shard, Stable to the rest;
                on data from every shard: Writes = txn.execute, Result = txn.result
  Persist:      reply to client FIRST, then Apply.Minimal to every replica
                (CoordinationAdapter.java:192-197).
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Dict, List, Optional, Set

from ..messages.base import Callback, FailureReply, TxnRequest
from ..messages.txn_messages import (
    Accept, AcceptNack, AcceptOk, Apply, ApplyOk, Commit, CommitNack, CommitOk,
    PreAccept, PreAcceptNack, PreAcceptOk, ReadNack, ReadOk,
)
from ..local.status import SaveStatus
from ..primitives.deps import Deps
from ..primitives.route import Route
from ..primitives.timestamp import Ballot, Timestamp, TxnId
from ..primitives.txn import Txn
from ..utils import async_ as au
from .errors import Exhausted, Insufficient, Invalidated, Preempted, Timeout
from .tracking import (AppliedTracker, FastPathTracker, QuorumTracker, ReadTracker,
                       RequestStatus)

if TYPE_CHECKING:
    from ..local.node import Node


class ExecutePath:
    FAST = "fast"
    SLOW = "slow"
    RECOVER = "recover"


def coordinate_transaction(node: "Node", txn_id: TxnId, txn: Txn,
                           result: au.Settable) -> None:
    route = node.compute_route(txn)
    _CoordinateTransaction(node, txn_id, txn, route, result).start()


class _CoordinateTransaction:
    def __init__(self, node: "Node", txn_id: TxnId, txn: Txn, route: Route,
                 result: au.Settable):
        self.node = node
        self.txn_id = txn_id
        self.txn = txn
        self.route = route
        self.result = result
        self.topologies = node.topology.with_unsynced_epochs(route, txn_id.epoch, txn_id.epoch)

    # -- PreAccept round ----------------------------------------------------
    def start(self) -> None:
        tracker = FastPathTracker(self.topologies)
        oks: Dict[int, PreAcceptOk] = {}
        this = self

        class PreAcceptCallback(Callback):
            done = False

            def on_success(self, from_node: int, reply) -> None:
                if self.done:
                    return
                if isinstance(reply, PreAcceptNack):
                    # a competing ballot exists (recovery in progress)
                    status = tracker.record_failure(from_node)
                else:
                    oks[from_node] = reply
                    status = tracker.record_success(from_node, reply.witnessed_fast_path)
                if status is RequestStatus.SUCCESS:
                    self.done = True
                    this.on_preaccepted(tracker, oks)
                elif status is RequestStatus.FAILED:
                    self.done = True
                    this.result.set_failure(Exhausted(this.txn_id, "preaccept"))

            def on_failure(self, from_node: int, failure: BaseException) -> None:
                if self.done:
                    return
                # a failure can DECIDE the round: an unreachable electorate
                # member is a fast-path reject, so the tracker may flip to
                # SUCCESS (slow path) here — not just FAILED
                status = tracker.record_failure(from_node)
                if status is RequestStatus.SUCCESS:
                    self.done = True
                    this.on_preaccepted(tracker, oks)
                elif status is RequestStatus.FAILED:
                    self.done = True
                    this.result.set_failure(Exhausted(this.txn_id, "preaccept"))

        callback = PreAcceptCallback()
        max_epoch = self.topologies.current_epoch
        self.node.send_to_each(
            tracker.nodes(),
            lambda to: self._preaccept_for(to, max_epoch),
            callback)

    def _preaccept_for(self, to: int, max_epoch: int) -> Optional[PreAccept]:
        scope = TxnRequest.compute_scope(to, self.topologies, self.route)
        if scope is None:
            return None
        wait_for = TxnRequest.compute_wait_for_epoch(to, self.topologies)
        partial = self.txn.slice(_scope_ranges(self.node, scope, max_epoch), to == self.node.id)
        return PreAccept(self.txn_id, scope, wait_for, partial, max_epoch,
                         route=self.route)

    def on_preaccepted(self, tracker: FastPathTracker, oks: Dict[int, PreAcceptOk]) -> None:
        # executeAt = fold mergeMax over witnessed timestamps (CoordinatePreAccept:152-163)
        execute_at: Optional[Timestamp] = None
        for ok in oks.values():
            execute_at = ok.witnessed_at if execute_at is None else execute_at.merge_max(ok.witnessed_at)

        observer = getattr(self.node, "observer", None)
        if tracker.has_fast_path_accepted():
            if observer is not None:
                observer.on_path(self.txn_id, ExecutePath.FAST,
                                 tracker.fast_path_votes())
            # merge deps only from replicas that voted fast-path (they witnessed
            # everything that could execute before us) — CoordinateTransaction:71-77
            deps = Deps.merge([ok.deps for ok in oks.values() if ok.witnessed_fast_path])
            self.execute(ExecutePath.FAST, self.txn_id.as_timestamp(), deps)
        elif execute_at is not None and execute_at.is_rejected:
            self.result.set_failure(Invalidated(self.txn_id, "preaccept rejected"))
        else:
            if observer is not None:
                observer.on_path(self.txn_id, ExecutePath.SLOW,
                                 tracker.fast_path_votes())
            deps = Deps.merge([ok.deps for ok in oks.values()])
            self.extend_to_epoch(execute_at,
                                 lambda: self.propose(Ballot.ZERO, execute_at, deps))

    def extend_to_epoch(self, execute_at: Timestamp, cont) -> None:
        """Epoch-spanning coordination (CoordinationAdapter.Invoke topology
        recompute; AbstractCoordinatePreAccept epoch-extension): when executeAt
        lands in a later epoch than txnId, every subsequent round must also
        contact the execution epoch's replicas — otherwise replicas that joined
        in the new epoch never receive Stable/Apply and replica sets diverge."""
        if execute_at is None or execute_at.epoch <= self.topologies.current_epoch:
            cont()
            return

        def go(_v, f):
            if f is not None:
                self.result.set_failure(f)
                return
            self.topologies = self.node.topology.with_unsynced_epochs(
                self.route, self.txn_id.epoch, execute_at.epoch)
            cont()

        self.node.with_epoch(execute_at.epoch).begin(go)

    # -- Propose (Accept round, Propose.java) --------------------------------
    def propose(self, ballot: Ballot, execute_at: Timestamp, deps: Deps) -> None:
        topologies = self.topologies
        tracker = QuorumTracker(topologies)
        accept_oks: List[AcceptOk] = []
        this = self

        class AcceptCallback(Callback):
            done = False

            def on_success(self, from_node: int, reply) -> None:
                if self.done:
                    return
                if isinstance(reply, AcceptNack):
                    self.done = True
                    this.result.set_failure(Preempted(this.txn_id, f"by {reply.supersceded_by}"))
                    return
                accept_oks.append(reply)
                if tracker.record_success(from_node) is RequestStatus.SUCCESS:
                    self.done = True
                    stable_deps = this.merge_accept_deps(deps, accept_oks)
                    this.stabilise_and_execute(execute_at, stable_deps, ballot)

            def on_failure(self, from_node: int, failure: BaseException) -> None:
                if self.done:
                    return
                if tracker.record_failure(from_node) is RequestStatus.FAILED:
                    self.done = True
                    this.result.set_failure(Exhausted(this.txn_id, "accept"))

        callback = AcceptCallback()
        self.node.send_to_each(
            tracker.nodes(),
            lambda to: self._accept_for(to, ballot, execute_at, deps),
            callback)

    def _accept_for(self, to: int, ballot: Ballot, execute_at: Timestamp,
                    deps: Deps) -> Optional[Accept]:
        scope = TxnRequest.compute_scope(to, self.topologies, self.route)
        if scope is None:
            return None
        wait_for = TxnRequest.compute_wait_for_epoch(to, self.topologies)
        ranges = _scope_ranges(self.node, scope, self.topologies.current_epoch)
        from ..primitives.keys import Ranges as _Ranges
        keys = self.txn.keys.intersection(ranges) if isinstance(self.txn.keys, _Ranges) \
            else self.txn.keys.slice(ranges)
        return Accept(self.txn_id, scope, wait_for, ballot, execute_at,
                      keys, deps.slice(ranges), route=self.route)

    def merge_accept_deps(self, deps: Deps, accept_oks: List[AcceptOk]) -> Deps:
        """Deps at executeAt = merge of accept-ok deps (Propose.java).  Sync
        points override: their deps are fixed by PreAccept (all < txnId), so
        waiting never forms cycles between concurrent sync points
        (CoordinateSyncPoint.java:129 'we don't need to fetch deps from Accept')."""
        return Deps.merge([deps] + [ok.deps for ok in accept_oks])

    # -- Stabilise + Execute -------------------------------------------------
    def execute(self, path: str, execute_at: Timestamp, deps: Deps) -> None:
        """Fast path: Stable+Read immediately (stability is recoverable from the
        fast-path quorum)."""
        _ExecuteTxn(self.node, self.txn_id, self.txn, self.route, self.topologies,
                    SaveStatus.STABLE, execute_at, deps, self.result,
                    require_stable_quorum=False).start()

    def stabilise_and_execute(self, execute_at: Timestamp, deps: Deps,
                              ballot: Ballot = Ballot.ZERO) -> None:
        """Slow path: the Stable round must reach a quorum per shard before the
        outcome is reported, so recovery always finds the stable deps
        (Stabilise.java)."""
        _ExecuteTxn(self.node, self.txn_id, self.txn, self.route, self.topologies,
                    SaveStatus.STABLE, execute_at, deps, self.result,
                    require_stable_quorum=True, ballot=ballot).start()


class _ExecuteTxn:
    """Sends Stable(+Read fused) and collects per-shard Data (ExecuteTxn.java:53-200,
    ReadCoordinator.java)."""

    def __init__(self, node: "Node", txn_id: TxnId, txn: Txn, route: Route,
                 topologies, kind_status: SaveStatus, execute_at: Timestamp, deps: Deps,
                 result: au.Settable, require_stable_quorum: bool,
                 ballot: Ballot = Ballot.ZERO):
        self.node = node
        self.txn_id = txn_id
        self.txn = txn
        self.route = route
        self.topologies = topologies
        self.kind_status = kind_status
        self.execute_at = execute_at
        self.deps = deps
        self.result = result
        self.require_stable_quorum = require_stable_quorum
        self.ballot = ballot
        # reads execute against the EXECUTION epoch's replicas only (a replica
        # that lost a range by then cannot serve its data) — ExecuteTxn.java
        from ..topology.topology import Topologies
        self.read_tracker = ReadTracker(Topologies([topologies.current()]))
        self.stable_tracker = QuorumTracker(topologies)
        self.data = None
        self.done = False
        # partial-coverage accounting: per shard, the footprint slice still
        # unread.  A replica mid-bootstrap serves its clean slice and reports
        # the pending remainder unavailable (ReadOk.unavailable); coverage
        # completes when the UNION of replies covers each shard — no single
        # replica needs to serve the whole slice (ReadCoordinator capability;
        # without it, wide range reads deadlocked against bootstrap fences
        # under topology churn)
        # transient-nack read re-rounds: obsolete/unavailable mean a replica
        # state that RESOLVES by itself (PRE_APPLIED drains to APPLIED, where
        # the MVCC read serves; bootstrap completes) — when a read round
        # exhausts on only those, re-run it after a beat instead of failing
        # the whole (often recovery-driven) execution.  Bounded so a
        # genuinely wedged footprint still escalates (sustained-chaos
        # recovery livelocked exactly here: every copy raced to APPLIED and
        # each attempt's reads exhausted on obsolete — seed-4 churn stall).
        self.read_rounds = 0
        self._read_retry_pending = False
        self._init_unread()
        # grandfathered-coverage accounting (the seed-6 refencing wedge):
        # residue snapshot at the last retry-round launch — a round that
        # strictly SHRANK the residue doesn't charge the round budget
        self._last_residue = None

    MAX_READ_ROUNDS = 3

    def _init_unread(self) -> None:
        parts = self.route.participants()
        from ..primitives.keys import Ranges as _Rs
        self._unread = {}
        for i, t in enumerate(self.read_tracker.trackers):
            if isinstance(parts, _Rs):
                sl = parts.intersection(_Rs.of(t.shard.range))
                if len(sl):
                    self._unread[i] = sl
            else:
                ks = {k for k in parts if t.shard.range.contains(k)}
                if ks:
                    self._unread[i] = ks

    def _residue_snapshot(self):
        """Canonical snapshot of the per-shard unread residues.  Coverage is
        monotone (``absorb_partial`` only intersects), so inequality with the
        previous round's snapshot means the residue strictly shrank."""
        out = []
        for i in sorted(self._unread):
            cur = self._unread[i]
            if isinstance(cur, set):
                out.append((i, tuple(sorted(cur))))
            else:
                out.append((i, tuple((r.start, r.end) for r in cur)))
        return tuple(out)

    def retry_read_round_or_fail(self) -> None:
        """A read round exhausted on TRANSIENT nacks (obsolete: the copy is
        mid-apply and will serve from the MVCC snapshot once APPLIED;
        unavailable: bootstrap in flight).  Re-run the round after a beat —
        bounded, so a genuinely wedged footprint still fails the attempt.

        One retry per ROUND: the tracker reports FAILED independently per
        exhausted shard, and without the pending guard a multi-shard route
        would burn the whole round budget (and launch racing duplicate
        rounds) on a single exhaustion."""
        if self._read_retry_pending:
            return
        cfg = getattr(self.node, "config", None)
        max_rounds = cfg.max_read_rounds if cfg is not None \
            else self.MAX_READ_ROUNDS
        snap = self._residue_snapshot()
        if self._last_residue is not None and snap != self._last_residue:
            # the last round strictly shrank the unread residue: coverage IS
            # assembling, so the round is progress, not a failure — only
            # NO-PROGRESS rounds charge the budget (the residue is monotone
            # non-increasing over a finite set of reply boundaries, so this
            # still terminates).  Without it, the round budget raced the
            # truncation/staleness ladder's re-fencing cadence and a read
            # gathering one new slice per round still exhausted (seed 6).
            self.read_rounds = 0
        self._last_residue = snap
        if self.read_rounds >= max_rounds:
            # NOTE: rounds exhausted partly by hard (link FAILURE) replies
            # still retry — in the chaos model link failures are transient
            # (links re-randomize every few sim-seconds), and failing the
            # attempt on any hard failure livelocks recovery churn (measured:
            # hostile seed 0 stalls).  A shard whose candidates ALL hard-fail
            # already Exhausts immediately via on_failure.
            self.done = True
            self.result.set_failure(Exhausted(self.txn_id, "read"))
            return
        self.read_rounds += 1
        self._read_retry_pending = True

        def go():
            self._read_retry_pending = False
            if self.done:
                return
            from ..topology.topology import Topologies
            # GRANDFATHER the assembled coverage (the seed-6 refencing
            # wedge): slices already served are FINAL — the read is at a
            # fixed executeAt and the data store is an immutable MVCC
            # snapshot there — so the union built in earlier rounds
            # survives into this one.  Resetting it each round raced
            # coverage assembly against the truncation/staleness ladder's
            # re-fencing cadence: every round restarted from zero while a
            # fresh catch-up fence kept SOME slice pending somewhere, and
            # the budget exhausted into Exhausted(read) -> recovery churn.
            prev_unread = self._unread
            self.read_tracker = ReadTracker(Topologies([self.topologies.current()]))
            self._init_unread()
            for i in list(self._unread):
                if i in prev_unread:
                    self._unread[i] = prev_unread[i]
            # shards the union already covers need no further reads: mark
            # them read so neither contacts nor candidate exhaustion is
            # burned on them (their data was banked in an earlier round)
            for i, t in enumerate(self.read_tracker.trackers):
                cur = self._unread.get(i)
                if cur is None or not len(cur):
                    t.data_received = True
            # rotate EVERY shard's pick per round: re-contacting the same
            # (deterministically chosen) stuck copy every round re-creates
            # the livelock the rounds exist to break
            for to in self.read_tracker.initial_contacts(
                    prefer=self.node.id, rotate=self.read_rounds,
                    avoid=self.node.slow_peers()):
                self.send_read_retry(to)
            self._arm_read_speculation()   # retry rounds speculate too
        delay = cfg.read_retry_delay_s if cfg is not None else 0.15
        self.node.scheduler.once(delay, go)

    @property
    def needs_read(self) -> bool:
        """Sync points (and any read-less txn) have no data to collect: their
        execution phase is a pure dependency wait at the replicas, so no read
        is fused with Stable (ExecuteSyncPoint vs ExecuteTxn,
        CoordinationAdapter.java:214-264)."""
        return self.txn.read is not None and not self.txn_id.kind.is_sync_point

    def start(self) -> None:
        # route the per-shard data reads around peers the gray-failure
        # tracker currently marks slow (paused-but-alive, stalled disk):
        # contacting one burns a whole reply-timeout + speculation round
        read_nodes = set(self.read_tracker.initial_contacts(
            prefer=self.node.id, avoid=self.node.slow_peers())) \
            if self.needs_read else set()
        this = self

        class ExecuteCallback(Callback):
            def on_success(self, from_node: int, reply) -> None:
                if this.done:
                    return
                if isinstance(reply, ReadOk):
                    if reply.data is not None:
                        this.data = reply.data if this.data is None else this.data.merge(reply.data)
                    this.on_stable_ack(from_node)
                    if reply.unavailable is not None and len(reply.unavailable):
                        # partial read: absorb the served slice; the shard
                        # completes when the union of replies covers it
                        if this.absorb_partial(from_node, reply.unavailable):
                            if this.read_tracker.record_read_success(from_node) \
                                    is RequestStatus.SUCCESS:
                                this.maybe_finish()
                            return
                        status, retries = this.read_tracker.record_read_failure(
                            from_node, avoid=this.node.slow_peers())
                        if status is RequestStatus.FAILED:
                            this.retry_read_round_or_fail()
                            return
                        for to in retries:
                            this.send_read_retry(to)
                        return
                    this.absorb_partial(from_node, None)
                    if not this.done and this.read_tracker.record_read_success(from_node) \
                            is RequestStatus.SUCCESS:
                        this.maybe_finish()
                elif isinstance(reply, ReadNack):
                    if reply.reason in ("unavailable", "obsolete"):
                        # bootstrapping replica, or one that raced past
                        # ReadyToExecute (an Apply won): read elsewhere
                        # (the Stable part already acked separately)
                        status, retries = this.read_tracker.record_read_failure(
                            from_node, avoid=this.node.slow_peers())
                        if status is RequestStatus.FAILED:
                            this.retry_read_round_or_fail()
                            return
                        for to in retries:
                            this.send_read_retry(to)
                        return
                    this.done = True
                    this.result.set_failure(Insufficient(this.txn_id, reply.reason))
                elif isinstance(reply, CommitNack):
                    from ..local.commands import CommitOutcome
                    this.done = True
                    if reply.outcome is CommitOutcome.REJECTED_BALLOT:
                        this.result.set_failure(Preempted(this.txn_id, "commit"))
                    else:
                        this.result.set_failure(Insufficient(this.txn_id, str(reply.outcome)))
                else:  # CommitOk / StableAck
                    this.on_stable_ack(from_node)
                    if not this.done:
                        this.maybe_finish()

            def on_failure(self, from_node: int, failure: BaseException) -> None:
                if this.done:
                    return
                if this.stable_tracker.record_failure(from_node) is RequestStatus.FAILED:
                    this.done = True
                    this.result.set_failure(Exhausted(this.txn_id, "stabilise"))
                    return
                if not this.needs_read:
                    return
                status, retries = this.read_tracker.record_read_failure(
                    from_node, avoid=this.node.slow_peers())
                if status is RequestStatus.FAILED:
                    this.done = True
                    this.result.set_failure(Exhausted(this.txn_id, "read"))
                    return
                for to in retries:
                    this.send_read_retry(to)

        self.callback = ExecuteCallback()
        # send_to_each: a node whose route scope slice is empty (topology
        # churn) must FAIL its tracker slot, not silently skip — the same
        # hang fixed in Node.send_to_each applies to this tracker too
        self.node.send_to_each(
            self.stable_tracker.nodes(),
            lambda to: self.commit_for(to, read=to in read_nodes),
            self.callback)
        if read_nodes:
            self._arm_read_speculation()

    def _arm_read_speculation(self) -> None:
        """Slow-replica read speculation (ReadTracker.java): if any shard's
        data read hasn't answered within the slow threshold, speculatively
        contact the next-preferred replica — without failing the slow one.
        The reference speculates immediately on its slow signal; waiting for
        the failure path instead costs whole reply-timeout rounds under
        chaos (VERDICT r04 item 3)."""
        cfg = getattr(self.node, "config", None)
        delay = cfg.slow_read_threshold_s if cfg is not None else 1.5

        def fire():
            if self.done:
                return
            for to in self.read_tracker.speculate(
                    avoid=self.node.slow_peers()):
                self.send_read_retry(to)
        self.node.scheduler.once(delay, fire)

    def commit_for(self, to: int, read: bool) -> Optional[Commit]:
        scope = TxnRequest.compute_scope(to, self.topologies, self.route)
        if scope is None:
            return None
        wait_for = TxnRequest.compute_wait_for_epoch(to, self.topologies)
        ranges = _scope_ranges(self.node, scope, self.topologies.current_epoch)
        partial = self.txn.slice(ranges, to == self.node.id)
        return Commit(self.txn_id, scope, wait_for, self.kind_status, self.execute_at,
                      partial, self.deps.slice(ranges), read=read, ballot=self.ballot,
                      route=self.route)

    def send_read_retry(self, to: int) -> None:
        self.node.send_to_each([to], lambda t: self.commit_for(t, read=True),
                               self.callback)

    def on_stable_ack(self, from_node: int) -> None:
        self.stable_tracker.record_success(from_node)

    def absorb_partial(self, from_node: int, unavailable) -> bool:
        """Fold one read reply's coverage into the per-shard unread residue:
        remaining = remaining ∩ unavailable (what this replica could NOT
        serve).  Returns True iff every shard this node was reading for is
        now fully covered by the union of replies so far."""
        all_covered = True
        for i, t in enumerate(self.read_tracker.trackers):
            if from_node not in t.in_flight_reads:
                continue
            cur = self._unread.get(i)
            if cur is None:
                continue
            if unavailable is None or not len(unavailable):
                cur = type(cur)() if isinstance(cur, set) else cur.without(cur)
            elif isinstance(cur, set):
                cur = {k for k in cur if unavailable.contains(k)}
            else:
                cur = cur.intersection(unavailable)
            self._unread[i] = cur
            if cur and len(cur):
                all_covered = False
            else:
                # the union of replies covers this shard: it is READ — the
                # tracker must not burn further candidates on it (exhausting
                # them reported spurious read failure while coverage was
                # already complete)
                t.data_received = True
        return all_covered

    def maybe_finish(self) -> None:
        if self.done:
            return
        reads_done = not self.needs_read \
            or self.read_tracker._all_success(lambda t: t.data_received)
        stable_done = (not self.require_stable_quorum
                       or self.stable_tracker.has_reached_quorum())
        if reads_done and stable_done:
            self.done = True
            self.persist()

    # -- Persist (PersistTxn; client callback FIRST) -------------------------
    def persist(self) -> None:
        txn_result = self.txn.result(self.txn_id, self.execute_at, self.data)
        writes = self.txn.execute(self.txn_id, self.execute_at, self.data)
        self.result.set_success(txn_result)
        # sync points always apply Maximal so any replica (e.g. one that never
        # witnessed it) can apply without prior state (CoordinationAdapter:214-264)
        apply_kind = Apply.MAXIMAL if self.txn_id.kind.is_sync_point else Apply.MINIMAL
        self.send_applies(writes, txn_result, apply_kind,
                          on_quorum_applied=self.inform_durable)

    def send_applies(self, writes, txn_result, apply_kind: str,
                     on_quorum_applied=None, on_quorum_impossible=None) -> None:
        """Broadcast Apply to every replica; fire ``on_quorum_applied`` once a
        quorum of every shard has acked (PersistTxn.java; progress logs then
        stand down via InformDurable), or ``on_quorum_impossible`` once some
        shard can no longer reach an apply quorum.  MAXIMAL applies carry the
        full txn definition so any replica can apply without prior state.

        When EVERY contacted replica acks, a second InformDurable wave
        upgrades the txn to UNIVERSAL — per-txn universal durability is the
        sound gate for transitive-elision (a merely-majority-applied txn may
        be unapplied at the very replica a later txn's elided deps reach;
        universality is what the range durability rounds proved when they
        were the only gate)."""
        applied = AppliedTracker(self.topologies)
        this = self
        contacted: List[int] = []

        class ApplyCallback(Callback):
            informed = False
            acked: Set[int] = set()
            impossible_universal = False

            def _failed(self, from_node: int) -> None:
                self.impossible_universal = True
                if applied.record_failure(from_node) is RequestStatus.FAILED \
                        and not self.informed:
                    self.informed = True
                    if on_quorum_impossible is not None:
                        on_quorum_impossible()

            def on_success(self, from_node: int, reply) -> None:
                if not isinstance(reply, ApplyOk):
                    # e.g. ReadNack("insufficient"): NOT a durable apply ack
                    self._failed(from_node)
                    return
                if not self.informed \
                        and applied.record_success(from_node) is RequestStatus.SUCCESS:
                    self.informed = True
                    if on_quorum_applied is not None:
                        on_quorum_applied()
                self.acked.add(from_node)
                if not self.impossible_universal \
                        and len(self.acked) == len(contacted):
                    this.inform_universal()

            def on_failure(self, from_node: int, failure: BaseException) -> None:
                self._failed(from_node)

        callback = ApplyCallback()
        for to in self.topologies.nodes():
            scope = TxnRequest.compute_scope(to, self.topologies, self.route)
            if scope is None:
                continue
            contacted.append(to)
            wait_for = TxnRequest.compute_wait_for_epoch(to, self.topologies)
            ranges = _scope_ranges(self.node, scope, self.topologies.current_epoch)
            partial_txn = self.txn.slice(ranges, include_query=False) \
                if apply_kind == Apply.MAXIMAL else None
            self.node.send(to, Apply(
                self.txn_id, scope, wait_for, apply_kind, self.execute_at,
                self.deps.slice(ranges), partial_txn, writes.slice(ranges),
                txn_result, route=self.route), callback)

    def inform_universal(self) -> None:
        """Every contacted replica acked its Apply: broadcast the UNIVERSAL
        durability upgrade (widens the per-txn elision gate everywhere)."""
        from ..local.status import Durability
        from ..messages.status_messages import InformDurable
        for to in self.topologies.nodes():
            scope = TxnRequest.compute_scope(to, self.topologies, self.route)
            if scope is None:
                continue
            wait_for = TxnRequest.compute_wait_for_epoch(to, self.topologies)
            self.node.send(to, InformDurable(self.txn_id, scope, wait_for,
                                             self.execute_at,
                                             Durability.UNIVERSAL))

    def inform_durable(self) -> None:
        from ..local.status import Durability
        from ..messages.status_messages import InformDurable, InformHomeDurable
        for to in self.topologies.nodes():
            scope = TxnRequest.compute_scope(to, self.topologies, self.route)
            if scope is None:
                continue
            wait_for = TxnRequest.compute_wait_for_epoch(to, self.topologies)
            self.node.send(to, InformDurable(self.txn_id, scope, wait_for,
                                             self.execute_at, Durability.MAJORITY))
        # the HOME shard owns global progress responsibility: tell it
        # explicitly so its progress machinery stands down even where it
        # holds no data for the txn (InformHomeDurable.java)
        home_scope = self.route.home_key_only()
        topology = self.node.topology.topology_for_epoch(self.txn_id.epoch)
        shard = topology.for_key_required(self.route.home_key)
        for to in shard.nodes:
            self.node.send(to, InformHomeDurable(
                self.txn_id, home_scope, self.txn_id.epoch,
                self.execute_at, Durability.MAJORITY))


# ---------------------------------------------------------------------------
# Recovery re-entry points (CoordinationAdapter.Step.InitiateRecovery): recovery
# resumes the standard pipeline at the phase matching the strongest evidence it
# found, carrying its ballot through every subsequent round.
# ---------------------------------------------------------------------------

def _resume_coordinator(node: "Node", txn_id: TxnId, txn: Txn, route: Route,
                        result: au.Settable) -> "_CoordinateTransaction":
    """Recovery resume must drive sync points through the sync-point adapter:
    their execution phase is a pure dependency wait with MAXIMAL applies and NO
    read round — resuming one through the txn adapter sends reads that replicas
    past ReadyToExecute nack as obsolete, exhausting every recovery attempt
    (CoordinationAdapter recovery adapters, CoordinationAdapter.java:214-264)."""
    if txn_id.kind.is_sync_point:
        from .sync_point import _CoordinateSyncPoint
        return _CoordinateSyncPoint(node, txn_id, txn, route, result, blocking=True)
    return _CoordinateTransaction(node, txn_id, txn, route, result)


def resume_propose(node: "Node", txn_id: TxnId, txn: Txn, route: Route,
                   result: au.Settable, ballot: Ballot, execute_at: Timestamp,
                   deps: Deps) -> None:
    """Re-run the Accept round at ``ballot`` (recovery of an Accepted txn, or
    re-proposal at txnId when the fast path may have succeeded)."""
    c = _resume_coordinator(node, txn_id, txn, route, result)
    c.extend_to_epoch(execute_at, lambda: c.propose(ballot, execute_at, deps))


def resume_stabilise(node: "Node", txn_id: TxnId, txn: Txn, route: Route,
                     result: au.Settable, ballot: Ballot, execute_at: Timestamp,
                     deps: Deps) -> None:
    """Re-run Stable+Execute (recovery of a Committed/Stable txn)."""
    c = _resume_coordinator(node, txn_id, txn, route, result)
    c.extend_to_epoch(execute_at,
                      lambda: c.stabilise_and_execute(execute_at, deps, ballot))


def persist_maximal(node: "Node", txn_id: TxnId, txn: Txn, route: Route,
                    topologies, execute_at: Timestamp, deps: Deps, writes,
                    txn_result) -> None:
    """Broadcast Apply.Maximal — carrying the full txn definition and deps so any
    replica can apply without prior state (recovery with a known outcome,
    Persist.java / CoordinationAdapter.java:192-197)."""
    for to in topologies.nodes():
        scope = TxnRequest.compute_scope(to, topologies, route)
        if scope is None:
            continue
        wait_for = TxnRequest.compute_wait_for_epoch(to, topologies)
        ranges = _scope_ranges(node, scope, topologies.current_epoch)
        node.send(to, Apply(
            txn_id, scope, wait_for, Apply.MAXIMAL, execute_at,
            deps.slice(ranges), txn.slice(ranges, include_query=False),
            writes.slice(ranges) if writes is not None else None, txn_result,
            route=route))


def _scope_ranges(node: "Node", scope: Route, max_epoch: int):
    """The ranges a scope covers (for slicing txn/deps payloads)."""
    if scope.covering is not None:
        return scope.covering
    from ..primitives.keys import Ranges
    out = Ranges.EMPTY
    for e in range(node.topology.min_epoch, max_epoch + 1):
        if node.topology.has_epoch(e):
            out = out.union(node.topology.topology_for_epoch(e).ranges())
    return out
