"""CollectDeps — quorum deps collection over a footprint.

Capability parity with ``accord.coordinate.CollectDeps`` (CollectDeps.java):
drive a GetDeps round to a quorum of every shard covering ``keys`` and merge
the replies.  Used by recovery when the merged commit evidence is
insufficient for part of the footprint (Recover.withCommittedDeps,
Recover.java:384-400).
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Dict

from ..messages.base import Callback, TxnRequest
from ..messages.deps_messages import (GetDeps, GetDepsOk, GetMaxConflict,
                                      GetMaxConflictOk)
from ..primitives.deps import Deps
from ..primitives.route import Route
from ..primitives.timestamp import Timestamp, TxnId
from ..utils import async_ as au
from .errors import Exhausted
from .tracking import QuorumTracker, RequestStatus

if TYPE_CHECKING:
    from ..local.node import Node


def collect_deps(node: "Node", txn_id: TxnId, route: Route, keys,
                 execute_at: Timestamp) -> au.AsyncResult:
    """Resolve with the merged Deps for ``keys`` at ``execute_at``."""
    result = au.settable()
    topologies = node.topology.precise_epochs(route, txn_id.epoch,
                                              execute_at.epoch)
    tracker = QuorumTracker(topologies)
    oks: Dict[int, Deps] = {}
    state = {"done": False}

    class CollectCallback(Callback):
        def on_success(self, from_node: int, reply) -> None:
            if state["done"]:
                return
            if isinstance(reply, GetDepsOk):
                oks[from_node] = reply.deps
                if tracker.record_success(from_node) is RequestStatus.SUCCESS:
                    state["done"] = True
                    result.set_success(Deps.merge(list(oks.values())))

        def on_failure(self, from_node: int, failure: BaseException) -> None:
            if state["done"]:
                return
            if tracker.record_failure(from_node) is RequestStatus.FAILED:
                state["done"] = True
                result.set_failure(Exhausted(txn_id, "GetDeps quorum unreachable"))

    callback = CollectCallback()
    for to in tracker.nodes():
        scope = TxnRequest.compute_scope(to, topologies, route)
        if scope is None:
            continue
        node.send(to, GetDeps(txn_id, scope,
                              TxnRequest.compute_wait_for_epoch(to, topologies),
                              keys, execute_at), callback)
    return result


def fetch_max_conflict(node: "Node", txn_id: TxnId, route: Route,
                       keys) -> au.AsyncResult:
    """FetchMaxConflict (FetchMaxConflict.java): quorum max of every shard's
    MaxConflicts over ``keys`` — resolves with the highest Timestamp witnessed
    (or None if nothing conflicts anywhere)."""
    result = au.settable()
    topologies = node.topology.precise_epochs(route, txn_id.epoch,
                                              txn_id.epoch)
    tracker = QuorumTracker(topologies)
    best: Dict[str, object] = {"ts": None}
    state = {"done": False}

    class MaxCallback(Callback):
        def on_success(self, from_node: int, reply) -> None:
            if state["done"]:
                return
            if isinstance(reply, GetMaxConflictOk):
                ts = reply.max_conflict
                if ts is not None and (best["ts"] is None or ts > best["ts"]):
                    best["ts"] = ts
                if tracker.record_success(from_node) is RequestStatus.SUCCESS:
                    state["done"] = True
                    result.set_success(best["ts"])

        def on_failure(self, from_node: int, failure: BaseException) -> None:
            if state["done"]:
                return
            if tracker.record_failure(from_node) is RequestStatus.FAILED:
                state["done"] = True
                result.set_failure(
                    Exhausted(txn_id, "GetMaxConflict quorum unreachable"))

    callback = MaxCallback()
    for to in tracker.nodes():
        scope = TxnRequest.compute_scope(to, topologies, route)
        if scope is None:
            continue
        node.send(to, GetMaxConflict(
            txn_id, scope, TxnRequest.compute_wait_for_epoch(to, topologies),
            keys), callback)
    return result
