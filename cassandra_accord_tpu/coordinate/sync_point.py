"""Sync point coordination.

Capability parity with ``accord.coordinate`` CoordinateSyncPoint / ProposeSyncPoint /
ExecuteSyncPoint (CoordinateSyncPoint.java:58-140, CoordinationAdapter.java:214-264):
a sync point is an empty transaction (kind SyncPoint or ExclusiveSyncPoint) coordinated
through the standard PreAccept/Accept/Stable pipeline whose *execution* is pure
dependency-wait — once applied, every transaction in its dependency set is decided
(and, for a quorum-applied sync point, durably applied at a quorum per shard).

- inclusive, async:   resolves with the SyncPoint handle once stable (deps known);
                      applies proceed in the background (CoordinateSyncPoint.inclusive).
- inclusive, blocking: resolves once a quorum of every shard has Applied.
- exclusive:          kind ExclusiveSyncPoint — witnesses everything before it and is
                      witnessed by everything after; used by bootstrap, epoch closure
                      and shard-durability rounds.  Always quorum-applied, and notifies
                      the epoch-closure hook (CoordinationAdapter.java:214-264).
"""
from __future__ import annotations

from typing import Optional, TYPE_CHECKING

from ..local.status import SaveStatus
from ..primitives.keys import Ranges
from ..primitives.sync_point import SyncPoint
from ..primitives.timestamp import Ballot, TxnId, TxnKind
from ..primitives.txn import Seekables, Txn
from ..utils import async_ as au
from .coordinate_transaction import _CoordinateTransaction, _ExecuteTxn
from ..messages.txn_messages import Apply

if TYPE_CHECKING:
    from ..local.node import Node


def coordinate_inclusive(node: "Node", seekables: Seekables,
                         blocking: bool = False) -> au.AsyncResult:
    """Coordinate an inclusive sync point over ``seekables``
    (CoordinateSyncPoint.inclusive / inclusiveAndAwaitQuorum)."""
    return _coordinate(node, TxnKind.SYNC_POINT, seekables, blocking)


def coordinate_exclusive(node: "Node", ranges: Ranges,
                         blocking: bool = True,
                         txn_id: Optional[TxnId] = None) -> au.AsyncResult:
    """Coordinate an exclusive sync point over ``ranges``
    (CoordinateSyncPoint.exclusive; used by Bootstrap and durability rounds).
    ``txn_id`` may be pre-allocated by the caller (Bootstrap marks
    bootstrappedAt with it BEFORE coordinating)."""
    return _coordinate(node, TxnKind.EXCLUSIVE_SYNC_POINT, ranges,
                       blocking=blocking, txn_id=txn_id)


def _coordinate(node: "Node", kind: TxnKind, seekables: Seekables,
                blocking: bool, txn_id: Optional[TxnId] = None) -> au.AsyncResult:
    txn = Txn.empty(kind, seekables)
    if txn_id is None:
        txn_id = node.next_txn_id(kind, txn.domain)
    result = au.settable()

    def start(_v, f):
        if f is not None:
            result.set_failure(f)
            return
        route = node.compute_route(txn)
        _CoordinateSyncPoint(node, txn_id, txn, route, result, blocking).start()

    node.with_epoch(txn_id.epoch).begin(start)
    return result


class _CoordinateSyncPoint(_CoordinateTransaction):
    """Drives the standard pipeline but executes as a sync point."""

    def __init__(self, node: "Node", txn_id: TxnId, txn: Txn, route, result,
                 blocking: bool):
        super().__init__(node, txn_id, txn, route, result)
        self.blocking = blocking

    def on_preaccepted(self, tracker, oks) -> None:
        """CoordinateSyncPoint.onPreAccepted: deps merge from ALL replies; only
        a plain SyncPoint may take the fast path (exclusive always proposes);
        Accept-round deps are ignored (deps stay < txnId, so concurrent sync
        points' waits are acyclic)."""
        from ..primitives.deps import Deps
        from ..primitives.timestamp import Ballot as _Ballot
        execute_at = None
        for ok in oks.values():
            execute_at = ok.witnessed_at if execute_at is None \
                else execute_at.merge_max(ok.witnessed_at)
        if execute_at is not None and execute_at.is_rejected:
            from .errors import Invalidated
            self.result.set_failure(Invalidated(self.txn_id, "preaccept rejected"))
            return
        deps = Deps.merge([ok.deps for ok in oks.values()])
        from .coordinate_transaction import ExecutePath
        if tracker.has_fast_path_accepted() and self.txn_id.kind is TxnKind.SYNC_POINT:
            self.execute(ExecutePath.FAST, self.txn_id.as_timestamp(), deps)
        else:
            # sync points agree DEPS, never a bumped executeAt
            # (CoordinateSyncPoint.java): a fence's whole meaning is
            # "everything before txnId".  Proposing the merged witnessed_at
            # instead made the ACCEPT/recovery rounds recompute deps at the
            # HIGHER bound, pulling in later-started sync points — an
            # earlier fence then waited on a later one, which (correctly)
            # waited back on it: the wait-cycle anchor of the PRE_APPLIED
            # livelock class.  The epoch still extends to the witnessed
            # epoch so scopes cover churn.
            self.extend_to_epoch(
                execute_at,
                lambda: self.propose(_Ballot.ZERO,
                                     self.txn_id.as_timestamp(), deps))

    def merge_accept_deps(self, deps, accept_oks):
        return deps

    def execute(self, path: str, execute_at, deps) -> None:
        _ExecuteSyncPoint(self.node, self.txn_id, self.txn, self.route,
                          self.topologies, SaveStatus.STABLE, execute_at, deps,
                          self.result, require_stable_quorum=False,
                          blocking=self.blocking).start()

    def stabilise_and_execute(self, execute_at, deps, ballot=Ballot.ZERO) -> None:
        _ExecuteSyncPoint(self.node, self.txn_id, self.txn, self.route,
                          self.topologies, SaveStatus.STABLE, execute_at, deps,
                          self.result, require_stable_quorum=True, ballot=ballot,
                          blocking=self.blocking).start()


class _ExecuteSyncPoint(_ExecuteTxn):
    """ExecuteSyncPoint.java: same Stable round, but the result is the SyncPoint
    handle, applies are MAXIMAL (any replica can apply without prior state), and
    a blocking sync point resolves only once a quorum of every shard applied."""

    def __init__(self, *args, blocking: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        self.blocking = blocking

    def persist(self) -> None:
        sync_point = SyncPoint(self.txn_id, self.route, self.deps,
                               execute_at=self.execute_at)
        txn_result = self.txn.result(self.txn_id, self.execute_at, self.data)
        writes = self.txn.execute(self.txn_id, self.execute_at, self.data)
        if not self.blocking:
            self.result.set_success(sync_point)
            self.send_applies(writes, txn_result, Apply.MAXIMAL,
                              on_quorum_applied=lambda: (
                                  self.on_quorum_applied(sync_point),
                                  self.inform_durable()))
            return

        # blocking: the quorum must mean EXECUTED, not merely recorded — send
        # ApplyThenWaitUntilApplied, whose ack is deferred until the txn (and
        # hence its whole dependency set) has applied locally
        # (ExecuteSyncPoint.ExecuteBlocking, ExecuteSyncPoint.java)
        from ..messages.base import Callback, TxnRequest
        from ..messages.txn_messages import ApplyOk, ApplyThenWaitUntilApplied
        from .tracking import QuorumTracker, RequestStatus
        from .coordinate_transaction import _scope_ranges
        tracker = QuorumTracker(self.topologies)
        this = self
        state = {"done": False}

        def finish_ok():
            state["done"] = True
            if not this.result.is_done():
                this.result.set_success(sync_point)
            this.on_quorum_applied(sync_point)
            this.inform_durable()

        class AppliedCallback(Callback):
            def on_success(self, from_node: int, reply) -> None:
                if state["done"]:
                    return
                if not isinstance(reply, ApplyOk):
                    self.on_failure(from_node, RuntimeError(f"bad reply {reply!r}"))
                    return
                if tracker.record_success(from_node) is RequestStatus.SUCCESS:
                    finish_ok()

            def on_failure(self, from_node: int, failure: BaseException) -> None:
                if state["done"]:
                    return
                if tracker.record_failure(from_node) is RequestStatus.FAILED:
                    state["done"] = True
                    from .errors import Exhausted
                    if not this.result.is_done():
                        this.result.set_failure(Exhausted(this.txn_id, "apply quorum"))

        callback = AppliedCallback()
        for to in self.topologies.nodes():
            scope = TxnRequest.compute_scope(to, self.topologies, self.route)
            if scope is None:
                continue
            wait_for = TxnRequest.compute_wait_for_epoch(to, self.topologies)
            ranges = _scope_ranges(self.node, scope, self.topologies.current_epoch)
            self.node.send(to, ApplyThenWaitUntilApplied(
                self.txn_id, scope, wait_for, Apply.MAXIMAL, self.execute_at,
                self.deps.slice(ranges), self.txn.slice(ranges, include_query=False),
                writes.slice(ranges) if writes is not None else None,
                txn_result, route=self.route), callback)

    def on_quorum_applied(self, sync_point: SyncPoint) -> None:
        """Hook: exclusive sync points mark epochs closed / redundancy bounds
        here (wired by durability scheduling and bootstrap)."""
        if self.txn_id.kind is TxnKind.EXCLUSIVE_SYNC_POINT:
            participants = self.route.participants()
            if isinstance(participants, Ranges):
                self.node.on_exclusive_sync_point_applied(
                    self.txn_id, participants)
                # the applied fence witnessed every in-flight txn on these
                # ranges in the epochs below it: they are CLOSED to new
                # coordination (CoordinationAdapter exclusive sync point
                # epoch-closure, CoordinationAdapter.java:214-264)
                for e in range(self.node.topology.min_epoch, self.txn_id.epoch):
                    if self.node.topology.has_epoch(e):
                        self.node.on_epoch_closed(participants, e)
