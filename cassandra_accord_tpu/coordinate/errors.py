"""Coordination failure hierarchy (accord.coordinate.CoordinationFailed family)."""
from __future__ import annotations

from typing import Optional

from ..primitives.timestamp import TxnId


class CoordinationFailed(Exception):
    def __init__(self, txn_id: Optional[TxnId] = None, msg: str = ""):
        super().__init__(f"{type(self).__name__}({txn_id}) {msg}".strip())
        self.txn_id = txn_id


class Timeout(CoordinationFailed):
    pass


class Preempted(CoordinationFailed):
    """A higher ballot took over coordination."""


class Invalidated(CoordinationFailed):
    """The txn was invalidated; it did not and will not execute."""


class Truncated(CoordinationFailed):
    """The txn's outcome was truncated before we could retrieve it."""


class Exhausted(CoordinationFailed):
    """Too many replicas failed to achieve a quorum."""


class Overloaded(CoordinationFailed):
    """Shed by admission control: the node refused new work while over its
    load watermark.  A fast, explicit nack — the caller learns in one
    round-trip what a timeout would have taken seconds to say."""


class Insufficient(CoordinationFailed):
    """A replica lacked the state needed to process a request."""


class TopologyMismatch(CoordinationFailed):
    pass


class StaleTopology(CoordinationFailed):
    pass
