"""Cheap liveness probe escalating to full recovery.

Capability parity with ``accord.coordinate.MaybeRecover`` + the standalone
``Invalidate`` coordination (MaybeRecover.java, Invalidate.java:1-297): probe the
cluster's knowledge of a txn via CheckStatus; if the txn has progressed past the
caller's last-seen ProgressToken, just report the new token (someone is making
progress — stand down).  Otherwise escalate: reconstitute the txn from the merged
partials and run full recovery, or — when the definition is unrecoverable because
the txn was never witnessed at a quorum — invalidate it so nothing can block on it
forever.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, NamedTuple, Optional

from ..local.status import Durability, SaveStatus, Status
from ..messages.status_messages import CheckStatusOk, propagate_knowledge
from ..primitives.route import Route
from ..primitives.timestamp import Ballot, TxnId
from ..utils import async_ as au
from .errors import Invalidated, Truncated
from .fetch_data import check_status_quorum
from .recover import invalidate as do_invalidate, recover as do_recover

if TYPE_CHECKING:
    from ..local.node import Node


class ProgressToken(NamedTuple):
    """(durability, status, promised) progress lattice (ProgressToken.java):
    any component advancing means someone, somewhere, is driving the txn."""
    durability: Durability = Durability.NOT_DURABLE
    status_ordinal: int = 0
    promised: Ballot = Ballot.ZERO

    @staticmethod
    def of(merged: CheckStatusOk) -> "ProgressToken":
        return ProgressToken(merged.durability, merged.save_status.ordinal,
                             merged.promised)

    def advanced_from(self, prev: Optional["ProgressToken"]) -> bool:
        if prev is None:
            return True
        return (self.durability > prev.durability
                or self.status_ordinal > prev.status_ordinal
                or self.promised > prev.promised)

    def advanced_materially_from(self, prev: Optional["ProgressToken"]) -> bool:
        """Durability/status advance only.  A promised ballot rising with no
        status movement is the signature of FAILED recovery attempts (mutual
        preemption), not of progress — monitors treating it as progress reset
        their backoff and keep the attempt rate high forever (the hostile
        chaos+churn burns livelocked on exactly this: ballots ratcheted for
        hundreds of sim-seconds with every replica READY_TO_EXECUTE)."""
        if prev is None:
            return True
        return (self.durability > prev.durability
                or self.status_ordinal > prev.status_ordinal)

    @property
    def is_done(self) -> bool:
        return self.status_ordinal >= SaveStatus.APPLIED.ordinal


class Outcome(NamedTuple):
    """What MaybeRecover concluded: the latest token, plus whether the txn is
    settled (applied / invalidated / truncated)."""
    token: ProgressToken
    settled: bool


def maybe_recover(node: "Node", txn_id: TxnId, route: Route,
                  prev_token: Optional[ProgressToken]) -> au.AsyncResult:
    """Probe; escalate to Recover/Invalidate only if nothing progressed since
    ``prev_token``.  Resolves with an Outcome (never with the txn's result — the
    caller is a progress log, not a client)."""
    result = au.settable()

    def on_checked(merged: Optional[CheckStatusOk], failure):
        if failure is not None:
            result.set_failure(failure)
            return
        if merged is None:
            merged = CheckStatusOk.empty(txn_id)
        token = ProgressToken.of(merged)
        status = merged.save_status
        if status.is_terminal or status.is_truncated:
            if merged.route is not None:
                propagate_knowledge(node, txn_id, merged)
            result.set_success(Outcome(token, settled=True))
            return
        if token.advanced_from(prev_token):
            result.set_success(Outcome(token, settled=False))
            return

        # stalled: escalate (RecoverWithRoute)
        txn = merged.full_txn()
        if merged.route is not None and merged.route.full:
            full_route = merged.route
        elif txn is not None:
            full_route = node.compute_route(txn)   # real footprint, not the hint
        else:
            full_route = route
        rec = au.settable()
        if txn is not None:
            do_recover(node, txn_id, txn, full_route, rec)
        else:
            # definition unrecoverable: nothing durably witnessed it — invalidate
            do_invalidate(node, txn_id, full_route, rec)

        def on_recovered(_value, rec_failure):
            if rec_failure is None or isinstance(rec_failure, (Invalidated, Truncated)):
                # recovered, durably invalidated, or already truncated (decided
                # and cleaned up): the txn is settled either way
                result.set_success(Outcome(
                    ProgressToken(token.durability, SaveStatus.APPLIED.ordinal,
                                  token.promised), settled=True))
            else:
                # preempted / timed out: report the probe token; caller retries
                result.set_success(Outcome(token, settled=False))
        rec.add_listener(on_recovered)

    check_status_quorum(node, txn_id, route, include_info=True) \
        .to_chain().begin(on_checked)
    return result
