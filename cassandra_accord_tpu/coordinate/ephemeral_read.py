"""Ephemeral read coordination — 1 round trip, nothing durable.

Capability parity with ``accord.coordinate.CoordinateEphemeralRead``
(CoordinateEphemeralRead.java:57-150): a quorum per shard reports deps (writes the
read must be ordered after) and the latest epoch; then ``ExecuteEphemeralRead``
sends ReadEphemeralTxnData to one replica per shard (with slow-replica retry via
ReadTracker), which waits for the deps to apply locally and serves the read.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Dict, List

from ..messages.base import Callback, TxnRequest
from ..messages.ephemeral_messages import (GetEphemeralReadDeps,
                                           GetEphemeralReadDepsOk,
                                           ReadEphemeralTxnData)
from ..messages.txn_messages import ReadNack, ReadOk
from ..primitives.deps import Deps
from ..primitives.route import Route
from ..primitives.timestamp import TxnId
from ..primitives.txn import Txn
from ..utils import async_ as au
from .coordinate_transaction import _scope_ranges
from .errors import Exhausted, Insufficient
from .tracking import QuorumTracker, ReadTracker, RequestStatus

if TYPE_CHECKING:
    from ..local.node import Node


def coordinate_ephemeral_read(node: "Node", txn_id: TxnId, txn: Txn,
                              result: au.Settable) -> None:
    route = node.compute_route(txn)
    _CoordinateEphemeralRead(node, txn_id, txn, route, result).start()


class _CoordinateEphemeralRead:
    def __init__(self, node: "Node", txn_id: TxnId, txn: Txn, route: Route,
                 result: au.Settable):
        self.node = node
        self.txn_id = txn_id
        self.txn = txn
        self.route = route
        self.result = result
        self.topologies = node.topology.with_unsynced_epochs(route, txn_id.epoch,
                                                             txn_id.epoch)
        self.execute_at_epoch = txn_id.epoch
        self.all_oks: List[GetEphemeralReadDepsOk] = []

    # -- deps round ----------------------------------------------------------
    def start(self) -> None:
        """Contact a quorum over the topologies spanning [txnId.epoch,
        execute_at_epoch].  If a reply reveals a LATER epoch, re-contact over
        the extended topologies so new-epoch replicas contribute deps and the
        read executes against current topology (the reference's
        onPreAcceptedOrNewEpoch loop, AbstractCoordinatePreAccept.java)."""
        contacted_epoch = self.execute_at_epoch
        tracker = QuorumTracker(self.topologies)
        oks = self.all_oks
        this = self

        class DepsCallback(Callback):
            done = False

            def on_success(self, from_node: int, reply) -> None:
                if self.done or not isinstance(reply, GetEphemeralReadDepsOk):
                    return
                oks.append(reply)
                if reply.latest_epoch > this.execute_at_epoch:
                    this.execute_at_epoch = reply.latest_epoch
                if tracker.record_success(from_node) is RequestStatus.SUCCESS:
                    self.done = True
                    if this.execute_at_epoch > contacted_epoch:
                        this._restart_for_epoch()
                    else:
                        this.execute(Deps.merge([ok.deps for ok in oks]))

            def on_failure(self, from_node: int, failure: BaseException) -> None:
                if self.done:
                    return
                if tracker.record_failure(from_node) is RequestStatus.FAILED:
                    self.done = True
                    this.result.set_failure(Exhausted(this.txn_id, "ephemeral deps"))

        callback = DepsCallback()
        self.node.send_to_each(tracker.nodes(), self._deps_request_for, callback)

    def _restart_for_epoch(self) -> None:
        def go(_v, f):
            if f is not None:
                self.result.set_failure(f)
                return
            self.topologies = self.node.topology.with_unsynced_epochs(
                self.route, self.txn_id.epoch, self.execute_at_epoch)
            self.start()

        self.node.with_epoch(self.execute_at_epoch).begin(go)

    def _deps_request_for(self, to: int):
        scope = TxnRequest.compute_scope(to, self.topologies, self.route)
        if scope is None:
            return None
        wait_for = TxnRequest.compute_wait_for_epoch(to, self.topologies)
        ranges = _scope_ranges(self.node, scope, self.topologies.current_epoch)
        from ..primitives.keys import Ranges as _Ranges
        keys = self.txn.keys.intersection(ranges) \
            if isinstance(self.txn.keys, _Ranges) else self.txn.keys.slice(ranges)
        return GetEphemeralReadDeps(self.txn_id, scope, wait_for, keys)

    # -- execute round -------------------------------------------------------
    def execute(self, deps: Deps) -> None:
        read_tracker = ReadTracker(self.topologies)
        this = self
        data_holder = {"data": None, "done": False}

        class ReadCallback(Callback):
            def on_success(self, from_node: int, reply) -> None:
                if data_holder["done"]:
                    return
                if isinstance(reply, ReadOk):
                    if reply.data is not None:
                        data_holder["data"] = reply.data if data_holder["data"] is None \
                            else data_holder["data"].merge(reply.data)
                    if read_tracker.record_read_success(from_node) \
                            is RequestStatus.SUCCESS:
                        data_holder["done"] = True
                        this.finish(data_holder["data"])
                elif isinstance(reply, ReadNack):
                    # transient single-replica conditions (bootstrapping /
                    # stale topology): retry the shard's other replicas
                    self._retry(from_node)

            def on_failure(self, from_node: int, failure: BaseException) -> None:
                if data_holder["done"]:
                    return
                self._retry(from_node)

            def _retry(self, from_node: int) -> None:
                status, retries = read_tracker.record_read_failure(
                    from_node, avoid=this.node.slow_peers())
                if status is RequestStatus.FAILED:
                    data_holder["done"] = True
                    this.result.set_failure(Exhausted(this.txn_id, "ephemeral read"))
                    return
                for to in retries:
                    req = this._read_request_for(to, deps)
                    if req is not None:
                        this.node.send(to, req, self.callback_ref)

        callback = ReadCallback()
        callback.callback_ref = callback
        for to in read_tracker.initial_contacts(prefer=self.node.id,
                                                avoid=self.node.slow_peers()):
            req = self._read_request_for(to, deps)
            if req is not None:
                self.node.send(to, req, callback)

    def _read_request_for(self, to: int, deps: Deps):
        scope = TxnRequest.compute_scope(to, self.topologies, self.route)
        if scope is None:
            return None
        wait_for = TxnRequest.compute_wait_for_epoch(to, self.topologies)
        ranges = _scope_ranges(self.node, scope, self.topologies.current_epoch)
        partial = self.txn.slice(ranges, to == self.node.id)
        return ReadEphemeralTxnData(self.txn_id, scope, wait_for, partial,
                                    deps.slice(ranges), self.execute_at_epoch)

    def finish(self, data) -> None:
        self.result.set_success(
            self.txn.result(self.txn_id, self.txn_id.as_timestamp(), data))
