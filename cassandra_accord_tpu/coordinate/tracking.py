"""Per-shard quorum trackers driving coordinator state machines.

Capability parity with ``accord.coordinate.tracking`` (AbstractTracker.java,
QuorumTracker.java, FastPathTracker.java:33-160, ReadTracker.java,
RecoveryTracker.java): a tracker owns one ShardTracker per (epoch, shard) across the
contacted Topologies and aggregates per-shard outcomes into an overall RequestStatus.
"""
from __future__ import annotations

import enum
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..topology.topology import Shard, Topologies
from ..utils.invariants import check_state


class RequestStatus(enum.Enum):
    NO_CHANGE = 0
    SUCCESS = 1
    FAILED = 2


class ShardTracker:
    __slots__ = ("shard", "successes", "failures")

    def __init__(self, shard: Shard):
        self.shard = shard
        self.successes: Set[int] = set()
        self.failures: Set[int] = set()

    def has_reached_quorum(self) -> bool:
        return len(self.successes) >= self.shard.slow_path_quorum_size

    def has_failed(self) -> bool:
        return len(self.failures) > self.shard.max_failures

    def has_in_flight(self) -> bool:
        return len(self.successes) + len(self.failures) < self.shard.rf()


class AbstractTracker:
    """Tracks one ShardTracker per unique (epoch, shard)."""

    def __init__(self, topologies: Topologies, tracker_cls=ShardTracker):
        self.topologies = topologies
        self.trackers: List = []
        self._by_node: Dict[int, List] = {}
        for topology in topologies:
            for shard in topology.shards:
                t = tracker_cls(shard)
                self.trackers.append(t)
                for n in shard.nodes:
                    self._by_node.setdefault(n, []).append(t)
        self.waiting_on_shards = len(self.trackers)

    def nodes(self) -> List[int]:
        return sorted(self._by_node.keys())

    def trackers_for(self, node: int) -> List:
        return self._by_node.get(node, [])

    def _all_success(self, predicate) -> bool:
        return all(predicate(t) for t in self.trackers)


class QuorumTracker(AbstractTracker):
    """Simple-majority per shard (QuorumTracker.java)."""

    def record_success(self, node: int) -> RequestStatus:
        newly = False
        for t in self.trackers_for(node):
            if node in t.successes or node in t.failures:
                continue
            pre = t.has_reached_quorum()
            t.successes.add(node)
            if not pre and t.has_reached_quorum():
                newly = True
        if newly and self._all_success(ShardTracker.has_reached_quorum):
            return RequestStatus.SUCCESS
        return RequestStatus.NO_CHANGE

    def record_failure(self, node: int) -> RequestStatus:
        for t in self.trackers_for(node):
            if node in t.successes or node in t.failures:
                continue
            t.failures.add(node)
            if t.has_failed():
                return RequestStatus.FAILED
        return RequestStatus.NO_CHANGE

    def has_reached_quorum(self) -> bool:
        return self._all_success(ShardTracker.has_reached_quorum)


class FastPathShardTracker(ShardTracker):
    __slots__ = ("fast_path_accepts", "fast_path_rejects")

    def __init__(self, shard: Shard):
        super().__init__(shard)
        self.fast_path_accepts: Set[int] = set()
        self.fast_path_rejects: Set[int] = set()

    def has_met_fast_path_criteria(self) -> bool:
        return len(self.fast_path_accepts) >= self.shard.fast_path_quorum_size

    def has_rejected_fast_path(self) -> bool:
        return self.shard.rejects_fast_path(len(self.fast_path_rejects))


class FastPathTracker(AbstractTracker):
    """PreAccept tracker (FastPathTracker.java:33-160): counts fast-path votes
    (witnessedAt == txnId) within each shard's electorate alongside the slow-path
    quorum.  SUCCESS fires once all shards have a slow quorum AND the fast-path
    outcome is decided (achieved everywhere or rejected somewhere)."""

    def __init__(self, topologies: Topologies):
        super().__init__(topologies, FastPathShardTracker)

    def record_success(self, node: int, with_fast_path_vote: bool) -> RequestStatus:
        for t in self.trackers_for(node):
            if node in t.successes or node in t.failures:
                continue
            t.successes.add(node)
            if node in t.shard.fast_path_electorate:
                if with_fast_path_vote:
                    t.fast_path_accepts.add(node)
                else:
                    t.fast_path_rejects.add(node)
        return self._status()

    def record_failure(self, node: int) -> RequestStatus:
        for t in self.trackers_for(node):
            if node in t.successes or node in t.failures:
                continue
            t.failures.add(node)
            # an unreachable electorate member can no longer vote for the fast path
            if node in t.shard.fast_path_electorate:
                t.fast_path_rejects.add(node)
            if t.has_failed():
                return RequestStatus.FAILED
        return self._status()

    def _status(self) -> RequestStatus:
        if not self._all_success(ShardTracker.has_reached_quorum):
            return RequestStatus.NO_CHANGE
        # quorum reached everywhere: success once fast-path is decided
        if self.has_fast_path_accepted():
            return RequestStatus.SUCCESS
        for t in self.trackers:
            if not t.has_rejected_fast_path() and not t.has_met_fast_path_criteria() \
                    and t.has_in_flight():
                return RequestStatus.NO_CHANGE  # fast path still undecided; keep waiting
        return RequestStatus.SUCCESS

    def has_fast_path_accepted(self) -> bool:
        return self._all_success(FastPathShardTracker.has_met_fast_path_criteria)

    def fast_path_votes(self) -> Tuple[int, int]:
        """(accepts, rejects) electorate vote totals across every shard — the
        observability accessor behind the flight recorder's
        ``txn.fastpath.votes_*`` counters (why a txn went slow-path is the
        first question a latency investigation asks)."""
        accepts = sum(len(t.fast_path_accepts) for t in self.trackers)
        rejects = sum(len(t.fast_path_rejects) for t in self.trackers)
        return accepts, rejects


class ReadShardTracker(ShardTracker):
    __slots__ = ("data_received", "in_flight_reads")

    def __init__(self, shard: Shard):
        super().__init__(shard)
        self.data_received = False
        self.in_flight_reads: Set[int] = set()


class ReadTracker(AbstractTracker):
    """One successful data read per shard, with retry on failure
    (ReadTracker.java slow-replica speculation simplified to failure-retry)."""

    def __init__(self, topologies: Topologies):
        super().__init__(topologies, ReadShardTracker)
        self._contacted: Set[int] = set()

    def initial_contacts(self, prefer: Optional[int] = None,
                         rotate: int = 0,
                         avoid: frozenset = frozenset()) -> List[int]:
        """Pick one replica per shard (preferring ``prefer`` — normally self).

        ``rotate`` shifts EVERY shard's pick index by that many positions, so
        retry rounds contact a different replica per shard — a global
        preferred node only rotates shards that contain it.

        ``avoid`` holds replicas the coordinator's gray-failure tracker
        currently marks slow (paused-but-alive, stalled-disk, saturated):
        the pick shifts past them when ANY non-slow alternative exists, so a
        known-slow replica never costs a whole timeout/speculation round.
        When every replica of a shard is marked slow, the base pick stands —
        avoidance must never starve a shard of its read.

        Shards already marked ``data_received`` are skipped: a retry round
        with grandfathered partial-read coverage (coordinate_transaction)
        pre-marks fully-covered shards, and re-reading them would burn
        replies — or spurious exhaustion — on data already banked."""
        out: Set[int] = set()
        for t in self.trackers:
            if t.data_received:
                continue
            nodes = t.shard.nodes
            base = nodes.index(prefer) if prefer in nodes else 0
            pick = nodes[(base + rotate) % len(nodes)]
            if avoid and pick in avoid:
                for off in range(1, len(nodes)):
                    alt = nodes[(base + rotate + off) % len(nodes)]
                    if alt not in avoid:
                        pick = alt
                        break
            t.in_flight_reads.add(pick)
            out.add(pick)
        self._contacted.update(out)
        return sorted(out)

    def speculate(self, avoid: frozenset = frozenset()) -> List[int]:
        """Slow-replica speculation (ReadTracker.java's slow/insufficient
        ladder): for each shard still awaiting data, contact ONE additional
        untried replica WITHOUT failing the in-flight one — a slow replica
        costs only the duplicate read, not a whole reply-timeout round.
        Known-slow candidates (``avoid``) are picked last."""
        extra: Set[int] = set()
        for t in self.trackers:
            if t.data_received:
                continue
            candidates = [n for n in t.shard.nodes
                          if n not in t.failures
                          and n not in t.in_flight_reads]
            if candidates:
                pick = next((n for n in candidates if n not in avoid),
                            candidates[0])
                t.in_flight_reads.add(pick)
                extra.add(pick)
        self._contacted.update(extra)
        return sorted(extra)

    def record_read_success(self, node: int) -> RequestStatus:
        for t in self.trackers_for(node):
            if node in t.in_flight_reads:
                t.in_flight_reads.discard(node)
                t.data_received = True
        if self._all_success(lambda t: t.data_received):
            return RequestStatus.SUCCESS
        return RequestStatus.NO_CHANGE

    def record_read_failure(self, node: int,
                            avoid: frozenset = frozenset()) \
            -> Tuple[RequestStatus, List[int]]:
        """Returns (status, additional nodes to contact).  Replacement picks
        prefer replicas NOT currently marked slow (``avoid``)."""
        retries: Set[int] = set()
        for t in self.trackers_for(node):
            t.in_flight_reads.discard(node)
            t.failures.add(node)
            if t.data_received or t.in_flight_reads:
                continue
            candidates = [n for n in t.shard.nodes
                          if n not in t.failures and n not in t.in_flight_reads]
            if not candidates:
                return RequestStatus.FAILED, []
            pick = next((n for n in candidates if n not in avoid),
                        candidates[0])
            t.in_flight_reads.add(pick)
            retries.add(pick)
        self._contacted.update(retries)
        return RequestStatus.NO_CHANGE, sorted(retries)


class RecoveryShardTracker(ShardTracker):
    __slots__ = ("fast_path_rejects",)

    def __init__(self, shard: Shard):
        super().__init__(shard)
        self.fast_path_rejects = 0

    def rejects_fast_path(self) -> bool:
        """True when so many electorate members witnessed the txn at a timestamp
        other than its txnId that the original coordinator cannot have gathered a
        fast-path quorum (RecoveryTracker.java:44-47)."""
        return self.shard.rejects_fast_path(self.fast_path_rejects)


class RecoveryTracker(AbstractTracker):
    """BeginRecovery tracker (RecoveryTracker.java): a slow-path quorum per shard,
    additionally accounting fast-path vote evidence for the recovery decision."""

    def __init__(self, topologies: Topologies):
        super().__init__(topologies, RecoveryShardTracker)

    def record_success(self, node: int, accepts_fast_path: bool) -> RequestStatus:
        newly = False
        for t in self.trackers_for(node):
            if node in t.successes or node in t.failures:
                continue
            pre = t.has_reached_quorum()
            t.successes.add(node)
            if not accepts_fast_path and node in t.shard.fast_path_electorate:
                t.fast_path_rejects += 1
            if not pre and t.has_reached_quorum():
                newly = True
        if newly and self._all_success(ShardTracker.has_reached_quorum):
            return RequestStatus.SUCCESS
        return RequestStatus.NO_CHANGE

    def record_failure(self, node: int) -> RequestStatus:
        for t in self.trackers_for(node):
            if node in t.successes or node in t.failures:
                continue
            t.failures.add(node)
            if t.has_failed():
                return RequestStatus.FAILED
        return RequestStatus.NO_CHANGE

    def rejects_fast_path(self) -> bool:
        return any(t.rejects_fast_path() for t in self.trackers)


class AppliedTracker(QuorumTracker):
    """Tracks Apply acks reaching a quorum (AppliedTracker)."""
