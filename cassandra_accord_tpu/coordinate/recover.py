"""Recovery coordination.

Capability parity with ``accord.coordinate`` Recover / Invalidate
(Recover.java:80-471, Invalidate.java:1-297): a recovering coordinator promises a
ballot at a slow-path quorum of every shard via ``BeginRecovery`` (which also
pre-accepts the txn wherever it was unwitnessed), then resumes the standard pipeline
at the phase matching the strongest evidence found:

  outcome known (PreApplied+)      -> persist (Apply.Maximal) and report the result
  Stable                           -> execute (Stable+Read) at the known executeAt
  (Pre)Committed                   -> stabilise then execute at the known executeAt
  Accepted                         -> re-propose (Accept round at our ballot) the
                                      max-ballot proposal's executeAt/deps
  AcceptedInvalidate               -> propose invalidation, then commit-invalidate
  all PreAccepted or unwitnessed   -> fast-path analysis (Recover.java:354-380):
      * any shard where too many electorate members witnessed a timestamp other
        than txnId (tracker), or any replica that witnessed a conflicting txn
        ordered after ours without our txnId in its deps => the original
        coordinator CANNOT have fast-committed: safe to invalidate;
      * otherwise the fast path may have succeeded, so it must be completed: wait
        (WaitOnCommit) for any earlier-started txn that proposed an executeAt after
        ours without witnessing us to commit, retry recovery; when none remain,
        re-propose at executeAt = txnId with the merged pre-accept deps.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Dict, List, Optional

from ..messages.base import Callback, TxnRequest
from ..messages.recovery_messages import (
    AcceptInvalidate, BeginRecovery, CommitInvalidate, InvalidateNack, InvalidateOk,
    RecoverNack, RecoverOk, WaitOnCommit, WaitOnCommitOk, max_accepted_reply,
)
from ..local.status import Phase, Status
from ..primitives.deps import Deps
from ..primitives.keys import Ranges
from ..primitives.latest_deps import LatestDeps
from ..primitives.route import Route
from ..primitives.timestamp import Ballot, Timestamp, TxnId
from ..primitives.txn import Txn
from ..utils import async_ as au
from .coordinate_transaction import persist_maximal, resume_propose, resume_stabilise
from .errors import Exhausted, Invalidated, Preempted, Timeout, Truncated
from .tracking import QuorumTracker, RecoveryTracker, RequestStatus

if TYPE_CHECKING:
    from ..local.node import Node


def recover(node: "Node", txn_id: TxnId, txn: Txn, route: Route,
            result: au.Settable, ballot: Optional[Ballot] = None) -> None:
    """Entry point (Recover.recover): pick a ballot above anything we've issued and
    drive recovery of ``txn_id`` to a terminal outcome.  ``result`` resolves with
    the txn's Result on success, or Invalidated/Preempted/Exhausted."""
    if ballot is None:
        ballot = node.ballot_after(None)
    observer = getattr(node, "observer", None)
    if observer is not None:
        # recovery attribution: the txn's span records who tried to recover
        # it and how often (the flight recorder's recovery.* counters); the
        # sim timestamp feeds the trace export's recovery counter track and
        # closes the auditor's unattended-SLO flag
        observer.on_recovery(node.id, txn_id, ballot, node.now_micros())
    _Recover(node, ballot, txn_id, txn, route, result).start()


class _Recover:
    def __init__(self, node: "Node", ballot: Ballot, txn_id: TxnId, txn: Txn,
                 route: Route, result: au.Settable):
        self.node = node
        self.ballot = ballot
        self.txn_id = txn_id
        self.txn = txn
        self.route = route
        self.result = result
        self.topologies = node.topology.precise_epochs(route, txn_id.epoch, txn_id.epoch)
        self.tracker = RecoveryTracker(self.topologies)
        self.oks: Dict[int, RecoverOk] = {}
        self.done = False

    # -- BeginRecovery round -------------------------------------------------
    def start(self) -> None:
        this = self

        class RecoverCallback(Callback):
            def on_success(self, from_node: int, reply) -> None:
                if this.done:
                    return
                if isinstance(reply, RecoverNack):
                    if reply.superseded_by is None:
                        # the txn was truncated: it is durably decided everywhere
                        # that matters; report the terminal outcome, don't retry
                        this.fail(Truncated(this.txn_id, "truncated before recovery"))
                    else:
                        this.fail(Preempted(this.txn_id,
                                            f"recovery superseded by {reply.superseded_by}"))
                    return
                this.oks[from_node] = reply
                fast_path_vote = reply.execute_at is not None \
                    and reply.execute_at == this.txn_id.as_timestamp()
                if this.tracker.record_success(from_node, fast_path_vote) is RequestStatus.SUCCESS:
                    this.analyse()

            def on_failure(self, from_node: int, failure: BaseException) -> None:
                if this.done:
                    return
                if this.tracker.record_failure(from_node) is RequestStatus.FAILED:
                    this.fail(Exhausted(this.txn_id, "recovery quorum unreachable"))

        callback = RecoverCallback()
        self.node.send_to_each(
            self.tracker.nodes(),
            lambda to: self._begin_recovery_for(to),
            callback)

    def _begin_recovery_for(self, to: int) -> Optional[BeginRecovery]:
        scope = TxnRequest.compute_scope(to, self.topologies, self.route)
        if scope is None:
            return None
        wait_for = TxnRequest.compute_wait_for_epoch(to, self.topologies)
        ranges = scope.covering
        partial = self.txn.slice(ranges, to == self.node.id) if ranges is not None \
            else self.txn.slice(self.node.topology.topology_for_epoch(self.txn_id.epoch).ranges(),
                                to == self.node.id)
        return BeginRecovery(self.txn_id, scope, wait_for, partial, self.ballot,
                             route=self.route)

    # -- quorum analysis (Recover.recover, Recover.java:245-380) --------------
    def analyse(self) -> None:
        oks = list(self.oks.values())
        best = max_accepted_reply(oks)
        latest = LatestDeps.merge_all([ok.deps for ok in oks])

        if best is not None:
            status, execute_at = best.status, best.execute_at
            if status is Status.INVALIDATED or status is Status.TRUNCATED:
                self.commit_invalidate()
                return
            if status.has_been(Status.PRE_APPLIED):
                self.persist_known_outcome(execute_at, latest)
                return
            if status.has_been(Status.STABLE) or status.has_been(Status.PRE_COMMITTED):
                # executeAt decided: (re-)stabilise at it with the PHASE-AWARE
                # deps merge (LatestDeps.mergeCommit): committed-grade ranges
                # use the decided deps; fast-path ranges may substitute local
                # calculations; anything else is fetched via GetDeps.  Claim
                # ``done`` NOW: a straggler nack arriving during the async
                # GetDeps round must not settle the result out from under the
                # stabilisation this branch has committed to
                self.done = True

                def stabilise_with(deps: Deps) -> None:
                    resume_stabilise(self.node, self.txn_id, self.txn, self.route,
                                     self.result, self.ballot, execute_at, deps)
                    self._on_settled()
                self.with_committed_deps(execute_at, latest, stabilise_with)
                return
            if status is Status.ACCEPTED:
                self.done = True
                resume_propose(self.node, self.txn_id, self.txn, self.route,
                               self.result, self.ballot, execute_at,
                               latest.merge_proposal())
                self._on_settled()
                return
            if status is Status.ACCEPTED_INVALIDATE:
                self.propose_invalidate()
                return

        # all replies PreAccepted (BeginRecovery pre-accepts unwitnessed replicas)
        if self.tracker.rejects_fast_path() or any(ok.rejects_fast_path for ok in oks):
            # the fast path provably did not commit; nothing else was proposed
            self.propose_invalidate()
            return

        ecw = Deps.merge([ok.earlier_committed_witness for ok in oks])
        eanw = Deps.merge([ok.earlier_accepted_no_witness for ok in oks]).without(ecw.contains)
        if not eanw.is_empty():
            # earlier txns proposed to execute after us without witnessing us: if
            # one commits that way, our fast path provably failed; wait for them
            # to settle then re-examine from scratch (Recover.java:361-375)
            self.await_commits(eanw)
            return

        lnw = Deps.merge([ok.later_unknown_witness for ok in oks])
        if not lnw.is_empty():
            # LATER-started in-flight conflicts whose deps are undecided:
            # completing the fast path at txnId is only sound once every
            # later-started conflicting COMMIT provably witnessed us — wait
            # for them to settle, then re-examine (their decided deps either
            # include us, or become rule-1 fast-path-rejection evidence and
            # we invalidate).  The superseding race (KNOWN_ISSUES seed 112):
            # without this wait, recovery completed a fast path that a
            # later fast-committed conflict had already ordered around.
            self.await_commits(lnw)
            return

        # the fast path may have committed: complete it at executeAt = txnId
        self.done = True
        resume_propose(self.node, self.txn_id, self.txn, self.route, self.result,
                       self.ballot, self.txn_id.as_timestamp(),
                       latest.merge_proposal())
        self._on_settled()

    def with_committed_deps(self, execute_at: Timestamp, latest: LatestDeps,
                            use_deps) -> None:
        """Phase-aware commit deps (Recover.withCommittedDeps,
        Recover.java:384-400): merge the quorum's evidence per range; any part
        of the footprint the merge is insufficient for is collected fresh via
        a GetDeps round at executeAt.  Callers have already claimed ``done``,
        so failures settle the result DIRECTLY (the progress log retries) —
        routing them through fail() would drop them on the floor."""
        deps, sufficient = latest.merge_commit(self.txn_id, execute_at)
        missing = [key for key in self.txn.keys
                   if not sufficient.contains(
                       key.to_routing() if hasattr(key, "to_routing") else key)] \
            if not isinstance(self.txn.keys, Ranges) \
            else self.txn.keys.without(sufficient)
        if (isinstance(missing, Ranges) and missing.is_empty()) or not missing:
            use_deps(deps)
            return
        this = self
        from .collect_deps import collect_deps

        def on_collected(extra, failure):
            if failure is not None:
                this.result.set_failure(failure)
                return
            use_deps(deps.with_merged(extra))

        collect_deps(self.node, self.txn_id, self.route, missing,
                     execute_at).add_listener(on_collected)

    def persist_known_outcome(self, execute_at: Timestamp,
                              latest: LatestDeps) -> None:
        """Some replica applied the txn: assemble the COMPLETE outcome before
        re-disseminating it.  A single RecoverOk's writes are that replica's
        per-shard SLICE — persisting a slice as if it were the whole write-set
        silently drops the other shards' writes at every replica that adopts it
        (the divergence class the hostile burn caught).  Fetch the outcome over
        the full route (slice-union + applied_for coverage check,
        CheckStatusOk.merge); if the union does not yet cover the footprint,
        fall back to re-stabilise/execute at the known executeAt with
        phase-aware merged deps."""
        this = self
        self.done = True
        from .fetch_data import fetch_data

        def on_fetched(merged, failure):
            if failure is not None:
                this.result.set_failure(failure)   # progress log retries
                return
            parts = this.route.participants()
            if merged is not None and merged.writes is not None \
                    and merged.execute_at is not None \
                    and merged.applied_for.contains_all(parts) \
                    and merged.partial_deps is not None \
                    and merged.stable_for.contains_all(parts):
                persist_maximal(this.node, this.txn_id, this.txn, this.route,
                                this.topologies, merged.execute_at,
                                merged.partial_deps, merged.writes, merged.result)
                this.node.agent.metrics_events_listener().on_recover(
                    this.txn_id, this.ballot)
                this.result.set_success(merged.result)
            else:
                def stabilise_with(deps: Deps) -> None:
                    resume_stabilise(this.node, this.txn_id, this.txn, this.route,
                                     this.result, this.ballot, execute_at, deps)
                    this._on_settled()
                this.with_committed_deps(execute_at, latest, stabilise_with)

        fetch_data(self.node, self.txn_id, self.route).add_listener(on_fetched)

    # -- await earlier uncommitted no-witness txns ----------------------------
    def await_commits(self, waiting_on: Deps) -> None:
        txn_ids = waiting_on.txn_ids()
        remaining = {"n": len(txn_ids)}
        this = self

        def one_done(_v, failure):
            if this.done:
                return
            if failure is not None:
                this.fail(failure)
                return
            remaining["n"] -= 1
            if remaining["n"] == 0:
                this.retry()

        for dep_id in txn_ids:
            _AwaitCommit(self.node, dep_id, waiting_on.participants(dep_id)) \
                .result.add_listener(one_done)

    def retry(self) -> None:
        self.done = True
        _Recover(self.node, self.node.ballot_after(self.ballot), self.txn_id,
                 self.txn, self.route, self.result).start()

    # -- invalidation ---------------------------------------------------------
    def propose_invalidate(self) -> None:
        """Propose invalidation at our ballot to a quorum of the home shard
        (Propose.Invalidate.proposeInvalidate)."""
        topology = self.node.topology.topology_for_epoch(self.txn_id.epoch)
        shard = topology.for_key_required(self.route.home_key)
        tracker = QuorumTracker(self.node.topology.precise_epochs(
            self.route.home_key_only(), self.txn_id.epoch, self.txn_id.epoch))
        this = self

        class InvalidateCallback(Callback):
            def on_success(self, from_node: int, reply) -> None:
                if this.done:
                    return
                if isinstance(reply, InvalidateNack):
                    if reply.truncated:
                        # settled below the durable fence: adopt and report
                        adopt_erased(this.node, this.txn_id, this.route)
                        this.fail(Truncated(this.txn_id,
                                            "below the durable fence"))
                    elif reply.committed:
                        # txn (pre)committed concurrently: restart recovery to
                        # pick up the commit evidence
                        this.retry()
                    else:
                        this.fail(Preempted(this.txn_id,
                                            f"invalidate superseded by {reply.superseded_by}"))
                    return
                if reply.status.has_been(Status.PRE_COMMITTED):
                    this.retry()
                    return
                if reply.status.has_been(Status.ACCEPTED):
                    # a real Accept vote at some ballot: the txn may have been
                    # committed by that proposer — re-run recovery to adopt it
                    # (the Paxos value-adoption rule; invalidating would race a
                    # completed commit)
                    this.retry()
                    return
                if tracker.record_success(from_node) is RequestStatus.SUCCESS:
                    this.commit_invalidate()

            def on_failure(self, from_node: int, failure: BaseException) -> None:
                if this.done:
                    return
                if tracker.record_failure(from_node) is RequestStatus.FAILED:
                    this.fail(Exhausted(this.txn_id, "invalidate quorum unreachable"))

        scope = self.route.home_key_only()
        for to in shard.nodes:
            self.node.send(to, AcceptInvalidate(self.txn_id, scope, self.txn_id.epoch,
                                                self.ballot), InvalidateCallback())

    def commit_invalidate(self) -> None:
        """Broadcast CommitInvalidate across the route and report Invalidated
        (Propose.Invalidate.proposeAndCommitInvalidate tail)."""
        for to in self.topologies.nodes():
            scope = TxnRequest.compute_scope(to, self.topologies, self.route)
            if scope is None:
                continue
            wait_for = TxnRequest.compute_wait_for_epoch(to, self.topologies)
            self.node.send(to, CommitInvalidate(self.txn_id, scope, wait_for))
        self.fail(Invalidated(self.txn_id, "invalidated during recovery"))

    # -- terminal -------------------------------------------------------------
    def succeed(self, txn_result) -> None:
        if not self.done:
            self.done = True
            self.node.agent.metrics_events_listener().on_recover(self.txn_id, self.ballot)
            self.result.set_success(txn_result)

    def fail(self, failure: BaseException) -> None:
        if not self.done:
            self.done = True
            self.result.set_failure(failure)

    def _on_settled(self) -> None:
        """Metrics hook once a resumed pipeline settles the result."""
        node, txn_id, ballot = self.node, self.txn_id, self.ballot

        def notify(_v, failure):
            if failure is None:
                node.agent.metrics_events_listener().on_recover(txn_id, ballot)
        self.result.add_listener(notify)


def adopt_erased(node: "Node", txn_id: TxnId, route: Route) -> None:
    """A home-shard quorum member asserted ``txn_id`` sits below its durable
    fence: the txn is settled — but 'settled' means EITHER it can never commit
    OR it applied at a quorum and was then erased.  A locally-undecided copy
    cannot tell the two apart, and erasing it in the second case would drop
    the dep as truncated and let waiters execute without the committed write's
    data (the truncate() data-gap guard is gated on PRE_COMMITTED, so it never
    fires here).  So: fetch_data FIRST — if a peer still carries the outcome,
    Propagate applies it and the normal path (with its own gap-heal) takes
    over.  Any copy STILL undecided after the fetch is erased with a
    conservative stale-mark + peer-snapshot heal of its local write footprint,
    because the outcome remains unknowable.  Decided local copies are left
    alone — they resolve through the normal apply path."""
    from ..local import commands as C
    from ..local.durability import Cleanup
    from ..local.status import Status

    def adopt(_merged=None, _failure=None) -> None:
        # runs whether the fetch succeeded or not: on failure (quorum
        # unreachable) waiters must still unblock, and the conservative heal
        # below keeps reads redirected until the data plane is whole again
        def for_store(safe_store) -> None:
            from ..local.status import SaveStatus as _SS
            probe = safe_store.get_if_exists(txn_id)
            if (probe is None or not probe.listeners) \
                    and C._is_shard_redundant(safe_store, txn_id, route):
                # GC physically erased this txn below the shard fence: do
                # not resurrect a fresh stub just to mark it ERASED (ballot
                # regression; the fend-off shared with accept/propagate) —
                # unless a local waiter still lists it (listeners), in
                # which case the truncation below is exactly what unblocks
                # the waiter
                return
            cmd = safe_store.get_if_exists(txn_id)
            if cmd is None or cmd.save_status.is_truncated \
                    or cmd.save_status is _SS.INVALIDATED \
                    or cmd.has_been(Status.PRE_COMMITTED):
                # an INVALIDATED tombstone already unblocks waiters and must
                # persist AS INVALIDATED until the shard fence (never
                # downgrade to ERASED: the round-4 resurrection class)
                return
            if txn_id.is_write:
                cmd_route = cmd.route if cmd.route is not None else route
                local_parts = cmd_route.participants().slice(
                    safe_store.current_ranges())
                if len(local_parts):
                    from ..messages.status_messages import _heal_store_gaps
                    _heal_store_gaps(node, safe_store, local_parts)
            C.truncate(safe_store, cmd, Cleanup.ERASE)

        node.for_each_local(route, txn_id.epoch, txn_id.epoch, for_store)

    from .fetch_data import fetch_data
    fetch_data(node, txn_id, route).add_listener(adopt)


def invalidate(node: "Node", txn_id: TxnId, route: Route, result: au.Settable,
               ballot: Optional[Ballot] = None) -> None:
    """Standalone invalidation (Invalidate.java): used when a txn blocks others but
    its definition cannot be recovered (never witnessed at a quorum).  Promises a
    ballot at a quorum of the home-key shard, then commit-invalidates everywhere.
    Resolves ``result`` with Invalidated on success (the txn is settled: it will
    never execute), Preempted if a competing coordinator holds a higher ballot or
    the txn turns out to be committed."""
    if ballot is None:
        ballot = node.ballot_after(None)
    observer = getattr(node, "observer", None)
    if observer is not None:
        # invalidation attribution for the txn's flight-recorder span
        observer.on_invalidate(node.id, txn_id, node.now_micros())
    topologies = node.topology.precise_epochs(route, txn_id.epoch, txn_id.epoch)
    topology = node.topology.topology_for_epoch(txn_id.epoch)
    shard = topology.for_key_required(route.home_key)
    tracker = QuorumTracker(node.topology.precise_epochs(
        route.home_key_only(), txn_id.epoch, txn_id.epoch))
    state = {"done": False, "learned_route": None, "has_definition": False,
             "has_accept": False}

    def finish(failure: BaseException) -> None:
        if not state["done"]:
            state["done"] = True
            result.set_failure(failure)

    def commit_invalidate() -> None:
        for to in topologies.nodes():
            scope = TxnRequest.compute_scope(to, topologies, route)
            if scope is None:
                continue
            node.send(to, CommitInvalidate(
                txn_id, scope, TxnRequest.compute_wait_for_epoch(to, topologies)))
        finish(Invalidated(txn_id, "invalidated (definition unrecoverable)"))

    def escalate(learned_route: Route) -> None:
        """SAFETY (Invalidate.java): our home-shard quorum intersects any fast-path
        quorum, so if a contacted replica knows the definition the txn may have
        fast-committed — recover it instead of invalidating.  Fetch the definition
        cluster-wide, reconstitute, and run full recovery.

        LIVENESS (Invalidate.java:125-135, 170-195): when even a quorum read of
        EVERY shard of the full route cannot reassemble the definition and no
        shard shows Accepted+, the fast path provably never committed (a fast
        quorum per shard must hold that shard's definition slice, and every
        majority read intersects every fast quorum), and our home-shard promises
        block any future fast-path decision — so invalidation is safe.  Without
        this rule, a PreAccept that reached only a minority of some shard makes
        invalidate<->recover ping-pong forever."""
        state["done"] = True
        from .fetch_data import fetch_data

        def attempt(fetch_route: Route, allow_refetch: bool) -> None:
            def on_fetched(merged, failure):
                if failure is not None:
                    result.set_failure(failure)
                    return
                txn = merged.full_txn() if merged is not None else None
                mroute = merged.route if merged is not None else None
                if txn is not None:
                    full_route = mroute if mroute is not None and mroute.full \
                        else fetch_route
                    recover(node, txn_id, txn, full_route, result,
                            ballot=node.ballot_after(ballot))
                    return
                if allow_refetch and mroute is not None and mroute.full \
                        and mroute != fetch_route:
                    attempt(mroute, False)   # now query the txn's FULL footprint
                    return
                if mroute is not None and mroute.full and merged is not None \
                        and not merged.save_status.has_been(Status.ACCEPTED):
                    # quorum of every shard read; no definition, nothing Accepted+
                    state["done"] = False    # re-arm terminal bookkeeping
                    commit_invalidate()
                    return
                result.set_failure(Exhausted(
                    txn_id, "definition known but not reconstitutable yet"))

            fetch_data(node, txn_id, fetch_route).add_listener(on_fetched)

        attempt(learned_route, True)

    class InvalidateCallback(Callback):
        def on_success(self, from_node: int, reply) -> None:
            if state["done"]:
                return
            if isinstance(reply, InvalidateNack):
                if reply.truncated:
                    # below the home shard's durable fence: settled — adopt
                    # the tombstone locally so waiters unblock, report
                    # Truncated (outcome unknowable here)
                    adopt_erased(node, txn_id, route)
                    finish(Truncated(txn_id, "below the durable fence"))
                    return
                finish(Preempted(txn_id, "invalidation superseded"
                                 if not reply.committed else "txn committed"))
                return
            if reply.status.has_been(Status.PRE_COMMITTED):
                finish(Preempted(txn_id, "txn committed concurrently"))
                return
            if reply.status.has_been(Status.ACCEPTED):
                # a real Accept vote (which carries no definition): the txn may
                # be committed — never count this toward an invalidation quorum;
                # escalate to recovery via the definition-fetch path instead
                # (Paxos value adoption: the highest accepted value governs)
                state["has_accept"] = True
            if reply.has_definition or reply.route is not None:
                state["has_definition"] = state["has_definition"] or reply.has_definition
                if reply.route is not None:
                    state["learned_route"] = reply.route if state["learned_route"] is None \
                        else state["learned_route"]
            if tracker.record_success(from_node) is RequestStatus.SUCCESS:
                if state["has_definition"] or state["has_accept"]:
                    escalate(state["learned_route"] if state["learned_route"] is not None
                             else route)
                else:
                    commit_invalidate()

        def on_failure(self, from_node: int, failure: BaseException) -> None:
            if state["done"]:
                return
            if tracker.record_failure(from_node) is RequestStatus.FAILED:
                finish(Exhausted(txn_id, "invalidate quorum unreachable"))

    scope = route.home_key_only()
    callback = InvalidateCallback()
    for to in shard.nodes:
        node.send(to, AcceptInvalidate(txn_id, scope, txn_id.epoch, ballot), callback)


class _AwaitCommit:
    """Quorum WaitOnCommit on one txn's participants (Recover.AwaitCommit)."""

    def __init__(self, node: "Node", txn_id: TxnId, participants):
        self.result = au.settable()
        # Deps.participants returns the (RoutingKeys, Ranges) footprint pair
        keys, ranges = participants
        if len(keys):
            route = Route.for_keys(keys[0], keys)
        else:
            route = Route.for_ranges(ranges[0].start, ranges)
        topologies = node.topology.precise_epochs(route, txn_id.epoch, txn_id.epoch)
        tracker = QuorumTracker(topologies)
        this = self

        class WaitCallback(Callback):
            def on_success(self, from_node: int, reply) -> None:
                if tracker.record_success(from_node) is RequestStatus.SUCCESS:
                    this.result.try_success(None)

            def on_failure(self, from_node: int, failure: BaseException) -> None:
                if tracker.record_failure(from_node) is RequestStatus.FAILED:
                    this.result.set_failure(Timeout(txn_id, "await-commit quorum unreachable"))

        callback = WaitCallback()
        for to in tracker.nodes():
            scope = TxnRequest.compute_scope(to, topologies, route)
            if scope is None:
                continue
            node.send(to, WaitOnCommit(txn_id, scope,
                                       TxnRequest.compute_wait_for_epoch(to, topologies)),
                      callback)
