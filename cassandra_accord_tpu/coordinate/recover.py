"""Recovery coordination (Recover.java:80-471) — placeholder pending the recovery
milestone; see coordinate_transaction for the standard pipeline this resumes into."""
from __future__ import annotations

from typing import TYPE_CHECKING

from ..primitives.route import Route
from ..primitives.timestamp import TxnId
from ..utils import async_ as au
from .errors import CoordinationFailed

if TYPE_CHECKING:
    from ..local.node import Node


def recover(node: "Node", txn_id: TxnId, route: Route, result: au.Settable) -> None:
    result.set_failure(CoordinationFailed(txn_id, "recovery not yet implemented"))
