"""Live-state multichip dry run: shard a REAL burn's resolver indexes.

VERDICT r03 item 5: ``dryrun_multichip`` must execute protocol-BUILT state,
not synthetic arrays.  This module runs a small contended burn with the
device resolver mirrors live on every command store (the per-store conflict
index the protocol actually maintained: registrations, elision cover bits,
prunes, recycled slots), stacks those indexes store-per-device, and replays
the burn's OWN recorded consult stream through the mesh-sharded consult
(``parallel.build_sharded_store_consult``) — asserting parity against the
unsharded single-device computation on the same arrays.

The cross-store timestamp-proposal reduce (all_gather + lane-lex max over
ICI) is exactly the on-device analog of ``CommandStores.map_reduce`` over
``SafeCommandStore.max_conflict`` (CommandStores.java:580-620), now driven
by live protocol state.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


# the arrays stack_store_indexes consumes — snapshots copy nothing else
_FRAME_KEYS = ("live_inc", "key_inc", "ts", "txn_id", "kind", "status",
               "active")


def collect_live_state(n_stores: int, seed: int = 7, ops: int = 1000,
                       concurrency: int = 16,
                       snapshot_fracs: Tuple[float, ...] = (1 / 3, 2 / 3)):
    """Run a contended burn recording every store's consult stream; return
    (stores, recorder, snapshots) where ``stores`` are the n_stores command
    stores with the largest live device indexes and ``snapshots`` are
    MID-STREAM copies of each store's device mirrors (host arrays + key-slot
    map + recorded-event position) captured at the given op fractions —
    VERDICT r04 item 7: mid-stream states over the mesh, not just the final
    index."""
    from ..harness.burn import run_burn
    from ..harness.consult_trace import ConsultRecorder

    rec = ConsultRecorder()
    targets = sorted({max(1, int(ops * f)) for f in snapshot_fracs})
    snapshots: List[Dict] = []

    def snap(op_id, _txn_id, _txn, _coord) -> None:
        if not targets or op_id != targets[0]:
            return
        targets.pop(0)
        frame: Dict = {}
        for store, events in rec.streams.items():
            tpu = _tpu(store)
            tpu._flush()
            if tpu._h is None:
                continue
            frame[store] = {
                "h": {k: np.array(tpu._h[k]) for k in _FRAME_KEYS},
                "key_slot": dict(tpu.key_slot),
                "event_pos": len(events),
            }
        snapshots.append(frame)

    # shards*nodes >= n_stores so every device can own a distinct live store;
    # few keys -> contention -> deep deps rows in the live index
    run_burn(seed, ops=ops, concurrency=concurrency, nodes=4, rf=3,
             key_count=6, num_shards=max(2, (n_stores + 3) // 4),
             resolver="tpu", consult_recorder=rec, on_submit=snap)
    stores = list(rec.streams.keys())
    stores.sort(key=lambda s: -len(_tpu(s).txns))
    stores = stores[:n_stores]
    # final state as the last "snapshot" frame (live mirrors, full stream)
    final: Dict = {}
    for store in stores:
        tpu = _tpu(store)
        tpu._flush()
        if tpu._h is None:
            continue
        final[store] = {
            "h": {k: np.array(tpu._h[k]) for k in _FRAME_KEYS},
            "key_slot": dict(tpu.key_slot),
            "event_pos": len(rec.streams.get(store, ())),
        }
    snapshots.append(final)
    return stores, rec, snapshots


def _tpu(store):
    r = store.resolver
    # unwrap the recording shim, then any verify pairing
    r = getattr(r, "inner", r)
    return getattr(r, "tpu", r)


def stack_store_indexes(stores, frame: Dict = None) -> Dict[str, np.ndarray]:
    """Stack each store's canonical host mirror into [S, T, ...] arrays,
    padded to the max capacity (pad rows inactive — the kernels mask).
    With ``frame`` (a snapshot from collect_live_state), the SNAPSHOTTED
    mirrors are stacked instead of the live ones."""
    hs = []
    for s in stores:
        if frame is not None:
            hs.append(frame[s]["h"])
            continue
        tpu = _tpu(s)
        tpu._flush()
        hs.append(tpu._h)
    T = max(h["key_inc"].shape[0] for h in hs)
    K = max(h["key_inc"].shape[1] for h in hs)
    S = len(hs)
    out = {
        "live_inc": np.zeros((S, T, K), dtype=np.int8),
        "key_inc": np.zeros((S, T, K), dtype=np.int8),
        "ts": np.zeros((S, T, 5), dtype=np.int32),
        "txn_id": np.zeros((S, T, 5), dtype=np.int32),
        "kind": np.zeros((S, T), dtype=np.int8),
        "status": np.zeros((S, T), dtype=np.int8),
        "active": np.zeros((S, T), dtype=np.bool_),
    }
    for i, h in enumerate(hs):
        t, k = h["key_inc"].shape
        out["live_inc"][i, :t, :k] = h["live_inc"]
        out["key_inc"][i, :t, :k] = h["key_inc"]
        out["ts"][i, :t] = h["ts"]
        out["txn_id"][i, :t] = h["txn_id"]
        out["kind"][i, :t] = h["kind"]
        out["status"][i, :t] = h["status"]
        out["active"][i, :t] = h["active"]
    return out


def build_query_batches(stores, recorder, K: int, batch: int = 8,
                        frame: Dict = None) -> Tuple[np.ndarray, np.ndarray,
                                                     np.ndarray, int]:
    """Per-store [S, B, ...] query arrays from each store's RECORDED consult
    stream — MIXED ops: key_conflicts (kc), max-conflict (mc), and range
    queries (rc, expanded to the indexed keys inside the range), replayed
    through the key-slot mapping of the state they're asked against.  With
    ``frame`` (a mid-stream snapshot), only events recorded BEFORE the
    snapshot replay, against the snapshotted slots.  Stores with fewer than
    ``batch`` replayable queries pad with zero (no-key) queries.

    mc rows use a zero ``before`` bound with kind 0 — the consult kernel's
    max tier ignores the bound (elision never applies to MaxConflicts)."""
    S = len(stores)
    q = np.zeros((S, batch, K), dtype=np.int8)
    before = np.zeros((S, batch, 5), dtype=np.int32)
    qkind = np.zeros((S, batch), dtype=np.int8)
    total_real = 0
    for i, s in enumerate(stores):
        key_slot = frame[s]["key_slot"] if frame is not None \
            else _tpu(s).key_slot
        events = recorder.streams.get(s, [])
        if frame is not None:
            events = events[:frame[s]["event_pos"]]
        got = 0
        # replay the LATEST queries first: they saw the most index state
        for ev in reversed(events):
            if got >= batch:
                break
            tag = ev[0]
            if tag == "kc":
                _t, by, keys, bound = ev
                cols = [key_slot.get(rk) for rk in keys]
                if any(c is None for c in cols) or not cols:
                    continue   # keys pruned from the index since: skip
                q[i, got, cols] = 1
                before[i, got] = bound.pack_lanes()
                qkind[i, got] = int(by.kind)
            elif tag == "mc":
                cols = [key_slot.get(rk) for rk in ev[1]]
                if any(c is None for c in cols) or not cols:
                    continue
                q[i, got, cols] = 1    # before stays 0: max-tier row
            elif tag == "rc":
                _t, by, rng, bound = ev
                cols = [c for rk, c in key_slot.items() if rng.contains(rk)]
                if not cols:
                    continue
                q[i, got, cols] = 1
                before[i, got] = bound.pack_lanes()
                qkind[i, got] = int(by.kind)
            else:
                continue
            got += 1
        total_real += got
    return q, before, qkind, total_real


def host_lex_max(vals: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """[..., N, 5] lane-lexicographic max over N where mask [..., N]; zeros
    when empty — host reference for the device lane-lex reduces."""
    lead = mask.shape[:-1]
    out = np.zeros(lead + (5,), dtype=np.int64)
    tie = mask.copy()
    for lane in range(5):
        v = np.where(tie, vals[..., lane], -1)
        best = v.max(axis=-1)
        tie = tie & (vals[..., lane] == best[..., None])
        out[..., lane] = np.maximum(best, 0)
    return out
