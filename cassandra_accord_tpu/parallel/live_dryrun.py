"""Live-state multichip dry run: shard a REAL burn's resolver indexes.

VERDICT r03 item 5: ``dryrun_multichip`` must execute protocol-BUILT state,
not synthetic arrays.  This module runs a small contended burn with the
device resolver mirrors live on every command store (the per-store conflict
index the protocol actually maintained: registrations, elision cover bits,
prunes, recycled slots), stacks those indexes store-per-device, and replays
the burn's OWN recorded consult stream through the mesh-sharded consult
(``parallel.build_sharded_store_consult``) — asserting parity against the
unsharded single-device computation on the same arrays.

The cross-store timestamp-proposal reduce (all_gather + lane-lex max over
ICI) is exactly the on-device analog of ``CommandStores.map_reduce`` over
``SafeCommandStore.max_conflict`` (CommandStores.java:580-620), now driven
by live protocol state.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


def collect_live_state(n_stores: int, seed: int = 7, ops: int = 60,
                       concurrency: int = 8):
    """Run a contended burn recording every store's consult stream; return
    (stores, recorder) where ``stores`` are the n_stores command stores with
    the largest live device indexes."""
    from ..harness.burn import run_burn
    from ..harness.consult_trace import ConsultRecorder

    rec = ConsultRecorder()
    # shards*nodes >= n_stores so every device can own a distinct live store;
    # few keys -> contention -> deep deps rows in the live index
    run_burn(seed, ops=ops, concurrency=concurrency, nodes=4, rf=3,
             key_count=6, num_shards=max(2, (n_stores + 3) // 4),
             resolver="tpu", consult_recorder=rec)
    stores = list(rec.streams.keys())
    stores.sort(key=lambda s: -len(_tpu(s).txns))
    return stores[:n_stores], rec


def _tpu(store):
    r = store.resolver
    # unwrap the recording shim, then any verify pairing
    r = getattr(r, "inner", r)
    return getattr(r, "tpu", r)


def stack_store_indexes(stores) -> Dict[str, np.ndarray]:
    """Stack each store's canonical host mirror into [S, T, ...] arrays,
    padded to the max capacity (pad rows inactive — the kernels mask)."""
    hs = []
    for s in stores:
        tpu = _tpu(s)
        tpu._flush()
        hs.append(tpu._h)
    T = max(h["key_inc"].shape[0] for h in hs)
    K = max(h["key_inc"].shape[1] for h in hs)
    S = len(hs)
    out = {
        "live_inc": np.zeros((S, T, K), dtype=np.int8),
        "key_inc": np.zeros((S, T, K), dtype=np.int8),
        "ts": np.zeros((S, T, 5), dtype=np.int32),
        "txn_id": np.zeros((S, T, 5), dtype=np.int32),
        "kind": np.zeros((S, T), dtype=np.int8),
        "status": np.zeros((S, T), dtype=np.int8),
        "active": np.zeros((S, T), dtype=np.bool_),
    }
    for i, h in enumerate(hs):
        t, k = h["key_inc"].shape
        out["live_inc"][i, :t, :k] = h["live_inc"]
        out["key_inc"][i, :t, :k] = h["key_inc"]
        out["ts"][i, :t] = h["ts"]
        out["txn_id"][i, :t] = h["txn_id"]
        out["kind"][i, :t] = h["kind"]
        out["status"][i, :t] = h["status"]
        out["active"][i, :t] = h["active"]
    return out


def build_query_batches(stores, recorder, K: int,
                        batch: int = 8) -> Tuple[np.ndarray, np.ndarray,
                                                 np.ndarray, int]:
    """Per-store [S, B, ...] query arrays from each store's RECORDED consult
    stream (the protocol's own key_conflicts calls, replayed against the
    final index through the final key-slot mapping).  Stores with fewer than
    ``batch`` replayable queries pad with zero (no-key) queries."""
    S = len(stores)
    q = np.zeros((S, batch, K), dtype=np.int8)
    before = np.zeros((S, batch, 5), dtype=np.int32)
    qkind = np.zeros((S, batch), dtype=np.int8)
    total_real = 0
    for i, s in enumerate(stores):
        tpu = _tpu(s)
        events = recorder.streams.get(s, [])
        got = 0
        # replay the LATEST queries first: they saw the most index state
        for ev in reversed(events):
            if got >= batch:
                break
            if ev[0] != "kc":
                continue
            _tag, by, keys, bound = ev
            cols = [tpu.key_slot.get(rk) for rk in keys]
            if any(c is None for c in cols) or not cols:
                continue   # keys pruned from the index since: skip
            q[i, got, cols] = 1
            before[i, got] = bound.pack_lanes()
            qkind[i, got] = int(by.kind)
            got += 1
        total_real += got
    return q, before, qkind, total_real


def host_lex_max(vals: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """[..., N, 5] lane-lexicographic max over N where mask [..., N]; zeros
    when empty — host reference for the device lane-lex reduces."""
    lead = mask.shape[:-1]
    out = np.zeros(lead + (5,), dtype=np.int64)
    tie = mask.copy()
    for lane in range(5):
        v = np.where(tie, vals[..., lane], -1)
        best = v.max(axis=-1)
        tie = tie & (vals[..., lane] == best[..., None])
        out[..., lane] = np.maximum(best, 0)
    return out
