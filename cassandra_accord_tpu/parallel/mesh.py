"""Multi-device sharding of the conflict-graph data plane.

The reference scales its metadata plane by splitting key ranges across
single-threaded ``CommandStore`` shards inside one JVM (CommandStores.java:79,
§2.4 of SURVEY.md); across machines it scales by topology shards.  The TPU
build keeps both of those control-plane axes AND adds a device axis: one
logical command-store shard's conflict graph can itself be sharded over a
``jax.sharding.Mesh`` so the adjacency matrix and key-incidence matrix grow
beyond one chip's HBM.

Layout (axis name "shard"):
- ``key_inc``  [T, K]   row-sharded over txn slots
- ``ts/txn_id`` [T, 5]  row-sharded
- ``kind/status/active`` [T] sharded
- ``adj``      [T, T]   row-sharded (each device owns its txns' outgoing
                        dependency edges)
- incoming txn batches are REPLICATED (they are small; every device joins
  them against its local slice)

Collectives (all via shard_map, riding ICI):
- overlap_join: none — [B, K] @ [K, T/n] keeps the output sharded by T.
- conflict-max: jax.lax.all_gather of per-device [B, 5] partial maxes, then
  a lane-lexicographic reduce (deterministic, device-order independent).
- kahn frontier: all_gather of the [T/n] done-vector slices (tiny), local
  [T/n, T] matmul.
- closure: all_gather of the row-sharded reachability (the classic
  row-parallel boolean semiring squaring).

This module is exercised on a virtual 8-device CPU mesh in tests and by the
driver's ``dryrun_multichip``; on hardware the same code spans real chips.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax.shard_map graduated from jax.experimental in 0.4.4x; the pinned
# toolchain (0.4.37) still exports it only from the experimental module
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        # the experimental signature spells the replication check "check_rep"
        return _experimental_shard_map(f, mesh=mesh, in_specs=in_specs,
                                       out_specs=out_specs,
                                       check_rep=check_vma)

from ..ops import graph_state as gs
from ..ops import deps_kernels as dk
from ..models.conflict_graph import TxnBatch

SHARD = "shard"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()[:n_devices] if n_devices else jax.devices()
    return Mesh(np.asarray(devices), (SHARD,))


def state_specs() -> gs.GraphState:
    """PartitionSpec pytree for GraphState: txn-slot axis sharded."""
    return gs.GraphState(
        key_inc=P(SHARD, None),
        ts=P(SHARD, None),
        txn_id=P(SHARD, None),
        kind=P(SHARD),
        status=P(SHARD),
        adj=P(SHARD, None),
        active=P(SHARD),
    )


def batch_specs() -> TxnBatch:
    """Incoming batches are replicated on every device."""
    return TxnBatch(slots=P(), key_inc=P(), txn_id=P(), kind=P(), valid=P())


def shard_state(state: gs.GraphState, mesh: Mesh) -> gs.GraphState:
    """Place a host-built GraphState onto the mesh with the standard layout."""
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        state, state_specs())


def _lex_max_over_axis0(vals: jax.Array) -> jax.Array:
    """Lexicographic max over axis 0 of [n, B, 5] lane arrays."""
    tie = jnp.ones(vals.shape[:2], dtype=jnp.bool_)
    out = []
    for lane in range(vals.shape[-1]):
        m = jnp.where(tie, vals[..., lane], -1)
        best = jnp.max(m, axis=0)                 # [B]
        tie = tie & (vals[..., lane] == best[None, :])
        out.append(jnp.maximum(best, 0))
    return jnp.stack(out, axis=-1)                # [B, 5]


def build_sharded_step(mesh: Mesh):
    """The full training-step analog, jitted over the mesh: witness a
    replicated batch against the sharded graph, stabilise, run one execution
    wave.  Local slot indexing: batch.slots are GLOBAL slot ids; each device
    claims the ones falling in its slice.

    Returns step(state, batch) -> (state', conflict_max [B,5], applied [T])."""

    def local_step(state: gs.GraphState, batch: TxnBatch):
        # ---- join against the local row slice (no collective) -------------
        deps_local = dk.overlap_join(state.key_inc, state.txn_id, state.kind,
                                     state.status, state.active,
                                     batch.key_inc, batch.txn_id, batch.kind)
        deps_local = deps_local & batch.valid[:, None]          # [B, T/n]

        # ---- conflict max: combine per-device partial maxes over ICI ------
        cmax_local, _ = dk.max_conflict_ts(state.ts, deps_local)  # [B, 5]
        cmax_all = jax.lax.all_gather(cmax_local, SHARD)          # [n, B, 5]
        conflict_max = _lex_max_over_axis0(cmax_all)
        any_dep_local = jnp.any(deps_local, axis=1)
        any_dep = jax.lax.psum(any_dep_local.astype(jnp.int32), SHARD) > 0

        # ---- insert: each device scatters the batch rows it owns ----------
        t_local = state.key_inc.shape[0]
        first = jax.lax.axis_index(SHARD) * t_local
        mine = batch.valid & (batch.slots >= first) & (batch.slots < first + t_local)
        # rows this device does not own scatter out of bounds and are dropped
        # (an in-bounds dummy slot would collide with real inserts)
        lslot = jnp.where(mine, batch.slots - first, t_local)

        # adjacency rows are GLOBAL width: gather the full deps row for the
        # owner of each batch txn
        deps_full = jax.lax.all_gather(deps_local, SHARD, axis=1,
                                       tiled=True)               # [B, T]

        fast = ~any_dep | gs.ts_less(conflict_max, batch.txn_id)
        exec_at = jnp.where(fast[:, None], batch.txn_id,
                            gs.ts_next(conflict_max, 0))

        def put(col, upd):
            return col.at[lslot].set(upd, mode="drop")

        state = gs.GraphState(
            key_inc=put(state.key_inc, batch.key_inc),
            ts=put(state.ts, exec_at),
            txn_id=put(state.txn_id, batch.txn_id),
            kind=put(state.kind, batch.kind),
            status=put(state.status, jnp.full_like(batch.kind, gs.STABLE)),
            adj=put(state.adj, deps_full.astype(jnp.int8)),
            active=state.active.at[lslot].set(True, mode="drop"),
        )

        # ---- one execution wave: frontier over the sharded adjacency ------
        dep_done_local = ((state.status == gs.APPLIED)
                          | (state.status == gs.INVALIDATED)
                          | ~state.active)                        # [T/n]
        dep_done = jax.lax.all_gather(dep_done_local, SHARD,
                                      tiled=True)                # [T]
        waiting = jnp.einsum("ij,j->i", state.adj.astype(jnp.float32),
                             (~dep_done).astype(jnp.float32)) > 0
        ready = state.active & (state.status == gs.STABLE) & ~waiting
        state = state._replace(
            status=jnp.where(ready, jnp.int8(gs.APPLIED), state.status))
        applied = jax.lax.all_gather(ready, SHARD, tiled=True)   # [T]
        return state, conflict_max, applied

    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(state_specs(), batch_specs()),
        out_specs=(state_specs(), P(), P()),
        check_vma=False)
    return jax.jit(sharded)


def build_sharded_store_consult(mesh: Mesh):
    """The PROTOCOL data plane over the mesh: command-store parallelism.

    Accord's native scaling axis is per-range command stores; on TPU each
    device owns a store's conflict index and answers its consults locally
    (impl/tpu_resolver device tier == ops.deps_kernels.consult), while the
    coordinator-side timestamp proposal takes the lexicographic max of the
    per-store max-conflicts ACROSS stores — an all_gather + lane-lex reduce
    riding ICI (the on-device analog of SafeCommandStore.max_conflict merged
    over CommandStores.map_reduce).

    Inputs are store-stacked: index arrays [S, T, K]/[S, T, 5]/[S, T] and
    query batches [S, B, K]/[S, B, 5]/[S, B], sharded over the store axis.
    Returns (deps [S, B, T] sharded, global_max [B, 5] replicated)."""

    def local(live_inc, key_inc, ts, txn_id, kind, status, active,
              q, before, qkind):
        deps, max_lanes = jax.vmap(dk.consult)(
            live_inc, key_inc, ts, txn_id, kind, status, active,
            q, before, qkind)                                   # [Sl, B, T/5]
        # reduce the LOCAL store axis first (a device may own several stores
        # when S > mesh size), then combine across devices
        local_max = _lex_max_over_axis0(max_lanes)               # [B, 5]
        gathered = jax.lax.all_gather(local_max, SHARD)          # [n, B, 5]
        global_max = _lex_max_over_axis0(gathered)               # [B, 5]
        return deps, global_max

    spec3 = P(SHARD, None, None)
    spec2 = P(SHARD, None)
    sharded = shard_map(
        local, mesh=mesh,
        in_specs=(spec3, spec3, spec3, spec3, spec2, spec2, spec2,
                  spec3, spec3, spec2),
        out_specs=(spec3, P()),
        check_vma=False)
    return jax.jit(sharded)


def build_sharded_frontier(mesh: Mesh):
    """Per-store execution frontier over the mesh: each device runs
    kahn_frontier on its own store's wait graph (no collective — stores'
    frontiers are independent; cross-store ordering flows through deps)."""

    def local(adj, status, active):
        return jax.vmap(dk.kahn_frontier)(adj, status, active)

    sharded = shard_map(
        local, mesh=mesh,
        in_specs=(P(SHARD, None, None), P(SHARD, None), P(SHARD, None)),
        out_specs=P(SHARD, None),
        check_vma=False)
    return jax.jit(sharded)


def build_sharded_closure(mesh: Mesh):
    """Row-parallel transitive closure over the mesh: log2(T) rounds of
    (all_gather rows) then local [T/n, T] @ [T, T] matmul."""

    def local_closure(adj_local: jax.Array) -> jax.Array:        # [T/n, T]
        t = adj_local.shape[1]
        iters = max(1, int(t - 1).bit_length())

        def body(_, r_local):
            r_full = jax.lax.all_gather(r_local, SHARD, tiled=True)  # [T, T]
            prod = jax.lax.dot_general(
                r_local.astype(jnp.bfloat16), r_full.astype(jnp.bfloat16),
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) > 0.0
            return r_local | prod

        return jax.lax.fori_loop(0, iters, body, adj_local.astype(jnp.bool_))

    sharded = shard_map(
        local_closure, mesh=mesh,
        in_specs=(P(SHARD, None),), out_specs=P(SHARD, None),
        check_vma=False)
    return jax.jit(sharded)
