"""Device-mesh parallelism for the conflict-graph data plane."""
from .mesh import (
    SHARD, make_mesh, state_specs, batch_specs, shard_state,
    build_sharded_step, build_sharded_closure,
)

__all__ = ["SHARD", "make_mesh", "state_specs", "batch_specs", "shard_state",
           "build_sharded_step", "build_sharded_closure"]
