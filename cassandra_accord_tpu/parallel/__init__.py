"""Device-mesh parallelism for the conflict-graph data plane."""
from .mesh import (
    SHARD, make_mesh, state_specs, batch_specs, shard_state,
    build_sharded_step, build_sharded_closure,
    build_sharded_store_consult, build_sharded_frontier,
)

__all__ = ["SHARD", "make_mesh", "state_specs", "batch_specs", "shard_state",
           "build_sharded_step", "build_sharded_closure",
           "build_sharded_store_consult", "build_sharded_frontier"]
