"""ALL command state transitions.

Capability parity with ``accord.local.Commands`` (Commands.java:106-1293): static
functions operating on (SafeCommandStore, Command): preaccept, accept,
accept_invalidate, commit/precommit/stable, commit_invalidate, apply, maybe_execute,
the WaitingOn initialisation/update machinery, and durability marking.  Every
transition is ballot-gated and monotonic; listeners (dependent commands and transient
message waiters) are notified on every status change.
"""
from __future__ import annotations

import enum
from typing import TYPE_CHECKING, Optional

from ..primitives.deps import Deps
from ..primitives.keys import Ranges
from ..primitives.route import Route
from ..primitives.timestamp import Ballot, Timestamp, TxnId, TxnKind
from ..primitives.txn import PartialTxn, Writes
from ..protocol_batch.columns import ENGAGE_FLOOR
from ..utils.invariants import Invariants, check_state
from .cfk import InternalStatus, manages_execution
from .command import Command, WaitingOn
from .command_store import SafeCommandStore
from .status import Durability, SaveStatus, Status

if TYPE_CHECKING:
    from ..api.interfaces import Result


class AcceptOutcome(enum.Enum):
    SUCCESS = 0
    REDUNDANT = 1          # already progressed past this phase
    REJECTED_BALLOT = 2
    INSUFFICIENT = 3       # missing definition (recovery edge)
    TRUNCATED = 4


def _observe_transition(safe_store: SafeCommandStore, command: Command) -> None:
    """Report a just-applied SaveStatus transition to the run's flight
    recorder (observe.FlightRecorder) — the per-node/per-store txn lifecycle
    span plane.  Passive by contract: reads sim time, touches no RNG and
    schedules nothing (zero observer effect).  The live ``command`` and
    ``CommandStore`` ride along so the InvariantAuditor can read decision
    state (executeAt, deps, ballots, watermarks) at the transition — reads
    only; the recorder base class ignores them."""
    store = safe_store.store
    if store.batch_engine is not None:
        # the columnar mirror rides the SAME choke point: every SaveStatus
        # change flows through here, so the struct-of-arrays row is fresh at
        # every point a vectorized scan reads it (the exact-skip proofs in
        # protocol_batch/engine.py depend on this)
        store.batch_engine.note_transition(command)
    ss = command.save_status
    if ss.is_terminal:
        # terminal transitions reach the resolver's frontier mirror HERE, not
        # through register_witness: the witness path is gated behind cfk key
        # indexing, which refuses demoted-cold/pruned entries (and truncation
        # never re-registers at all) — the mirror then kept a stale STABLE
        # status and the kernel frontier reported the txn ready forever (the
        # one-sided device mirror leak, KNOWN_ISSUES round 6-11)
        store.resolver.note_terminal(
            command.txn_id, invalidated=ss is SaveStatus.INVALIDATED)
    obs = store.observer()
    if obs is not None:
        obs.on_transition(store.node.id, store.id, command.txn_id,
                          command.save_status.name,
                          safe_store.time().now_micros(),
                          command=command, command_store=store)


# ---------------------------------------------------------------------------
# PreAccept (Commands.java:113)
# ---------------------------------------------------------------------------

def _is_shard_redundant(safe_store: SafeCommandStore, txn_id: TxnId,
                        route: Optional[Route]) -> bool:
    """Erased-tombstone guard: a txn below the shard-applied watermark on its
    whole footprint was applied (with everything before it) at a quorum; late
    messages about it must not resurrect state (Commands' redundantBefore
    checks / ErasedSafeCommand semantics)."""
    if route is None:
        return False
    return safe_store.redundant_before().is_shard_redundant(
        txn_id, route.participants())


def preaccept(safe_store: SafeCommandStore, txn_id: TxnId, partial_txn: PartialTxn,
              route: Route, ballot: Ballot = Ballot.ZERO) -> AcceptOutcome:
    """Witness the txn; propose witnessedAt = txnId if no conflict is later, else a
    fresh unique timestamp greater than every conflict (PreAccept.java:245-267)."""
    if _is_shard_redundant(safe_store, txn_id, route):
        return AcceptOutcome.TRUNCATED
    command = safe_store.get_or_create(txn_id)
    if command.save_status.is_truncated:
        return AcceptOutcome.TRUNCATED
    if command.has_been(Status.PRE_ACCEPTED):
        # duplicate delivery / recovery re-preaccept: report current state
        if ballot < command.promised:
            return AcceptOutcome.REJECTED_BALLOT
        return AcceptOutcome.REDUNDANT
    if ballot < command.promised:
        return AcceptOutcome.REJECTED_BALLOT

    command.route = route if command.route is None else command.route.union(route)
    command.partial_txn = partial_txn
    command.promised = command.promised.merge_max(ballot)

    # timestamp proposal
    keys = partial_txn.keys if not isinstance(partial_txn.keys, Ranges) else None
    ranges = partial_txn.keys if isinstance(partial_txn.keys, Ranges) else None
    max_conflict = safe_store.max_conflict(keys, ranges)
    if max_conflict is None or max_conflict < txn_id:
        command.execute_at = txn_id.as_timestamp()
    else:
        command.execute_at = safe_store.time().unique_now_at_least(max_conflict)
    command.set_save_status(SaveStatus.PRE_ACCEPTED)
    _observe_transition(safe_store, command)
    safe_store.register_witness(command, InternalStatus.PREACCEPTED)
    safe_store.progress_log().pre_accepted(command, _is_progress_shard(safe_store, command))
    safe_store.journal_save(command)
    safe_store.notify_listeners(command)
    return AcceptOutcome.SUCCESS


# ---------------------------------------------------------------------------
# Recover (Commands.java:118)
# ---------------------------------------------------------------------------

def recover(safe_store: SafeCommandStore, txn_id: TxnId, partial_txn: PartialTxn,
            route: Route, ballot: Ballot) -> AcceptOutcome:
    """Ballot-gated recovery witness: promise ``ballot`` (refusing lower-ballot
    coordinators) and pre-accept the txn if this replica never witnessed it
    (Commands.java:118).  The caller then reports this replica's full evidence
    (status, accepted ballot, deps, fast-path rejection) via RecoverOk."""
    command = safe_store.get_or_create(txn_id)
    if command.save_status.is_truncated:
        return AcceptOutcome.TRUNCATED
    if ballot < command.promised:
        return AcceptOutcome.REJECTED_BALLOT
    command.promised = command.promised.merge_max(ballot)
    if not command.has_been(Status.PRE_ACCEPTED):
        outcome = preaccept(safe_store, txn_id, partial_txn, route, ballot)
        if outcome is AcceptOutcome.TRUNCATED:
            # the region (or the txn) is below this store's redundancy bound:
            # report truncated — asserting here kills the reply and starves the
            # recovery quorum forever
            return AcceptOutcome.TRUNCATED
        check_state(outcome is AcceptOutcome.SUCCESS,
                    "recovery preaccept failed with %s", outcome)
    return AcceptOutcome.SUCCESS


# ---------------------------------------------------------------------------
# Accept — slow-path proposal (Commands.java:202)
# ---------------------------------------------------------------------------

def accept(safe_store: SafeCommandStore, txn_id: TxnId, ballot: Ballot, route: Route,
           execute_at: Timestamp, partial_deps: Deps) -> AcceptOutcome:
    if _is_shard_redundant(safe_store, txn_id, route):
        # GC physically erased this txn (applied at every replica, below the
        # shard fence): a LATE Accept — chaos latencies reach seconds — must
        # not re-create it fresh at ballot zero (the auditor catches the
        # resurrection as a promised-ballot regression; stale re-created
        # ACCEPTED evidence is the round-3/4 unsound-recovery shape)
        return AcceptOutcome.TRUNCATED
    command = safe_store.get_or_create(txn_id)
    if command.save_status.is_truncated:
        return AcceptOutcome.TRUNCATED
    if command.has_been(Status.PRE_COMMITTED):
        return AcceptOutcome.REDUNDANT
    if ballot < command.promised:
        return AcceptOutcome.REJECTED_BALLOT

    command.route = route if command.route is None else command.route.union(route)
    command.promised = command.promised.merge_max(ballot)
    command.accepted_or_committed = ballot
    command.execute_at = execute_at
    command.partial_deps = partial_deps
    command.set_save_status(SaveStatus.ACCEPTED)
    _observe_transition(safe_store, command)
    safe_store.register_witness(command, InternalStatus.ACCEPTED)
    safe_store.progress_log().accepted(command, _is_progress_shard(safe_store, command))
    safe_store.journal_save(command)
    safe_store.notify_listeners(command)
    return AcceptOutcome.SUCCESS


def accept_invalidate(safe_store: SafeCommandStore, txn_id: TxnId, ballot: Ballot,
                      scope: Optional[Route] = None) -> AcceptOutcome:
    """Promise not to accept anything below ballot, voting for invalidation
    (Commands.java:250)."""
    if _is_shard_redundant(safe_store, txn_id, scope):
        # GC erased this txn because it (and everything before it) durably
        # applied at every replica — answering NOT_DEFINED here would let a
        # quorum of erased replicas invalidate an already-applied txn
        # (ErasedSafeCommand tombstone semantics)
        return AcceptOutcome.TRUNCATED
    command = safe_store.get_or_create(txn_id)
    if command.save_status.is_truncated:
        return AcceptOutcome.TRUNCATED
    if command.has_been(Status.PRE_COMMITTED):
        return AcceptOutcome.REDUNDANT
    if ballot < command.promised:
        return AcceptOutcome.REJECTED_BALLOT
    command.promised = command.promised.merge_max(ballot)
    # the invalidation vote is an ACCEPT-phase decision at ``ballot``: recovery
    # must rank it against competing Accepts BY BALLOT (an AcceptedInvalidate
    # at a later ballot supersedes an Accept at an earlier one — otherwise a
    # recoverer re-proposes the txn while the invalidator commit-invalidates)
    command.accepted_or_committed = command.accepted_or_committed.merge_max(ballot)
    if command.save_status < SaveStatus.ACCEPTED_INVALIDATE:
        command.set_save_status(SaveStatus.ACCEPTED_INVALIDATE)
        _observe_transition(safe_store, command)
    safe_store.journal_save(command)
    safe_store.notify_listeners(command)
    return AcceptOutcome.SUCCESS


# ---------------------------------------------------------------------------
# Commit / Stable (Commands.java:289,353)
# ---------------------------------------------------------------------------

class CommitOutcome(enum.Enum):
    SUCCESS = 0
    REDUNDANT = 1
    REJECTED_BALLOT = 2
    INSUFFICIENT = 3


def precommit(safe_store: SafeCommandStore, txn_id: TxnId, execute_at: Timestamp) -> CommitOutcome:
    """Mark executeAt agreed without deps (Commands.java:353)."""
    command = safe_store.get_or_create(txn_id)
    if command.has_been(Status.PRE_COMMITTED):
        _check_consistent_execute_at(safe_store, command, execute_at)
        return CommitOutcome.REDUNDANT
    command.execute_at = execute_at
    command.set_save_status(SaveStatus.PRE_COMMITTED)
    _observe_transition(safe_store, command)
    safe_store.journal_save(command)
    safe_store.progress_log().precommitted(command)
    safe_store.notify_listeners(command)
    return CommitOutcome.SUCCESS


def commit(safe_store: SafeCommandStore, txn_id: TxnId, save_status: SaveStatus,
           ballot: Ballot, route: Route, partial_txn: Optional[PartialTxn],
           execute_at: Timestamp, partial_deps: Deps) -> CommitOutcome:
    """CommitSlowPath (-> COMMITTED) or Stable* (-> STABLE + initialise WaitingOn +
    maybe_execute) — Commands.java:289."""
    check_state(save_status in (SaveStatus.COMMITTED, SaveStatus.STABLE),
                "commit called with %s", save_status)
    if _is_shard_redundant(safe_store, txn_id, route):
        return CommitOutcome.REDUNDANT
    command = safe_store.get_or_create(txn_id)
    if command.save_status.is_truncated or command.save_status is SaveStatus.INVALIDATED:
        return CommitOutcome.REDUNDANT
    if save_status is SaveStatus.COMMITTED and command.has_been(Status.COMMITTED):
        _check_consistent_execute_at(safe_store, command, execute_at)
        return CommitOutcome.REDUNDANT
    if command.has_been(Status.STABLE):
        _check_consistent_execute_at(safe_store, command, execute_at)
        return CommitOutcome.REDUNDANT
    if ballot < command.promised:
        return CommitOutcome.REJECTED_BALLOT

    command.route = route if command.route is None else command.route.union(route)
    if partial_txn is not None:
        command.partial_txn = partial_txn if command.partial_txn is None \
            else command.partial_txn.with_merged(partial_txn)
    if command.partial_txn is None:
        return CommitOutcome.INSUFFICIENT
    command.accepted_or_committed = command.accepted_or_committed.merge_max(ballot)
    command.execute_at = execute_at
    command.partial_deps = partial_deps
    command.set_save_status(save_status)
    _observe_transition(safe_store, command)
    safe_store.register_witness(command, InternalStatus.COMMITTED if save_status is SaveStatus.COMMITTED
                                else InternalStatus.STABLE)
    safe_store.journal_save(command)
    if save_status is SaveStatus.STABLE:
        initialise_waiting_on(safe_store, command)
        safe_store.progress_log().stable(command, _is_progress_shard(safe_store, command))
        maybe_execute(safe_store, command, always_notify_listeners=True)
    else:
        safe_store.notify_listeners(command)
    return CommitOutcome.SUCCESS


def adopt_truncated_outcome(safe_store: SafeCommandStore, command: Command,
                            route: Route, execute_at: Timestamp, writes,
                            result) -> None:
    """The cluster truncated this txn AFTER it applied and the outcome is still
    carried (TRUNCATE_WITH_OUTCOME): a lagging replica adopts it directly —
    writes land out of dependency order (safe: the data store orders entries by
    executeAt and applies idempotently; reads snapshot at their own executeAt)
    and the command becomes a truncated tombstone, unblocking local waiters
    (the reference's Propagate handling of truncated evidence, Propagate.java;
    Infer.safeToCleanup)."""
    command.route = route if command.route is None else command.route
    command.execute_at = execute_at
    command.writes = writes
    command.result = result

    def post(_=None, failure=None):
        if failure is not None:
            safe_store.agent().on_uncaught_exception(failure)
            return
        # adoption lands writes out of dependency order: merge the per-key
        # registers monotonically, no validation (the safeToReadAt-gated
        # case).  Owned keys only — unowned registry entries would never GC
        # (shard_redundant_before has no bound for them)
        if writes is not None and not writes.is_empty():
            _merge_applied_writes(safe_store.store, writes, execute_at)
        command.partial_txn = None
        command.partial_deps = None
        command.waiting_on = None
        command.set_save_status(SaveStatus.TRUNCATED_APPLY)
        _observe_transition(safe_store, command)
        safe_store.journal_save(command)
        safe_store.register_witness(command, InternalStatus.APPLIED)
        safe_store.progress_log().clear(command.txn_id)
        safe_store.notify_listeners(command)

    if writes is None or writes.is_empty():
        post()
    else:
        writes.apply_to(safe_store, safe_store.store.all_ranges()).begin(post)


def commit_invalidate(safe_store: SafeCommandStore, txn_id: TxnId,
                      scope: Optional[Route] = None) -> None:
    """Commands.java:434."""
    if _is_shard_redundant(safe_store, txn_id, scope):
        return   # erased-tombstone guard: the txn durably applied everywhere
    command = safe_store.get_or_create(txn_id)
    if command.has_been(Status.PRE_COMMITTED) and command.save_status is not SaveStatus.INVALIDATED:
        # a txn cannot be both committed and invalidated
        safe_store.agent().on_inconsistent_timestamp(command, command.execute_at, None)
        return
    if command.save_status is SaveStatus.INVALIDATED:
        return
    command.set_save_status(SaveStatus.INVALIDATED)
    _observe_transition(safe_store, command)
    safe_store.journal_save(command)
    safe_store.register_witness(command, InternalStatus.INVALIDATED)
    safe_store.progress_log().invalidated(command, _is_progress_shard(safe_store, command))
    safe_store.notify_listeners(command)


def _merge_applied_writes(store, writes, execute_at) -> None:
    """Merge a write's per-key execution registers monotonically, without
    write-order validation (out-of-dependency-order landings: truncation
    adoption, restart replay).  Owned keys only — unowned registry entries
    would never GC (shard_redundant_before has no bound for them)."""
    tfk = store.timestamps_for_key
    owned = store.all_ranges()
    for key in writes.keys:
        rk = key.to_routing() if hasattr(key, "to_routing") else key
        if owned.contains(rk):
            tfk.merge_applied_write(key, execute_at)


# ---------------------------------------------------------------------------
# Apply (Commands.java:462)
# ---------------------------------------------------------------------------

def apply_(safe_store: SafeCommandStore, txn_id: TxnId, route: Route,
           execute_at: Timestamp, partial_deps: Optional[Deps],
           partial_txn: Optional[PartialTxn], writes: Optional[Writes], result) -> CommitOutcome:
    if _is_shard_redundant(safe_store, txn_id, route):
        return CommitOutcome.REDUNDANT
    command = safe_store.get_or_create(txn_id)
    if command.save_status.is_truncated or command.save_status is SaveStatus.INVALIDATED:
        return CommitOutcome.REDUNDANT
    if command.has_been(Status.PRE_APPLIED):
        _check_consistent_execute_at(safe_store, command, execute_at)
        return CommitOutcome.REDUNDANT

    command.route = route if command.route is None else command.route.union(route)
    if partial_txn is not None and command.partial_txn is None:
        command.partial_txn = partial_txn
    if partial_deps is not None and command.partial_deps is None:
        command.partial_deps = partial_deps
    if command.partial_deps is None:
        return CommitOutcome.INSUFFICIENT
    command.execute_at = execute_at
    command.writes = writes
    command.result = result
    if command.waiting_on is None:
        initialise_waiting_on(safe_store, command)
    command.set_save_status(SaveStatus.PRE_APPLIED)
    _observe_transition(safe_store, command)
    safe_store.journal_save(command)
    safe_store.register_witness(command, InternalStatus.COMMITTED)
    maybe_execute(safe_store, command, always_notify_listeners=True)
    return CommitOutcome.SUCCESS


# ---------------------------------------------------------------------------
# Execution frontier (Commands.java:617-804)
# ---------------------------------------------------------------------------

def initialise_waiting_on(safe_store: SafeCommandStore, command: Command) -> None:
    """Build the WaitingOn frontier from partial_deps (Commands.java:688):
    include every dep not yet locally applied/invalidated whose executeAt is (or may
    yet be) before ours; register as listener on each."""
    if command.waiting_on is not None:
        return
    execute_at = command.execute_at
    waiting = set()
    deferred = False
    local_ranges = safe_store.store.all_ranges()
    deps = command.partial_deps.slice(local_ranges) if command.partial_deps is not None else Deps.NONE
    redundant = safe_store.redundant_before()
    # fast path: any dep below the store-wide minimum LOCAL fence is redundant
    # without a per-dep participants scan
    min_fence = None
    have_fence = True
    for rng in local_ranges:
        f = redundant.min_fence_over(rng, local_only=True)
        if f is None:
            have_fence = False
            break
        min_fence = f if min_fence is None or f < min_fence else min_fence
    min_fence = min_fence if have_fence else None
    awaits_only = command.txn_id.awaits_only_deps
    dep_ids = deps.txn_ids()
    engine = safe_store.store.batch_engine
    blocks_mask = decided_mask = None
    if engine is not None and len(dep_ids) >= ENGAGE_FLOOR:
        # the columnar frontier-init pass: one vectorized gather answers
        # _still_blocks for every dep the mirror can decide (terminal rows,
        # decided executeAt orderings); the rest fall through to the scalar
        # predicate.  Dep states are stable across this loop (it mutates
        # only the waiter and creates NOT_DEFINED stubs), so the mask
        # computed up front stays valid.
        blocks_mask, decided_mask = engine.still_blocks_mask(
            dep_ids, execute_at, awaits_only)
    for i, dep_id in enumerate(dep_ids):
        if dep_id == command.txn_id:
            continue
        if awaits_only and command.txn_id < dep_id:
            # fences (exclusive sync points) take deps only on LOWER ids: a
            # higher-id dep is structurally impossible and waiting on one
            # builds a cycle with the later fence that (correctly) waits on
            # us — defense in depth against any deps path that computed at a
            # bound above txnId
            continue
        # removeRedundantDependencies (Commands.java:704-705): deps below the
        # locally-redundant bound have applied (or are subsumed by bootstrap)
        if min_fence is not None and dep_id < min_fence:
            # the fence may be a bootstrap mark whose fetch has not landed:
            # without a local-apply proof the dep's write is not provably in
            # the local snapshot — note it (the read-serve path re-checks)
            _note_elided_unless_applied(safe_store, command, dep_id)
            continue
        dep_parts = deps.participants(dep_id)
        if dep_parts is not None and redundant.is_locally_redundant(dep_id, dep_parts):
            _note_elided_unless_applied(safe_store, command, dep_id)
            continue
        if dep_parts is not None and not _participates_at_epoch(
                safe_store, dep_id, dep_parts,
                max_epoch=execute_at.epoch if execute_at is not None else None):
            # this store owns none of the dep's footprint at ANY epoch the
            # dep can execute in — [dep.txnId.epoch, OUR executeAt.epoch]:
            # the dep executes before us, so its executeAt epoch is bounded
            # by ours — so its Apply will never be addressed HERE and
            # waiting would deadlock topology-spanning commands
            # (StoreParticipants execution gating).  Judging by the TXN
            # epoch alone dropped epoch-spanning slow-path deps at stores
            # that joined the range by the EXECUTION epoch — their applies
            # DO arrive here, and executing without them served reads
            # missing their writes (the 15-node elastic cycle: op 186's
            # read of k58 missed op 181, txn epoch 5 executed at epoch 9).
            _note_elided_unless_applied(safe_store, command, dep_id)
            continue
        if bool(blocks_mask[i]) if (decided_mask is not None
                                    and decided_mask[i]) \
                else _still_blocks(safe_store, command, dep_id, execute_at):
            waiting.add(dep_id)
            dep = safe_store.get_or_create(dep_id)
            dep.listeners.add(command.txn_id)
            deferred |= _maybe_defer_execute_at_least(safe_store, command, dep,
                                                     notify=False)
        else:
            dep = safe_store.store.commands.get(dep_id)
            executes_after = dep is not None \
                and dep.has_been(Status.PRE_COMMITTED) \
                and dep.effective_execute_at() is not None \
                and execute_at is not None \
                and dep.effective_execute_at() >= execute_at
            if not executes_after:
                _note_elided_unless_applied(safe_store, command, dep_id)
    command.waiting_on = WaitingOn(waiting)
    # mirror the wait edges into the resolver's execution-frontier plane
    safe_store.store.resolver.register_waiting(command.txn_id, waiting)
    if engine is not None:
        engine.note_waiting(command)   # deps row pointer (frontier width)
    if deferred:
        safe_store.notify_listeners(command)


def _participates_at_epoch(safe_store: SafeCommandStore, dep_id: TxnId,
                           dep_parts, max_epoch: Optional[int] = None) -> bool:
    """Does this store own any of the dep's footprint at any epoch the dep
    can apply in — [dep.txnId.epoch, max_epoch]?  ``max_epoch`` is the
    WAITER's execution epoch (the dep executes before the waiter, so its
    executeAt epoch is bounded by it); None restricts to the dep's own
    epoch.  Applies are addressed to the replicas of every epoch in
    [txnId.epoch, executeAt.epoch], so ownership anywhere in the window
    means the apply may land here and is worth waiting for."""
    store = safe_store.store
    hi = dep_id.epoch if max_epoch is None else max(dep_id.epoch, max_epoch)
    owned = Ranges.EMPTY
    for e in range(dep_id.epoch, hi + 1):
        owned = owned.union(store.ranges_at(e))
    if not owned:
        return False
    keys, rngs = dep_parts
    for k in keys:
        if owned.contains(k):
            return True
    for r in rngs:
        if owned.intersects(Ranges.of(r)):
            return True
    return False


def _maybe_defer_execute_at_least(safe_store: SafeCommandStore, waiter: Command,
                                  dep: Command, notify: bool = True) -> bool:
    """An awaits-only-deps waiter (sync point) whose dep decided an executeAt
    AFTER the waiter's id defers its effective execution past that dep
    (updateExecuteAtLeast, Commands.java:727-728).  Ordinary txns then order
    against the DEFERRED time and stop waiting on the sync point — breaking
    the fence→later-write→earlier-write→fence wait cycle."""
    if not waiter.txn_id.awaits_only_deps:
        return False
    if not dep.has_been(Status.PRE_COMMITTED) or dep.execute_at is None:
        return False
    if dep.execute_at > waiter.txn_id.as_timestamp():
        cur = waiter.execute_at_least
        if cur is None or dep.execute_at > cur:
            waiter.execute_at_least = dep.execute_at
            if notify:
                # waiters ordering against us must re-evaluate
                safe_store.notify_listeners(waiter)
            return True
    return False


def _still_blocks(safe_store: SafeCommandStore, command: Command, dep_id: TxnId,
                  execute_at: Timestamp) -> bool:
    from ..primitives.timestamp import TxnKind as _TK
    if dep_id.kind is _TK.READ:
        # MVCC execution rule: a read-only dependency WRITES nothing, so its
        # local apply contributes nothing to any later txn's snapshot — and
        # the read itself stays servable at its own executeAt from the
        # timestamped store no matter what applies above it (the same
        # property the applied-copy exclusive-snapshot serve relies on).
        # The reference (ReadData over a non-versioned store) must order
        # writes after reads; here that edge is pure liveness surface: under
        # churn it is THE seed-6 wedge — a client range read that cannot
        # assemble partial coverage blocks every later write AND the
        # bootstrap fence sync points, whose pending ranges are exactly why
        # the read lacks coverage.  Reads still wait on THEIR deps (writes
        # below their snapshot); nothing waits on reads.
        return False
    if dep_id in safe_store.store.cold:
        # eviction admits only terminal commands (applied/invalidated/
        # truncated), none of which block — answering from the cold set
        # avoids a full journal decode per dep on every WaitingOn build
        return False
    dep = safe_store.get_if_exists(dep_id)
    if dep is None:
        return True  # unwitnessed: must wait for it to commit locally
    if dep.save_status in (SaveStatus.APPLIED, SaveStatus.INVALIDATED) \
            or dep.save_status.is_truncated:
        return False
    if dep.has_been(Status.PRE_COMMITTED) and not command.txn_id.awaits_only_deps:
        dep_ea = dep.effective_execute_at()
        # >= not >: genuine executeAts of distinct txns are never equal
        # (unique_now hlc+node tiebreak), so equality can only mean the dep
        # is a sync point DEFERRED to exactly our executeAt
        # (updateExecuteAtLeast adopting dep.execute_at) — it waits on OUR
        # apply and executes after us.  Treating the tie as blocking built a
        # permanent write<->fence wait cycle (the PRE_APPLIED-backlog stall
        # root, found by the restart-matrix stall watchdog: W [STABLE]
        # waiting_on=[X], X [PRE_APPLIED] waiting_on=[W]).
        if dep_ea is not None and dep_ea >= execute_at:
            return False  # dep executes (or was deferred to execute) after us
    return True


def _writes_cover_owned_footprint(store, footprint, written_keys) -> bool:
    """Does a locally-APPLIED dep's writes slice (``written_keys``: routing
    keys, or None when the payload is stripped) cover every part of its
    footprint this store owns (in ANY epoch)?  "Applied" is per-SLICE: a
    store that held only part of the dep's payload (it owned only part of
    the footprint at the dep's epochs) applied only that part — a slice it
    adopted LATER never got its write (that arrives with the bootstrap
    fetch), and the partial apply must not certify it (seed-6 trajectory:
    node 1's other-key-only APPLY of op 12 certified the k285 write it
    never held, and a read served over the pending fetch missed v12.1)."""
    if footprint is None:
        return False
    from ..primitives.keys import Ranges as _Ranges
    owned = store.all_ranges()
    if isinstance(footprint, _Ranges):
        # range-domain writes carry no per-key payload to compare; only an
        # empty owned overlap is trivially covered
        return not owned.intersects(footprint)
    for key in footprint:
        rk = key.to_routing() if hasattr(key, "to_routing") else key
        if owned.contains(rk) and (written_keys is None
                                   or rk not in written_keys):
            return False
    return True


def _written_routing_keys(writes):
    if writes is None:
        return None
    # memoized on the (immutable) Writes object: the writes-cover check runs
    # once per dep per waiter on the frontier-init/elision path, and
    # rebuilding this set per call was a measured wall slice
    rk = writes._rk
    if rk is None:
        rk = writes._rk = {k.to_routing() if hasattr(k, "to_routing") else k
                           for k in writes.keys}
    return rk


def _dep_full_footprint(cmd):
    """The dep's FULL footprint for the writes-cover check: the route (which
    travels whole) — the partial_txn is SLICED to what this store received,
    so judging coverage by it would certify exactly the slices the store
    never held (the hole the check exists to close)."""
    if cmd.route is not None:
        return cmd.route.participants()
    return cmd.partial_txn.keys if cmd.partial_txn is not None else None


def _dep_applied_locally(store, dep_id: TxnId) -> bool:
    """Is ``dep_id``'s write provably in THIS store's data (or provably
    nonexistent)?  APPLIED / applied_locally means the dependency-ordered
    apply ran here — for the SLICE the store held (checked against the
    writes payload, see _writes_cover_owned_footprint); INVALIDATED writes
    nothing.  Cold deps answer from their terminal summaries (which carry
    applied_locally and the writes cover) without a fault-in.  A dep that
    applied while still carrying unresolved elisions of its own does NOT
    count: its write landed but the fence floor-dep it stood in for may
    cover predecessors that did not (transitive contamination)."""
    from .status import SaveStatus as _SS
    if dep_id in store.cold:
        summary = store.cold_summaries.get(dep_id)
        if summary is None:
            return False
        if summary.save_status is _SS.INVALIDATED:
            # an INVALIDATED write/read never happened — clean; an
            # INVALIDATED sync point is an ABANDONED fence whose barrier
            # claim never materialized, yet it may have been handed out as
            # the floor dep standing in for writes elided below its
            # (pre-marked) bootstrapped_at bound — those writes are
            # unaccounted, so the floor stays unresolved (seed-6 v9..v85
            # prefix loss rode an abandoned-fence floor removal)
            return not dep_id.kind.is_sync_point
        applied = summary.save_status is _SS.APPLIED or summary.applied_locally
        if not applied:
            return False
        if dep_id.is_write:
            # a WRITE dep resolves on its own write's local presence; its
            # OWN elided predecessors are NOT inherited — any of them that
            # conflict with the waiter below the waiter's executeAt are in
            # the WAITER's deps (directly or via floors) and accounted for
            # separately.  Inheriting them built never-resolving taint
            # chains through applied writes (the seed-8 liveness wedge).
            return _writes_cover_owned_footprint(store, summary.full_footprint,
                                                 summary.written_keys)
        # sync points write nothing: applied with no unresolved elisions IS
        # the (per-store) barrier claim — everything below it on its local
        # slice applied here.  A floor with unresolved elisions stays
        # unresolved: it STANDS IN for exactly those writes.
        return not summary.elided_unapplied
    dep = store.commands.get(dep_id)
    if dep is None:
        return False
    if dep.save_status is _SS.INVALIDATED:
        # abandoned fences are NOT clean: see the cold-summary branch above
        return not dep_id.kind.is_sync_point
    applied = dep.save_status is _SS.APPLIED or dep.applied_locally
    if not applied:
        return False
    if dep_id.is_write:
        return _writes_cover_owned_footprint(store, _dep_full_footprint(dep),
                                             _written_routing_keys(dep.writes))
    return not dep.elided_unapplied


def _note_elided_unless_applied(safe_store: SafeCommandStore, command: Command,
                                dep_id: TxnId) -> None:
    """Record a WaitingOn drop that lacks a local-apply proof.  Read deps
    never matter (they contribute nothing to any snapshot); WRITES do, and
    so do SYNC POINTS — a fence floor dep STANDS IN for the write deps the
    deps calculation elided below it, so dropping a fence whose own
    elisions are unresolved inherits the risk (transitive: the seed-6
    v12.1 loss rode exactly this — the bootstrap fence replaced the write
    in the waiter's deps and was itself applied mid-fetch)."""
    from ..primitives.timestamp import TxnKind as _TK
    if dep_id.kind is _TK.READ:
        return
    if _dep_applied_locally(safe_store.store, dep_id):
        return
    # ASSIGN-ONLY (never mutate in place): the journal's identity-diff skip
    # keys on object identity, and an in-place add would silently journal a
    # stale set (harness/journal.py _FIELDS note)
    prev = command.elided_unapplied or frozenset()
    if dep_id not in prev:
        command.elided_unapplied = set(prev) | {dep_id}


def update_dependency_and_maybe_execute(safe_store: SafeCommandStore, waiter: Command,
                                        dep: Command) -> None:
    """Called when ``dep`` changes status and ``waiter`` is listening
    (Commands.java:777)."""
    if waiter.waiting_on is None or not waiter.waiting_on.is_waiting_on(dep.txn_id):
        return
    _maybe_defer_execute_at_least(safe_store, waiter, dep)
    if not _still_blocks(safe_store, waiter, dep.txn_id, waiter.execute_at):
        applied = dep.save_status is SaveStatus.APPLIED or dep.save_status.is_truncated
        dep_ea = dep.effective_execute_at()
        executes_after = dep.has_been(Status.PRE_COMMITTED) \
            and dep_ea is not None and waiter.execute_at is not None \
            and dep_ea >= waiter.execute_at \
            and dep.save_status is not SaveStatus.APPLIED \
            and not dep.save_status.is_truncated
        if not executes_after:
            _note_elided_unless_applied(safe_store, waiter, dep.txn_id)
        waiter.waiting_on.remove(dep.txn_id, applied)
        safe_store.store.resolver.remove_waiting(waiter.txn_id, dep.txn_id)
        # (the columnar mirror's ``waiting`` column deliberately keeps the
        # INIT-time frontier width — it is a layout/diagnostic plane, no
        # decision reads it, and a per-edge refresh here was measured pure
        # overhead on the release fan-out path)
        dep.listeners.discard(waiter.txn_id)
        maybe_execute(safe_store, waiter, always_notify_listeners=False)


def _root_blocker(safe_store: SafeCommandStore, command: Command):
    """Walk the LOCAL dependency graph down from ``command`` to a root blocker:
    a txn that is not itself locally waiting on anything (unwitnessed here,
    or committed/stable with a drained frontier but never applied).  Escalating
    the ROOT is what makes blocked-progress resolution converge — driving an
    intermediate (itself-blocked) dependency just re-commits it without
    unblocking anyone (the reference's NotifyWaitingOn graph walk,
    Commands.java:617-775).  Returns (root_txn_id, parent_command) where
    ``parent`` is the waiter one level above the root (for route/participant
    hints)."""
    cur = command
    visited = {command.txn_id}
    while True:
        nxt_id = None
        for cand in cur.waiting_on.waiting:
            if cand not in visited:
                nxt_id = cand
                break
        if nxt_id is None:
            # fully-visited cycle: fall back to any member
            return next(iter(cur.waiting_on.waiting)), cur
        visited.add(nxt_id)
        nxt = safe_store.get_if_exists(nxt_id)
        if nxt is None or nxt.waiting_on is None or not nxt.waiting_on.is_waiting():
            return nxt_id, cur
        cur = nxt


def maybe_execute(safe_store: SafeCommandStore, command: Command,
                  always_notify_listeners: bool,
                  from_frontier: bool = False) -> bool:
    """Fire ReadyToExecute / Applying when the frontier drains (Commands.java:617).

    ``from_frontier``: the call comes from the device-frontier release task
    (frontier-driven execution mode) — bypass the exec_deferred parking."""
    if command.save_status not in (SaveStatus.STABLE, SaveStatus.PRE_APPLIED):
        if always_notify_listeners:
            safe_store.notify_listeners(command)
        return False
    if command.waiting_on is not None and command.waiting_on.is_waiting():
        # capture the blocking dep BEFORE notifying: notification can re-enter
        # this command (a dependent applies, notifying its listeners, which may
        # include us) and drain waiting_on under our feet
        blocking, parent = _root_blocker(safe_store, command)
        if always_notify_listeners:
            safe_store.notify_listeners(command)
            if command.save_status not in (SaveStatus.STABLE, SaveStatus.PRE_APPLIED):
                return False  # re-entrant notification already advanced us
        if command.waiting_on.is_waiting():
            participants = parent.partial_deps.participants(blocking) \
                if parent is not None and parent.partial_deps is not None else None
            route = parent.route if parent is not None else command.route
            safe_store.progress_log().waiting(blocking, None, route, participants)
            return False
        # frontier drained during notification but no one executed us: fall through

    if command.save_status is SaveStatus.STABLE:
        # frontier-driven execution mode (SURVEY §7 stage 8: execute-phase
        # topological wait on device): when enabled, an INDEXED key-domain
        # txn whose event-driven frontier just drained is NOT fired inline —
        # it parks in exec_deferred and only the device frontier
        # (kahn_frontier over the resolver's mirrored wait graph) releases
        # it.  The event path still does all bookkeeping, so a frontier that
        # misses a ready txn stalls the burn (loud parity failure) rather
        # than executing out of order.
        store = safe_store.store
        if store.frontier_exec and not from_frontier \
                and store.resolver.is_indexed(command.txn_id):
            store.exec_deferred.add(command.txn_id)
            return False
        command.set_save_status(SaveStatus.READY_TO_EXECUTE)
        _observe_transition(safe_store, command)
        safe_store.progress_log().ready_to_execute(command)
        safe_store.notify_listeners(command)
        return True

    # PRE_APPLIED -> Applying -> Applied
    command.set_save_status(SaveStatus.APPLYING)
    _observe_transition(safe_store, command)
    _apply_writes(safe_store, command)
    return True


def _apply_writes(safe_store: SafeCommandStore, command: Command) -> None:
    """writes.apply + postApply (Commands.java:587-597)."""
    ranges = safe_store.store.all_ranges()
    t0 = safe_store.time().now_micros()

    def post_apply(_=None, failure=None):
        if failure is not None:
            safe_store.agent().on_uncaught_exception(failure)
            return
        # per-key execution registers: the NORMAL (dependency-ordered) apply
        # path validates write monotonicity (TimestampsForKeys.java:36-69)
        if command.writes is not None and not command.writes.is_empty():
            tfk = safe_store.store.timestamps_for_key
            for key in command.writes.keys:
                rk = key.to_routing() if hasattr(key, "to_routing") else key
                if ranges.contains(rk):
                    tfk.update_last_execution(safe_store, key,
                                              command.execute_at, True,
                                              txn_id=command.txn_id)
        command.set_save_status(SaveStatus.APPLIED)
        _observe_transition(safe_store, command)
        command.applied_locally = True
        safe_store.journal_save(command)
        safe_store.register_witness(command, InternalStatus.APPLIED)
        # an applied exclusive sync point waited on everything before it on its
        # ranges: all of that has now locally applied (RedundantBefore advance)
        if command.txn_id.kind is TxnKind.EXCLUSIVE_SYNC_POINT \
                and command.route is not None:
            participants = command.route.participants()
            from ..primitives.keys import Ranges as _Ranges
            if isinstance(participants, _Ranges):
                safe_store.mark_locally_applied_before(command.txn_id, participants)
        safe_store.progress_log().executed(command, _is_progress_shard(safe_store, command))
        agent = safe_store.agent()
        agent.metrics_events_listener().on_applied(command, t0)
        safe_store.notify_listeners(command)

    if command.writes is None or command.writes.is_empty():
        post_apply()
    else:
        command.writes.apply_to(safe_store, ranges).begin(post_apply)


# ---------------------------------------------------------------------------
# Journal replay (node restart; the reference's Journal.replay -> Commands load)
# ---------------------------------------------------------------------------

# save_status -> witness-plane registration at replay, mirroring what the live
# transition path registered (ACCEPTED_INVALIDATE / PRE_COMMITTED / ERASED are
# not indexed on the live path either; PRE_APPLIED registers COMMITTED exactly
# as apply_ does pre-execution)
_REPLAY_WITNESS = {
    SaveStatus.PRE_ACCEPTED: InternalStatus.PREACCEPTED,
    SaveStatus.ACCEPTED: InternalStatus.ACCEPTED,
    SaveStatus.COMMITTED: InternalStatus.COMMITTED,
    SaveStatus.STABLE: InternalStatus.STABLE,
    SaveStatus.PRE_APPLIED: InternalStatus.COMMITTED,
    SaveStatus.APPLIED: InternalStatus.APPLIED,
    SaveStatus.TRUNCATED_APPLY: InternalStatus.APPLIED,
    SaveStatus.INVALIDATED: InternalStatus.INVALIDATED,
}


def _replay_integrity_problem(command: Command) -> Optional[str]:
    """Structural validation of one journal-rebuilt command BEFORE it touches
    any index: a record that passed its checksum can still decode to state
    replay cannot execute (field-level damage, or a harness bug).  Returns a
    description of the problem, or None when the command is installable.
    Conservative: only conditions replay itself depends on are checked."""
    status = command.save_status
    if not isinstance(status, SaveStatus):
        return f"save_status decoded to {type(status).__name__}"
    if status in (SaveStatus.STABLE, SaveStatus.PRE_APPLIED):
        # pass 2 re-derives the execution frontier from these
        if command.execute_at is None:
            return f"{status.name} without execute_at"
        if command.partial_deps is None:
            return f"{status.name} without partial_deps"
        if command.route is None:
            return f"{status.name} without route"
        if command.partial_txn is None and not command.txn_id.kind.awaits_only_deps:
            return f"{status.name} without partial_txn"
    elif command.has_been(Status.PRE_COMMITTED) and not status.is_truncated \
            and status is not SaveStatus.INVALIDATED \
            and command.execute_at is None:
        return f"{status.name} without execute_at"
    return None


def install_quarantine_tombstone(safe_store: SafeCommandStore,
                                 txn_id: TxnId) -> Command:
    """Replace journal-lost state with an ERASED tombstone (the truncated
    tier).  The distinction is load-bearing for evidence soundness: an
    absent command answers recovery/inference with "never witnessed", and a
    quorum of quarantined replicas then PROVES a false negative — the
    durability-watermark ``invalid_if_undecided`` inference invalidated an
    applied-at-UNIVERSAL txn on every replica that asked.  A truncated
    tombstone instead answers "decided but unknowable": recovery gives up
    (Lost-class), preaccept refuses resurrection, and the quarantine
    bootstrap streams the actual outcome's data from peers."""
    command = Command(txn_id)
    command.save_status = SaveStatus.ERASED
    safe_store.store.commands[txn_id] = command
    _observe_transition(safe_store, command)
    safe_store.journal_save(command)
    return command


def replay_journal(safe_store: SafeCommandStore, rebuilt,
                   on_damaged=None) -> None:
    """Install journal-reconstructed commands into a FRESH store (restart after
    crash).  Volatile state was lost with the process: commands arrive at
    their durable tier (STABLE / PRE_APPLIED at most transient-wise) with no
    waiting_on and no listeners.  Two passes keep the planes consistent:

    1. install + re-index every command (cfk / resolver / range table /
       max-conflicts via register_witness; per-key execution registers for
       terminal applied writes, merged monotonically — replay order is
       arbitrary, so no write-order validation);
    2. re-derive the execution frontier (initialise_waiting_on) for
       STABLE / PRE_APPLIED commands and resume execution.  Deps that are
       unknown locally (their Commit/Apply was in flight to the dead node)
       stay in waiting_on; maybe_execute reports them to the progress log's
       blocked-dependency machinery, which fetches or recovers them — that is
       how a restarted replica catches up past what its journal predates.

    Corruption handling: each command is structurally validated BEFORE
    touching any index.  A damaged one is reported through
    ``on_damaged(txn_id, command, problem)`` — the restart path quarantines
    its journal entries and bootstraps its footprint — and replaced by an
    ERASED tombstone via ``install_quarantine_tombstone``: the replica's
    knowledge was LOST, not absent, so it must answer "truncated /
    unknowable", never "never witnessed" (a quarantined replica presenting
    watermark-based non-witness evidence got an APPLIED txn invalidated
    cluster-wide).  With no handler the damage halts replay loudly (a
    silently-installed broken command is how replicas diverge)."""
    store = safe_store.store
    damaged: set = set()
    for txn_id, command in rebuilt.items():
        problem = _replay_integrity_problem(command)
        if problem is not None:
            check_state(on_damaged is not None,
                        "journal replay of %s decoded damaged state: %s",
                        txn_id, problem)
            damaged.add(txn_id)
            on_damaged(txn_id, command, problem)
            install_quarantine_tombstone(safe_store, txn_id)
            continue
        # NOT_DEFINED records (e.g. an InformOfTxn-created stub) install too —
        # the journal tracks them, so the store must keep tracking them or the
        # end-of-burn persistence check reads the gap as an untracked erasure
        store.commands[txn_id] = command
        _observe_transition(safe_store, command)   # timeline: replayed tier
        status = _REPLAY_WITNESS.get(command.save_status)
        if status is not None:
            safe_store.register_witness(command, status)
        if command.save_status in (SaveStatus.APPLIED, SaveStatus.TRUNCATED_APPLY) \
                and command.writes is not None and not command.writes.is_empty() \
                and command.execute_at is not None:
            # empty-writes gate mirrors the live apply paths: a range READ's
            # Writes carries its read footprint (Ranges) in .keys
            _merge_applied_writes(store, command.writes, command.execute_at)
    for command in list(rebuilt.values()):
        if command.txn_id in damaged:
            continue
        if command.save_status in (SaveStatus.STABLE, SaveStatus.PRE_APPLIED):
            initialise_waiting_on(safe_store, command)
            maybe_execute(safe_store, command, always_notify_listeners=False)


# ---------------------------------------------------------------------------
# Truncation / erasure (Commands.java:824-930, Cleanup.java)
# ---------------------------------------------------------------------------

def truncate(safe_store: SafeCommandStore, command: Command, cleanup) -> None:
    """Apply a Cleanup decision: strip payloads, downgrade to a truncated
    SaveStatus.  TRUNCATE_WITH_OUTCOME keeps writes/result for peers that may
    still need the outcome; ERASE drops everything but the tombstone.

    DATA-GAP GUARD: truncating a WRITE that never applied LOCALLY leaves a
    hole in this replica's data (waiters drop the dep and execute without its
    writes; the cluster truncated it so its Apply will never arrive) — the
    store is marked stale over the txn's local footprint (reads redirect to
    peers) and a peer-snapshot heal is scheduled.  The hostile 1000-op burns
    caught readers observing the hole without this."""
    from .durability import Cleanup
    # committed-or-later only: truncating a NEVER-COMMITTED write (the
    # below-fence settled/erased case) leaves no hole — no writes exist
    # anywhere — and must not refuse reads or launch heals
    if command.txn_id.is_write and command.has_been(Status.PRE_COMMITTED) \
            and not command.has_been(Status.APPLIED) \
            and command.save_status is not SaveStatus.INVALIDATED \
            and command.route is not None:
        local_parts = command.route.participants().slice(
            safe_store.current_ranges())
        if len(local_parts):
            if command.writes is not None and command.execute_at is not None:
                # the outcome is retained (TRUNCATE_WITH_OUTCOME arriving
                # here, or an adopted outcome): land its OWN writes locally
                # before anything else — no network needed for this txn's gap
                command.writes.apply_to(safe_store, safe_store.store.all_ranges())
                _merge_applied_writes(safe_store.store, command.writes,
                                      command.execute_at)
            # predecessors may be missing too (that is WHY this txn never
            # applied): stale-mark + peer-snapshot heal over the footprint
            from ..messages.status_messages import _heal_store_gaps
            _heal_store_gaps(safe_store.store.node, safe_store, local_parts)
    if command.save_status is SaveStatus.INVALIDATED:
        # invalidation is terminal: strip any payloads left from earlier phases
        command.partial_txn = None
        command.partial_deps = None
        command.waiting_on = None
        safe_store.notify_listeners(command)
        command.listeners.clear()
        return
    command.partial_deps = None
    command.waiting_on = None
    if cleanup is Cleanup.TRUNCATE_WITH_OUTCOME:
        command.partial_txn = None
        command.set_save_status(SaveStatus.TRUNCATED_APPLY)
    elif cleanup is Cleanup.TRUNCATE:
        command.partial_txn = None
        command.writes = None
        command.result = None
        command.set_save_status(SaveStatus.TRUNCATED_APPLY)
    elif cleanup is Cleanup.ERASE:
        command.partial_txn = None
        command.writes = None
        command.result = None
        command.set_save_status(SaveStatus.ERASED)
    _observe_transition(safe_store, command)
    safe_store.journal_save(command)
    # waiters must LEARN of the truncation (a truncated dep no longer blocks,
    # _still_blocks) — clearing their registrations silently would strand them
    # in waiting_on forever
    safe_store.notify_listeners(command)
    command.listeners.clear()


# ---------------------------------------------------------------------------
# Durability (Commands.java:927)
# ---------------------------------------------------------------------------

def set_durability(safe_store: SafeCommandStore, txn_id: TxnId, durability: Durability,
                   route: Optional[Route] = None,
                   execute_at: Optional[Timestamp] = None) -> Command:
    command = safe_store.get_or_create(txn_id)
    if route is not None and command.route is None:
        command.route = route
    if execute_at is not None and not command.has_been(Status.PRE_COMMITTED):
        command.execute_at = execute_at
    if durability > command.durability:
        was = command.durability
        command.durability = durability
        safe_store.progress_log().durable(command)
        if durability >= Durability.UNIVERSAL and was < Durability.UNIVERSAL:
            # the outcome is applied at EVERY replica (the coordinator saw
            # all Apply acks — inform_universal): widen the per-key elision
            # gate NOW instead of at the next range durability round — this
            # is what keeps per-op deps cost flat with history.  MAJORITY is
            # NOT sufficient: a later txn's elided deps can reach the very
            # replica the majority missed, whose local apply order then
            # silently loses the elided txn (round-5 stale-cascade)
            safe_store.mark_txn_durable(command)
    safe_store.journal_save(command)   # route/execute_at may have changed too
    return command


# ---------------------------------------------------------------------------

def _check_consistent_execute_at(safe_store: SafeCommandStore, command: Command,
                                 execute_at: Timestamp) -> None:
    if command.execute_at is not None and execute_at is not None \
            and command.has_been(Status.PRE_COMMITTED) and command.execute_at != execute_at:
        safe_store.agent().on_inconsistent_timestamp(command, command.execute_at, execute_at)


def _is_progress_shard(safe_store: SafeCommandStore, command: Command) -> bool:
    """Is this store the home (progress) shard for the txn?"""
    return (command.route is not None
            and safe_store.store.current_ranges().contains(command.route.home_key))
