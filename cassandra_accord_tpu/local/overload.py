"""Overload robustness primitives: admission control and retry budgets.

Metastable failures (KNOWN_ISSUES rounds 4 and 7) are load problems, not
protocol bugs: offered work above capacity — client arrivals, recovery
retries, bootstrap re-fencing — feeds on itself until goodput collapses and
STAYS collapsed after the trigger passes.  The defense here has two local
mechanisms, both deterministic and RNG-stream-free:

- ``AdmissionController``: watermark hysteresis over a composite per-node
  load signal (outstanding RPC callbacks at the node's sink + the
  command stores' ``unapplied_pressure``, the PR-7 signal).  Over the high
  watermark the node sheds NEW work with a fast explicit ``Overloaded``
  nack — the caller learns in one round-trip what a timeout would have
  taken seconds to say — and readmits only once load drains below the low
  watermark, so the verdict doesn't flap per message.

- ``TokenBucket``: a sim-time token bucket whose refill rate carries
  deterministic hash-derived jitter (golden-ratio mixing, the same
  construction as ``backoff_timeout_us``) so co-resident buckets never
  phase-lock into a retry herd.  It consumes NO RNG stream: with the
  budgets off, trajectories are byte-identical to the pre-budget tree, and
  with them on, two same-seed runs are byte-identical to each other.

Both are constructed only when their ``LocalConfig`` knob is on; the
default-off path allocates nothing and touches nothing.
"""
from __future__ import annotations

_GOLD = 0x9E3779B97F4A7C15
_MIX = 0xD1B54A32D192ED03
_MASK = 0xFFFFFFFFFFFFFFFF


def hash_jitter(salt: int, n: int, frac: float) -> float:
    """Deterministic jitter in ``[-frac, +frac)`` for draw ``n`` of stream
    ``salt`` — golden-ratio hash mixing, no RNG stream consumed (the
    ``backoff_timeout_us`` construction, recentered around zero)."""
    h = (salt * _GOLD + (n + 1) * _MIX) & _MASK
    return frac * (2.0 * ((h >> 40) / float(1 << 24)) - 1.0)


class TokenBucket:
    """Deterministic sim-time token bucket with hash-jittered refill.

    ``try_acquire(now_s)`` lazily refills from the elapsed sim-time, takes a
    token if one is available, and counts the denial otherwise — callers
    defer denied work to their next natural cadence (poll tick, retry rung)
    rather than rescheduling, which is what de-herds the retry surfaces."""

    __slots__ = ("rate", "burst", "jitter", "salt", "tokens", "last_s",
                 "refills", "denied", "granted")

    def __init__(self, rate_per_s: float, burst: float,
                 jitter_frac: float = 0.0, salt: int = 0,
                 now_s: float = 0.0):
        assert rate_per_s > 0.0 and burst > 0.0
        self.rate = rate_per_s
        self.burst = burst
        self.jitter = jitter_frac
        self.salt = salt
        self.tokens = burst          # start full: the first burst is free
        self.last_s = now_s
        self.refills = 0
        self.denied = 0
        self.granted = 0

    def _refill(self, now_s: float) -> None:
        dt = now_s - self.last_s
        if dt <= 0.0:
            return
        self.last_s = now_s
        self.refills += 1
        rate = self.rate * (1.0 + hash_jitter(self.salt, self.refills,
                                              self.jitter))
        self.tokens = min(self.burst, self.tokens + dt * rate)

    def try_acquire(self, now_s: float, n: float = 1.0) -> bool:
        self._refill(now_s)
        if self.tokens >= n:
            self.tokens -= n
            self.granted += 1
            return True
        self.denied += 1
        return False


class AdmissionController:
    """Watermark-hysteresis admission control for one node.

    Load = outstanding reply callbacks at the node's message sink (every
    un-replied RPC this node has in flight) + the sum of per-store
    ``unapplied_pressure`` (decided-but-unapplied txns older than the age
    horizon — the execution plane visibly behind).  The composite is
    recomputed at most once per sim 100 ms (the pressure scan is O(commands);
    per-message recomputation would make admission itself the overload),
    which stays deterministic because sim-time is.

    Hysteresis: shedding starts at/above ``admission_hi`` and stops only
    at/below ``admission_lo``, so a node hovering at the watermark doesn't
    flap per message.  Only work-INITIATING requests are ever shed
    (replica-side PreAccepts; harness clients consult ``overloaded()``
    before dispatching) — never mid-protocol Commit/Apply/recovery traffic,
    which must drain for load to ever fall."""

    __slots__ = ("node", "hi", "lo", "pressure_age_s", "shedding", "nacks",
                 "sheds", "_cache_bucket", "_cache_load")

    # sim-time granularity of the load recomputation, in micros
    _RECOMPUTE_US = 100_000

    def __init__(self, node):
        cfg = node.config
        self.node = node
        self.hi = cfg.admission_hi
        self.lo = min(cfg.admission_lo, cfg.admission_hi)
        self.pressure_age_s = cfg.admission_pressure_age_s
        self.shedding = False
        self.nacks = 0               # replica-side Overloaded nacks sent
        self.sheds = 0               # client-entry sheds recorded against us
        self._cache_bucket = -1
        self._cache_load = 0

    def load(self) -> int:
        """The composite load signal, recomputed at most once per 100 sim-ms."""
        bucket = self.node.now_micros() // self._RECOMPUTE_US
        if bucket == self._cache_bucket:
            return self._cache_load
        sink = self.node.message_sink
        n = len(getattr(sink, "callbacks", ()))
        for cs in self.node.command_stores.all_stores():
            n += cs.unapplied_pressure(self.pressure_age_s)
        self._cache_bucket = bucket
        self._cache_load = n
        return n

    def overloaded(self) -> bool:
        """Update the hysteresis state from the current load and return it."""
        load = self.load()
        if self.shedding:
            if load <= self.lo:
                self.shedding = False
        elif load >= self.hi:
            self.shedding = True
        return self.shedding
