"""Per-transaction replica state.

Capability parity with ``accord.local.Command`` (Command.java:1-1824): the state a
replica holds for one TxnId — save status, route, partial txn/deps, ballots, executeAt,
writes/result, and the **WaitingOn** execution frontier initialised at Stable and
drained as dependencies apply.

The reference models each status tier as an immutable subclass; here Command is a
single mutable record mutated only inside its owning CommandStore (single-logical-
thread discipline, enforced by ``CommandStore.check_in_store``), with monotonicity
asserted on every transition.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Dict, List, Optional, Set

from ..primitives.deps import Deps
from ..primitives.route import Route
from ..primitives.timestamp import Ballot, Timestamp, TxnId
from ..primitives.txn import PartialTxn, Writes
from ..utils.invariants import Invariants, check_state
from .status import Durability, SaveStatus, Status

if TYPE_CHECKING:
    from ..api.interfaces import Result


class WaitingOn:
    """The execution frontier of a Stable command (Command.java:1225-1320): the set
    of dependency TxnIds not yet locally applied/invalidated/pruned.  The reference
    encodes this as bitsets over the deps arrays; semantics here are identical with
    explicit sets (the TPU data plane batches frontier pops in ops.deps_kernels)."""

    __slots__ = ("waiting", "applied_or_invalidated")

    def __init__(self, waiting: Set[TxnId], applied_or_invalidated: Optional[Set[TxnId]] = None):
        self.waiting = waiting
        self.applied_or_invalidated = applied_or_invalidated if applied_or_invalidated is not None else set()

    @staticmethod
    def none() -> "WaitingOn":
        return WaitingOn(set())

    def is_waiting(self) -> bool:
        return bool(self.waiting)

    def is_waiting_on(self, txn_id: TxnId) -> bool:
        return txn_id in self.waiting

    def remove(self, txn_id: TxnId, applied: bool) -> bool:
        """Returns True if removed (was present)."""
        if txn_id in self.waiting:
            self.waiting.discard(txn_id)
            if applied:
                self.applied_or_invalidated.add(txn_id)
            return True
        return False

    def __repr__(self) -> str:
        return f"WaitingOn({len(self.waiting)} pending)"


class Command:
    __slots__ = (
        "txn_id", "save_status", "durability",
        "route", "partial_txn", "partial_deps",
        "promised", "accepted_or_committed",
        "execute_at", "execute_at_least", "writes", "result",
        "waiting_on", "listeners", "applied_locally", "elided_unapplied",
    )

    def __init__(self, txn_id: TxnId):
        self.txn_id = txn_id
        self.save_status = SaveStatus.NOT_DEFINED
        self.durability = Durability.NOT_DURABLE
        self.route: Optional[Route] = None
        self.partial_txn: Optional[PartialTxn] = None
        self.partial_deps: Optional[Deps] = None
        # promised: max ballot we have promised not to go below (recovery gate)
        self.promised: Ballot = Ballot.ZERO
        # accepted_or_committed: ballot at which executeAt/deps were accepted
        self.accepted_or_committed: Ballot = Ballot.ZERO
        self.execute_at: Optional[Timestamp] = None
        # awaits-only-deps txns (sync points) with a dependency deciding a
        # LATER executeAt defer their effective local execution past it
        # (WaitingOn.Update.updateExecuteAtLeast, Commands.java:727-728) —
        # ordinary waiters order against max(execute_at, execute_at_least),
        # which is what breaks the fence<->write wait cycle
        self.execute_at_least: Optional[Timestamp] = None
        self.writes: Optional[Writes] = None
        self.result = None
        self.waiting_on: Optional[WaitingOn] = None
        # commands locally waiting on us (by TxnId) — notified on status change
        self.listeners: Set[TxnId] = set()
        # True iff the DEPENDENCY-ORDERED apply path ran here (_apply_writes):
        # every dep's write is then locally present.  Truncated-with-outcome
        # copies that adopted/landed writes out of order stay False — serving
        # a read from them requires their gap to be stale-fenced.  Journaled:
        # a cache-miss fault-in must restore it, else evicted TRUNCATED_APPLY
        # copies refuse reads they can serve and recovery livelocks return.
        self.applied_locally: bool = False
        # WRITE dependency ids dropped from this command's WaitingOn WITHOUT
        # a local-apply proof at removal time — elided below a bootstrap
        # fence (the fetch snapshot covers them, but only once it lands) or
        # truncated without applying here.  Empty/None means the frontier
        # drained entirely through local applies, so the local MVCC snapshot
        # at executeAt is COMPLETE on the footprint — the grandfathered-serve
        # condition that lets reads ignore pending-bootstrap/stale marks a
        # LATER re-fence added (the seed-6 bootstrapping-refencing wedge).
        # Entries are re-checked (and pruned) at serve time: an elided dep
        # that has SINCE applied locally (or was delivered by a completed
        # bootstrap fetch) stops being a risk.  JOURNALED (harness/journal
        # _FIELDS) so crash-restart restores it for terminal commands, and
        # snapshotted into CommandSummary so cache-miss fault-ins restore
        # it — either gap launders a tainted floor dep clean.  ASSIGN-ONLY:
        # the journal's identity-diff skip keys on object identity.
        self.elided_unapplied: Optional[Set[TxnId]] = None

    # -- status queries -----------------------------------------------------
    @property
    def status(self) -> Status:
        return self.save_status.status

    def has_been(self, status: Status) -> bool:
        return self.save_status.has_been(status)

    def is_truncated(self) -> bool:
        return self.save_status.is_truncated

    def is_stable(self) -> bool:
        return self.has_been(Status.STABLE) and not self.save_status.is_truncated \
            and self.save_status is not SaveStatus.INVALIDATED

    def known(self):
        from .status import known_for
        return known_for(self.save_status,
                         self.route is not None,
                         self.partial_txn is not None)

    # -- ballot gates (Commands.java preacceptInternal/accept/recover) -------
    def can_proceed_with(self, ballot: Ballot) -> bool:
        return self.promised <= ballot

    # -- transitions (monotonicity enforced; callers are local.commands) -----
    def set_save_status(self, new_status: SaveStatus) -> None:
        check_state(new_status.ordinal >= self.save_status.ordinal
                    or self.save_status in (SaveStatus.READY_TO_EXECUTE, SaveStatus.APPLYING),
                    "status regression %s -> %s for %s", self.save_status, new_status, self.txn_id)
        self.save_status = new_status

    def execute_at_if_known(self) -> Optional[Timestamp]:
        return self.execute_at if self.has_been(Status.PRE_COMMITTED) else None

    def effective_execute_at(self) -> Optional[Timestamp]:
        """Execution-ordering timestamp as seen by waiters: executeAt, deferred
        past execute_at_least for awaits-only-deps commands."""
        if self.execute_at_least is not None and (
                self.execute_at is None or self.execute_at_least > self.execute_at):
            return self.execute_at_least
        return self.execute_at

    def __repr__(self) -> str:
        return f"Command({self.txn_id!r}, {self.save_status.name}, @{self.execute_at!r})"
