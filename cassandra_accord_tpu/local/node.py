"""The per-process facade: clock, coordination entry, message dispatch.

Capability parity with ``accord.local.Node`` (Node.java:100-775): owns the node id,
the CAS hybrid-logical clock (``unique_now``), the TopologyManager, the CommandStores,
and the send/receive plumbing.  ``coordinate(txn)`` is the client entry point
(Node.java:573); ``receive(request, from, reply_ctx)`` the server entry point
(Node.java:705) with its wait-for-epoch gate.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Callable, Dict, List, Optional

from ..api.interfaces import (Agent, ConfigurationService, DataStore, MessageSink,
                              ProgressLog, Scheduler)
from ..primitives.keys import Keys, Ranges, RoutingKey
from ..primitives.route import Route
from ..primitives.timestamp import Ballot, Domain, Timestamp, TxnId, TxnKind
from ..primitives.txn import Txn
from ..utils import async_ as au
from ..utils.invariants import check_state
from ..utils.random import RandomSource
from ..topology.manager import EpochReady, TopologyManager
from .command_store import AgentExecutor, CommandStores, SafeCommandStore

if TYPE_CHECKING:
    from ..messages.base import Callback, Reply, Request
    from ..topology.topology import Topologies, Topology


class Node(ConfigurationService.Listener):
    def __init__(self, node_id: int, message_sink: MessageSink,
                 config_service: ConfigurationService, agent: Agent,
                 scheduler: Scheduler, data_store: DataStore,
                 random: RandomSource, now_micros: Callable[[], int],
                 num_shards: int = 1,
                 executor_factory: Optional[Callable[[int], AgentExecutor]] = None,
                 progress_log_factory: Optional[Callable[[object], ProgressLog]] = None,
                 resolver: Optional[str] = None,
                 config=None):
        from ..config import LocalConfig
        self.id = node_id
        self.message_sink = message_sink
        self.config_service = config_service
        self.agent = agent
        self.scheduler = scheduler
        self.data_store = data_store
        self.random = random
        self._now_micros = now_micros
        # one injected config object (config/LocalConfig.java); env vars are
        # the default source, the object is the override surface
        self.config: LocalConfig = config if config is not None \
            else LocalConfig.from_env()
        # deps-resolver data plane selection (impl/resolver.py): cpu|tpu|verify
        from ..impl.resolver import check_resolver_kind
        self.resolver_kind = check_resolver_kind(
            resolver if resolver is not None else self.config.resolver_kind)
        # flight recorder (observe.FlightRecorder) — assigned by the harness
        # cluster after construction; None outside instrumented runs.  Hooks
        # must stay passive (zero observer effect): they may read sim state
        # but never touch RNG, wall clock, or scheduling.
        self.observer = None
        # wall-clock profiler (observe.WallProfiler) — assigned by the
        # harness cluster like the observer; explicitly OUTSIDE the
        # determinism contract (it reads wall clocks) but still forbidden
        # from perturbing the sim (no RNG, no scheduling, no message path)
        self.profiler = None
        # overload plane (local/overload.py): the admission controller exists
        # only when its knob is on — the default-off path allocates nothing
        # and every trajectory stays byte-identical.  The counters dict is
        # plain state the retry budgets increment (budget denials) so the
        # burn harness can sum them without an observer attached.
        self.admission = None
        if self.config.admission_enabled:
            from .overload import AdmissionController
            self.admission = AdmissionController(self)
        self.overload_counters: Dict[str, int] = {"nacks": 0,
                                                  "budget_denied": 0}
        self.topology = TopologyManager(node_id)
        self._epoch_watchdogs: set = set()
        self.command_stores = CommandStores(self, num_shards, executor_factory)
        self._progress_log_factory = progress_log_factory
        self._exclusive_sync_point_listeners: List[Callable] = []
        self._last_hlc = 0
        config_service.register_listener(self)
        topo = config_service.current_topology()
        if topo is not None and topo.size > 0:
            self.on_topology_update(topo, start_sync=True)

    def slow_peers(self) -> frozenset:
        """Peers the sink's gray-failure tracker currently marks slow
        (reply-latency EWMA over threshold, or inside the post-timeout
        penalty window).  Coordinators route per-shard data reads around
        them; empty when the sink has no tracker (maelstrom, mocks)."""
        tracker = getattr(self.message_sink, "slow_replicas", None)
        if tracker is None:
            return frozenset()
        return tracker.slow_peers()

    # -- time (Node.java:335-360) -------------------------------------------
    def now_micros(self) -> int:
        return self._now_micros()

    def unique_now(self) -> Timestamp:
        hlc = max(self._now_micros(), self._last_hlc + 1)
        self._last_hlc = hlc
        return Timestamp(self.epoch(), hlc, self.id)

    def unique_now_at_least(self, at_least: Timestamp) -> Timestamp:
        hlc = max(self._now_micros(), self._last_hlc + 1, at_least.hlc + 1)
        self._last_hlc = hlc
        epoch = max(self.epoch(), at_least.epoch)
        return Timestamp(epoch, hlc, self.id)

    def epoch(self) -> int:
        return self.topology.current_epoch

    def next_txn_id(self, kind: TxnKind, domain: Domain) -> TxnId:
        ts = self.unique_now()
        return TxnId(ts.epoch, ts.hlc, self.id, kind, domain)

    def ballot_after(self, after: Optional[Ballot]) -> Ballot:
        ts = self.unique_now() if after is None else self.unique_now_at_least(after)
        return Ballot.from_timestamp(ts)

    # -- topology (Node.java:249, ConfigurationService.Listener) --------------
    def on_topology_update(self, topology: "Topology", start_sync: bool) -> au.AsyncResult:
        if self.topology.current_epoch >= topology.epoch and self.topology.current_epoch > 0:
            return au.success_result()
        first_epoch = self.topology.current_epoch == 0
        # diff against the PREVIOUS epoch only: a range this node replicated in
        # some older epoch but not the last one was written without us — it
        # must re-bootstrap like any fresh adoption
        prev_epoch = topology.epoch - 1
        prev_ranges = {store.id: store.ranges_at(prev_epoch)
                       for store in self.command_stores.all_stores()}
        self.command_stores.update_topology(topology)
        if self._progress_log_factory is not None:
            for store in self.command_stores.all_stores():
                if isinstance(store.progress_log, type(ProgressLog.NOOP)):
                    store.progress_log = self._progress_log_factory(store)

        # which stores adopted new ranges? (CommandStores.java:402-482)
        added_per_store = []
        if not first_epoch:
            for store in self.command_stores.all_stores():
                added = store.ranges_at(topology.epoch).without(
                    prev_ranges.get(store.id, Ranges.EMPTY))
                # dedup: ranges already being bootstrapped (an earlier epoch's
                # in-flight attempt) need no second concurrent attempt — under
                # rapid churn duplicates otherwise stack up unboundedly
                added = added.without(store.pending_bootstrap)
                if added:
                    added_per_store.append((store, added))

        data_ready = au.settable() if added_per_store else None

        def ready_factory(topo):
            if data_ready is None:
                return EpochReady.done(topo.epoch)
            return EpochReady(topo.epoch, data=data_ready, reads=data_ready)

        # register the epoch FIRST: bootstrap coordination needs it
        ready = self.topology.on_topology_update(topology, ready_factory)

        if added_per_store:
            from .bootstrap import Bootstrap
            bootstraps = [Bootstrap(self, store, added, topology.epoch).start()
                          for store, added in added_per_store]
            au.all_of([b.to_chain() for b in bootstraps]).begin(
                lambda _v, f: data_ready.set_failure(f) if f is not None
                else data_ready.set_success(None))

        self.config_service.acknowledge_epoch(ready, start_sync)
        return au.success_result()

    def on_remote_sync_complete(self, node_id: int, epoch: int) -> None:
        self.topology.on_remote_sync_complete(node_id, epoch)

    def on_epoch_closed(self, ranges: Ranges, epoch: int) -> None:
        self.topology.on_epoch_closed(ranges, epoch)

    def on_epoch_redundant(self, ranges: Ranges, epoch: int) -> None:
        self.topology.on_epoch_redundant(ranges, epoch)

    def truncate_topology_until(self, epoch: int) -> None:
        self.topology.truncate_until(epoch)

    # epoch-fetch watchdog: re-request an awaited epoch on this cadence, and
    # give up (failing the waiters) after this many attempts — an unreachable
    # configuration service must not stall epoch-gated work forever
    # (TopologyManager.java fetch watchdog / LocalConfig epoch timeouts)

    def with_epoch(self, epoch: int) -> au.AsyncChain:
        """Await local knowledge of ``epoch`` (Node.java:289-322)."""
        if self.topology.has_epoch(epoch):
            return au.done(None)
        self.config_service.fetch_topology_for_epoch(epoch)
        if epoch not in self._epoch_watchdogs:
            self._epoch_watchdogs.add(epoch)
            self._arm_epoch_watchdog(epoch, 0)
        return self.topology.await_epoch(epoch).to_chain()

    def _arm_epoch_watchdog(self, epoch: int, attempts: int) -> None:
        def check():
            if self.topology.has_epoch(epoch):
                self._epoch_watchdogs.discard(epoch)
                return
            if attempts + 1 >= self.config.epoch_fetch_attempts:
                self._epoch_watchdogs.discard(epoch)
                from ..coordinate.errors import Timeout
                self.topology.fail_epoch_waiters(
                    epoch, Timeout(None, f"epoch {epoch} unobtainable "
                                   f"after {attempts + 1} fetch attempts"))
                return
            self.config_service.fetch_topology_for_epoch(epoch)
            self._arm_epoch_watchdog(epoch, attempts + 1)
        self.scheduler.once(self.config.epoch_fetch_retry_s, check)

    # -- coordination entry points (Node.java:573+) ---------------------------
    def coordinate(self, txn: Txn, txn_id: Optional[TxnId] = None) -> au.AsyncResult:
        from ..coordinate.coordinate_transaction import coordinate_transaction
        from ..coordinate.ephemeral_read import coordinate_ephemeral_read
        if txn_id is None:
            txn_id = self.next_txn_id(txn.kind, txn.domain)
        start = coordinate_ephemeral_read if txn.kind is TxnKind.EPHEMERAL_READ \
            else coordinate_transaction
        result = au.settable()
        self.with_epoch(txn_id.epoch).begin(
            lambda _v, f: result.set_failure(f) if f is not None
            else start(self, txn_id, txn, result))
        return result

    def recover(self, txn_id: TxnId, txn: Txn, route: Route) -> au.AsyncResult:
        """Recover (complete or invalidate) a txn whose coordinator may have died
        (Node.java:675)."""
        from ..coordinate.recover import recover as do_recover
        result = au.settable()
        self.with_epoch(txn_id.epoch).begin(
            lambda _v, f: result.set_failure(f) if f is not None
            else do_recover(self, txn_id, txn, route, result))
        return result

    def barrier(self, seekables, min_epoch: Optional[int] = None,
                barrier_type=None) -> au.AsyncResult:
        """Coordinate a barrier over keys/ranges (Barrier.java)."""
        from ..api.interfaces import BarrierType
        from ..coordinate.barrier import barrier as do_barrier
        if barrier_type is None:
            barrier_type = BarrierType.GLOBAL_SYNC
        epoch = min_epoch if min_epoch is not None else self.epoch()
        return do_barrier(self, seekables, epoch, barrier_type)

    def sync_point(self, seekables, exclusive: bool = False,
                   blocking: bool = True, txn_id: Optional[TxnId] = None) -> au.AsyncResult:
        """Coordinate a sync point (CoordinateSyncPoint.java)."""
        from ..coordinate import sync_point as sp
        if exclusive:
            return sp.coordinate_exclusive(self, seekables, blocking=blocking,
                                           txn_id=txn_id)
        return sp.coordinate_inclusive(self, seekables, blocking=blocking)

    def on_exclusive_sync_point_applied(self, txn_id: TxnId, ranges: Ranges) -> None:
        """Hook fired when an exclusive sync point this node coordinated reaches
        quorum-applied: everything before it on ``ranges`` is shard-durable.
        Wired into the durability/GC machinery (RedundantBefore/DurableBefore)."""
        for listener in list(self._exclusive_sync_point_listeners):
            listener(txn_id, ranges)

    def add_exclusive_sync_point_listener(self, listener) -> None:
        self._exclusive_sync_point_listeners.append(listener)

    def overloaded(self) -> bool:
        """Admission verdict for NEW work (False when admission is off).
        Harness clients consult this before dispatching a coordination —
        a shed there is provably sound (no txn id was ever allocated)."""
        return self.admission is not None and self.admission.overloaded()

    # -- message dispatch (Node.java:705, :425-527) ---------------------------
    def receive(self, request: "Request", from_node: int, reply_context) -> None:
        if self.admission is not None and self._admission_nack(
                request, from_node, reply_context):
            return
        wait_for = request.wait_for_epoch()
        if wait_for > 0 and not self.topology.has_epoch(wait_for):
            self.with_epoch(wait_for).begin(
                lambda _v, f: self._process_or_fail(request, from_node, reply_context, f))
            return
        self._process_or_fail(request, from_node, reply_context, None)

    def _admission_nack(self, request: "Request", from_node: int,
                        reply_context) -> bool:
        """Shed work-INITIATING requests with a fast explicit Overloaded nack
        while over the watermark.  Only PreAccept is ever shed: it is the
        sole request class that ADDS a txn to this replica — nacking
        mid-protocol traffic (Commit/Apply/recovery/reads) would block the
        very draining that lets load fall, and a shed there would leave the
        txn's fate indeterminate.  A nacked PreAccept is safe: the
        coordinator treats it like any replica failure (quorum from the
        rest, or a CoordinationFailed the harness probes to a sound
        resolution)."""
        from ..messages.base import FailureReply, MessageType
        if request.type is not MessageType.PRE_ACCEPT_REQ:
            return False
        if not self.admission.overloaded():
            return False
        self.admission.nacks += 1
        self.overload_counters["nacks"] += 1
        obs = self.observer
        if obs is not None:
            obs.registry.counter("overload.nacks", node=self.id).inc()
        from ..coordinate.errors import Overloaded
        self.message_sink.reply(from_node, reply_context, FailureReply(
            Overloaded(getattr(request, "txn_id", None),
                       f"node {self.id} shed by admission control")))
        return True

    def _process_or_fail(self, request: "Request", from_node: int, reply_context,
                         failure: Optional[BaseException]) -> None:
        if failure is not None:
            self.agent.on_handled_exception(failure)
            self.message_sink.reply_with_unknown_failure(from_node, reply_context, failure)
            return
        profiler = self.profiler
        t_start = profiler.now() if profiler is not None else 0.0
        prov = getattr(self.observer, "provenance", None) \
            if self.observer is not None else None
        if prov is not None:
            # causal bracket: sends/transitions this handler makes become
            # children of the handler event, itself a child of the delivery
            # (RECV) that triggered it — pure bookkeeping, zero observer
            # effect like the profiler bracket below
            prov.begin_handler(self.id, type(request).__name__,
                               getattr(request, "txn_id", None),
                               self._now_micros())
        try:
            request.process(self, from_node, reply_context)
        except BaseException as e:  # noqa: BLE001 — must reply so the caller unblocks
            self.agent.on_handled_exception(e)
            self.message_sink.reply_with_unknown_failure(from_node, reply_context, e)
        finally:
            if prov is not None:
                prov.end()
            if profiler is not None:
                # per-message-type handler CPU (wall plane): measured around
                # the replica-side state machine, attributed to the txn so
                # the Perfetto export can flow-link sim spans to host slices
                profiler.on_handler(self.id, type(request).__name__,
                                    getattr(request, "txn_id", None),
                                    t_start, self._now_micros())

    def send(self, to: int, request: "Request", callback: Optional["Callback"] = None) -> None:
        if callback is None:
            self.message_sink.send(to, request)
        else:
            self.message_sink.send_with_callback(to, request, callback)

    def send_to_each(self, nodes, request_factory: Callable[[int], Optional["Request"]],
                     callback: Optional["Callback"] = None) -> None:
        skipped = []
        for to in nodes:
            request = request_factory(to)
            if request is not None:
                self.send(to, request, callback)
            elif callback is not None:
                skipped.append(to)
        if skipped:
            # a factory returning None means the node has NO slice of the
            # route in the contacted epochs (compute_scope under topology
            # churn): the tracker still counts it, and silently skipping
            # leaves that slot pending FOREVER — coordinations (most
            # visibly bootstrap fence sync points) then hang un-settled and
            # their store's pending_bootstrap never clears (seed-7 replica
            # divergence).  Report each as an immediate failure so quorum
            # accounting completes; scheduled async to keep callback
            # re-entrancy out of the send loop.
            def fail_skipped():
                for to in skipped:
                    try:
                        callback.on_failure(to, RuntimeError(
                            "no route scope for node in contacted epochs"))
                    except BaseException as e:  # noqa: BLE001
                        callback.on_callback_failure(to, e)
            self.scheduler.once(0.0, fail_skipped)

    def reply(self, to: int, reply_context, reply: "Reply") -> None:
        self.message_sink.reply(to, reply_context, reply)

    # -- local map/reduce over stores (Node.java:384-422) ---------------------
    def map_reduce_consume_local(self, unseekables, min_epoch: int, max_epoch: int,
                                 map_fn: Callable[[SafeCommandStore], object],
                                 reduce_fn: Callable[[object, object], object],
                                 preload=None) -> au.AsyncChain:
        return self.command_stores.map_reduce(unseekables, min_epoch, max_epoch,
                                              map_fn, reduce_fn, preload=preload)

    def for_each_local(self, unseekables, min_epoch: int, max_epoch: int,
                       fn: Callable[[SafeCommandStore], None],
                       preload=None) -> au.AsyncResult:
        """Run ``fn`` in every intersecting store.  EAGER (unlike map_reduce_
        consume_local): the chain is begun here — fire-and-forget callers
        (CommitInvalidate, Propagate, Inform*) must not silently no-op."""
        chain = self.command_stores.for_each(unseekables, min_epoch, max_epoch,
                                             fn, preload=preload)
        result = au.settable()

        def on_done(_value, failure):
            if failure is not None:
                self.agent.on_uncaught_exception(failure)
                result.set_failure(failure)
            else:
                result.set_success(None)

        chain.begin(on_done)
        return result

    # -- route computation (Node.java:604-624) --------------------------------
    def compute_route(self, txn: Txn) -> Route:
        """Pick a homeKey from the txn's footprint in the current epoch and build
        the full route."""
        return txn.to_route()

    def __repr__(self) -> str:
        return f"Node({self.id})"
