"""GC bounds and durability watermarks.

Capability parity with ``accord.local`` RedundantBefore / DurableBefore / MaxConflicts
/ Cleanup (RedundantBefore.java:49-529, DurableBefore.java:39+, MaxConflicts.java:32,
Cleanup.java):

- ``RedundantBefore``: per-range bounds below which transactions are redundant —
  locally applied-or-invalidated (safe to stop tracking as dependencies locally),
  shard applied (a quorum of the shard applied them), plus bootstrap/staleness marks.
- ``DurableBefore``: per-range durability watermarks — majority (applied at a quorum)
  and universal (applied at every replica) — fed by the durability coordination rounds.
- ``MaxConflicts``: per-range max executeAt witnessed, consulted when proposing
  PreAccept timestamps.
- ``Cleanup``: the truncation decision lattice combining both.

All are piecewise-constant maps over the routing-key space
(``utils.interval_map.ReducingIntervalMap``).
"""
from __future__ import annotations

import enum
from typing import NamedTuple, Optional, Tuple

from ..primitives.keys import Range, Ranges, RoutingKey
from ..primitives.timestamp import Timestamp, TxnId
from ..utils.interval_map import ReducingIntervalMap
from .status import Durability, SaveStatus, Status


def _max_ts(a: Optional[Timestamp], b: Optional[Timestamp]) -> Optional[Timestamp]:
    if a is None:
        return b
    if b is None:
        return a
    return a if a >= b else b


def _min_ts(a: Optional[Timestamp], b: Optional[Timestamp]) -> Optional[Timestamp]:
    if a is None or b is None:
        return None
    return a if a <= b else b


class RedundantEntry(NamedTuple):
    """Bounds for one range (RedundantBefore.Entry)."""
    locally_applied_before: Optional[TxnId] = None
    shard_applied_before: Optional[TxnId] = None
    bootstrapped_at: Optional[TxnId] = None
    stale_until_at_least: Optional[Timestamp] = None

    def merge(self, other: "RedundantEntry") -> "RedundantEntry":
        return RedundantEntry(
            _max_ts(self.locally_applied_before, other.locally_applied_before),
            _max_ts(self.shard_applied_before, other.shard_applied_before),
            _max_ts(self.bootstrapped_at, other.bootstrapped_at),
            _max_ts(self.stale_until_at_least, other.stale_until_at_least))

    def fence(self, local_only: bool = False) -> Optional[TxnId]:
        """Strongest fence txn of this entry; ``local_only`` restricts to bounds
        implying LOCAL application (locally-applied / bootstrap)."""
        out = _max_ts(self.locally_applied_before, self.bootstrapped_at)
        if not local_only:
            out = _max_ts(out, self.shard_applied_before)
        return out


class PreBootstrapOrStale(enum.Enum):
    """Classification of a txn vs bootstrap/staleness bounds
    (RedundantBefore.PreBootstrapOrStale)."""
    FULLY = "fully"
    PARTIALLY = "partially"
    POST_BOOTSTRAP = "post_bootstrap"


class RedundantBefore:
    """Range map of RedundantEntry (RedundantBefore.java)."""

    __slots__ = ("map",)

    EMPTY: "RedundantBefore"

    def __init__(self, map: Optional[ReducingIntervalMap] = None):
        self.map = map if map is not None else ReducingIntervalMap()

    @staticmethod
    def of(ranges: Ranges, **bounds) -> "RedundantBefore":
        entry = RedundantEntry(**bounds)
        pairs = [(r.start, r.end) for r in ranges]
        return RedundantBefore(ReducingIntervalMap.of_ranges(pairs, entry))

    def merge(self, other: "RedundantBefore") -> "RedundantBefore":
        return RedundantBefore(self.map.merge(other.map, lambda a, b: a.merge(b)))

    # -- queries -------------------------------------------------------------
    def entry(self, key: RoutingKey) -> Optional[RedundantEntry]:
        return self.map.get(key)

    def locally_redundant_before(self, key: RoutingKey) -> Optional[TxnId]:
        e = self.map.get(key)
        if e is None:
            return None
        # a txn pre-dating a bootstrap is redundant locally: its effects are
        # subsumed by the bootstrap snapshot (RedundantBefore.java bootstrappedAt)
        return _max_ts(e.locally_applied_before, e.bootstrapped_at)

    def shard_redundant_before(self, key: RoutingKey) -> Optional[TxnId]:
        e = self.map.get(key)
        return e.shard_applied_before if e is not None else None

    def is_locally_redundant(self, txn_id: TxnId, participants) -> bool:
        """True iff ``txn_id`` is below the locally-redundant bound at EVERY
        point of its footprint (it can be dropped as a dependency)."""
        entries = list(_entries_over(self.map, participants))
        if not entries:
            return False
        for e in entries:
            bound = None if e is None else \
                _max_ts(e.locally_applied_before, e.bootstrapped_at)
            if bound is None or not txn_id < bound:
                return False
        return True

    def max_locally_redundant_over(self, participants) -> Optional[TxnId]:
        """The HIGHEST locally-redundant bound anywhere on ``participants`` —
        a necessary condition filter: no txn at/above it can be cleanable
        (is_locally_redundant requires being below the bound EVERYWHERE)."""
        out: Optional[TxnId] = None
        for e in _entries_over(self.map, participants):
            if e is None:
                continue
            b = _max_ts(e.locally_applied_before, e.bootstrapped_at)
            out = _max_ts(out, b)
        return out

    def fence_before(self, key: RoutingKey) -> Optional[TxnId]:
        """The strongest fence txn covering ``key``: everything before it is
        implied-applied here (locally applied / bootstrap / shard-durable
        exclusive sync point).  Used to elide older deps from scans — the fence
        itself is contributed as the floor dependency (collectDeps)."""
        e = self.map.get(key)
        return e.fence() if e is not None else None

    def min_fence_over(self, rng: Range, local_only: bool = False) -> Optional[TxnId]:
        """The weakest fence over a whole range (None if any sub-interval has
        no fence): only txns below THIS may be elided from scans of the range.
        ``local_only``: consider only bounds implying LOCAL application
        (locally-applied / bootstrap) — required when DROPPING a dependency
        wait, since a shard-applied fence does not imply local apply."""
        fence: Optional[TxnId] = None
        for e in self.map.values_over(rng.start, rng.end):
            f = e.fence(local_only) if e is not None else None
            if f is None:
                return None
            fence = f if fence is None or f < fence else fence
        return fence

    def collect_deps(self, keys, ranges, add) -> None:
        """Contribute floor dependencies (RedundantBefore.collectDeps,
        RedundantBefore.java:183-192): for every participant with a fence bound,
        add the fence txn as a dependency — it transitively covers every elided
        transaction before it."""
        if keys is not None:
            for key in keys:
                rk = key.to_routing() if hasattr(key, "to_routing") else key
                fence = self.fence_before(rk)
                if fence is not None:
                    add(key, fence)
        if ranges is not None:
            from ..primitives.keys import Range as _Range
            for rng in ranges:
                # attribute each fence to ITS interval (clipped to the query),
                # never the whole query range: a fence recorded against foreign
                # ranges survives slicing at stores it can never apply on,
                # stranding their waiters forever.  Adjacent intervals with the
                # same fence coalesce — per-interval fragments would otherwise
                # balloon every deps set as the interval map refines
                plo = phi = pfence = None
                for lo, hi, e in self.map.items_over(rng.start, rng.end):
                    fence = e.fence() if e is not None else None
                    if fence is not None and fence == pfence and plo is not None \
                            and phi == lo:
                        phi = hi
                        continue
                    if pfence is not None and plo < phi:
                        add(_Range(plo, phi), pfence)
                    plo, phi, pfence = lo, hi, fence
                if pfence is not None and plo < phi:
                    add(_Range(plo, phi), pfence)

    def is_shard_redundant(self, txn_id: TxnId, participants) -> bool:
        """True iff ``txn_id`` is below the shard-applied bound at EVERY point
        of its footprint: a quorum applied it and everything before it, so late
        messages about it are safely dropped (erased-tombstone semantics)."""
        entries = list(_entries_over(self.map, participants))
        if not entries:
            return False
        for e in entries:
            bound = e.shard_applied_before if e is not None else None
            if bound is None or not txn_id < bound:
                return False
        return True

    def min_shard_redundant_before(self, participants) -> Optional[TxnId]:
        out = None
        first = True
        for e in _entries_over(self.map, participants):
            b = e.shard_applied_before if e is not None else None
            if first:
                out, first = b, False
            else:
                out = _min_ts(out, b)
        return out

    def max_shard_redundant_over(self, participants) -> Optional[TxnId]:
        """Highest shard-applied bound anywhere on the footprint (necessary-
        condition filter for is_shard_redundant, like
        max_locally_redundant_over)."""
        out = None
        for e in _entries_over(self.map, participants):
            if e is not None:
                out = _max_ts(out, e.shard_applied_before)
        return out

    def pre_bootstrap_or_stale(self, txn_id: TxnId, participants) -> PreBootstrapOrStale:
        """Is ``txn_id`` before a bootstrap (or staleness) bound on all / some /
        none of its footprint?"""
        pre = post = False
        for e in _entries_over(self.map, participants):
            bound = e.bootstrapped_at if e is not None else None
            stale = e.stale_until_at_least if e is not None else None
            is_pre = (bound is not None and txn_id < bound) or \
                     (stale is not None and txn_id.as_timestamp() < stale)
            pre, post = pre or is_pre, post or not is_pre
        if pre and not post:
            return PreBootstrapOrStale.FULLY
        if pre:
            return PreBootstrapOrStale.PARTIALLY
        return PreBootstrapOrStale.POST_BOOTSTRAP

    def __repr__(self):
        return f"RedundantBefore({self.map!r})"


RedundantBefore.EMPTY = RedundantBefore()


class DurableEntry(NamedTuple):
    """(majorityBefore, universalBefore) for one range (DurableBefore.Entry)."""
    majority_before: Optional[TxnId] = None
    universal_before: Optional[TxnId] = None

    def merge_max(self, other: "DurableEntry") -> "DurableEntry":
        return DurableEntry(_max_ts(self.majority_before, other.majority_before),
                            _max_ts(self.universal_before, other.universal_before))

    def merge_min(self, other: "DurableEntry") -> "DurableEntry":
        return DurableEntry(_min_ts(self.majority_before, other.majority_before),
                            _min_ts(self.universal_before, other.universal_before))


class DurableBefore:
    """Range map of DurableEntry (DurableBefore.java)."""

    __slots__ = ("map",)

    EMPTY: "DurableBefore"

    def __init__(self, map: Optional[ReducingIntervalMap] = None):
        self.map = map if map is not None else ReducingIntervalMap()

    @staticmethod
    def of(ranges: Ranges, majority_before: Optional[TxnId] = None,
           universal_before: Optional[TxnId] = None) -> "DurableBefore":
        entry = DurableEntry(majority_before, universal_before)
        pairs = [(r.start, r.end) for r in ranges]
        return DurableBefore(ReducingIntervalMap.of_ranges(pairs, entry))

    def merge(self, other: "DurableBefore") -> "DurableBefore":
        """Max-merge: combine knowledge (both maps' watermarks are true)."""
        return DurableBefore(self.map.merge(other.map, lambda a, b: a.merge_max(b)))

    def merge_min(self, other: "DurableBefore") -> "DurableBefore":
        """Min-merge: the watermark EVERY contributor agrees on — a range absent
        from either side is absent from the result (strict merge; an empty reply
        must NOT count as agreement, or watermarks would be falsely lifted to
        universal and enable premature erasure)."""
        return DurableBefore(self.map.merge(other.map, lambda a, b: a.merge_min(b),
                                            strict=True))

    def entry(self, key: RoutingKey) -> Optional[DurableEntry]:
        return self.map.get(key)

    def durability_of(self, txn_id: TxnId, key: RoutingKey) -> Durability:
        e = self.map.get(key)
        if e is None:
            return Durability.NOT_DURABLE
        if e.universal_before is not None and txn_id < e.universal_before:
            return Durability.UNIVERSAL
        if e.majority_before is not None and txn_id < e.majority_before:
            return Durability.MAJORITY
        return Durability.NOT_DURABLE

    def max_bounds_over(self, participants) -> Tuple[Optional[TxnId], Optional[TxnId]]:
        """(max majority, max universal) bound anywhere on the footprint —
        necessary-condition filters: no txn at/above the max can reach the
        corresponding cleanup tier (min_durability requires it everywhere)."""
        maj = uni = None
        for e in _entries_over(self.map, participants):
            if e is not None:
                maj = _max_ts(maj, e.majority_before)
                uni = _max_ts(uni, e.universal_before)
        return maj, uni

    def min_durability(self, txn_id: TxnId, participants) -> Durability:
        entries = list(_entries_over(self.map, participants))
        if not entries:
            return Durability.NOT_DURABLE
        out = None
        for e in entries:
            if e is None:
                return Durability.NOT_DURABLE
            if e.universal_before is not None and txn_id < e.universal_before:
                d = Durability.UNIVERSAL
            elif e.majority_before is not None and txn_id < e.majority_before:
                d = Durability.MAJORITY
            else:
                d = Durability.NOT_DURABLE
            out = d if out is None else min(out, d)
        return out if out is not None else Durability.NOT_DURABLE

    def __repr__(self):
        return f"DurableBefore({self.map!r})"


DurableBefore.EMPTY = DurableBefore()


class MaxConflicts:
    """Range map of max executeAt witnessed (MaxConflicts.java:32)."""

    __slots__ = ("map",)

    def __init__(self, map: Optional[ReducingIntervalMap] = None):
        self.map = map if map is not None else ReducingIntervalMap()

    def update(self, participants, ts: Timestamp) -> "MaxConflicts":
        pairs = _participant_pairs(participants)
        if not pairs:
            return self
        other = ReducingIntervalMap.of_ranges(pairs, ts)
        return MaxConflicts(self.map.merge(other, _max_ts))

    def get(self, participants) -> Optional[Timestamp]:
        out = None
        for v in _entries_over(self.map, participants):
            out = _max_ts(out, v)
        return out

    def __repr__(self):
        return f"MaxConflicts({self.map!r})"


class Cleanup(enum.Enum):
    """Truncation decision (Cleanup.java): what may be erased for an
    applied/invalidated txn given its redundancy + durability."""
    NO = "no"
    TRUNCATE_WITH_OUTCOME = "truncate_with_outcome"
    TRUNCATE = "truncate"
    ERASE = "erase"


def should_cleanup(command, redundant_before: RedundantBefore,
                   durable_before: DurableBefore) -> Cleanup:
    """Decide the strongest safe truncation for ``command``
    (Cleanup.shouldCleanup semantics, simplified to the three durability tiers)."""
    ss = command.save_status
    if ss.is_truncated or ss is SaveStatus.NOT_DEFINED:
        return Cleanup.NO
    # only applied or invalidated commands may be truncated
    if not (ss is SaveStatus.INVALIDATED or ss.has_been(Status.APPLIED)):
        return Cleanup.NO
    route = command.route
    if route is None:
        return Cleanup.NO
    participants = route.participants()
    if not redundant_before.is_locally_redundant(command.txn_id, participants):
        return Cleanup.NO
    if ss is SaveStatus.INVALIDATED:
        # no outcome to preserve: erase as soon as locally redundant
        return Cleanup.ERASE
    durability = durable_before.min_durability(command.txn_id, participants)
    if durability is Durability.UNIVERSAL:
        return Cleanup.ERASE
    if durability is Durability.MAJORITY:
        return Cleanup.TRUNCATE
    return Cleanup.TRUNCATE_WITH_OUTCOME


def _entries_over(map: ReducingIntervalMap, participants):
    """Every distinct map value a footprint touches: point lookups for keys,
    ``values_over`` sweeps for ranges."""
    if participants is None:
        return
    for item in participants:
        if isinstance(item, Range):
            yield from map.values_over(item.start, item.end)
        elif isinstance(item, RoutingKey):
            yield map.get(item)
        elif hasattr(item, "to_routing"):
            yield map.get(item.to_routing())
        else:
            # nested container, e.g. Deps.participants -> (RoutingKeys, Ranges)
            yield from _entries_over(map, item)


def _participant_pairs(participants):
    from ..primitives.keys import _Successor
    if participants is None:
        return ()
    if isinstance(participants, Ranges):
        return [(r.start, r.end) for r in participants]
    pairs = []
    for k in participants:
        rk = k if isinstance(k, RoutingKey) else k.to_routing()
        pairs.append((rk, _Successor(rk)))
    return pairs
