"""Command stores: single-logical-thread metadata shards and their manager.

Capability parity with ``accord.local.CommandStore/CommandStores/SafeCommandStore``
(CommandStore.java:1-788, CommandStores.java:79-737, SafeCommandStore.java:58-385):
each store owns a set of key ranges per epoch, all per-txn ``Command`` state and
per-key ``CommandsForKey`` indexes for those ranges, and executes every operation on
its own executor (one logical thread).  ``SafeCommandStore`` is the transactional view
handed to in-store operations, exposing the dependency-calculation queries
(``map_reduce_active``) and listener plumbing.  ``CommandStores`` routes operations to
the stores whose ranges intersect the operation's keys (``map_reduce_consume``
semantics) and swaps range assignments on topology change.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Callable, Dict, List, Optional, Set, Tuple

from ..api.interfaces import Agent, DataStore, ProgressLog
from ..primitives.deps import Deps
from ..protocol_batch.columns import ENGAGE_FLOOR
from ..primitives.keys import Range, Ranges, RoutingKey
from ..primitives.route import Route
from ..primitives.timestamp import Timestamp, TxnId, TxnKind
from ..utils import async_ as au
from ..utils.invariants import Invariants, check_state
from .cfk import CommandsForKey, InternalStatus, manages, manages_execution
from .command import Command
from .status import SaveStatus, Status

if TYPE_CHECKING:
    from .node import Node


class AgentExecutor:
    """Executor + agent pair (local/AgentExecutor.java). The default executes
    inline; the simulation harness substitutes a deterministic task queue."""

    def __init__(self, agent: Agent):
        self.agent = agent

    def execute(self, task: Callable[[], None]) -> None:
        try:
            task()
        except BaseException as e:  # noqa: BLE001
            self.agent.on_uncaught_exception(e)

    def submit(self, task: Callable[[], object]) -> au.AsyncChain:
        return au.of_callable(task, executor=self)


def command_footprint(cmd):
    """A command's key footprint: its partial txn's keys, else its route
    participants (may be Keys-like or Ranges).  Single definition shared by
    the live evidence scan and CommandSummary so the two can never drift."""
    if cmd.partial_txn is not None:
        return cmd.partial_txn.keys
    if cmd.route is not None:
        return cmd.route.participants()
    return None


class _SummaryDeps:
    """Minimal partial_deps stand-in for CommandSummary (contains only)."""
    __slots__ = ("ids",)

    def __init__(self, ids: frozenset):
        self.ids = ids

    def contains(self, txn_id) -> bool:
        return txn_id in self.ids


class CommandSummary:
    """Evidence-grade snapshot of a TERMINAL evicted command
    (impl/CommandsSummary.java): everything recovery_evidence reads —
    status lattice position, executeAt, deps membership, footprint — without
    a journal decode.  Terminal commands never change while cold, so the
    snapshot taken at evict time stays exact until fault-in discards it."""
    __slots__ = ("txn_id", "status", "save_status", "execute_at",
                 "partial_deps", "footprint", "applied_locally",
                 "elided_unapplied", "written_keys", "full_footprint")

    def __init__(self, cmd) -> None:
        self.txn_id = cmd.txn_id
        self.status = cmd.status
        self.save_status = cmd.save_status
        self.execute_at = cmd.execute_at
        self.partial_deps = None if cmd.partial_deps is None \
            else _SummaryDeps(frozenset(cmd.partial_deps.txn_ids()))
        self.footprint = command_footprint(cmd)
        # the grandfathered-serve plane reads these off evicted commands:
        # whether the dependency-ordered apply ran here, which write deps
        # were dropped without a local-apply proof (still unresolved at
        # evict time — terminal commands never resolve them later), and the
        # routing keys the local writes slice actually covered (evaluated
        # against all_ranges at QUERY time — ownership can grow after the
        # evict, so a cached verdict would over-claim)
        self.applied_locally = cmd.applied_locally
        self.elided_unapplied = frozenset(cmd.elided_unapplied) \
            if cmd.elided_unapplied else None
        self.written_keys = None if cmd.writes is None else frozenset(
            k.to_routing() if hasattr(k, "to_routing") else k
            for k in cmd.writes.keys)
        # full footprint for the writes-cover check (route travels whole;
        # the partial_txn is sliced and would certify slices never held)
        from ..local.commands import _dep_full_footprint
        self.full_footprint = _dep_full_footprint(cmd)


class CommandStore:
    """One metadata shard of one node."""

    _current: Optional["CommandStore"] = None   # logical-thread discipline check

    def __init__(self, store_id: int, node: "Node", executor: AgentExecutor):
        self.id = store_id
        self.node = node
        self.executor = executor
        # epoch -> Ranges this store covers (RangesForEpoch)
        self.ranges_by_epoch: Dict[int, Ranges] = {}
        self._all_ranges_cache: Optional[Ranges] = None
        self.commands: Dict[TxnId, Command] = {}
        self.cfks: Dict[RoutingKey, CommandsForKey] = {}
        # witnessed range-domain txns: TxnId -> (Ranges, status) for range deps calc
        # (InMemoryCommandStore.rangeCommands equivalent)
        self.range_txns: Dict[TxnId, Tuple[Ranges, InternalStatus]] = {}
        # transient listeners: txn_id -> callbacks fired on every status change
        self.transient_listeners: Dict[TxnId, List[Callable]] = {}
        self.progress_log: ProgressLog = ProgressLog.NOOP
        # GC bounds + durability watermarks + per-range max executeAt
        from .durability import DurableBefore, MaxConflicts, RedundantBefore
        self.redundant_before: RedundantBefore = RedundantBefore.EMPTY
        self.durable_before: DurableBefore = DurableBefore.EMPTY
        # MaxConflicts (MaxConflicts.java:32): per-range max executeAt of
        # RANGE-domain txns; key-domain maxima come precisely from each cfk
        self.max_conflicts: MaxConflicts = MaxConflicts()
        # ranges adopted but not yet bootstrapped: reads refused, writes apply
        self.pending_bootstrap: Ranges = Ranges.EMPTY
        # optional persistence hook (harness Journal; simulated durability)
        self.journal = None
        # bumped on every durability-watermark advance: the resolvers'
        # elision gates re-evaluate lazily against it
        self.durable_gen = 0
        # cache-miss plane (PreLoadContext.java / DelayedCommandStores
        # cache-miss injection): ids whose command state was EVICTED from
        # memory and lives only in the journal; faulted back in on access
        self.cold: set = set()
        # evidence-grade snapshots of evicted TERMINAL commands (the
        # reference's CommandsSummary): recovery evidence scans answer from
        # these instead of faulting the whole cold set through the journal
        # codec on every BeginRecovery (the seed-6 wall-clock storm: 125k+
        # fault-ins from repeated evidence scans at quiesce)
        self.cold_summaries: Dict[TxnId, "CommandSummary"] = {}
        # cold-GC memo: cold id -> the (redundant, majority, universal, shard)
        # max bounds it was last evaluated under; re-fault only on advance
        self.cold_gc_seen: dict = {}
        self.cache_miss_loads = 0
        # async pending-load plane: count of declared-cold ids loaded via the
        # PreLoadContext path (vs synchronous undeclared fault-ins)
        self.pending_loads = 0
        # diagnostic: local apply-order inversions recorded by the per-key
        # timestamp registers (legal under MVCC; see timestamps_for_key.py)
        self.tfk_inversions = 0
        # frontier-driven execution mode (burn harness): STABLE indexed txns
        # park here instead of firing ReadyToExecute inline; the device
        # kahn_frontier release task pops them (SURVEY §7 stage 8)
        self.frontier_exec = False
        self.exec_deferred: set = set()
        # per-key execution-timestamp registers (impl/TimestampsForKey.java):
        # last_write / last_executed / monotonic HLC, updated on the normal
        # execution path and merged on adoption/heal paths
        from .timestamps_for_key import TimestampsForKeys
        self.timestamps_for_key = TimestampsForKeys()
        # the conflict-index data plane (impl/resolver.py): answers the deps
        # and max-conflict queries; cpu = cfk walk, tpu = device GraphState
        from ..impl.resolver import make_resolver
        self.resolver = make_resolver(getattr(node, "resolver_kind", "cpu"),
                                      self, config=getattr(node, "config", None))
        # the columnar protocol engine (protocol_batch/): a struct-of-arrays
        # mirror of this store's hot command state + vectorized passes over
        # it (release fan-out, frontier classification, progress scans).
        # None when columnar=off: every legacy code path stays untouched.
        # Exact-skip contract: the engine never changes a protocol decision
        # (same-seed burns columnar on-vs-off are byte-identical, proven by
        # tests/test_protocol_batch.py).
        from ..protocol_batch import make_engine
        self.batch_engine = make_engine(self)

    def observer(self):
        """The run's flight recorder (observe.FlightRecorder), or None.
        Lives on the Node so every store of every incarnation reports into
        the same run-wide recorder."""
        return getattr(self.node, "observer", None)

    # -- ranges -------------------------------------------------------------
    def update_ranges(self, epoch: int, ranges: Ranges) -> None:
        self.ranges_by_epoch[epoch] = ranges
        self._all_ranges_cache = None   # the only mutation site

    def ranges_at(self, epoch: int) -> Ranges:
        """Ranges covered at ``epoch`` (latest known at-or-before epoch)."""
        best_e = None
        for e in self.ranges_by_epoch:
            if e <= epoch and (best_e is None or e > best_e):
                best_e = e
        return self.ranges_by_epoch.get(best_e, Ranges.EMPTY) if best_e is not None else Ranges.EMPTY

    def current_ranges(self) -> Ranges:
        if not self.ranges_by_epoch:
            return Ranges.EMPTY
        return self.ranges_by_epoch[max(self.ranges_by_epoch)]

    # -- cache-miss plane (PreLoadContext capability) ------------------------
    def lookup(self, txn_id: TxnId) -> Optional[Command]:
        """Fault-in-aware command read: EVERY reader (SafeCommandStore,
        progress log, barrier scans) must see evicted state as if resident."""
        cmd = self.commands.get(txn_id)
        if cmd is None and txn_id in self.cold:
            cmd = self._fault_in(txn_id)
        return cmd

    def _fault_in(self, txn_id: TxnId) -> Optional[Command]:
        """Reload an evicted command from the journal (the store of record) —
        the cache-miss path (PreLoadContext / AbstractSafeCommandStore async
        loads; reloads here are synchronous, with the interleaving dimension
        exercised by DelayedAgentExecutor's deferred store tasks)."""
        self.cold.discard(txn_id)
        summary = self.cold_summaries.pop(txn_id, None)
        cmd = self.journal.reconstruct_one(self, txn_id) \
            if self.journal is not None else None
        if cmd is not None:
            if summary is not None and summary.elided_unapplied:
                # restore the unresolved-elision set from the evict-time
                # summary: it is journaled too, but the summary is FRESHER
                # (serve-time prunes since the last journal save) — and
                # without either restore the fault-in LAUNDERS the command
                # into a falsely-clean floor dep and the grandfathered
                # serve certifies slices whose writes only the still-
                # outstanding bootstrap fetch can deliver (seed-6 k428
                # prefix loss rode exactly this wash cycle)
                cmd.elided_unapplied = set(summary.elided_unapplied)
            self.commands[txn_id] = cmd
            self.cache_miss_loads += 1
            if self.batch_engine is not None:
                # the reload made the command resident again: re-mirror it so
                # the columnar scans see it (absence would only cost speed —
                # unknown rows take the scalar path — but residency tracking
                # must never claim a row for an evicted command, so the
                # mirror follows residency in BOTH directions)
                self.batch_engine.note_fault_in(cmd)
        return cmd

    def all_ranges(self) -> Ranges:
        """Union of every epoch's owned ranges.  Memoized: this sits on the
        frontier-init / elision / apply hot paths (tens of thousands of
        calls per burn) and re-unioned the whole epoch map per call;
        ``update_ranges`` is the only mutation site and invalidates."""
        out = self._all_ranges_cache
        if out is None:
            out = Ranges.EMPTY
            for r in self.ranges_by_epoch.values():
                out = out.union(r)
            self._all_ranges_cache = out
        return out

    def unapplied_pressure(self, min_age_s: float = 10.0,
                           cap: int = 64) -> int:
        """Count of txns DECIDED (stable-or-later) at least ``min_age_s`` of
        sim-time ago that have not applied locally — the protocol-local
        signal behind the auditor's ``slo.unapplied`` flag plane, computed
        from store state only (never from the observer: zero observer
        effect).  The bootstrap retry ladder and the staleness catch-up
        escalation consult it to back off the re-fencing cadence while the
        execution plane is visibly behind — re-fencing faster than in-flight
        reads can assemble partial coverage is the seed-6 wedge."""
        from .status import SaveStatus as _SS, Status as _S
        horizon = self.node.now_micros() - int(min_age_s * 1_000_000)
        n = 0
        for cmd in self.commands.values():
            ss = cmd.save_status
            if not ss.has_been(_S.STABLE) or ss.is_truncated \
                    or ss is _SS.INVALIDATED \
                    or ss.ordinal >= _SS.APPLIED.ordinal:
                continue
            ts = cmd.execute_at if cmd.execute_at is not None else cmd.txn_id
            if ts.hlc <= horizon:
                n += 1
                if n >= cap:
                    # the consumers scale a delay that saturates long before
                    # this; don't finish an O(commands) scan per retry rung
                    # just to refine a number past its use
                    return n
        return n

    # -- execution ----------------------------------------------------------
    def execute(self, task: Callable[["SafeCommandStore"], None]) -> None:
        def run():
            prev, CommandStore._current = CommandStore._current, self
            try:
                task(SafeCommandStore(self))
            finally:
                CommandStore._current = prev
        self.executor.execute(run)

    def submit(self, task: Callable[["SafeCommandStore"], object],
               preload=None) -> au.AsyncChain:
        def run():
            prev, CommandStore._current = CommandStore._current, self
            try:
                return task(SafeCommandStore(self))
            finally:
                CommandStore._current = prev
        pending = self._cold_among(preload)
        if not pending:
            return self.executor.submit(run)
        result = au.settable()

        def start():
            self.executor.submit(run).begin(
                lambda v, f: result.set_failure(f) if f is not None
                else result.set_success(v))
        self._load_then(pending, start)
        return result.to_chain()

    def _cold_among(self, preload) -> list:
        """The declared ids whose state is evicted (PreLoadContext: the
        operation cannot run until these are loaded)."""
        if preload is None or not self.cold:
            return []
        return [tid for tid in preload if tid in self.cold]

    def _load_then(self, pending: list, start: Callable[[], None]) -> None:
        """The pending-load path (PreLoadContext.java /
        AbstractSafeCommandStore's load machinery): the declared-cold ids are
        faulted in by ONE separate executor task before the operation task is
        scheduled — the operation observes an async load boundary (under
        DelayedAgentExecutor both hops get random delays, so other store
        tasks interleave with the load: the interleaving the reference's
        cache-miss injection exists to stress,
        DelayedCommandStores.java:138-195).  One task for ALL of an
        operation's loads, not one per id: per-id hops serialized a
        delayed-store chain per cold dependency and ground hostile burns to
        a crawl."""
        self.pending_loads += len(pending)

        def run_loads():
            prev, CommandStore._current = CommandStore._current, self
            try:
                for tid in pending:
                    if tid in self.cold:
                        try:
                            self._fault_in(tid)
                        except BaseException as e:  # noqa: BLE001
                            # a failed load must not strand the operation
                            # (the chain would never settle): report and
                            # continue — the op sees the id as absent
                            self.agent().on_uncaught_exception(e)
            finally:
                CommandStore._current = prev
                start()
        self.executor.execute(run_loads)

    def check_in_store(self) -> None:
        Invariants.check_state(CommandStore._current is self,
                               "operation invoked outside its CommandStore")

    def agent(self) -> Agent:
        return self.executor.agent

    def __repr__(self) -> str:
        return f"CommandStore({self.id}@{self.node.id}, {self.current_ranges()!r})"


class SafeCommandStore:
    """Transactional view passed to every in-store operation."""

    __slots__ = ("store",)

    def __init__(self, store: CommandStore):
        self.store = store

    # -- commands -----------------------------------------------------------
    def get_or_create(self, txn_id: TxnId) -> Command:
        cmd = self.store.lookup(txn_id)
        if cmd is None:
            cmd = Command(txn_id)
            self.store.commands[txn_id] = cmd
        return cmd

    def get_if_exists(self, txn_id: TxnId) -> Optional[Command]:
        return self.store.lookup(txn_id)

    def _fault_in(self, txn_id: TxnId) -> Optional["Command"]:
        return self.store._fault_in(txn_id)

    def evict(self, txn_id: TxnId) -> bool:
        """Drop a TERMINAL command's in-memory state (journal keeps the
        record).  Terminal = applied/invalidated/truncated: no further
        transitions, so its listener registrations are historical and every
        waiter was already notified at the transition."""
        store = self.store
        cmd = store.commands.get(txn_id)
        if cmd is None or store.journal is None:
            return False
        from .status import SaveStatus as _SS
        terminal = cmd.save_status in (_SS.APPLIED, _SS.INVALIDATED) \
            or cmd.save_status.is_truncated
        if not terminal:
            return False
        del store.commands[txn_id]
        store.cold.add(txn_id)
        store.cold_summaries[txn_id] = CommandSummary(cmd)
        if store.batch_engine is not None:
            # residency left: the columnar mirror must forget the row, or a
            # vectorized scan would skip the fault-in the scalar path takes
            store.batch_engine.drop(txn_id)
        store.journal.on_evict(store, txn_id)
        obs = store.observer()
        if obs is not None:
            obs.registry.counter("store.evictions", node=store.node.id,
                                 store=store.id).inc()
        return True

    # -- cfk ----------------------------------------------------------------
    def cfk(self, key: RoutingKey) -> CommandsForKey:
        c = self.store.cfks.get(key)
        if c is None:
            c = CommandsForKey(key)
            self.store.cfks[key] = c
        return c

    def cfk_if_exists(self, key: RoutingKey) -> Optional[CommandsForKey]:
        return self.store.cfks.get(key)

    # -- deps queries (SafeCommandStore.mapReduceActive, :292) ---------------
    def map_reduce_active(self, keys, ranges, before: Timestamp, by: TxnId,
                          visit: Callable[[object, TxnId], None]) -> None:
        """Visit (key_or_range, dep_txn_id) for every active txn with txnId < before
        that conflicts with the given keys/ranges and is witnessed by ``by``'s kind.

        - key footprint: the resolver's per-key conflict index (cfk walk on CPU,
          one batched device join on TPU — impl/resolver.py);
        - plus range txns whose ranges intersect the keys;
        - range footprint: resolver query over indexed keys within the ranges +
          intersecting range txns (InMemoryCommandStore range scan :814-900).
        """
        local = self.store.current_ranges()
        rb = self.store.redundant_before
        resolver = self.store.resolver
        witnesses = by.witnesses
        if keys is not None:
            by_rk = {}
            for key in keys:
                rk = key.to_routing() if hasattr(key, "to_routing") else key
                if local.contains(rk):
                    by_rk[rk] = key
            for rk, dep in resolver.key_conflicts(by, list(by_rk), before):
                visit(by_rk[rk], dep)
            for rk, key in by_rk.items():
                fence = rb.fence_before(rk)
                for tid, (rngs, status) in self.store.range_txns.items():
                    if tid < before and status is not InternalStatus.INVALIDATED \
                            and (fence is None or not tid < fence) \
                            and witnesses(tid) and rngs.contains(rk):
                        visit(key, tid)
        if ranges is not None:
            for rng in ranges:
                # elide only below the MIN fence over the whole range (a txn may
                # intersect a sub-interval with a lower fence)
                fence = rb.min_fence_over(rng)
                for rk, dep in resolver.range_conflicts(by, rng, before):
                    if local.contains(rk):
                        visit(rk, dep)
                for tid, (rngs, status) in self.store.range_txns.items():
                    if tid < before and status is not InternalStatus.INVALIDATED \
                            and (fence is None or not tid < fence) \
                            and witnesses(tid):
                        # record the dep against the OVERLAP with its own
                        # footprint, not the querier's whole range: deps sliced
                        # to another store must not carry txns that never touch
                        # it (they would wait forever for an apply that cannot
                        # happen there) — RangeDeps participant semantics
                        for piece in rngs:
                            x = piece.intersection(rng)
                            if x is not None:
                                visit(x, tid)

    def max_conflict(self, keys, ranges) -> Optional[Timestamp]:
        """Max txnId/executeAt witnessed intersecting the footprint (MaxConflicts)."""
        out: Optional[Timestamp] = None
        resolver = self.store.resolver

        def bump(ts: Optional[Timestamp]):
            nonlocal out
            if ts is not None and (out is None or ts > out):
                out = ts

        if keys is not None:
            rks = [key.to_routing() if hasattr(key, "to_routing") else key
                   for key in keys]
            bump(resolver.max_conflict_keys(rks))
            # range txns covering these keys (per-range MaxConflicts map)
            bump(self.store.max_conflicts.get(keys))
        if ranges is not None:
            for rng in ranges:
                bump(resolver.max_conflict_range(rng))
            bump(self.store.max_conflicts.get(ranges))
        return out

    # -- registration -------------------------------------------------------
    def register_witness(self, command: Command, status: InternalStatus) -> None:
        """Index a txn in the per-key / range structures for deps calculation."""
        from .status import Status as _S, SaveStatus as _SS
        if status is InternalStatus.INVALIDATED \
                and command.has_been(_S.PRE_COMMITTED) \
                and command.save_status is not _SS.INVALIDATED:
            # a committed txn can never be invalidated: a late/erroneous
            # invalidation must not touch ANY index plane (cfk, resolver,
            # range table) — one choke point keeps the planes in lockstep
            return
        scope = command.route.participants() if command.route is not None else None
        if scope is None:
            return
        local = self.store.current_ranges()
        if isinstance(scope, Ranges):
            prev = self.store.range_txns.get(command.txn_id)
            rngs = scope.intersection(local)
            # keep the max status seen
            if prev is None or status > prev[1]:
                self.store.range_txns[command.txn_id] = (rngs, status)
            ts = command.execute_at if command.execute_at is not None else command.txn_id
            self.store.max_conflicts = self.store.max_conflicts.update(rngs, ts)
        else:
            ea = command.execute_at
            # feed the resolver exactly the keys the cfk indexed (it refuses
            # unmanaged txns and pruned-entry resurrection) so both data
            # planes stay in lockstep
            indexed = tuple(
                rk for rk in scope
                if local.contains(rk)
                and self.cfk(rk).update(command.txn_id, status, ea))
            if indexed:
                self.store.resolver.register(command.txn_id, status, ea, indexed)
                engine = self.store.batch_engine
                if engine is not None:
                    # the key-set offsets plane of the columnar layout (the
                    # ConsultBatch ingress bridge reads these CSR rows)
                    engine.note_keys(command.txn_id,
                                     [engine.key_slot(rk) for rk in indexed])

    def mark_txn_durable(self, command: Command) -> None:
        """Per-txn majority durability (InformDurable after the coordinator's
        apply quorum, Commands.setDurability → cfk): widen the per-key elision
        gate for this txn immediately and let terminal entries demote out of
        the hot walk (cfk.mark_durable)."""
        if command.route is None:
            return
        scope = command.route.participants()
        if isinstance(scope, Ranges):
            return    # range txns are indexed in range_txns, not cfk
        local = self.store.current_ranges()
        for key in scope:
            rk = key.to_routing() if hasattr(key, "to_routing") else key
            if not local.contains(rk):
                continue
            cfk = self.store.cfks.get(rk)
            if cfk is not None:
                cfk.mark_durable(command.txn_id)
        self.store.resolver.mark_durable(command.txn_id)

    def journal_save(self, command: Command) -> None:
        """Record the command's durable state in the attached journal (no-op
        without one) — the persistence contract hook (impl/basic/Journal)."""
        if self.store.journal is not None:
            self.store.journal.save(self.store, command)

    # -- listeners -----------------------------------------------------------
    def add_transient_listener(self, txn_id: TxnId, callback: Callable) -> None:
        self.store.transient_listeners.setdefault(txn_id, []).append(callback)

    def notify_listeners(self, command: Command) -> None:
        """Fire command-listeners (dependent txns) and transient listeners.

        With the columnar engine, the per-waiter release checks run as ONE
        batched pass over the listener set first (the vectorized
        ``remove_waiting`` fan-out): waiters the mirror PROVES still-blocked
        skip their scalar visit — the visit would read state and return
        without any mutation, observation, or fault-in (the skip proof is in
        BatchEngine.release_skip_mask).  A cascade that advances this
        command mid-fan-out invalidates the proof, so the dep snapshot is
        re-validated between visits; on any change the remaining waiters
        take the scalar path."""
        from . import commands as C
        listener_ids = list(command.listeners)
        engine = self.store.batch_engine
        skip = None
        if engine is not None and len(listener_ids) >= ENGAGE_FLOOR:
            skip = engine.release_skip_mask(command, listener_ids)
        if skip is None:
            for waiter_id in listener_ids:
                waiter = self.get_if_exists(waiter_id)
                if waiter is not None:
                    C.update_dependency_and_maybe_execute(self, waiter, command)
        else:
            snap = engine.release_snapshot(command)
            valid = True
            for i, waiter_id in enumerate(listener_ids):
                if valid and skip[i]:
                    if engine.release_snapshot(command) == snap:
                        continue
                    valid = False   # dep advanced mid-fan-out: proof void
                waiter = self.get_if_exists(waiter_id)
                if waiter is not None:
                    C.update_dependency_and_maybe_execute(self, waiter, command)
        for cb in list(self.store.transient_listeners.get(command.txn_id, ())):
            cb(self, command)

    def remove_transient_listener(self, txn_id: TxnId, callback: Callable) -> None:
        lst = self.store.transient_listeners.get(txn_id)
        if lst and callback in lst:
            lst.remove(callback)
            if not lst:
                del self.store.transient_listeners[txn_id]

    # -- durability / GC (RedundantBefore, DurableBefore, Cleanup) ------------
    def redundant_before(self):
        return self.store.redundant_before

    def durable_before(self):
        return self.store.durable_before

    def mark_locally_applied_before(self, txn_id: TxnId, ranges: Ranges) -> None:
        """Everything on ``ranges`` before ``txn_id`` has locally applied (fired
        when an exclusive sync point applies here: it waited on all of it).
        Advancing the fence also prunes the conflict indexes below it — the
        fence txn stands in for the pruned entries in future deps calcs."""
        from .durability import RedundantBefore
        local = ranges.intersection(self.store.all_ranges())
        if local:
            self.store.redundant_before = self.store.redundant_before.merge(
                RedundantBefore.of(local, locally_applied_before=txn_id))
            self._prune_below_fences()

    def _prune_below_fences(self) -> None:
        """Drop applied/invalidated index entries wholly below their fence."""
        from .cfk import InternalStatus as IS
        store = self.store
        rb = store.redundant_before
        for txn_id in list(store.range_txns):
            rngs, status = store.range_txns[txn_id]
            if status not in (IS.APPLIED, IS.INVALIDATED) or not rngs:
                continue
            fences = [rb.min_fence_over(r) for r in rngs]
            if all(f is not None and txn_id < f for f in fences):
                del store.range_txns[txn_id]
        for rk, cfk in store.cfks.items():
            fence = rb.fence_before(rk)
            if fence is not None:
                store.resolver.on_pruned(rk, cfk.prune_applied_before(fence))

    def mark_shard_durable(self, txn_id: TxnId, ranges: Ranges) -> None:
        """SetShardDurable: the durability round proved (via an all-replica
        WaitUntilApplied, CoordinateShardDurable.java) that everything on
        ``ranges`` before ``txn_id`` has applied at EVERY replica — advance
        both the majority and universal watermarks (matching
        CommandStore.markShardDurable, CommandStore.java:520-528) and the
        shard-applied redundancy bound."""
        from .durability import DurableBefore, RedundantBefore
        local = ranges.intersection(self.store.all_ranges())
        if local:
            self.store.durable_before = self.store.durable_before.merge(
                DurableBefore.of(local, majority_before=txn_id,
                                 universal_before=txn_id))
            self.store.redundant_before = self.store.redundant_before.merge(
                RedundantBefore.of(local, shard_applied_before=txn_id))
            self.store.durable_gen += 1   # elision gate may have widened
        self.run_gc()

    def merge_durable_before(self, durable_before) -> None:
        """SetGloballyDurable: adopt a cluster-wide durability watermark map."""
        self.store.durable_before = self.store.durable_before.merge(durable_before)
        self.store.durable_gen += 1       # elision gate may have widened
        self.run_gc()

    def run_gc(self) -> None:
        """Truncate/erase commands per the Cleanup lattice; prune per-key and
        range indexes below the shard-redundant bound (Cleanup.java, cfk pruning)."""
        from .durability import Cleanup, should_cleanup
        from . import commands as C
        store = self.store
        # evicted commands are still subject to GC — but only ids below the
        # highest locally-redundant bound can possibly be cleanable
        # (should_cleanup gates on is_locally_redundant), so only those fault
        # in; the rest stay cold.  A cold id is re-evaluated only when a bound
        # that could RAISE its cleanup tier has advanced since it was last
        # evaluated (cold_gc_seen memo): run_gc fires on every durability
        # message, and unconditionally re-faulting the whole cold set decoded
        # every journal entry each time — the hostile churn matrix spent most
        # of its wall-clock in exactly that codec thrash.
        footprint = store.all_ranges()
        gc_bound = store.redundant_before.max_locally_redundant_over(footprint)
        if gc_bound is not None:
            maj, uni = store.durable_before.max_bounds_over(footprint)
            shard = store.redundant_before.max_shard_redundant_over(footprint)
            sig = (gc_bound, maj, uni, shard)
            seen = store.cold_gc_seen
            # the store-wide maxes can miss a PER-RANGE bound advance (another
            # range's entries dominate every max): clear the memo on a slow
            # cadence so such cold commands are still eventually re-evaluated
            store.gc_runs = getattr(store, "gc_runs", 0) + 1
            if store.gc_runs % 32 == 0:
                seen = store.cold_gc_seen = {}
            for cold_id in list(store.cold):
                if cold_id < gc_bound and seen.get(cold_id) != sig:
                    seen[cold_id] = sig
                    self.get_if_exists(cold_id)
            if len(seen) > 2 * len(store.cold):
                store.cold_gc_seen = {
                    k: v for k, v in seen.items() if k in store.cold}
        for txn_id, cmd in list(store.commands.items()):
            cleanup = should_cleanup(cmd, store.redundant_before, store.durable_before)
            if cleanup is Cleanup.NO:
                continue
            if cleanup is Cleanup.ERASE:
                parts = cmd.route.participants() if cmd.route is not None else None
                if parts is not None \
                        and store.redundant_before.is_shard_redundant(txn_id, parts):
                    # physically drop: late messages are fended off by the
                    # shard-redundant guard in commands (_is_shard_redundant).
                    # INVALIDATED tombstones must ALSO wait for the shard
                    # fence: deleting one destroys the ballot promise and the
                    # decision evidence, so a later recovery re-creates the
                    # txn fresh, adopts stale ACCEPTED evidence from a replica
                    # the invalidation quorum never touched, and COMMITS a
                    # txn that was already invalidated at a quorum (seed-4
                    # fence trace: invalidate@[139] at {n1,n2,n4} erased,
                    # then recover@[146] committed via n5's old accept).
                    del store.commands[txn_id]
                    store.transient_listeners.pop(txn_id, None)
                    if store.batch_engine is not None:
                        store.batch_engine.drop(txn_id)
                    # the physical drop bypasses every transition choke point:
                    # tell the frontier mirror directly, or its slot keeps the
                    # last-registered status (STABLE rows then sit in the
                    # kernel frontier as ready forever — the mirror leak)
                    store.resolver.note_terminal(txn_id)
                    if store.journal is not None:
                        store.journal.erase(store, txn_id)
                    continue
                if cmd.save_status is SaveStatus.INVALIDATED:
                    # NOT yet shard-redundant: the tombstone must persist AS
                    # INVALIDATED until the shard fence (downgrading it to
                    # ERASED weakens "decided invalid" to "unknowable" and
                    # re-opens the round-4 resurrection class; the auditor's
                    # edge table forbids INVALIDATED -> ERASED for the same
                    # reason)
                    continue
            C.truncate(self, cmd, cleanup)
        # prune conflict indexes below the shard-applied bound per key, and
        # flag/demote entries below the majority-durable watermark (entries
        # that never saw a per-txn InformDurable still leave the hot walk)
        for rk, cfk in store.cfks.items():
            e = store.durable_before.entry(rk)
            if e is not None and e.majority_before is not None:
                cfk.mark_durable_below(e.majority_before)
            bound = store.redundant_before.shard_redundant_before(rk)
            if bound is not None:
                store.resolver.on_pruned(rk, cfk.prune_applied_before(bound))
        # trim the per-key execution registers below the same bound
        # (TimestampsForKey.withoutRedundant)
        store.timestamps_for_key.remove_redundant_by(
            lambda key: store.redundant_before.shard_redundant_before(
                key.to_routing() if hasattr(key, "to_routing") else key))
        for txn_id in list(store.range_txns):
            rngs, _status = store.range_txns[txn_id]
            if store.redundant_before.is_locally_redundant(txn_id, rngs) \
                    and store.redundant_before.min_shard_redundant_before(rngs) is not None \
                    and txn_id < store.redundant_before.min_shard_redundant_before(rngs):
                del store.range_txns[txn_id]

    # -- context ------------------------------------------------------------
    def data_store(self) -> DataStore:
        return self.store.node.data_store

    def agent(self) -> Agent:
        return self.store.agent()

    def progress_log(self) -> ProgressLog:
        return self.store.progress_log

    def time(self):
        return self.store.node

    def ranges_at(self, epoch: int) -> Ranges:
        return self.store.ranges_at(epoch)

    def current_ranges(self) -> Ranges:
        return self.store.current_ranges()

    def node(self) -> "Node":
        return self.store.node


class CommandStores:
    """Shard manager: routes operations to intersecting stores
    (CommandStores.java mapReduceConsume :580-620, updateTopology :402-482)."""

    def __init__(self, node: "Node", num_shards: int = 1,
                 executor_factory: Optional[Callable[[int], AgentExecutor]] = None):
        self.node = node
        self.num_shards = num_shards
        factory = executor_factory or (lambda i: AgentExecutor(node.agent))
        self.stores: List[CommandStore] = [
            CommandStore(i, node, factory(i)) for i in range(num_shards)
        ]
        # sticky range -> store assignment: a range must stay with the store that
        # holds its Command/cfk history across topology changes
        self._assignment: Dict[Range, int] = {}

    # -- topology -----------------------------------------------------------
    def update_topology(self, topology) -> None:
        """Distribute this node's ranges across stores. Previously-assigned ranges
        keep their store (their command/cfk state lives there); new ranges go to the
        least-loaded store (ShardDistributor.EvenSplit semantics)."""
        my_ranges = topology.ranges_for_node(self.node.id)
        buckets: List[List[Range]] = [[] for _ in self.stores]
        unassigned: List[Range] = []
        for rng in my_ranges:
            sid = self._assignment.get(rng)
            if sid is not None:
                buckets[sid].append(rng)
            else:
                unassigned.append(rng)
        for rng in unassigned:
            sid = min(range(len(buckets)), key=lambda i: len(buckets[i]))
            self._assignment[rng] = sid
            buckets[sid].append(rng)
        for store, bucket in zip(self.stores, buckets):
            store.update_ranges(topology.epoch, Ranges.of(*bucket))

    # -- routing ------------------------------------------------------------
    def intersecting_stores(self, unseekables, min_epoch: int, max_epoch: int) -> List[CommandStore]:
        if isinstance(unseekables, Route):
            unseekables = unseekables.participants()
        out = []
        for store in self.stores:
            for e in range(min_epoch, max_epoch + 1):
                ranges = store.ranges_at(e)
                if ranges and unseekables is not None and ranges.intersects(unseekables):
                    out.append(store)
                    break
                if ranges and unseekables is None:
                    out.append(store)
                    break
        return out

    def map_reduce(self, unseekables, min_epoch: int, max_epoch: int,
                   map_fn: Callable[[SafeCommandStore], object],
                   reduce_fn: Callable[[object, object], object],
                   preload=None) -> au.AsyncChain:
        """Run map_fn in every intersecting store (on its executor), reduce
        results.  ``preload`` declares the txn ids the operation touches
        (PreLoadContext): evicted ones are loaded asynchronously first."""
        stores = self.intersecting_stores(unseekables, min_epoch, max_epoch)
        if not stores:
            return au.done(None)
        if len(stores) == 1:
            # fast path (the PR-8 loop-is-the-wall finding): the common
            # single-shard routing built three AsyncChain layers per message
            # (all_of + map + reduce of one element) just to return the lone
            # store's result unchanged.  Submitting directly is value- and
            # timing-identical — all_of/map add no scheduling, only
            # callback wrapping — so this is pure event-loop relief.
            return stores[0].submit(map_fn, preload=preload)
        chains = [s.submit(map_fn, preload=preload) for s in stores]

        def reduce_all(results):
            acc = None
            first = True
            for r in results:
                if first:
                    acc, first = r, False
                else:
                    acc = reduce_fn(acc, r)
            return acc

        return au.all_of(chains).map(reduce_all)

    def for_each(self, unseekables, min_epoch: int, max_epoch: int,
                 fn: Callable[[SafeCommandStore], None],
                 preload=None) -> au.AsyncChain:
        return self.map_reduce(unseekables, min_epoch, max_epoch,
                               lambda s: (fn(s), None)[1], lambda a, b: None,
                               preload=preload)

    def all_stores(self) -> List[CommandStore]:
        return list(self.stores)
