"""Bootstrap: adoption of newly-replicated ranges on topology change.

Capability parity with ``accord.local.Bootstrap`` (Bootstrap.java:83-494, doc :51-82):
when a topology change gives a command store ranges it did not previously replicate,
the store must (1) fence the past with a coordinated **exclusive sync point** over the
new ranges, (2) fetch the data those ranges held before (``DataStore.fetch`` from
prior-epoch replicas, complete up to the sync point since sources applied it),
(3) mark ``bootstrapped_at`` in RedundantBefore — older dependencies are then
implicitly satisfied by the fetched snapshot — and re-evaluate any transactions that
were waiting on pre-bootstrap dependencies.  Until then the ranges are marked
pending so reads are refused (served by other replicas) while writes apply normally.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, List

from ..primitives.keys import Ranges
from ..utils import async_ as au

if TYPE_CHECKING:
    from .command_store import CommandStore
    from .node import Node


def refence_backoff(node: "Node", store: "CommandStore", delay: float) -> float:
    """Stretch a re-fencing delay by the store's unapplied pressure (txns
    decided ``refence_pressure_age_s`` ago with no local apply — the
    condition the auditor's ``slo.unapplied`` plane flags), capped at
    ``refence_backoff_max_s``.  Shared by the bootstrap retry ladder and the
    staleness catch-up escalation: both allocate fresh fence sync points and
    re-mark ``bootstrapped_at``, and firing them faster than in-flight reads
    assemble partial coverage is the seed-6 bootstrap-refencing wedge."""
    cfg = getattr(node, "config", None)
    age_s = cfg.refence_pressure_age_s if cfg is not None else 10.0
    cap_s = cfg.refence_backoff_max_s if cfg is not None else 30.0
    pressure = store.unapplied_pressure(age_s)
    if pressure <= 0:
        return delay
    obs = getattr(node, "observer", None)
    if obs is not None:
        obs.registry.counter("bootstrap.refence_backoffs",
                             node=node.id, store=store.id).inc()
    return min(max(delay, 1.0) * (1.0 + pressure), max(cap_s, delay))


class Bootstrap:
    """One bootstrap attempt for one store's added ranges at one epoch."""

    def __init__(self, node: "Node", store: "CommandStore", ranges: Ranges,
                 epoch: int, catch_up: bool = False):
        self.node = node
        self.store = store
        self.ranges = ranges
        self.epoch = epoch
        # catch-up mode: re-running the ladder IN PLACE for a stale range
        # (data lost to a truncation gap) — fetch sources from fence-epoch
        # peers instead of the prior topology (staleUntilAtLeast analog)
        self.catch_up = catch_up
        self.result = au.settable()
        self.attempts = 0
        # retry budget (local/overload.py): every rung of the ladder allocates
        # a fresh fence ESP that peers must then recover or invalidate — a
        # whole-cluster refencing storm (KNOWN_ISSUES round 7) is many such
        # ladders outrunning the heal rate.  The bucket bounds this store's
        # rung rate; a denied rung stretches to the refence cap instead of
        # firing.  None when the knob is off (default).
        self._budget = None
        cfg = getattr(node, "config", None)
        if cfg is not None and cfg.retry_budget_enabled:
            from .overload import TokenBucket
            self._budget = TokenBucket(
                cfg.retry_budget_rate_s, cfg.retry_budget_burst,
                cfg.retry_budget_jitter,
                salt=(node.id << 16) ^ (store.id + 0x5BD1) ^ epoch,
                now_s=node.now_micros() / 1e6)

    def _retry_delay(self) -> float:
        """Exponential backoff for the attempt ladder (Bootstrap.Attempt).
        Under chaos+churn a flat cadence floods stores with abandoned fence
        sync points (each attempt allocates a fresh ExclusiveSyncPoint txn
        that then needs recovery/invalidation) — the hostile matrix went
        superlinear on exactly this."""
        self.attempts += 1
        # exponent capped BEFORE exponentiation: 2.0**1024 raises
        # OverflowError, and a long-starved bootstrap (a quorumless range
        # retrying through a whole hostile run) gets past 1024 attempts —
        # values are identical below the cap (2**5 already saturates the 8s
        # ceiling)
        delay = min(0.5 * (2.0 ** min(self.attempts - 1, 8)), 8.0)
        # re-fencing cooperates with in-flight reads (the seed-6 wedge):
        # every retry rung allocates a FRESH fence ESP and re-marks
        # bootstrapped_at at the higher id.  While the store carries
        # unapplied pressure (txns decided long ago, not applied — the
        # slo.unapplied condition), the ladder is outrunning partial-read
        # coverage assembly: stretch the rung so the reads win the race.
        delay = refence_backoff(self.node, self.store, delay)
        if self._budget is not None and not self._budget.try_acquire(
                self.node.now_micros() / 1e6):
            # budget denied: this rung would join a refencing herd — stretch
            # it to the cap so the bucket refills before the next attempt
            cfg = self.node.config
            delay = max(delay, cfg.refence_backoff_max_s)
            counters = getattr(self.node, "overload_counters", None)
            if counters is not None:
                counters["budget_denied"] += 1
            obs = getattr(self.node, "observer", None)
            if obs is not None:
                obs.registry.counter("overload.budget_denied",
                                     node=self.node.id,
                                     store=self.store.id).inc()
        return delay

    def start(self) -> au.AsyncResult:
        self.store.pending_bootstrap = self.store.pending_bootstrap.union(self.ranges)
        self._attempt()
        return self.result

    def _attempt(self) -> None:
        """One sync-point attempt.  The fence id is allocated FIRST and
        ``bootstrapped_at`` marked with it BEFORE coordination
        (Bootstrap.java markBootstrapping): the bootstrapping store then elides
        pre-bootstrap dependencies — the fetched snapshot covers them — so the
        fence itself (and txns committed during bootstrap) can apply here.
        Without the early mark, a blocking fence over ranges whose replica set
        fully changed deadlocks: its apply quorum needs the NEW replicas, whose
        applies wait on data only the post-fence fetch can deliver."""
        from ..primitives.timestamp import Domain, TxnKind
        txn_id = self.node.next_txn_id(TxnKind.EXCLUSIVE_SYNC_POINT, Domain.RANGE)

        def mark(safe_store):
            from .durability import RedundantBefore
            self.store.redundant_before = self.store.redundant_before.merge(
                RedundantBefore.of(self.ranges, bootstrapped_at=txn_id))
            # re-evaluate pre-existing waiters ONCE per bootstrap (and again on
            # finish): the new fence's own WaitingOn is built AFTER this mark
            # and elides via the live bounds, so retry rungs gain nothing from
            # a rescan — per-rung rescans made churn quiesce O(rungs x edges)
            # (waiters whose deps fall between successive fence ids drain via
            # the progress log or the finish re-evaluation)
            if self.attempts == 0:
                _reevaluate_waiting(safe_store, self.ranges)
            self.node.sync_point(self.ranges, exclusive=True, blocking=True,
                                 txn_id=txn_id).add_listener(self._on_sync_point)

        self.store.execute(mark)

    def _on_sync_point(self, sync_point, failure) -> None:
        if failure is not None:
            # retry ladder (Bootstrap.Attempt): the agent decides; default retries
            def retry():
                self.node.scheduler.once(self._retry_delay(), self._attempt)
            self.node.agent.on_failed_bootstrap("sync point", self.ranges, retry,
                                                failure)
            return
        fetch_done = au.settable()
        self._fetch(sync_point, fetch_done)
        fetch_done.add_listener(
            lambda _v, f: self._on_fetched(sync_point, f))

    def _fetch(self, sync_point, fetch_done: au.Settable) -> None:
        class FetchRanges:
            def fetched(self_inner, ranges: Ranges) -> None:
                if not fetch_done.is_done():
                    fetch_done.set_success(ranges)

            def fail(self_inner, failure: BaseException) -> None:
                if not fetch_done.is_done():
                    fetch_done.set_failure(failure)

        import inspect
        supports_catch_up = "catch_up" in inspect.signature(
            self.node.data_store.fetch).parameters

        def run(safe_store):
            if supports_catch_up:
                self.node.data_store.fetch(self.node, safe_store, self.ranges,
                                           sync_point, FetchRanges(),
                                           catch_up=self.catch_up)
            else:
                # DataStore impls without catch-up support (SPI default);
                # a catch-up Bootstrap REQUIRES the stronger contract
                # (prior-topology mode can report lost ranges 'trivially
                # complete', silently masking data loss)
                assert not self.catch_up, \
                    "catch-up bootstrap needs a catch_up-aware DataStore.fetch"
                self.node.data_store.fetch(self.node, safe_store, self.ranges,
                                           sync_point, FetchRanges())

        self.store.execute(run)

    def _on_fetched(self, sync_point, failure) -> None:
        if failure is not None:
            def retry():
                self.node.scheduler.once(
                    self._retry_delay(),
                    lambda: self._on_sync_point(sync_point, None))
            self.node.agent.on_failed_bootstrap("fetch", self.ranges, retry, failure)
            return

        def finish(safe_store):
            from .durability import RedundantBefore
            store = self.store
            store.redundant_before = store.redundant_before.merge(
                RedundantBefore.of(self.ranges, bootstrapped_at=sync_point.txn_id))
            store.pending_bootstrap = store.pending_bootstrap.without(self.ranges)
            _reevaluate_waiting(safe_store, self.ranges)
            self.result.set_success(sync_point)

        self.store.execute(finish)


def _reevaluate_waiting(safe_store, ranges=None) -> None:
    """Drop now-redundant (pre-bootstrap) deps from every waiting command and
    try to execute it (Commands re-evaluation after bootstrappedAt advances).

    Runs on every bootstrap mark/finish — including each rung of the retry
    ladder — so the scan is aggressively filtered:

    - ``ranges``: the mark only advanced bounds on the bootstrapped ranges, so
      only deps whose footprint intersects them can have become redundant;
    - store-wide max-bound gate (a dep at/above the max locally-redundant
      bound anywhere is unprunable);
    - per-edge participants are cached on the store (immutable per deps
      object; rebuilding the key unions per rung dominated churn quiesce);
    - redundancy verdicts are memoised per (dep, footprint) within a pass."""
    from . import commands as C
    store = safe_store.store
    redundant = store.redundant_before
    max_bound = redundant.max_locally_redundant_over(store.all_ranges())
    if max_bound is None:
        return
    memo: dict = {}
    parts_cache = getattr(store, "_dep_parts_cache", None)
    if parts_cache is None:
        parts_cache = store._dep_parts_cache = {}
    elif len(parts_cache) > 50_000:
        parts_cache.clear()
    for command in list(store.commands.values()):
        waiting = command.waiting_on
        if waiting is None or not waiting.is_waiting():
            continue
        deps = command.partial_deps
        for dep_id in list(waiting.waiting):
            if not dep_id < max_bound:
                continue
            ck = (command.txn_id, dep_id)
            ent = parts_cache.get(ck)
            if ent is None or ent[0] is not deps:
                parts = deps.participants(dep_id) if deps is not None else None
                if parts is None:
                    parts_cache[ck] = (deps, None, None)
                    continue
                keys, rngs = parts
                mk = (dep_id, tuple(keys), tuple((r.start, r.end) for r in rngs))
                ent = parts_cache[ck] = (deps, parts, mk)
            _d, parts, mk = ent
            if parts is None:
                continue
            if ranges is not None and not _parts_intersect(parts, ranges):
                continue
            hit = memo.get(mk)
            if hit is None:
                hit = memo[mk] = redundant.is_locally_redundant(dep_id, parts)
            if hit:
                # elided below the advancing bootstrap bound: the write
                # arrives with the fetch, not a local apply — noted so the
                # read-serve path treats its slices as at-risk until it
                # proves the dep landed (grandfathered serve)
                C._note_elided_unless_applied(safe_store, command, dep_id)
                waiting.remove(dep_id, True)
                store.resolver.remove_waiting(command.txn_id, dep_id)
                dep = safe_store.get_if_exists(dep_id)
                if dep is not None:
                    dep.listeners.discard(command.txn_id)
        if not waiting.is_waiting():
            C.maybe_execute(safe_store, command, always_notify_listeners=False)


def _parts_intersect(parts, ranges: Ranges) -> bool:
    keys, rngs = parts
    for k in keys:
        if ranges.contains(k):
            return True
    for r in rngs:
        if ranges.intersects(Ranges.of(r)):
            return True
    return False
