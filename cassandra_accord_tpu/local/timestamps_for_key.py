"""Per-key execution-timestamp registers (TimestampsForKey).

Capability parity with the reference's ``impl/TimestampsForKey.java`` /
``TimestampsForKeys.java``: each key of a command store carries registers —

  * ``last_write``          — executeAt of the most recent WRITE applied here
  * ``last_executed``       — executeAt of the most recent execution (read or
                              write)
  * ``last_executed_hlc``   — a strictly-monotonic HLC the embedding store can
                              stamp local application with (the reference keeps
                              ``rawLastExecutedHlc`` with a MIN_VALUE sentinel;
                              we keep the resolved value and bump ties by one —
                              the same observable sequence)
  * ``last_ephemeral_read`` — snapshot point of the most recent EPHEMERAL read
                              served from this store

Role and design divergence (deliberate, documented):

The reference enforces strict per-key execution monotonicity (a write may
never execute below lastWrite/lastExecuted) because Cassandra's store applies
at the register HLC and is not timestamp-versioned.  Our data plane is a
timestamped MVCC store (``ListStore.get_at``): writes land with their
executeAt, reads snapshot at their own executeAt, so LOCAL apply-order
inversion between two committed writes is absorbed by the store and is legal
(it happens routinely across epoch changes and truncated-outcome adoption).
We therefore:

1. record write inversions as a per-store DIAGNOSTIC counter
   (``store.tfk_inversions``) rather than failing — the client-visible
   strict-serializability verifier owns the end-to-end ordering check;
2. hard-enforce the one register invariant our design DOES guarantee: a
   write may never apply below ``last_ephemeral_read``.  An ephemeral read
   serves only after every dep in its quorum-collected deps applied locally,
   and quorum intersection + HLC propagation put every write with a lower
   executeAt in those deps — so a later write landing below a served
   ephemeral snapshot means the snapshot missed a committed lower write: a
   genuine dependency-completeness bug, routed to
   ``Agent.on_inconsistent_timestamp`` (ephemeral reads are never witnessed,
   so NO other mechanism can catch this; the registers are the only record —
   the reference motivates TimestampsForKey the same way).

Out-of-order application paths (truncated-outcome adoption, bootstrap fence
shipping, pre-bootstrap applies) merge registers monotonically and are
exempt from the ephemeral check over stale/bootstrapping footprints, exactly
the cases the reference gates behind ``safeToReadAt``.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Dict, Optional

from ..primitives.timestamp import Timestamp

if TYPE_CHECKING:
    from ..primitives.keys import Key


class TimestampsForKey:
    """The per-key registers (TimestampsForKey.java:27-118)."""

    __slots__ = ("key", "last_executed", "last_executed_hlc", "last_write",
                 "last_ephemeral_read")

    def __init__(self, key):
        self.key = key
        self.last_executed: Optional[Timestamp] = None
        self.last_executed_hlc: int = 0
        self.last_write: Optional[Timestamp] = None
        self.last_ephemeral_read: Optional[Timestamp] = None

    def record_execution(self, execute_at: Timestamp, is_write: bool) -> bool:
        """Monotonic register advance for a read/write execution; returns
        True when the execution was an inversion (landed below an already-
        advanced register) — diagnostic only, see module doc."""
        inverted = False
        if is_write:
            if self.last_write is None or execute_at > self.last_write:
                self.last_write = execute_at
            else:
                inverted = execute_at != self.last_write
        if self.last_executed is None or execute_at > self.last_executed:
            hlc = execute_at.hlc
            self.last_executed_hlc = hlc if hlc > self.last_executed_hlc \
                else self.last_executed_hlc + 1
            self.last_executed = execute_at
        return inverted

    def record_ephemeral_read(self, snapshot_at: Timestamp) -> None:
        if self.last_ephemeral_read is None \
                or snapshot_at > self.last_ephemeral_read:
            self.last_ephemeral_read = snapshot_at
        self.record_execution(snapshot_at, False)

    def violates_ephemeral_fence(self, execute_at: Timestamp,
                                 is_write: bool) -> bool:
        """The enforced invariant: a WRITE landing below a served ephemeral
        snapshot missed that snapshot (deps incompleteness)."""
        return is_write and self.last_ephemeral_read is not None \
            and execute_at < self.last_ephemeral_read

    # -- GC (TimestampsForKey.withoutRedundant) ------------------------------
    def without_redundant(self, redundant_before: Timestamp) -> bool:
        """Clear registers strictly below the redundancy bound; returns True
        when the whole record became empty (the registry drops it)."""
        if self.last_executed is not None and self.last_executed < redundant_before:
            self.last_executed = None
        if self.last_executed_hlc and self.last_executed_hlc < redundant_before.hlc:
            self.last_executed_hlc = 0
        if self.last_write is not None and self.last_write < redundant_before:
            self.last_write = None
        if self.last_ephemeral_read is not None \
                and self.last_ephemeral_read < redundant_before:
            self.last_ephemeral_read = None
        return (self.last_executed is None and self.last_write is None
                and self.last_ephemeral_read is None
                and not self.last_executed_hlc)

    def __repr__(self) -> str:
        return (f"TimestampsForKey({self.key!r}, last_executed="
                f"{self.last_executed!r}, last_write={self.last_write!r})")


class TimestampsForKeys:
    """Per-store registry of TimestampsForKey records (the reference keeps a
    NavigableMap on InMemoryCommandStore, InMemoryCommandStore.java:99)."""

    __slots__ = ("_by_key",)

    def __init__(self):
        self._by_key: Dict[object, TimestampsForKey] = {}

    def get_or_create(self, key) -> TimestampsForKey:
        tfk = self._by_key.get(key)
        if tfk is None:
            tfk = self._by_key[key] = TimestampsForKey(key)
        return tfk

    def get_if_present(self, key) -> Optional[TimestampsForKey]:
        return self._by_key.get(key)

    def update_last_execution(self, safe_store, key, execute_at: Timestamp,
                              is_write: bool, txn_id=None) -> None:
        """Normal-path update.  Advances registers monotonically, counts
        write inversions, and enforces the ephemeral fence — except over
        bootstrap/stale footprints and for pre-bootstrap txns (``txn_id``
        below the key's bootstrapped_at), where out-of-order landing is
        expected (the reference's safeToReadAt gate)."""
        tfk = self.get_or_create(key)
        rk = key.to_routing() if hasattr(key, "to_routing") else key
        store = safe_store.store
        unsafe = (store.pending_bootstrap
                  and store.pending_bootstrap.contains(rk))
        if not unsafe:
            stale = getattr(safe_store.data_store(), "stale_ranges", None)
            unsafe = stale is not None and len(stale) and stale.contains(rk)
        if not unsafe and txn_id is not None:
            e = store.redundant_before.entry(rk)
            unsafe = e is not None and e.bootstrapped_at is not None \
                and txn_id < e.bootstrapped_at
        if not unsafe and tfk.violates_ephemeral_fence(execute_at, is_write):
            safe_store.agent().on_inconsistent_timestamp(
                txn_id, tfk.last_ephemeral_read, execute_at)
        if tfk.record_execution(execute_at, is_write):
            store.tfk_inversions += 1

    def record_ephemeral_read(self, key, snapshot_at: Timestamp) -> None:
        self.get_or_create(key).record_ephemeral_read(snapshot_at)

    def merge_applied_write(self, key, execute_at: Timestamp) -> None:
        self.get_or_create(key).record_execution(execute_at, True)

    def remove_redundant_by(self, bound_fn) -> None:
        """GC: trim each record below ``bound_fn(key) -> Optional[Timestamp]``
        (per-key shard-redundant bounds); drop records that become empty."""
        drop = []
        for k, tfk in self._by_key.items():
            bound = bound_fn(k)
            if bound is not None and tfk.without_redundant(bound):
                drop.append(k)
        for k in drop:
            del self._by_key[k]

    def remove_redundant(self, redundant_before: Timestamp) -> None:
        self.remove_redundant_by(lambda _k: redundant_before)

    def __len__(self) -> int:
        return len(self._by_key)
