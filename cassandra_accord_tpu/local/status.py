"""The command status lattice.

Capability parity with ``accord.local.SaveStatus/Status`` (SaveStatus.java:51-92,
Status.java:47-964): a command progresses monotonically through phases
None -> PreAccept -> Accept -> Commit -> Execute -> Persist -> Cleanup; ``SaveStatus``
refines Status with the local-execution sub-states (WaitingToExecute ->
ReadyToExecute -> WaitingToApply -> Applying -> Applied) and the truncation/erasure
terminal states.  The ``Known`` lattice tracks which facts about a txn a replica has
(route / definition / executeAt / deps / outcome) and is what CheckStatus merges
across replicas during recovery.
"""
from __future__ import annotations

import enum
from typing import NamedTuple, Optional


class Phase(enum.IntEnum):
    NONE = 0
    PRE_ACCEPT = 1
    ACCEPT = 2
    COMMIT = 3
    EXECUTE = 4
    PERSIST = 5
    CLEANUP = 6


class Status(enum.Enum):
    """Coarse protocol status (Status.java)."""
    NOT_DEFINED = (0, Phase.NONE)
    PRE_ACCEPTED = (1, Phase.PRE_ACCEPT)
    ACCEPTED_INVALIDATE = (2, Phase.ACCEPT)
    ACCEPTED = (3, Phase.ACCEPT)
    PRE_COMMITTED = (4, Phase.COMMIT)
    COMMITTED = (5, Phase.COMMIT)
    STABLE = (6, Phase.EXECUTE)
    PRE_APPLIED = (7, Phase.PERSIST)
    APPLIED = (8, Phase.PERSIST)
    TRUNCATED = (9, Phase.CLEANUP)
    INVALIDATED = (10, Phase.CLEANUP)

    def __init__(self, ordinal: int, phase: Phase):
        self.ordinal = ordinal
        self.phase = phase

    def has_been(self, other: "Status") -> bool:
        return self.ordinal >= other.ordinal

    def __lt__(self, other: "Status") -> bool:
        return self.ordinal < other.ordinal

    def __le__(self, other: "Status") -> bool:
        return self.ordinal <= other.ordinal

    def __gt__(self, other: "Status") -> bool:
        return self.ordinal > other.ordinal

    def __ge__(self, other: "Status") -> bool:
        return self.ordinal >= other.ordinal


class SaveStatus(enum.Enum):
    """Fine-grained save state (SaveStatus.java:51-92), including LocalExecution."""
    NOT_DEFINED = (0, Status.NOT_DEFINED)
    PRE_ACCEPTED = (1, Status.PRE_ACCEPTED)
    ACCEPTED_INVALIDATE = (2, Status.ACCEPTED_INVALIDATE)
    ACCEPTED = (3, Status.ACCEPTED)
    PRE_COMMITTED = (4, Status.PRE_COMMITTED)
    COMMITTED = (5, Status.COMMITTED)
    STABLE = (6, Status.STABLE)                   # == WaitingToExecute
    READY_TO_EXECUTE = (7, Status.STABLE)
    PRE_APPLIED = (8, Status.PRE_APPLIED)         # == WaitingToApply
    APPLYING = (9, Status.PRE_APPLIED)
    APPLIED = (10, Status.APPLIED)
    TRUNCATED_APPLY = (11, Status.TRUNCATED)
    ERASED = (12, Status.TRUNCATED)
    INVALIDATED = (13, Status.INVALIDATED)

    def __init__(self, ordinal: int, status: Status):
        self.ordinal = ordinal
        self.status = status

    @property
    def phase(self) -> Phase:
        return self.status.phase

    def has_been(self, status: Status) -> bool:
        return self.status.has_been(status)

    @property
    def is_terminal(self) -> bool:
        return self in (SaveStatus.APPLIED, SaveStatus.TRUNCATED_APPLY,
                        SaveStatus.ERASED, SaveStatus.INVALIDATED)

    @property
    def is_truncated(self) -> bool:
        return self in (SaveStatus.TRUNCATED_APPLY, SaveStatus.ERASED)

    @property
    def is_decided(self) -> bool:
        """executeAt agreed (committed) or invalidated."""
        return self.has_been(Status.PRE_COMMITTED)

    def __lt__(self, other: "SaveStatus") -> bool:
        return self.ordinal < other.ordinal

    def __le__(self, other: "SaveStatus") -> bool:
        return self.ordinal <= other.ordinal

    def __gt__(self, other: "SaveStatus") -> bool:
        return self.ordinal > other.ordinal

    def __ge__(self, other: "SaveStatus") -> bool:
        return self.ordinal >= other.ordinal


class Durability(enum.IntEnum):
    """Durability of a txn's outcome across its shards (Status.java:862)."""
    NOT_DURABLE = 0
    LOCAL = 1
    SHARD_UNIVERSAL = 2     # durable on every healthy replica of this shard
    MAJORITY = 3            # durable at a majority of every shard
    UNIVERSAL = 4           # durable at every healthy replica of every shard

    @property
    def is_durable(self) -> bool:
        return self >= Durability.MAJORITY

    @property
    def is_durable_or_invalidated(self) -> bool:
        return self.is_durable


# -- the Known lattice (Status.java:455-860) ---------------------------------

class KnownRoute(enum.IntEnum):
    MAYBE = 0
    COVERING = 1
    FULL = 2


class Definition(enum.IntEnum):
    UNKNOWN = 0
    KNOWN = 1
    ERASED = 2


class KnownExecuteAt(enum.IntEnum):
    UNKNOWN = 0
    PROPOSED = 1
    KNOWN = 2
    NO_EXECUTE_AT = 3      # invalidated


class KnownDeps(enum.IntEnum):
    UNKNOWN = 0
    PROPOSED = 1
    COMMITTED = 2          # deps agreed at commit
    KNOWN = 3              # stable deps
    NO_DEPS = 4            # invalidated / not needed


class Outcome(enum.IntEnum):
    UNKNOWN = 0
    APPLY = 1              # writes/result known
    INVALIDATED = 2
    ERASED = 3


class Known(NamedTuple):
    """What a replica knows about a txn; merged across replicas by CheckStatus."""
    route: KnownRoute = KnownRoute.MAYBE
    definition: Definition = Definition.UNKNOWN
    execute_at: KnownExecuteAt = KnownExecuteAt.UNKNOWN
    deps: KnownDeps = KnownDeps.UNKNOWN
    outcome: Outcome = Outcome.UNKNOWN

    def merge(self, other: "Known") -> "Known":
        return Known(
            max(self.route, other.route),
            max(self.definition, other.definition),
            max(self.execute_at, other.execute_at),
            max(self.deps, other.deps),
            max(self.outcome, other.outcome),
        )

    @property
    def is_definition_known(self) -> bool:
        return self.definition is Definition.KNOWN

    @property
    def is_decision_known(self) -> bool:
        return self.execute_at in (KnownExecuteAt.KNOWN, KnownExecuteAt.NO_EXECUTE_AT)

    @property
    def is_outcome_known(self) -> bool:
        return self.outcome is not Outcome.UNKNOWN


def known_for(save_status: SaveStatus, has_route: bool, has_txn: bool) -> Known:
    """Project a replica's SaveStatus onto the Known lattice."""
    route = KnownRoute.FULL if has_route else KnownRoute.MAYBE
    definition = Definition.KNOWN if has_txn else Definition.UNKNOWN
    if save_status is SaveStatus.INVALIDATED:
        return Known(route, definition, KnownExecuteAt.NO_EXECUTE_AT, KnownDeps.NO_DEPS,
                     Outcome.INVALIDATED)
    if save_status is SaveStatus.ERASED:
        return Known(route, Definition.ERASED, KnownExecuteAt.UNKNOWN, KnownDeps.UNKNOWN,
                     Outcome.ERASED)
    execute_at = KnownExecuteAt.UNKNOWN
    if save_status.has_been(Status.PRE_COMMITTED):
        execute_at = KnownExecuteAt.KNOWN
    elif save_status.has_been(Status.ACCEPTED):
        execute_at = KnownExecuteAt.PROPOSED
    deps = KnownDeps.UNKNOWN
    if save_status.has_been(Status.STABLE):
        deps = KnownDeps.KNOWN
    elif save_status.has_been(Status.COMMITTED):
        deps = KnownDeps.COMMITTED
    elif save_status in (SaveStatus.ACCEPTED, SaveStatus.PRE_ACCEPTED):
        deps = KnownDeps.PROPOSED
    outcome = Outcome.APPLY if save_status.has_been(Status.PRE_APPLIED) else Outcome.UNKNOWN
    return Known(route, definition, execute_at, deps, outcome)
