"""CommandsForKey — the per-key conflict index and per-key execution manager.

Capability parity with ``accord.local.cfk.CommandsForKey`` (CommandsForKey.java:82-1495):
for every key a CommandStore owns, an ordered index of all transactions that witnessed
the key, used for (a) dependency calculation at PreAccept/Accept (``map_reduce_active``)
and (b) driving execution order of key-domain reads/writes it manages.

Representation notes vs the reference: the reference packs TxnInfo into sorted arrays
with deps-by-omission encoding (divergences in ``missing[]``); this module keeps an
explicit sorted list of TxnInfo entries behind the same interface, with the
accelerator index (impl/tpu_resolver.py) as the batched data plane.

TRANSITIVE DEPENDENCY ELISION (CommandsForKey.java:144-157, mapReduceActive
:925-986): the deps query first establishes the latest committed WRITE whose
executeAt precedes the query bound — every committed txn executing before that
write, and witnessed by it, is transitively ordered by it and is elided from
the answer.  This is what keeps computed deps O(concurrent txns) instead of
O(key history): the covering write stands in for everything it orders.  The
recovery-safety argument is the reference's (doc :146-157): both the covering
write and the elided txn are committed at this replica, so any recovery
coordinator contacting it learns the agreed outcome directly and never needs
to decipher a fast-path decision from the elided dependency's presence.
"""
from __future__ import annotations

import enum
from bisect import bisect_left, insort
from typing import Callable, Dict, List, Optional, TYPE_CHECKING

from ..primitives.keys import RoutingKey
from ..primitives.timestamp import Domain, Timestamp, TxnId, TxnKind
from ..utils.invariants import Invariants, check_state

if TYPE_CHECKING:
    from .command import Command


def manages(txn_id: TxnId) -> bool:
    """CFK tracks txns that are key-domain and globally visible
    (CommandsForKey.java:185-189)."""
    return txn_id.domain is Domain.KEY and txn_id.kind.is_globally_visible


def manages_execution(txn_id: TxnId) -> bool:
    """CFK wholly manages execution of key-domain reads/writes
    (CommandsForKey.java:196-199): other txns need only a dependency on the Key."""
    return txn_id.domain is Domain.KEY and TxnKind.WRITE.witnesses(txn_id.kind)


class InternalStatus(enum.IntEnum):
    """Condensed per-key view of a txn's lifecycle (reference InternalStatus)."""
    TRANSITIVELY_KNOWN = 0   # witnessed only via another txn's deps
    PREACCEPTED = 1
    ACCEPTED = 2             # slow-path accepted (executeAt may move)
    COMMITTED = 3            # executeAt fixed
    STABLE = 4               # deps fixed
    APPLIED = 5
    INVALIDATED = 6


_DECIDED = (InternalStatus.COMMITTED, InternalStatus.STABLE, InternalStatus.APPLIED)


class TxnInfo:
    __slots__ = ("txn_id", "status", "execute_at", "ballot", "durable")

    def __init__(self, txn_id: TxnId, status: InternalStatus,
                 execute_at: Optional[Timestamp] = None, ballot=None):
        self.txn_id = txn_id
        self.status = status
        self.execute_at = execute_at if execute_at is not None else txn_id
        self.ballot = ballot
        # outcome applied at EVERY replica (per-txn InformDurable(UNIVERSAL) /
        # the range durability watermark) — the elision soundness gate
        self.durable = False

    def __lt__(self, other: "TxnInfo") -> bool:
        return self.txn_id < other.txn_id

    def __repr__(self) -> str:
        return f"TxnInfo({self.txn_id!r}, {self.status.name}, @{self.execute_at!r})"


class CommandsForKey:
    """Mutable per-key index (the safe/command-store layer guards all access)."""

    __slots__ = ("key", "by_id", "prune_before", "_max_applied_write",
                 "_max_applied_write_tid", "_unmanaged_waiting",
                 "_committed_writes", "cold", "_cold_max_ea", "_cold_max_tid",
                 "_pruned_max", "_merged_cache")

    def __init__(self, key: RoutingKey):
        self.key = key
        self.by_id: List[TxnInfo] = []          # sorted by txn_id
        self.prune_before: Optional[TxnId] = None
        self._max_applied_write: Optional[Timestamp] = None
        # unmanaged (range/syncpoint) txns registered to be notified when the key's
        # managed txns up to a bound have applied: list of (wait_until_ts, txn_id)
        self._unmanaged_waiting: List[tuple] = []
        # committed-or-later WRITEs sorted by executeAt (fixed at commit) —
        # the covering-write index for transitive elision (the reference's
        # committedByExecuteAt restricted to writes, CommandsForKey.java:929-944)
        # NOTE: retains demoted (cold) writes — maxcw must not recede
        self._committed_writes: List[tuple] = []    # (execute_at, txn_id)
        # the COLD tier: terminal (applied/invalidated) universally-durable
        # entries demoted out of the hot walk.  The hot-only walk is exact
        # for any query bound whose covering write executes after every cold
        # entry (`_cold_max_ea`); stale bounds take the merged walk — the
        # semantics of an unsplit by_id are preserved bit-for-bit, this is
        # purely the O(history) -> O(concurrency) walk-cost fix (the
        # reference bounds the same walk with prunedBefore + loadingPruned,
        # CommandsForKey.java:115-143; we can afford to keep the cold map)
        self.cold: Dict[TxnId, TxnInfo] = {}
        self._cold_max_ea: Optional[Timestamp] = None   # max ea of emittable cold
        self._cold_max_tid: Optional[TxnId] = None      # max tid of emittable cold
        self._pruned_max: Optional[Timestamp] = None    # max ts floor of removed
        self._max_applied_write_tid: Optional[TxnId] = None
        # memo of the cold+hot MERGED walk order (sync-point / stale-bound
        # queries): rebuilding sorted(cold + by_id) per query was
        # O(history log history) PER SYNC-POINT QUERY PER KEY.  Holds
        # TxnInfo REFERENCES, so in-place status upgrades stay visible
        # (txn_id order never changes); any MEMBERSHIP change invalidates
        # (_demote / _prune / hot insert)
        self._merged_cache: Optional[List[TxnInfo]] = None

    # -- lookup -------------------------------------------------------------
    def get(self, txn_id: TxnId) -> Optional[TxnInfo]:
        i = bisect_left(self.by_id, TxnInfo(txn_id, InternalStatus.TRANSITIVELY_KNOWN))
        if i < len(self.by_id) and self.by_id[i].txn_id == txn_id:
            return self.by_id[i]
        return self.cold.get(txn_id)

    def max_hlc(self) -> int:
        return max((info.txn_id.hlc for info in self.by_id), default=0)

    def max_timestamp(self) -> Optional[Timestamp]:
        """Max of txnId/executeAt witnessed on this key (for timestamp proposal).
        ``_pruned_max`` floors the answer past demotion/pruning: a proposal must
        exceed every write the key EVER witnessed, resident or not."""
        out: Optional[Timestamp] = self._pruned_max
        for info in self.by_id:
            c = info.execute_at if info.execute_at > info.txn_id else info.txn_id
            if out is None or c > out:
                out = c
        return out

    # -- registration -------------------------------------------------------
    def update(self, txn_id: TxnId, status: InternalStatus,
               execute_at: Optional[Timestamp] = None) -> bool:
        """Witness / upgrade a txn on this key. Monotonic: status never regresses,
        and execute_at only moves on a status upgrade or while ACCEPTED (the one
        phase where a re-proposal may legitimately change it; ballot gating happens
        upstream in Commands before cfk is told).

        Returns False when the txn is NOT indexed: unmanaged, or at-or-below
        ``prune_before`` and absent — a pruned (applied/invalidated) entry that a
        late message must not resurrect (the pruning protocol's reload guard,
        cfk/CommandsForKey.java:115-143: ids below the prune point are
        implied-applied and served by the RedundantBefore floor deps)."""
        if not manages(txn_id):
            return False
        if txn_id in self.cold:
            # demoted terminal entry: nothing can upgrade it, and a late
            # message must not re-index it (same resurrection guard as the
            # prune path below)
            return False
        probe = TxnInfo(txn_id, status, execute_at)
        i = bisect_left(self.by_id, probe)
        found = i < len(self.by_id) and self.by_id[i].txn_id == txn_id
        if not found and self.prune_before is not None \
                and txn_id <= self.prune_before:
            return False
        if found:
            info = self.by_id[i]
            if status is InternalStatus.INVALIDATED \
                    and info.status in _DECIDED:
                # a committed txn can never be invalidated: a late/erroneous
                # invalidation message must not corrupt the index (the
                # covering-write entries are final from COMMITTED on)
                return True
            if status > info.status:
                was = info.status
                info.status = status
                # executeAt is FINAL from COMMITTED on (the reference's
                # TxnInfo/committedByExecuteAt invariant): only the upgrade
                # that enters the committed lattice may (re)set it
                if execute_at is not None and was < InternalStatus.COMMITTED:
                    info.execute_at = execute_at
                self._maybe_index_committed_write(info, was)
                if self._demotable(info):
                    self.by_id.pop(i)
                    self._demote(info)
            elif (status == info.status and execute_at is not None
                  and status is InternalStatus.ACCEPTED):
                info.execute_at = execute_at
        else:
            self.by_id.insert(i, probe)
            self._merged_cache = None     # membership changed
            self._maybe_index_committed_write(probe, None)
        if status is InternalStatus.APPLIED and txn_id.is_write:
            ea = execute_at if execute_at is not None else txn_id
            if self._max_applied_write is None or ea > self._max_applied_write:
                self._max_applied_write = ea
                self._max_applied_write_tid = txn_id
                self._demote_sweep()   # older durable frontier entries now covered
        return True

    def witness_transitively(self, txn_id: TxnId) -> None:
        if self.get(txn_id) is None:
            self.update(txn_id, InternalStatus.TRANSITIVELY_KNOWN)

    def _maybe_index_committed_write(self, info: TxnInfo,
                                     was: Optional[InternalStatus]) -> None:
        """Track a WRITE's entry into the committed lattice (executeAt is final
        from COMMITTED on, so the by-executeAt position never moves)."""
        if info.txn_id.is_write \
                and info.status in _DECIDED \
                and (was is None or was < InternalStatus.COMMITTED):
            insort(self._committed_writes, (info.execute_at, info.txn_id))

    def max_committed_write_before(self, before: Timestamp) -> Optional[Timestamp]:
        """ExecuteAt of the latest committed WRITE executing strictly before
        ``before`` — the covering write for transitive elision
        (CommandsForKey.java:929-944)."""
        cw = self._covering_write_before(before)
        return cw[0] if cw is not None else None

    def _covering_write_before(self, before: Timestamp) -> Optional[tuple]:
        """(execute_at, txn_id) of the covering write — elision needs BOTH
        coordinates: a covered txn must execute before the cover AND have been
        witnessable by it (txn_id below the cover's), else the cover's own
        global deps never chained through it and eliding it breaks the
        local-apply transitivity fences rely on (round-5 stale-cascade #2:
        a REORDERED covering write — executeAt above, txnId below — elided
        entries it never witnessed)."""
        i = bisect_left(self._committed_writes, (before,)) - 1
        return self._committed_writes[i] if i >= 0 else None

    # -- dependency calculation (the HOT query; CommandsForKey.java:925-1000) ----
    def map_reduce_active(self, before: Timestamp, witnesses: Callable[[TxnId], bool],
                          fn: Callable[[TxnId], None],
                          durable_majority: Optional[TxnId] = None,
                          flag_elision: bool = True) -> None:
        """Visit every active managed txn with txnId < before that the caller's
        kind witnesses — MINUS committed txns transitively covered by the
        latest committed write executing before the bound (elision, module
        doc).  This is the PreAccept/Accept deps query.

        SOUNDNESS GATE (stronger than the reference, whose elision carries an
        unresolved 'prove the correctness of this approach' TODO,
        CommandsForKey.java:956): a txn is elided only when ALSO below
        ``durable_majority`` — the majority-durable watermark for this key.
        Elision removes the txn from later deps, which poisons per-replica
        recovery evidence ('T executed after ours WITHOUT witnessing us'
        would reject our fast path, BeginRecovery.java:329-380) at replicas
        where the elided txn is still undecided.  Majority durability restores
        the quorum argument: every recovery quorum then intersects a replica
        holding the txn APPLIED, so its agreed outcome is always discovered
        before any fast-path deciphering.  The hostile burn demonstrated the
        violation (a fast-committed range read invalidated by elision-poisoned
        evidence) before this gate."""
        cw = self._covering_write_before(before)
        maxcw, maxcw_tid = cw if cw is not None else (None, None)
        entries: List[TxnInfo] = self.by_id
        # the hot-only walk is exact iff every EMITTABLE cold entry would be
        # elided at this bound: flag elision applies (not a sync-point
        # query) and the bound's covering write dominates every cold entry
        # on BOTH coordinates (invalidated cold entries are never emitted)
        hot_only = self._cold_max_ea is None or (
            flag_elision and maxcw is not None
            and self._cold_max_ea < maxcw and self._cold_max_tid < maxcw_tid)
        if self.cold and not hot_only:
            # sync-point query or stale bound — take the merged walk,
            # bit-identical to an unsplit index.  Common bounds from normal
            # txns sit above every cold entry's covering write and walk the
            # hot tier only: O(concurrency), not O(history).  The merged
            # order is memoized (columnar-engine round): every exclusive
            # sync point re-sorted the key's WHOLE history per deps query
            # before this, O(history log history) per fence per key.
            entries = self._merged_cache
            if entries is None:
                entries = self._merged_cache = sorted(
                    list(self.cold.values()) + self.by_id)
        for info in entries:
            if info.txn_id >= before:
                break
            st = info.status
            if st is InternalStatus.INVALIDATED \
                    or st is InternalStatus.TRANSITIVELY_KNOWN:
                continue
            if not witnesses(info.txn_id):
                continue
            if maxcw is not None and st in _DECIDED \
                    and ((flag_elision and info.durable
                          and info.txn_id < maxcw_tid)
                         or (durable_majority is not None
                             and info.txn_id < durable_majority)) \
                    and info.execute_at < maxcw \
                    and TxnKind.WRITE.witnesses(info.txn_id.kind):
                continue    # ordered (and witnessed) by the covering write
            fn(info.txn_id)

    def map_reduce_full(self, fn: Callable[[TxnInfo], None]) -> None:
        for info in self.by_id:
            fn(info)

    # -- execution management ----------------------------------------------
    def next_waiting_to_apply(self) -> Optional[TxnInfo]:
        """Earliest committed-but-unapplied managed txn by executeAt."""
        best: Optional[TxnInfo] = None
        for info in self.by_id:
            if info.status in (InternalStatus.COMMITTED, InternalStatus.STABLE) \
                    and manages_execution(info.txn_id):
                if best is None or info.execute_at < best.execute_at:
                    best = info
        return best

    def blocking_txns(self, txn_id: TxnId, execute_at: Timestamp) -> List[TxnId]:
        """Managed txns that must apply before (txn_id, execute_at) may execute:
        all managed txns with executeAt (or txnId if undecided) < execute_at that are
        not yet applied/invalidated, and which txn_id witnesses-or-is-witnessed-by.

        Undecided txns with lower txnId may still commit with executeAt < ours, so
        they block; committed txns ordered after us do not."""
        out: List[TxnId] = []
        for info in self.by_id:
            if info.txn_id == txn_id:
                continue
            if not manages_execution(info.txn_id):
                continue
            if info.status in (InternalStatus.APPLIED, InternalStatus.INVALIDATED):
                continue
            if info.status in _DECIDED:
                if info.execute_at < execute_at and _conflicts(txn_id, info.txn_id):
                    out.append(info.txn_id)
            else:
                # undecided: blocks iff it could still be ordered before us
                if info.txn_id < execute_at and _conflicts(txn_id, info.txn_id):
                    out.append(info.txn_id)
        return out

    # -- unmanaged registration (CommandsForKey.Unmanaged, :447) -------------
    def register_unmanaged(self, txn_id: TxnId, wait_until: Timestamp) -> None:
        self._unmanaged_waiting.append((wait_until, txn_id))

    def ready_unmanaged(self) -> List[TxnId]:
        """Unmanaged txns whose wait bound is satisfied: every managed txn with
        executeAt <= bound is applied or invalidated."""
        ready, keep = [], []
        for bound, tid in self._unmanaged_waiting:
            if self._all_applied_until(bound):
                ready.append(tid)
            else:
                keep.append((bound, tid))
        self._unmanaged_waiting = keep
        return ready

    def _all_applied_until(self, bound: Timestamp) -> bool:
        for info in self.by_id:
            if not manages_execution(info.txn_id):
                continue
            if info.status in (InternalStatus.APPLIED, InternalStatus.INVALIDATED):
                continue
            at = info.execute_at if info.status in _DECIDED else info.txn_id
            if at <= bound:
                return False
        return True

    # -- per-txn durability + hot/cold demotion ------------------------------
    def _note_removed_max(self, info: TxnInfo) -> None:
        c = info.execute_at if info.execute_at > info.txn_id else info.txn_id
        if self._pruned_max is None or c > self._pruned_max:
            self._pruned_max = c

    def _demotable(self, info: TxnInfo) -> bool:
        """May this entry leave the hot walk?  INVALIDATED entries are never
        emitted/blocking at any bound; APPLIED entries must be universally durable,
        WRITE-witnessed, and strictly below the latest applied write — keeping
        the covering write itself hot guarantees fresh query bounds see
        ``maxcw > _cold_max_ea`` and stay on the O(concurrency) hot walk."""
        if info.status is InternalStatus.INVALIDATED:
            return True
        return (info.status is InternalStatus.APPLIED and info.durable
                and TxnKind.WRITE.witnesses(info.txn_id.kind)
                and self._max_applied_write is not None
                and info.execute_at < self._max_applied_write
                and self._max_applied_write_tid is not None
                and info.txn_id < self._max_applied_write_tid)

    def _demote_sweep(self) -> None:
        """The max applied write advanced: entries that were the frontier when
        flagged durable (applies land roughly in executeAt order, so the
        newest write never passes the cover check at its own apply) are now
        covered — demote them."""
        demoted = False
        keep: List[TxnInfo] = []
        for info in self.by_id:
            if self._demotable(info):
                self._demote(info)
                demoted = True
            else:
                keep.append(info)
        if demoted:
            self.by_id = keep

    def _demote(self, info: TxnInfo) -> None:
        self.cold[info.txn_id] = info
        self._merged_cache = None         # membership changed (hot -> cold)
        self._note_removed_max(info)
        if info.status is not InternalStatus.INVALIDATED:
            ea = info.execute_at
            if self._cold_max_ea is None or ea > self._cold_max_ea:
                self._cold_max_ea = ea
            if self._cold_max_tid is None or info.txn_id > self._cold_max_tid:
                self._cold_max_tid = info.txn_id

    def mark_durable(self, txn_id: TxnId) -> None:
        """The txn's outcome is applied at EVERY replica (per-txn
        InformDurable(UNIVERSAL) after the coordinator saw all Apply acks, or
        a durability watermark advance).  Widens the elision gate for this
        entry NOW — instead of waiting for the next range durability round —
        and demotes it to the cold tier once terminal."""
        i = bisect_left(self.by_id, TxnInfo(txn_id, InternalStatus.TRANSITIVELY_KNOWN))
        if i >= len(self.by_id) or self.by_id[i].txn_id != txn_id:
            return
        info = self.by_id[i]
        info.durable = True
        if self._demotable(info):
            self.by_id.pop(i)
            self._demote(info)

    def mark_durable_below(self, bound: TxnId) -> None:
        """Range durability watermark advance: flag + demote everything below."""
        keep: List[TxnInfo] = []
        for info in self.by_id:
            if info.txn_id < bound:
                info.durable = True
                if self._demotable(info):
                    self._demote(info)
                    continue
            keep.append(info)
        if len(keep) != len(self.by_id):
            self.by_id = keep

    # -- pruning (doc CommandsForKey.java:115-143) ---------------------------
    def _prune(self, prunable: Callable[["TxnInfo"], bool]) -> List[TxnId]:
        """Drop APPLIED/INVALIDATED entries matching ``prunable`` (hot and
        cold tiers); prune_before is retained so late-arriving deps below it
        are treated as already-applied rather than unknown.  Returns the
        pruned ids (the resolver data plane evicts the same incidences)."""
        keep: List[TxnInfo] = []
        pruned: List[TxnId] = []
        highest: Optional[TxnId] = self.prune_before
        for info in self.by_id:
            if info.status in (InternalStatus.APPLIED, InternalStatus.INVALIDATED) \
                    and prunable(info):
                pruned.append(info.txn_id)
                self._note_removed_max(info)
                if highest is None or info.txn_id > highest:
                    highest = info.txn_id
            else:
                keep.append(info)
        for txn_id in [t for t, info in self.cold.items() if prunable(info)]:
            del self.cold[txn_id]
            pruned.append(txn_id)
            if highest is None or txn_id > highest:
                highest = txn_id
        if pruned:
            self.by_id = keep
            self._merged_cache = None     # membership changed
            self.prune_before = highest
            gone = set(pruned)
            self._committed_writes = [e for e in self._committed_writes
                                      if e[1] not in gone]
        return pruned

    def maybe_prune(self, prune_before_hlc_delta: int) -> List[TxnId]:
        """HLC-delta policy prune: drop applied entries well behind the max HLC."""
        if not self.by_id:
            return []
        cutoff_hlc = self.max_hlc() - prune_before_hlc_delta
        return self._prune(lambda info: info.txn_id.hlc < cutoff_hlc)

    def prune_applied_before(self, bound: TxnId) -> List[TxnId]:
        """Bound-driven prune (GC by RedundantBefore): drop applied entries with
        txn_id < bound; they are implied-applied for late arrivals."""
        return self._prune(lambda info: info.txn_id < bound)

    def is_pruned(self, txn_id: TxnId) -> bool:
        # prune_before is the highest pruned id, inclusive
        return self.prune_before is not None and txn_id <= self.prune_before \
            and self.get(txn_id) is None

    def size(self) -> int:
        return len(self.by_id)

    def __repr__(self) -> str:
        return f"CFK({self.key!r}, {len(self.by_id)} txns)"


def _conflicts(a: TxnId, b: TxnId) -> bool:
    return a.witnesses(b) or b.witnesses(a)
