"""Batched dependency-graph kernels: overlap join, closure, elision, Kahn, SCC.

These are the TPU data-plane replacements for the reference's hot loops:

- ``overlap_join``        <- ``CommandsForKey.mapReduceActive`` per-key scans
                            (cfk/CommandsForKey.java:925-1000) + KeyDeps builder
                            merges (KeyDeps.java:110-148).  One bf16 matmul on
                            the MXU computes the whole PreAccept batch's
                            conflicts against every in-flight txn at once,
                            with the Txn.Kind witness matrix (Txn.java:221-262)
                            and started-before predicate fused as masks.
- ``transitive_closure``  <- the implicit transitive reachability the reference
                            maintains via deps-by-omission/elision
                            (CommandsForKey.java:101-157).  log2(T) boolean
                            matrix squarings.
- ``elide``               <- transitive dependency elision (doc :144-157):
                            drop edge i->j when a longer path i->..->j exists.
- ``kahn_frontier``/``kahn_levels`` <- the WaitingOn execution frontier
                            (Command.java:1225-1320, Commands.maybeExecute
                            Commands.java:617): which txns have all deps
                            applied and may execute now, and the full
                            topological schedule.
- ``scc_condense``        <- cycle handling: Accord's deps graph may contain
                            cycles (the decided executeAt breaks them at
                            execution time, Commands.java:707-775); SCC
                            membership via forward&backward reachability lets
                            a batch executor order a cycle-heavy graph by
                            (condensed topo level, executeAt).

Everything is static-shape, jit-safe, and deterministic.  Matmuls are bf16 on
the MXU with f32 accumulation; inputs are 0/1 and only zero/nonzero of the
product is consumed, so results are exact.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .graph_state import ts_less, STABLE, APPLIED, INVALIDATED


def _witness_table() -> np.ndarray:
    """WITNESSES[a, b] = does a txn of kind-code a depend on conflicting txns
    of kind-code b (Txn.Kind.witnesses, Txn.java:221-262).  Built from the
    host enum so device and control plane can never disagree."""
    from ..primitives.timestamp import TxnKind
    n = len(TxnKind)
    w = np.zeros((n, n), dtype=np.bool_)
    for a in TxnKind:
        for b in TxnKind:
            w[a, b] = a.witnesses(b)
    return w


WITNESSES = jnp.asarray(_witness_table())


def _bool_matmul(a: jax.Array, b: jax.Array) -> jax.Array:
    """Boolean matrix product (a @ b) > 0 via bf16 MXU matmul."""
    p = jax.lax.dot_general(
        a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
        dimension_numbers=(((a.ndim - 1,), (b.ndim - 2,)), ((), ())),
        preferred_element_type=jnp.float32)
    return p > 0.0


# ---------------------------------------------------------------------------
# Overlap join — the PreAccept/Accept dependency calculation
# ---------------------------------------------------------------------------

@jax.jit
def overlap_join(index_key_inc: jax.Array,   # [T, K] int8 — in-flight txns
                 index_txn_id: jax.Array,    # [T, 5] int32 — their TxnIds
                 index_kind: jax.Array,      # [T] int8
                 index_status: jax.Array,    # [T] int8
                 index_active: jax.Array,    # [T] bool
                 batch_key_inc: jax.Array,   # [B, K] int8 — new txns' keys
                 batch_before: jax.Array,    # [B, 5] int32 — started-before bound
                 batch_kind: jax.Array,      # [B] int8
                 ) -> jax.Array:
    """For each of B new transactions, the set of in-flight txns it must
    depend on: shares >=1 key, witness-matrix hit, active, not invalidated,
    and STARTED BEFORE the query bound (mapReduceActive's
    TestStartedAt.STARTED_BEFORE, SafeCommandStore.java:65-72).  The bound is
    the txnId for PreAccept and the proposed executeAt for the Accept round's
    deps-at-executeAt (Accept.java:84-118).

    Returns deps: [B, T] bool."""
    share_key = _bool_matmul(batch_key_inc, index_key_inc.T)             # [B, T]
    started_before = ts_less(index_txn_id[None, :, :],
                             batch_before[:, None, :])                   # [B, T]
    witnesses = WITNESSES[batch_kind[:, None].astype(jnp.int32),
                          index_kind[None, :].astype(jnp.int32)]         # [B, T]
    eligible = index_active & (index_status != INVALIDATED)              # [T]
    return share_key & started_before & witnesses & eligible[None, :]


def _lex_max_masked(vals: jax.Array, mask: jax.Array) -> jax.Array:
    """Lexicographic max of packed timestamps vals[B, T, L] over axis 1,
    considering only entries where mask[B, T]; fully-masked rows yield zero
    lanes (= Timestamp.NONE — all real lanes are >= 0)."""
    lanes = vals.shape[-1]
    tie = mask
    out = []
    for lane in range(lanes):
        m = jnp.where(tie, vals[..., lane], -1)
        best = jnp.max(m, axis=1)                      # [B]
        tie = tie & (vals[..., lane] == best[:, None])
        out.append(jnp.maximum(best, 0))
    return jnp.stack(out, axis=-1)                     # [B, L]


@jax.jit
def max_conflict_ts(index_exec_at: jax.Array,  # [T, 5] int32
                    deps: jax.Array,           # [B, T] bool
                    ) -> Tuple[jax.Array, jax.Array]:
    """Per new txn, the lexicographic max executeAt over its conflict set —
    the ``maxConflicts`` input to the replica-side timestamp proposal
    (Commands.preaccept / PreAccept.java:245-267, MaxConflicts.java:32).

    The proposal itself (txnId if maxConflict < txnId, else
    unique_now_at_least(maxConflict)) stays HOST-side: the HLC register that
    uniquifies proposals is host clock state (Node.unique_now_at_least,
    local/node.py), so the device reports the max and the control plane
    finalises — keeping device results bit-identical to the host resolver.

    Returns (conflict_max [B, 5] int32, any_dep [B] bool)."""
    conflict_max = _lex_max_masked(
        jnp.broadcast_to(index_exec_at[None, :, :],
                         deps.shape + (index_exec_at.shape[-1],)), deps)
    return conflict_max, jnp.any(deps, axis=1)


@jax.jit
def max_conflict_keys(index_key_inc: jax.Array,  # [T, K] int8
                      index_ts: jax.Array,       # [T, 5] int32 executeAt
                      index_txn_id: jax.Array,   # [T, 5] int32
                      index_active: jax.Array,   # [T] bool
                      batch_key_inc: jax.Array,  # [B, K] int8
                      ) -> jax.Array:
    """Per query, the lexicographic max of max(executeAt, txnId) over every
    active indexed txn sharing a key — the per-key half of the MaxConflicts
    consult in the replica timestamp proposal (cfk.max_timestamp per key,
    Commands.preaccept; MaxConflicts.java:32).  Returns [B, 5] int32 (zero
    lanes = none)."""
    share_key = _bool_matmul(batch_key_inc, index_key_inc.T)   # [B, T]
    mask = share_key & index_active[None, :]
    per_slot = jnp.where(ts_less(index_ts, index_txn_id)[:, None],
                         index_txn_id, index_ts)               # [T, 5]
    return _lex_max_masked(
        jnp.broadcast_to(per_slot[None, :, :], mask.shape + (per_slot.shape[-1],)),
        mask)


@jax.jit
def consult(index_live_inc: jax.Array,  # [T, K] int8 — covered bits zeroed
            index_key_inc: jax.Array,   # [T, K] int8 — full incidence
            index_ts: jax.Array,        # [T, 5] int32 executeAt
            index_txn_id: jax.Array,    # [T, 5] int32
            index_kind: jax.Array,      # [T] int8
            index_status: jax.Array,    # [T] int8
            index_active: jax.Array,    # [T] bool
            batch_key_inc: jax.Array,   # [B, K] int8
            batch_before: jax.Array,    # [B, 5] int32
            batch_kind: jax.Array,      # [B] int8
            ) -> Tuple[jax.Array, jax.Array]:
    """The fused replica consult: one launch answers BOTH halves of a
    PreAccept-class query batch — the dependency calculation
    (mapReduceActive / overlap_join) and the timestamp-proposal max
    (MaxConflicts / max_conflict_keys).  This is the per-message device
    round-trip collapsed to one, and with B > 1 it is the whole delivery
    window's deps traffic in one MXU dispatch.

    The deps join runs over the LIVE incidence — the full matrix minus
    per-incidence covered bits, which implement cfk transitive elision
    (CommandsForKey.java:144-157) for bounds above the per-key covering
    bound (the caller routes other bounds to the exact per-key path).  The
    timestamp-proposal max runs over the FULL incidence: elision never
    applies to MaxConflicts.

    Returns (deps [B, T] bool, max_lanes [B, 5] int32)."""
    share_live = _bool_matmul(batch_key_inc, index_live_inc.T)           # [B, T]
    started_before = ts_less(index_txn_id[None, :, :],
                             batch_before[:, None, :])                   # [B, T]
    witnesses = WITNESSES[batch_kind[:, None].astype(jnp.int32),
                          index_kind[None, :].astype(jnp.int32)]         # [B, T]
    eligible = index_active & (index_status != INVALIDATED)              # [T]
    deps = share_live & started_before & witnesses & eligible[None, :]
    share_full = _bool_matmul(batch_key_inc, index_key_inc.T)            # [B, T]
    mc_mask = share_full & index_active[None, :]
    per_slot = jnp.where(ts_less(index_ts, index_txn_id)[:, None],
                         index_txn_id, index_ts)                         # [T, 5]
    max_lanes = _lex_max_masked(
        jnp.broadcast_to(per_slot[None, :, :],
                         mc_mask.shape + (per_slot.shape[-1],)), mc_mask)
    return deps, max_lanes


@jax.jit
def consult_packed(index_live_inc: jax.Array, index_key_inc: jax.Array,
                   index_ts: jax.Array, index_txn_id: jax.Array,
                   index_kind: jax.Array, index_status: jax.Array,
                   index_active: jax.Array, batch_key_inc: jax.Array,
                   batch_before: jax.Array, batch_kind: jax.Array,
                   ) -> Tuple[jax.Array, jax.Array]:
    """``consult`` with the deps mask BIT-PACKED on device ([B, T/8] uint8,
    little-endian bit order, T a multiple of 8): at T = 64k the [B, T] bool
    transfer dominates the launch round-trip (16 MB at B = 256); packing cuts
    it 8× before it leaves HBM.  Hosts unpack with np.unpackbits."""
    deps, max_lanes = consult(index_live_inc, index_key_inc, index_ts,
                              index_txn_id, index_kind, index_status,
                              index_active, batch_key_inc, batch_before,
                              batch_kind)
    b, t = deps.shape
    bits = deps.reshape(b, t // 8, 8).astype(jnp.uint32)
    weights = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], dtype=jnp.uint32)
    packed = jnp.sum(bits * weights, axis=-1).astype(jnp.uint8)
    return packed, max_lanes


# ---------------------------------------------------------------------------
# Transitive closure / elision
# ---------------------------------------------------------------------------

@jax.jit
def transitive_closure(adj: jax.Array) -> jax.Array:
    """Reachability closure of a [T, T] bool adjacency by repeated squaring:
    R_{k+1} = R_k | R_k @ R_k, log2(T)+1 iterations, one MXU matmul each."""
    t = adj.shape[0]
    iters = max(1, int(t - 1).bit_length())
    reach = adj.astype(jnp.bool_)

    def body(_, r):
        return r | _bool_matmul(r, r)

    return jax.lax.fori_loop(0, iters, body, reach)


@jax.jit
def elide(adj: jax.Array) -> jax.Array:
    """Transitive reduction on DAG edges: drop i->j if a path i->k->..->j
    exists.  Mirrors the reference's dependency elision
    (CommandsForKey.java:144-157) — a dependency already implied transitively
    need not be tracked.  Edges inside a cycle are kept (reduction is only
    unique on the condensation)."""
    a = adj.astype(jnp.bool_)
    reach = transitive_closure(a)
    implied = _bool_matmul(a, reach)   # path of length >= 2
    in_cycle = reach & reach.T
    return a & (~implied | in_cycle)


# ---------------------------------------------------------------------------
# Execution frontier (Kahn) and full schedule
# ---------------------------------------------------------------------------

@jax.jit
def kahn_frontier(adj: jax.Array, status: jax.Array,
                  active: jax.Array) -> jax.Array:
    """Which txns are ready to execute NOW: stable, active, and every
    dependency already applied/invalidated/evicted (Commands.maybeExecute,
    Commands.java:617-652: Stable + !isWaiting -> ReadyToExecute).
    Returns [T] bool."""
    dep_done = (status == APPLIED) | (status == INVALIDATED) | ~active
    waiting = _bool_matmul(adj, (~dep_done)[:, None].astype(jnp.int8))[:, 0]
    return active & (status == STABLE) & ~waiting


@jax.jit
def kahn_levels(adj: jax.Array, active: jax.Array) -> jax.Array:
    """Full topological schedule: level[i] = longest dependency chain below i;
    executing levels in order respects every edge.  While-loop peeling
    zero-indegree txns, one matmul per level.  Cycle members never peel and
    keep level -1 (route them through scc_condense).  Returns [T] int32."""
    t = adj.shape[0]
    a = adj.astype(jnp.bool_) & active[:, None] & active[None, :]

    def cond(carry):
        _, done, it = carry
        return (it < t) & jnp.any(active & ~done)

    def body(carry):
        level, done, it = carry
        blocked = _bool_matmul(a, (~done)[:, None].astype(jnp.int8))[:, 0]
        newly = active & ~done & ~blocked
        progressed = jnp.any(newly)
        level = jnp.where(newly, it, level)
        done = done | newly
        it = jnp.where(progressed, it + 1, t)   # no progress => cycle: stop
        return level, done, it

    level0 = jnp.full((t,), -1, dtype=jnp.int32)
    level, _, _ = jax.lax.while_loop(cond, body, (level0, ~active, jnp.int32(0)))
    return level


# ---------------------------------------------------------------------------
# SCC condensation (cycle-heavy adversarial graphs, BASELINE config 5)
# ---------------------------------------------------------------------------

@jax.jit
def scc_condense(adj: jax.Array, active: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Strongly-connected-component labels via matmul reachability:
    i ~ j iff reach[i,j] & reach[j,i].  Label = smallest member slot.

    Returns (labels [T] int32, level [T] int32): level is a topological level
    over the condensation, shared by all members of an SCC — combined with
    executeAt order inside the component this yields a total execution order
    even for cyclic dependency graphs."""
    t = adj.shape[0]
    a = adj.astype(jnp.bool_) & active[:, None] & active[None, :]
    reach = transitive_closure(a)
    same = ((reach & reach.T) | jnp.eye(t, dtype=jnp.bool_))
    same = same & active[:, None] & active[None, :]
    idx = jnp.arange(t, dtype=jnp.int32)
    labels = jnp.min(jnp.where(same, idx[None, :], t), axis=1).astype(jnp.int32)
    labels = jnp.where(active, labels, -1)

    cond_edge = a & (labels[:, None] != labels[None, :])

    def cond_fn(carry):
        _, done, it = carry
        return (it < t) & jnp.any(active & ~done)

    def body_fn(carry):
        level, done, it = carry
        blocked = _bool_matmul(cond_edge, (~done)[:, None].astype(jnp.int8))[:, 0]
        # whole components move together: ready iff NO undone member has a
        # blocked cross-component dependency
        comp_blocked = jnp.zeros((t,), dtype=jnp.int32).at[labels].max(
            (blocked & active & ~done).astype(jnp.int32), mode="drop")
        ready = active & ~done & (comp_blocked[labels] == 0)
        progressed = jnp.any(ready)
        level = jnp.where(ready, it, level)
        done = done | ready
        it = jnp.where(progressed, it + 1, t)
        return level, done, it

    level0 = jnp.full((t,), -1, dtype=jnp.int32)
    level, _, _ = jax.lax.while_loop(cond_fn, body_fn,
                                     (level0, ~active, jnp.int32(0)))
    return labels, level
