"""Device-resident conflict-graph state for one command-store shard.

This is the TPU-native replacement for the reference's per-key CSR conflict
indexes (``accord.local.cfk.CommandsForKey`` byId/committedByExecuteAt arrays,
CommandsForKey.java:615-628, and ``accord.primitives.KeyDeps`` CSR maps,
KeyDeps.java:150-187).  Instead of pointer-chasing sorted arrays per key, a
shard keeps ONE fixed-shape pytree of device arrays covering every in-flight
transaction it manages:

- ``key_inc``   [T, K]  key-incidence matrix (txn slot x key slot), int8 on
                        host, cast to bf16 on the MXU path.  Key slots are
                        assigned exactly (host-side dict key->slot), never
                        hashed, so the computed dependency graph is bit-exact
                        with the reference resolver ("deps-graph parity").
- ``ts``        [T, 5]  execute-at/witnessed-at timestamp per slot: int32
                        lanes (epoch, hlc>>31, hlc&0x7FFFFFFF, flags, node)
                        from host Timestamp.pack_lanes().  Lexicographic over
                        the 5 lanes == host total order (epoch, hlc, flags,
                        node); all lanes non-negative and int32 so the device
                        plane never needs x64 mode (bounds enforced by
                        pack_lanes at the host boundary).
- ``txn_id``    [T, 5]  original TxnId packed the same way (slot identity).
- ``kind``      [T]     int8 Txn.Kind code (primitives.TxnKind) — drives the
                        witness matrix (Txn.java:221-262) during the join.
- ``status``    [T]     int8 InternalStatus code (local.cfk.InternalStatus).
- ``adj``       [T, T]  dependency adjacency: adj[i, j] = 1 iff txn i depends
                        on (must execute after) txn j.
- ``active``    [T]     slot-occupied mask.

All shapes are static: T (txn slots) and K (key slots) are capacity bounds;
slots are recycled by host-side compaction when RedundantBefore advances
(the GC watermark, RedundantBefore.java:49-529).  Everything in this module is
a pure function of arrays -> arrays and is jit/shard_map-safe.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# InternalStatus codes mirrored from ..local.cfk.InternalStatus (kept as plain
# ints here so device code never imports the host control plane).
TRANSITIVELY_KNOWN = 0
PREACCEPTED = 1
ACCEPTED = 2
COMMITTED = 3
STABLE = 4
APPLIED = 5
INVALIDATED = 6

TS_LANES = 5  # (epoch, hlc_hi, hlc_lo, flags, node)


class GraphState(NamedTuple):
    """One shard's device-resident conflict graph (see module doc)."""
    key_inc: jax.Array   # [T, K] int8
    ts: jax.Array        # [T, 5] int32 — execute-at (witnessed-at until fixed)
    txn_id: jax.Array    # [T, 5] int32 — slot identity
    kind: jax.Array      # [T] int8 — Txn.Kind code
    status: jax.Array    # [T] int8
    adj: jax.Array       # [T, T] int8
    active: jax.Array    # [T] bool

    @property
    def txn_slots(self) -> int:
        return self.key_inc.shape[0]

    @property
    def key_slots(self) -> int:
        return self.key_inc.shape[1]


def init_state(txn_slots: int, key_slots: int) -> GraphState:
    """Fresh empty shard state with static capacity (T, K)."""
    return GraphState(
        key_inc=jnp.zeros((txn_slots, key_slots), dtype=jnp.int8),
        ts=jnp.zeros((txn_slots, TS_LANES), dtype=jnp.int32),
        txn_id=jnp.zeros((txn_slots, TS_LANES), dtype=jnp.int32),
        kind=jnp.zeros((txn_slots,), dtype=jnp.int8),
        status=jnp.zeros((txn_slots,), dtype=jnp.int8),
        adj=jnp.zeros((txn_slots, txn_slots), dtype=jnp.int8),
        active=jnp.zeros((txn_slots,), dtype=jnp.bool_),
    )


def ts_less(a: jax.Array, b: jax.Array) -> jax.Array:
    """Lexicographic a < b over packed-timestamp lanes.

    a, b: [..., 5] int32 (broadcastable).  All lanes are non-negative
    (Timestamp.pack_lanes bounds, enforced at the host boundary) so signed
    compare is safe."""
    lt = a[..., TS_LANES - 1] < b[..., TS_LANES - 1]
    for lane in range(TS_LANES - 2, -1, -1):
        lt = (a[..., lane] < b[..., lane]) | ((a[..., lane] == b[..., lane]) & lt)
    return lt


def ts_next(ts: jax.Array, node_id) -> jax.Array:
    """Smallest timestamp strictly greater than ts for the given node:
    hlc+1 (with carry hlc_lo -> hlc_hi), flags cleared, node stamped —
    device analog of Node.unique_now_at_least when the conflict dominates
    the local clock (local/node.py).  ts: [..., 5] int32."""
    lo = ts[..., 2] + 1
    carry = lo >> 31                 # lo <= 2^31-1, so +1 overflows into bit 31
    return jnp.stack([
        ts[..., 0],
        ts[..., 1] + carry,
        lo & 0x7FFFFFFF,
        jnp.zeros_like(ts[..., 3]),
        jnp.broadcast_to(jnp.asarray(node_id, dtype=ts.dtype), ts[..., 4].shape),
    ], axis=-1)


def insert_batch(state: GraphState,
                 slots: jax.Array,       # [B] int32 target slot per new txn
                 key_inc: jax.Array,     # [B, K] int8
                 ts: jax.Array,          # [B, 5] int32
                 txn_id: jax.Array,      # [B, 5] int32
                 kind: jax.Array,        # [B] int8
                 status: jax.Array,      # [B] int8
                 deps_mask: jax.Array,   # [B, T] int8 — adjacency rows
                 ) -> GraphState:
    """Scatter a batch of newly witnessed transactions into their slots.

    Slot assignment is host-side (the control plane picks free slots
    deterministically); on-device this is a pure scatter so the whole
    PreAccept batch is one fused update."""
    return GraphState(
        key_inc=state.key_inc.at[slots].set(key_inc),
        ts=state.ts.at[slots].set(ts),
        txn_id=state.txn_id.at[slots].set(txn_id),
        kind=state.kind.at[slots].set(kind),
        status=state.status.at[slots].set(status),
        adj=state.adj.at[slots].set(deps_mask),
        active=state.active.at[slots].set(True),
    )


def set_status_batch(state: GraphState, slots: jax.Array,
                     status: jax.Array) -> GraphState:
    return state._replace(status=state.status.at[slots].set(status))


def set_execute_at_batch(state: GraphState, slots: jax.Array,
                         ts: jax.Array) -> GraphState:
    return state._replace(ts=state.ts.at[slots].set(ts))


def evict_mask(state: GraphState, keep: jax.Array) -> GraphState:
    """Clear every slot where keep[i] is False (GC/compaction: RedundantBefore
    advancing makes applied txns evictable, Cleanup.java semantics).  Also
    clears dependency edges *onto* evicted slots — an applied/GC'd dependency
    is no longer waiting-on (Commands.java:704-705 removeRedundantDependencies)."""
    keep_i8 = keep.astype(jnp.int8)
    keep_i32 = keep[:, None].astype(jnp.int32)
    return GraphState(
        key_inc=state.key_inc * keep_i8[:, None],
        ts=state.ts * keep_i32,
        txn_id=state.txn_id * keep_i32,
        kind=state.kind * keep_i8,
        status=state.status * keep_i8,
        adj=state.adj * keep_i8[:, None] * keep_i8[None, :],
        active=state.active & keep,
    )


def to_host_deps(state: GraphState) -> np.ndarray:
    """Adjacency back to host as a dense bool matrix (for parity checks)."""
    return np.asarray(state.adj, dtype=np.int8) != 0


def adj_edges(state: GraphState):
    """The adjacency as host (src, dst) int32 edge lists — the frontier
    tier's CSR ingress (ops.frontier_kernels): work proportional to edges,
    not slots.  Edge (i, j) = txn i waits on txn j, matching ``adj``."""
    src, dst = np.nonzero(np.asarray(state.adj, dtype=np.int8))
    return src.astype(np.int32), dst.astype(np.int32)
