"""TPU data plane: device-resident conflict graphs + batched deps kernels.

Timestamps cross the host<->device boundary as five non-negative int32 lanes
(Timestamp.pack_lanes) whose lexicographic order equals the host total order,
so the device plane needs no x64 mode and importing this package has no
global JAX-config side effects.
"""
from .graph_state import (
    GraphState, init_state, insert_batch, set_status_batch,
    set_execute_at_batch, evict_mask, ts_less, to_host_deps, adj_edges,
    TS_LANES,
)
from .deps_kernels import (
    overlap_join, max_conflict_ts, transitive_closure, elide,
    kahn_frontier, kahn_levels, scc_condense,
)
from .frontier_kernels import (
    edges_from_dense, kahn_frontier_csr, kahn_levels_csr, scc_condense_csr,
    transitive_closure_csr, elide_csr, closure_condensed,
    frontier_ready_from_edges,
)

__all__ = [
    "GraphState", "init_state", "insert_batch", "set_status_batch",
    "set_execute_at_batch", "evict_mask", "ts_less", "to_host_deps",
    "adj_edges", "TS_LANES",
    "overlap_join", "max_conflict_ts", "transitive_closure", "elide",
    "kahn_frontier", "kahn_levels", "scc_condense",
    "edges_from_dense", "kahn_frontier_csr", "kahn_levels_csr",
    "scc_condense_csr", "transitive_closure_csr", "elide_csr",
    "closure_condensed", "frontier_ready_from_edges",
]
