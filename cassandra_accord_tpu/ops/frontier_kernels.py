"""Frontier-tier dependency-graph kernels: CSR edge lists + wavefront relaxation.

The dense kernels in ``deps_kernels.py`` answer closure / elision / SCC
questions by repeated [T, T] bool-matmul powering — O(T^3 log T) work no
matter how sparse the graph is.  At T = 8192 on the CPU backend that is
45.5 s (``transitive_closure``) and 41.8 s (``scc_condense``) for a graph
whose Kahn frontier the same machine answers in 0.15 s, because elision
bounds real deps graphs to ~concurrency edges per txn: the work is
proportional to T^2-per-iteration, the information is proportional to E.

This module is the frontier-shaped replacement (PAPERS: Tascade's
atomic-free asynchronous reduction trees — every per-round combine below is
a one-pass segment scatter-reduce over the edge list, no atomics, no
ordering sensitivity; DPU-v2's irregular-DAG execution — the edge list IS
the schedule).  Everything decision-bearing is computed in one of two
shapes:

- **jitted wavefront relaxation** (``lax.while_loop`` over static-shape
  [E]/[T] arrays, bounded iteration): trimming, min-label SCC coloring,
  backward root-reach, Kahn level peeling, the execution frontier.  Work per
  round is O(E) segment ops; rounds are bounded by graph depth (level
  peeling), SCC diameter (label flood), or SCC count (outer extraction) —
  never by T^2.
- **level-synchronous packed-bitset DP** (host numpy): reachability over the
  *condensation* (always a DAG) as uint8-packed rows combined dep-first in
  topological waves — O(E_cond * C/8) byte ops instead of log T dense
  matmuls.

The dense kernels REMAIN in-tree as the bit-identity cross-check tier, the
way ``consult`` keeps its host fallback: every public function here is
asserted equal to its dense twin on randomized graphs (cycles included) by
tests/test_ops_kernels.py, and bench.py's ``deps_graph`` stage measures both
tiers side by side.

Edge convention matches ``GraphState.adj``: an edge (i, j) means txn i
depends on (must execute after) txn j; ``src`` holds i, ``dst`` holds j.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .graph_state import STABLE, APPLIED, INVALIDATED


def edges_from_dense(adj) -> Tuple[np.ndarray, np.ndarray]:
    """Dense [T, T] adjacency -> (src, dst) int32 edge lists (host)."""
    src, dst = np.nonzero(np.asarray(adj))
    return src.astype(np.int32), dst.astype(np.int32)


def _pow2(n: int, floor: int = 8) -> int:
    return 1 << max(floor.bit_length() - 1, (max(1, n) - 1).bit_length())


def _pad_edges(src: np.ndarray, dst: np.ndarray,
               e_pad: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad edge lists to a pow2 jit bucket with a validity mask; padding
    edges point at slot 0 and are masked out of every reduction."""
    e = len(src)
    valid = np.zeros((e_pad,), dtype=bool)
    valid[:e] = True
    s = np.zeros((e_pad,), dtype=np.int32)
    d = np.zeros((e_pad,), dtype=np.int32)
    s[:e] = src
    d[:e] = dst
    return s, d, valid


# ---------------------------------------------------------------------------
# Execution frontier (the command-store release path)
# ---------------------------------------------------------------------------

@jax.jit
def kahn_frontier_edges(src: jax.Array, dst: jax.Array, evalid: jax.Array,
                        status: jax.Array, active: jax.Array) -> jax.Array:
    """``deps_kernels.kahn_frontier`` over an edge list: one edge-parallel
    pass instead of a [T, T] matmul.  Returns [T] bool."""
    dep_done = (status == APPLIED) | (status == INVALIDATED) | ~active
    contrib = (evalid & ~dep_done[dst]).astype(jnp.int32)
    waiting = jnp.zeros(status.shape, jnp.int32).at[src].max(
        contrib, mode="drop") > 0
    return active & (status == STABLE) & ~waiting


@jax.jit
def kahn_levels_edges(src: jax.Array, dst: jax.Array, evalid: jax.Array,
                      active: jax.Array) -> jax.Array:
    """``deps_kernels.kahn_levels`` over an edge list: identical round
    structure (peel the zero-blocked wave, one pass per level), but each
    round is an O(E) segment reduce instead of a [T, T] matmul.  Cycle
    members never peel and keep level -1.  Returns [T] int32."""
    t = active.shape[0]
    em = evalid & active[src] & active[dst]

    def cond(carry):
        _, done, it = carry
        return (it < t) & jnp.any(active & ~done)

    def body(carry):
        level, done, it = carry
        contrib = (em & ~done[dst]).astype(jnp.int32)
        blocked = jnp.zeros((t,), jnp.int32).at[src].max(
            contrib, mode="drop") > 0
        newly = active & ~done & ~blocked
        progressed = jnp.any(newly)
        level = jnp.where(newly, it, level)
        done = done | newly
        it = jnp.where(progressed, it + 1, t)   # no progress => cycle: stop
        return level, done, it

    level0 = jnp.full((t,), -1, dtype=jnp.int32)
    level, _, _ = jax.lax.while_loop(cond, body, (level0, ~active,
                                                  jnp.int32(0)))
    return level


# ---------------------------------------------------------------------------
# SCC condensation by trim + min-label wavefront coloring
# ---------------------------------------------------------------------------

@jax.jit
def scc_condense_edges(src: jax.Array, dst: jax.Array, evalid: jax.Array,
                       active: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """``deps_kernels.scc_condense`` over an edge list, without ever forming
    reach & reach.T.  Three wavefront phases:

    1. TRIM: peel nodes that cannot sit on a cycle (no in- or no out-edge
       within the remaining core) — these are singleton SCCs labeled by
       their own slot, and on protocol graphs (cycles bounded by
       concurrency) they are almost everything.
    2. EXTRACT (outer loop, >= 1 SCC per round): flood the min reachable
       ancestor label forward to fixpoint ("color"); a node whose color is
       its own index is a root, and the nodes of its color class that reach
       it backward are exactly SCC(root) — the flood and the backward reach
       both stay inside the color class by construction, so the restriction
       loses nothing.  Extracted labels are the min member slot, matching
       the dense kernel bit-for-bit.
    3. LEVELS: the same condensed-component peeling the dense kernel runs,
       with the per-round blocked/comp_blocked reductions as edge-list
       segment ops.

    Returns (labels [T] int32, level [T] int32)."""
    t = active.shape[0]
    idx = jnp.arange(t, dtype=jnp.int32)
    em = evalid & active[src] & active[dst]

    def trim(core):
        def tcond(carry):
            _, changed = carry
            return changed

        def tbody(carry):
            core, _ = carry
            e = (em & core[src] & core[dst]).astype(jnp.int32)
            has_out = jnp.zeros((t,), jnp.int32).at[src].max(
                e, mode="drop") > 0
            has_in = jnp.zeros((t,), jnp.int32).at[dst].max(
                e, mode="drop") > 0
            new = core & has_out & has_in
            return new, jnp.any(new != core)

        core, _ = jax.lax.while_loop(tcond, tbody, (core, jnp.bool_(True)))
        return core

    labels0 = jnp.where(active, idx, -1)   # singletons label themselves
    core0 = trim(active)

    def ocond(carry):
        _, core, it = carry
        return (it < t) & jnp.any(core)

    def obody(carry):
        labels, core, it = carry
        e_core = em & core[src] & core[dst]

        # forward min-ancestor flood (rounds ~ SCC diameter)
        def pcond(carry2):
            _, changed = carry2
            return changed

        def pbody(carry2):
            color, _ = carry2
            cand = jnp.where(e_core, color[src], t)
            upd = jnp.full((t,), t, jnp.int32).at[dst].min(cand, mode="drop")
            new = jnp.minimum(color, upd)
            return new, jnp.any(new != color)

        color0 = jnp.where(core, idx, t)
        color, _ = jax.lax.while_loop(pcond, pbody, (color0, jnp.bool_(True)))

        # backward reach to each root, restricted to its color class
        def bcond(carry2):
            _, changed = carry2
            return changed

        def bbody(carry2):
            flag, _ = carry2
            cand = (e_core & flag[dst]
                    & (color[src] == color[dst])).astype(jnp.int32)
            upd = jnp.zeros((t,), jnp.int32).at[src].max(
                cand, mode="drop") > 0
            new = flag | (core & upd)
            return new, jnp.any(new != flag)

        flag0 = core & (color == idx)
        flag, _ = jax.lax.while_loop(bcond, bbody, (flag0, jnp.bool_(True)))
        labels = jnp.where(flag, color, labels)
        return labels, trim(core & ~flag), it + 1

    labels, _, _ = jax.lax.while_loop(ocond, obody,
                                      (labels0, core0, jnp.int32(0)))

    # condensed topological levels — the dense kernel's peeling, edge-parallel
    cond_e = em & (labels[src] != labels[dst])

    def lcond(carry):
        _, done, it = carry
        return (it < t) & jnp.any(active & ~done)

    def lbody(carry):
        level, done, it = carry
        contrib = (cond_e & ~done[dst]).astype(jnp.int32)
        blocked = jnp.zeros((t,), jnp.int32).at[src].max(
            contrib, mode="drop") > 0
        comp_blocked = jnp.zeros((t,), jnp.int32).at[labels].max(
            (blocked & active & ~done).astype(jnp.int32), mode="drop")
        ready = active & ~done & (comp_blocked[labels] == 0)
        progressed = jnp.any(ready)
        level = jnp.where(ready, it, level)
        done = done | ready
        it = jnp.where(progressed, it + 1, t)
        return level, done, it

    level0 = jnp.full((t,), -1, dtype=jnp.int32)
    level, _, _ = jax.lax.while_loop(lcond, lbody, (level0, ~active,
                                                    jnp.int32(0)))
    return labels, level


# ---------------------------------------------------------------------------
# Host wrappers (pow2 jit buckets; dense-twin signatures for the cross-check)
# ---------------------------------------------------------------------------

def _prep_edges(src, dst):
    s, d, v = _pad_edges(src, dst, _pow2(len(src)))
    return jnp.asarray(s), jnp.asarray(d), jnp.asarray(v)


def _prep(adj, active=None):
    a = np.asarray(adj)
    t = a.shape[0]
    src, dst = edges_from_dense(a)
    s, d, v = _prep_edges(src, dst)
    act = np.ones((t,), dtype=bool) if active is None \
        else np.asarray(active, dtype=bool)
    return t, s, d, v, jnp.asarray(act)


def kahn_frontier_csr(adj, status, active) -> np.ndarray:
    """Frontier-tier twin of ``deps_kernels.kahn_frontier`` (dense in,
    [T] bool out) — for the cross-check tier and bench."""
    t, s, d, v, act = _prep(adj, active)
    return np.asarray(kahn_frontier_edges(
        s, d, v, jnp.asarray(np.asarray(status)), act))


def kahn_levels_csr(adj, active) -> np.ndarray:
    t, s, d, v, act = _prep(adj, active)
    return np.asarray(kahn_levels_edges(s, d, v, act))


def scc_condense_csr(adj, active) -> Tuple[np.ndarray, np.ndarray]:
    t, s, d, v, act = _prep(adj, active)
    labels, level = scc_condense_edges(s, d, v, act)
    return np.asarray(labels), np.asarray(level)


def closure_condensed(adj):
    """The decision-bearing form of the transitive closure: per-node compact
    component index [T], packed component-level reachability [C, ceil(C/8)]
    (uint8, little-endian bits), and the nontrivial-component mask [C].
    ``reach[i, j] == comp_reach[comp[i]] bit comp[j] | (comp[i] == comp[j]
    and nontrivial)`` — ``transitive_closure_csr`` is exactly this view
    expanded dense, so ordering decisions read the condensed form directly
    and only the cross-check tier ever pays the [T, T] materialization."""
    return _condensation(np.asarray(adj))


def _condensation(a: np.ndarray, edges=None):
    """(per-node compact comp index, packed comp-level reachability,
    nontrivial mask, comp count) for a dense adjacency — the shared
    substrate of ``transitive_closure_csr`` and ``elide_csr``.

    Comp-level reachability is a level-synchronous packed-bitset DP over the
    condensation DAG: processing components dep-first (increasing Kahn
    level), each component's row is the OR of (dep's bit | dep's row) over
    its out-edges — uint8-packed so a T = 8k graph's whole closure is C/8
    bytes per row instead of a [T, T] matmul chain."""
    n = a.shape[0]
    if edges is None:
        edges = edges_from_dense(a)
    src, dst = edges
    s, d, v = _prep_edges(src, dst)
    labels, _ = scc_condense_edges(s, d, v,
                                   jnp.asarray(np.ones((n,), dtype=bool)))
    comp_of = np.asarray(labels).astype(np.int64)  # label = min member slot
    comp_ids, node_comp = np.unique(comp_of, return_inverse=True)
    c = len(comp_ids)
    csrc, cdst = node_comp[src], node_comp[dst]
    # nontrivial component: >= 2 members, or a self-loop member
    sizes = np.bincount(node_comp, minlength=c)
    nontrivial = sizes > 1
    self_loops = csrc[src == dst]
    nontrivial[self_loops] = True
    # condensation edges (deduped)
    cross = csrc != cdst
    if cross.any():
        ce = np.unique(np.stack([csrc[cross], cdst[cross]], axis=1), axis=0)
        ce_src, ce_dst = ce[:, 0].astype(np.int32), ce[:, 1].astype(np.int32)
    else:
        ce_src = ce_dst = np.zeros((0,), dtype=np.int32)
    # dep-first order over the (acyclic) condensation
    cadj_levels = np.zeros((c,), dtype=np.int64)
    if len(ce_src):
        s, d, v = _pad_edges(ce_src, ce_dst, _pow2(len(ce_src)))
        cadj_levels = np.asarray(kahn_levels_edges(
            jnp.asarray(s), jnp.asarray(d), jnp.asarray(v),
            jnp.asarray(np.ones((c,), dtype=bool)))).astype(np.int64)
    words = (c + 7) // 8
    reach_p = np.zeros((c, words), dtype=np.uint8)
    if len(ce_src):
        bit = np.zeros((c, words), dtype=np.uint8)
        bit[np.arange(c), np.arange(c) // 8] = 1 << (np.arange(c) % 8)
        order = np.argsort(ce_src, kind="stable")
        e_src, e_dst = ce_src[order], ce_dst[order]
        lev_of_edge = cadj_levels[e_src]
        for lv in np.unique(lev_of_edge):
            sel = lev_of_edge == lv
            s_lv, d_lv = e_src[sel], e_dst[sel]
            rows = reach_p[d_lv] | bit[d_lv]          # dep's row | dep's bit
            starts = np.flatnonzero(np.diff(s_lv, prepend=-1))
            merged = np.bitwise_or.reduceat(rows, starts, axis=0)
            reach_p[s_lv[starts]] |= merged
    return node_comp, reach_p, nontrivial, c


def _unpack_cols(packed: np.ndarray, c: int) -> np.ndarray:
    return np.unpackbits(packed, axis=1, bitorder="little")[:, :c].astype(bool)


def transitive_closure_csr(adj) -> np.ndarray:
    """Frontier-tier twin of ``deps_kernels.transitive_closure``: SCC
    condensation + packed-bitset DP over the condensation DAG, expanded back
    to a dense [T, T] bool reach matrix.  Bit-identical to the dense kernel
    on any graph (cycles included): reach[i, j] iff comp(i) reaches comp(j)
    in the condensation, or they share a nontrivial component."""
    a = np.asarray(adj) != 0
    node_comp, reach_p, nontrivial, c = _condensation(a)
    comp_reach = _unpack_cols(reach_p, c)            # [C, C]
    comp_reach[np.arange(c), np.arange(c)] |= nontrivial
    return comp_reach[np.ix_(node_comp, node_comp)]


def elide_csr(adj) -> np.ndarray:
    """Frontier-tier twin of ``deps_kernels.elide`` (transitive reduction,
    cycle edges kept).  An edge (i, j) is implied iff some dependency k of i
    reaches j — evaluated per EDGE against the packed component reachability
    rows (one gather + segment-OR over the edge list), never as the dense
    a @ reach matmul."""
    a = np.asarray(adj) != 0
    n = a.shape[0]
    src, dst = edges_from_dense(a)
    if not len(src):
        return np.zeros_like(a)
    node_comp, reach_p, nontrivial, c = _condensation(a, edges=(src, dst))
    words = reach_p.shape[1]
    bit = np.zeros((c, words), dtype=np.uint8)
    bit[np.arange(c), np.arange(c) // 8] = 1 << (np.arange(c) % 8)
    # reach*[k] row = comps reachable from k with >= 1 step (incl. own comp
    # when nontrivial)
    star = reach_p | np.where(nontrivial[:, None], bit, 0)
    # implied rows per node: OR of star[comp(k)] over i's dep edges (i, k)
    order = np.argsort(src, kind="stable")
    e_src, e_dst = src[order], dst[order]
    rows = star[node_comp[e_dst]]                    # [E, words]
    starts = np.flatnonzero(np.diff(e_src, prepend=-1))
    implied_p = np.zeros((n, words), dtype=np.uint8)
    implied_p[e_src[starts]] = np.bitwise_or.reduceat(rows, starts, axis=0)
    # per-edge verdict
    cj = node_comp[dst]
    implied_edge = (implied_p[src, cj // 8] >> (cj % 8).astype(np.uint8)) & 1
    in_cycle = (node_comp[src] == cj) & nontrivial[cj]
    keep = (implied_edge == 0) | in_cycle
    out = np.zeros_like(a)
    out[src[keep], dst[keep]] = True
    return out


# ---------------------------------------------------------------------------
# Resolver frontier entry (dict-of-edges ingress, no dense matrix ever)
# ---------------------------------------------------------------------------

def frontier_ready_from_edges(edge_src: np.ndarray, edge_dst: np.ndarray,
                              status: np.ndarray,
                              active: np.ndarray) -> np.ndarray:
    """The command-store release path: compacted wait-graph edge arrays in,
    ready mask out — pow2-bucketed on (E, T) so steady-state compilations
    stay bounded like the consult kernels.  [T] bool."""
    t = len(status)
    t_pad = _pow2(t)
    e_pad = _pow2(len(edge_src))
    s, d, v = _pad_edges(edge_src.astype(np.int32), edge_dst.astype(np.int32),
                         e_pad)
    st = np.zeros((t_pad,), dtype=status.dtype)
    ac = np.zeros((t_pad,), dtype=bool)
    st[:t] = status
    ac[:t] = active
    ready = np.asarray(kahn_frontier_edges(
        jnp.asarray(s), jnp.asarray(d), jnp.asarray(v),
        jnp.asarray(st), jnp.asarray(ac)))
    return ready[:t]
