"""Pallas TPU kernel: fused key-overlap join + predicate mask.

The single hottest operation in Accord is the PreAccept/Accept dependency
calculation — for every incoming transaction, find every in-flight transaction
sharing a key that it witnesses and that started before it (reference:
``CommandsForKey.mapReduceActive`` cfk/CommandsForKey.java:925-1000, executed
per key per txn, plus the KeyDeps LinearMerger merges KeyDeps.java:110-148).

``overlap_join`` in deps_kernels.py expresses this as matmul + masks and lets
XLA fuse; this module hand-fuses the masking into the matmul epilogue inside
one Pallas kernel so the [B, T] f32 conflict product never round-trips
through HBM.  The predicate mask (started-before x witness-matrix x eligible)
is precomputed in XLA (cheap VPU lane compares); the kernel itself only
touches bf16/f32/int32 — dtypes v5e Mosaic vector-compares natively.  Grid tiles are
(128, 128) output blocks, MXU-aligned, K looped per block.

On CPU (tests, simulation) the same kernel runs with ``interpret=True``; the
``overlap_join_fused`` entry point dispatches automatically and is a drop-in
replacement for deps_kernels.overlap_join.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .graph_state import ts_less, INVALIDATED
from .deps_kernels import WITNESSES

_BLOCK_B = 128
_BLOCK_T = 128


def _join_kernel(batch_keys_ref,   # [BB, K] bf16
                 index_keys_ref,   # [BT, K] bf16
                 pred_ref,         # [BB, BT] f32 — precomputed predicates
                 out_ref,          # [BB, BT] int32
                 ):
    # f32 compares only: v5e Mosaic rejects int8/bf16 vector compares
    share = jax.lax.dot_general(
        batch_keys_ref[...], index_keys_ref[...],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # [BB, BT]
    out_ref[...] = ((share > 0.0) & (pred_ref[...] > 0.0)).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_join(batch_key_inc: jax.Array,   # [B, K] int8
                 index_key_inc: jax.Array,   # [T, K] int8
                 pred: jax.Array,            # [B, T] f32
                 interpret: bool) -> jax.Array:
    b, k = batch_key_inc.shape
    t = index_key_inc.shape[0]
    bb, bt = min(b, _BLOCK_B), min(t, _BLOCK_T)
    grid = (b // bb if b % bb == 0 else b // bb + 1,
            t // bt if t % bt == 0 else t // bt + 1)
    # index-map constants must stay int32 under x64 mode (Mosaic rejects
    # mixed i32/i64 block indices), so derive 0 from the i32 program id
    return pl.pallas_call(
        _join_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bb, k), lambda i, j: (i, i - i)),
            pl.BlockSpec((bt, k), lambda i, j: (j, j - j)),
            pl.BlockSpec((bb, bt), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((bb, bt), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((b, t), jnp.int32),
        interpret=interpret,
    )(batch_key_inc.astype(jnp.bfloat16),
      index_key_inc.astype(jnp.bfloat16),
      pred)


def overlap_join_fused(index_key_inc: jax.Array,   # [T, K] int8
                       index_txn_id: jax.Array,    # [T, 5] int32
                       index_kind: jax.Array,      # [T] int8
                       index_status: jax.Array,    # [T] int8
                       index_active: jax.Array,    # [T] bool
                       batch_key_inc: jax.Array,   # [B, K] int8
                       batch_txn_id: jax.Array,    # [B, 5] int32
                       batch_kind: jax.Array,      # [B] int8
                       interpret: bool | None = None) -> jax.Array:
    """Drop-in for deps_kernels.overlap_join with the join matmul + mask
    epilogue in a single Pallas kernel.  Returns [B, T] bool."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    started_before = ts_less(index_txn_id[None, :, :], batch_txn_id[:, None, :])
    witnesses = WITNESSES[batch_kind[:, None].astype(jnp.int32),
                          index_kind[None, :].astype(jnp.int32)]
    eligible = index_active & (index_status != INVALIDATED)
    pred = (started_before & witnesses & eligible[None, :]).astype(jnp.float32)
    return _pallas_join(batch_key_inc, index_key_inc, pred,
                        interpret=bool(interpret)) != 0
