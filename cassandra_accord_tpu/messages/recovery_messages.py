"""Recovery wire messages: BeginRecovery, invalidation, and commit-waits.

Capability parity with ``accord.messages`` BeginRecovery / Accept.Invalidate /
Commit.Invalidate / WaitOnCommit (BeginRecovery.java:1-381, Accept.java:219-296,
Commit.java:312-409, WaitOnCommit.java): ``BeginRecovery`` promises a ballot on every
intersecting store, pre-accepting the txn if unwitnessed, and reports the replica's
full recovery evidence:

  - status / accepted ballot / executeAt / deps (the Paxos-style "highest accepted"
    evidence merged coordinator-side by phase-then-ballot),
  - ``rejects_fast_path``: this replica witnessed a conflicting txn that was accepted
    or committed *started after ours* — or decided to *execute after ours* — without
    our txnId in its deps, which is incompatible with our txn having taken the fast
    path (BeginRecovery.java:354-380),
  - ``earlier_committed_witness`` / ``earlier_accepted_no_witness``: conflicting txns
    started before ours that did / did not witness us — the "wait before deciding the
    fast path succeeded" sets (BeginRecovery.java:329-352).
"""
from __future__ import annotations

from typing import TYPE_CHECKING, List, Optional, Set

from ..local import commands as C
from ..local.command_store import SafeCommandStore
from ..local.status import Phase, SaveStatus, Status
from ..primitives.deps import Deps, DepsBuilder
from ..primitives.keys import Ranges
from ..primitives.latest_deps import KnownDeps, LatestDeps
from ..primitives.route import Route
from ..primitives.timestamp import Ballot, Timestamp, TxnId
from ..primitives.txn import PartialTxn
from .base import MessageType, Reply, Request, TxnRequest
from .txn_messages import calculate_partial_deps

if TYPE_CHECKING:
    from ..local.command import Command
    from ..local.node import Node


# ---------------------------------------------------------------------------
# replies
# ---------------------------------------------------------------------------

class RecoverOk(Reply):
    __slots__ = ("txn_id", "status", "accepted", "execute_at", "deps",
                 "earlier_committed_witness", "earlier_accepted_no_witness",
                 "later_unknown_witness", "rejects_fast_path", "writes",
                 "result")

    def __init__(self, txn_id: TxnId, status: Status, accepted: Ballot,
                 execute_at: Optional[Timestamp], deps: LatestDeps,
                 earlier_committed_witness: Deps, earlier_accepted_no_witness: Deps,
                 rejects_fast_path: bool, writes, result,
                 later_unknown_witness: Deps = Deps.NONE):
        self.txn_id = txn_id
        self.status = status
        self.accepted = accepted
        self.execute_at = execute_at
        self.deps = deps
        self.earlier_committed_witness = earlier_committed_witness
        self.earlier_accepted_no_witness = earlier_accepted_no_witness
        self.later_unknown_witness = later_unknown_witness
        self.rejects_fast_path = rejects_fast_path
        self.writes = writes
        self.result = result

    @property
    def type(self):
        return MessageType.BEGIN_RECOVER_RSP

    def merge(self, other: "RecoverOk") -> "RecoverOk":
        """Merge two per-store/per-node replies (BeginRecovery.reduce): keep the
        evidence of the max (phase, ballot-within-Accept-phase) reply, merge the
        LatestDeps maps per range by phase, union the earlier-witness sets."""
        a, b = self, other
        if _reply_order_key(b) > _reply_order_key(a):
            a, b = b, a
        ecw = a.earlier_committed_witness.with_merged(b.earlier_committed_witness)
        eanw = a.earlier_accepted_no_witness.with_merged(b.earlier_accepted_no_witness) \
            .without(ecw.contains)
        if a.status is Status.PRE_ACCEPTED:
            execute_at = a.execute_at if b.execute_at is None \
                else (b.execute_at if a.execute_at is None
                      else a.execute_at.merge_max(b.execute_at))
        else:
            execute_at = a.execute_at
        return RecoverOk(a.txn_id, a.status, a.accepted, execute_at,
                         a.deps.merge(b.deps), ecw, eanw,
                         a.rejects_fast_path or b.rejects_fast_path,
                         a.writes, b.result if a.result is None else a.result,
                         later_unknown_witness=a.later_unknown_witness
                         .with_merged(b.later_unknown_witness))

    def __repr__(self):
        return (f"RecoverOk({self.txn_id!r}, {self.status.name}, acc={self.accepted!r},"
                f" @{self.execute_at!r}, rejectsFP={self.rejects_fast_path})")


def _reply_order_key(ok: "RecoverOk"):
    """Ordering of recovery evidence (Status.max, Status.java:927-963): phase first;
    within the Accept phase the higher accepted ballot wins; otherwise status."""
    ballot_key = ok.accepted if ok.status.phase is Phase.ACCEPT else Ballot.ZERO
    return (ok.status.phase, ballot_key, ok.status.ordinal)


def max_accepted_reply(oks: List["RecoverOk"]) -> Optional["RecoverOk"]:
    """The reply whose evidence governs recovery: max by (phase, ballot) among those
    that reached at least the Accept phase (RecoverOk.maxAccepted)."""
    accepted = [ok for ok in oks if ok.status.phase >= Phase.ACCEPT]
    if not accepted:
        return None
    return max(accepted, key=_reply_order_key)


class RecoverNack(Reply):
    __slots__ = ("superseded_by",)

    def __init__(self, superseded_by: Optional[Ballot]):
        self.superseded_by = superseded_by

    @property
    def type(self):
        return MessageType.BEGIN_RECOVER_RSP

    def __repr__(self):
        return f"RecoverNack({self.superseded_by!r})"


# ---------------------------------------------------------------------------
# replica-side evidence queries (BeginRecovery.java:329-380)
# ---------------------------------------------------------------------------

def _footprint(command: "Command"):
    """A command's key footprint (shared definition — see
    command_store.command_footprint; CommandSummary snapshots the same)."""
    from ..local.command_store import command_footprint
    return command_footprint(command)


def _routing_set(keys) -> Optional[Set]:
    if keys is None or isinstance(keys, Ranges):
        return None
    return {k.to_routing() if hasattr(k, "to_routing") else k for k in keys}


def _intersects(a, b) -> bool:
    """Footprint intersection across Keys/Ranges combinations."""
    if a is None or b is None:
        return False
    a_keys, b_keys = _routing_set(a), _routing_set(b)
    if a_keys is not None and b_keys is not None:
        return not a_keys.isdisjoint(b_keys)
    if a_keys is not None:            # b is Ranges
        return any(b.contains(k) for k in a_keys)
    if b_keys is not None:            # a is Ranges
        return any(a.contains(k) for k in b_keys)
    return a.intersects(b)


def _add_overlap(builder: DepsBuilder, dep_id: TxnId, dep_footprint, our_keys) -> None:
    """Record dep_id against the overlapping portion of the footprints so
    Deps.participants(dep_id) later targets WaitOnCommit correctly."""
    our_set = _routing_set(our_keys)
    if isinstance(dep_footprint, Ranges):
        if our_set is None:
            for rng in dep_footprint:
                if our_keys.intersects(rng):
                    builder.add(rng, dep_id)
        else:
            for k in our_set:
                if dep_footprint.contains(k):
                    builder.add(k, dep_id)
    else:
        dep_set = _routing_set(dep_footprint)
        if our_set is None:
            for k in dep_set:
                if our_keys.contains(k):
                    builder.add(k, dep_id)
        else:
            for k in dep_set & our_set:
                builder.add(k, dep_id)


def _scan_conflicting(safe_store: SafeCommandStore, txn_id: TxnId, keys):
    """Yield (command, footprint) for every other command conflicting with ``keys``
    whose kind would witness ours (the mapReduceFull scan; the reference indexes
    this via cfk, we scan the command map — recovery is rare)."""
    # evicted commands answer from their CommandSummary (snapshotted at evict
    # time — terminal, so exact): the evidence scan must see EVERY conflicting
    # txn, memory-resident or not, but repeated scans must NOT re-decode the
    # whole cold set through the journal each time (BeginRecovery churn at
    # quiesce ran 125k+ fault-ins).  Summary-less cold ids (none in practice)
    # take the old peek-route + fault-in path.
    store = safe_store.store
    journal = store.journal
    for cold_id in list(store.cold):
        if cold_id == txn_id or not txn_id.witnessed_by(cold_id.kind):
            continue
        summary = store.cold_summaries.get(cold_id)
        if summary is not None:
            if summary.footprint is not None \
                    and _intersects(keys, summary.footprint):
                yield summary, summary.footprint
            continue
        if journal is not None:
            route = journal.peek_route(store, cold_id)
            if route is not None \
                    and not _intersects(keys, route.participants()):
                continue
        safe_store.get_if_exists(cold_id)
    for other_id, command in safe_store.store.commands.items():
        if other_id == txn_id or not txn_id.witnessed_by(other_id.kind):
            continue
        footprint = _footprint(command)
        if footprint is not None and _intersects(keys, footprint):
            yield command, footprint


def recovery_evidence(safe_store: SafeCommandStore, txn_id: TxnId, keys):
    """Compute (rejects_fast_path, earlier_committed_witness,
    earlier_accepted_no_witness, later_unknown_witness) for a
    pre-accepted-only txn."""
    rejects_fast_path = False
    ecw = DepsBuilder()
    eanw = DepsBuilder()
    lnw = DepsBuilder()
    for command, footprint in _scan_conflicting(safe_store, txn_id, keys):
        other = command.txn_id
        status = command.status
        # SOUNDNESS: 'did not witness us' is only evidence when the command's
        # DECIDED deps are actually present.  A deps-less command
        # (PRE_COMMITTED stores no deps; truncation strips them) must not be
        # read as a non-witness — the hostile 1000-op burns caught recovery
        # invalidating a FAST-COMMITTED txn off exactly that misreading.
        # (The fast-path argument needs real deps: any fast quorum of ours
        # intersects the other txn's preaccept quorum in a member that voted
        # for us first, so its decided deps MUST contain us.)
        deps_known = command.partial_deps is not None
        witnessed_us = deps_known and command.partial_deps.contains(txn_id)
        is_proposed = status in (Status.ACCEPTED, Status.PRE_COMMITTED, Status.COMMITTED)
        is_stable = (status.has_been(Status.STABLE)
                     and not command.save_status.is_truncated
                     and command.save_status is not SaveStatus.INVALIDATED)
        if deps_known and not witnessed_us:
            # started after ours and accepted/committed => our fast path cannot
            # have reached a quorum (its deps calc would have witnessed us)
            if other > txn_id and is_proposed:
                rejects_fast_path = True
            # decided to execute after ours without witnessing us — EXCEPT
            # awaits-only-deps kinds (exclusive sync points): they never agree
            # an execution time and only take deps on LOWER txnIds, so one
            # executing after us structurally cannot have witnessed us and
            # proves nothing about our fast path (the hostile burns caught an
            # ESP's evidence invalidating a fast-committed write here)
            if is_stable and not other.awaits_only_deps \
                    and command.execute_at is not None \
                    and command.execute_at > txn_id.as_timestamp():
                rejects_fast_path = True
        if other < txn_id:
            if is_stable and witnessed_us:
                _add_overlap(ecw, other, footprint, keys)
            elif is_proposed and not witnessed_us \
                    and not other.awaits_only_deps \
                    and command.execute_at is not None \
                    and command.execute_at > txn_id.as_timestamp():
                # (awaits-only-deps kinds excluded: they cannot witness a
                # higher txnId, so waiting for them to commit decides nothing)
                _add_overlap(eanw, other, footprint, keys)
        elif not deps_known and not other.awaits_only_deps \
                and status.has_been(Status.PRE_ACCEPTED) \
                and command.save_status is not SaveStatus.INVALIDATED \
                and not command.save_status.is_truncated:
            # LATER-started conflict whose witness status is UNKNOWN here
            # (in flight: no decided deps yet).  Completing our fast path at
            # txnId is only sound if every later-started conflicting COMMIT
            # witnessed us — which cannot be established while such txns are
            # unsettled (the superseding race, KNOWN_ISSUES seed 112): the
            # recovery coordinator must wait for them to settle and
            # re-examine (their decided deps then either witness us or
            # become rule-1 rejection evidence)
            _add_overlap(lnw, other, footprint, keys)
    return rejects_fast_path, ecw.build(), eanw.build(), lnw.build()


# ---------------------------------------------------------------------------
# BeginRecovery
# ---------------------------------------------------------------------------

class BeginRecovery(TxnRequest):
    __slots__ = ("partial_txn", "ballot", "route")

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int,
                 partial_txn: PartialTxn, ballot: Ballot,
                 route: Optional[Route] = None):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.partial_txn = partial_txn
        self.ballot = ballot
        # full route (BeginRecovery.java route field)
        self.route = route if route is not None else scope

    @property
    def type(self):
        return MessageType.BEGIN_RECOVER_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id, partial_txn, ballot, scope = self.txn_id, self.partial_txn, self.ballot, self.scope
        route = self.route

        def map_fn(safe_store: SafeCommandStore):
            outcome = C.recover(safe_store, txn_id, partial_txn, route, ballot)
            if outcome is C.AcceptOutcome.TRUNCATED:
                return RecoverNack(None)
            if outcome is C.AcceptOutcome.REJECTED_BALLOT:
                return RecoverNack(safe_store.get_if_exists(txn_id).promised)
            command = safe_store.get_if_exists(txn_id)
            # phase-aware deps evidence (BeginRecovery.java:95-121): the
            # coordinator-held deps at their knowledge phase, plus a fresh
            # local calculation while no committed/decided deps exist
            coordinated = command.partial_deps
            if command.has_been(Status.STABLE):
                known = KnownDeps.KNOWN
            elif command.has_been(Status.COMMITTED):
                known = KnownDeps.COMMITTED
            elif command.status is Status.ACCEPTED and coordinated is not None:
                known = KnownDeps.PROPOSED
            else:
                known = KnownDeps.UNKNOWN   # incl. PreCommitted/AcceptedInvalidate
            local = None
            if known <= KnownDeps.PROPOSED:
                local = calculate_partial_deps(safe_store, txn_id, partial_txn.keys,
                                               txn_id.as_timestamp())
            deps = LatestDeps.create(
                safe_store.store.ranges_at(txn_id.epoch),
                known, command.accepted_or_committed, coordinated, local)
            if command.has_been(Status.PRE_COMMITTED):
                rejects, ecw, eanw, lnw = False, Deps.NONE, Deps.NONE, Deps.NONE
            else:
                rejects, ecw, eanw, lnw = recovery_evidence(
                    safe_store, txn_id, partial_txn.keys)
            return RecoverOk(txn_id, command.status, command.accepted_or_committed,
                             command.execute_at, deps, ecw, eanw, rejects,
                             command.writes, command.result,
                             later_unknown_witness=lnw)

        def reduce_fn(a, b):
            if isinstance(a, RecoverNack):
                return a
            if isinstance(b, RecoverNack):
                return b
            return a.merge(b)

        def consume(result, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(from_node, reply_context, failure)
            else:
                node.reply(from_node, reply_context, result)

        node.map_reduce_consume_local(scope, node.topology.min_epoch, txn_id.epoch,
                                      map_fn, reduce_fn,
                                      preload=self.preload_ids()).begin(consume)

    def __repr__(self):
        return f"BeginRecovery({self.txn_id!r}, ballot={self.ballot!r})"


# ---------------------------------------------------------------------------
# Invalidation (Accept.Invalidate / Commit.Invalidate)
# ---------------------------------------------------------------------------

class InvalidateOk(Reply):
    __slots__ = ("status", "route", "has_definition")

    def __init__(self, status: Status, route: Optional[Route],
                 has_definition: bool = False):
        self.status = status
        self.route = route
        self.has_definition = has_definition

    @property
    def type(self):
        return MessageType.BEGIN_INVALIDATE_RSP

    def __repr__(self):
        return f"InvalidateOk({self.status.name})"


class InvalidateNack(Reply):
    """Rejected: a higher ballot holds the promise (``superseded_by``), the
    txn is already (pre)committed (``committed``), or it sits below this home
    shard's durable fence (``truncated`` — NOT a commit claim: a below-fence
    txn is SETTLED, having either durably applied everywhere that matters and
    been erased, or being forever unable to newly commit since preaccept
    below the fence refuses; conflating this with 'committed' sent
    invalidation into a permanent preempt loop)."""
    __slots__ = ("superseded_by", "committed", "truncated")

    def __init__(self, superseded_by: Optional[Ballot], committed: bool = False,
                 truncated: bool = False):
        self.superseded_by = superseded_by
        self.committed = committed
        self.truncated = truncated

    @property
    def type(self):
        return MessageType.BEGIN_INVALIDATE_RSP

    def __repr__(self):
        return (f"InvalidateNack(committed={self.committed}, "
                f"truncated={self.truncated})")


class AcceptInvalidate(TxnRequest):
    """Propose invalidation at ``ballot`` (Accept.Invalidate): replicas promise the
    ballot and vote AcceptedInvalidate unless the txn already (pre)committed."""
    __slots__ = ("ballot",)

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int, ballot: Ballot):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.ballot = ballot

    @property
    def type(self):
        return MessageType.ACCEPT_INVALIDATE_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id, ballot = self.txn_id, self.ballot

        def map_fn(safe_store: SafeCommandStore):
            outcome = C.accept_invalidate(safe_store, txn_id, ballot, scope=self.scope)
            command = safe_store.get_if_exists(txn_id)
            if outcome is C.AcceptOutcome.REJECTED_BALLOT:
                return InvalidateNack(command.promised)
            if outcome is C.AcceptOutcome.TRUNCATED:
                # below this shard's durable fence: SETTLED, not committed
                return InvalidateNack(None, truncated=True)
            if outcome is C.AcceptOutcome.REDUNDANT:
                return InvalidateNack(None, committed=True)
            return InvalidateOk(command.status, command.route,
                                has_definition=command.partial_txn is not None)

        def reduce_fn(a, b):
            if isinstance(a, InvalidateNack):
                return a
            if isinstance(b, InvalidateNack):
                return b
            keep = a if a.status >= b.status else b
            other = b if keep is a else a
            if not keep.has_definition and other.has_definition:
                keep = InvalidateOk(keep.status, other.route if keep.route is None
                                    else keep.route, True)
            return keep

        def consume(result, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(from_node, reply_context, failure)
            else:
                node.reply(from_node, reply_context, result)

        node.map_reduce_consume_local(self.scope, node.topology.min_epoch, txn_id.epoch,
                                      map_fn, reduce_fn,
                                      preload=self.preload_ids()).begin(consume)

    def __repr__(self):
        return f"AcceptInvalidate({self.txn_id!r}, ballot={self.ballot!r})"


class CommitInvalidate(TxnRequest):
    __slots__ = ()

    @property
    def type(self):
        return MessageType.COMMIT_INVALIDATE_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id = self.txn_id

        def for_store(safe_store: SafeCommandStore):
            C.commit_invalidate(safe_store, txn_id, scope=self.scope)

        node.for_each_local(self.scope, node.topology.min_epoch, txn_id.epoch,
                            for_store, preload=self.preload_ids())

    def __repr__(self):
        return f"CommitInvalidate({self.txn_id!r})"


# ---------------------------------------------------------------------------
# WaitOnCommit (WaitOnCommit.java)
# ---------------------------------------------------------------------------

class WaitOnCommitOk(Reply):
    __slots__ = ()

    @property
    def type(self):
        return MessageType.WAIT_ON_COMMIT_RSP

    def __repr__(self):
        return "WaitOnCommitOk"


WAIT_ON_COMMIT_OK = WaitOnCommitOk()


class WaitOnCommit(TxnRequest):
    """Reply once the txn is (pre)committed / invalidated / truncated on every
    intersecting store (used by recovery to await earlier-no-witness txns)."""
    __slots__ = ()

    @property
    def type(self):
        return MessageType.WAIT_ON_COMMIT_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        from ..utils import async_ as au
        txn_id = self.txn_id
        stores = node.command_stores.intersecting_stores(self.scope, txn_id.epoch, txn_id.epoch)
        if not stores:
            node.reply(from_node, reply_context, WAIT_ON_COMMIT_OK)
            return

        def wait_in(safe_store: SafeCommandStore) -> au.AsyncChain:
            result = au.settable()

            def is_done(command) -> bool:
                return (command.has_been(Status.PRE_COMMITTED)
                        or command.save_status is SaveStatus.INVALIDATED
                        or command.save_status.is_truncated)

            command = safe_store.get_or_create(txn_id)
            if is_done(command):
                result.set_success(None)
            else:
                def listener(s: SafeCommandStore, cmd):
                    if is_done(cmd):
                        s.remove_transient_listener(txn_id, listener)
                        result.try_success(None)
                safe_store.add_transient_listener(txn_id, listener)
            return result.to_chain()

        chains = [store.submit(wait_in, preload=(txn_id,))
                  .flat_map(lambda c: c) for store in stores]

        def consume(_values, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(from_node, reply_context, failure)
            else:
                node.reply(from_node, reply_context, WAIT_ON_COMMIT_OK)

        au.all_of(chains).begin(consume)

    def __repr__(self):
        return f"WaitOnCommit({self.txn_id!r})"
