"""Durability watermark plumbing.

Capability parity with ``accord.messages`` SetShardDurable / SetGloballyDurable /
QueryDurableBefore (SetShardDurable.java, SetGloballyDurable.java,
QueryDurableBefore.java): the durability coordination rounds (CoordinateShardDurable /
CoordinateGloballyDurable) feed every replica's ``DurableBefore`` map through these
messages, which in turn drives truncation/erasure GC (Cleanup).
"""
from __future__ import annotations

from typing import TYPE_CHECKING

from ..local.command_store import SafeCommandStore
from ..local.durability import DurableBefore
from ..primitives.keys import Ranges
from ..primitives.timestamp import TxnId
from .base import MessageType, Reply, Request

if TYPE_CHECKING:
    from ..local.node import Node


class SetShardDurable(Request):
    """The exclusive sync point ``txn_id`` (covering ``ranges``) applied at
    EVERY replica (the durability round's all-replica WaitUntilApplied barrier):
    everything before it on those ranges is universally durable."""

    __slots__ = ("txn_id", "ranges")

    def __init__(self, txn_id: TxnId, ranges: Ranges):
        self.txn_id = txn_id
        self.ranges = ranges

    @property
    def type(self):
        return MessageType.SET_SHARD_DURABLE_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id, ranges = self.txn_id, self.ranges

        def for_store(safe_store: SafeCommandStore) -> None:
            safe_store.mark_shard_durable(txn_id, ranges)

        from .txn_messages import SIMPLE_OK
        # for_each_local is EAGER: it returns a settled-able AsyncResult, not
        # a chain — listen, don't begin (a .begin here crashed every
        # SetShardDurable, silently failing shard-durable rounds)
        node.for_each_local(ranges, txn_id.epoch, txn_id.epoch, for_store) \
            .add_listener(
                lambda _v, f: node.message_sink.reply_with_unknown_failure(
                    from_node, reply_context, f) if f is not None
                else node.reply(from_node, reply_context, SIMPLE_OK))

    def __repr__(self):
        return f"SetShardDurable({self.txn_id!r}, {self.ranges!r})"


class SetGloballyDurable(Request):
    """Adopt a cluster-wide DurableBefore map (the MAX-merge of a quorum of
    nodes' maps — each entry was proved by a completed shard round, so
    dissemination only spreads established knowledge; no promotion)."""

    __slots__ = ("durable_before",)

    def __init__(self, durable_before: DurableBefore):
        self.durable_before = durable_before

    @property
    def type(self):
        return MessageType.SET_GLOBALLY_DURABLE_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        durable_before = self.durable_before

        def for_store(safe_store: SafeCommandStore) -> None:
            safe_store.merge_durable_before(durable_before)

        from .txn_messages import SIMPLE_OK
        # for_each_local is EAGER (AsyncResult): listen, don't begin
        node.for_each_local(None, node.topology.min_epoch, node.epoch(),
                            for_store).add_listener(
            lambda _v, f: node.message_sink.reply_with_unknown_failure(
                from_node, reply_context, f) if f is not None
            else node.reply(from_node, reply_context, SIMPLE_OK))

    def __repr__(self):
        return f"SetGloballyDurable({self.durable_before!r})"


class DurableBeforeReply(Reply):
    __slots__ = ("durable_before",)

    def __init__(self, durable_before: DurableBefore):
        self.durable_before = durable_before

    @property
    def type(self):
        return MessageType.QUERY_DURABLE_BEFORE_RSP

    def __repr__(self):
        return f"DurableBeforeReply({self.durable_before!r})"


class QueryDurableBefore(Request):
    """Report this node's DurableBefore map (max-merged across its stores —
    each covers distinct ranges)."""

    __slots__ = ()

    @property
    def type(self):
        return MessageType.QUERY_DURABLE_BEFORE_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        def map_fn(safe_store: SafeCommandStore) -> DurableBefore:
            return safe_store.durable_before()

        def consume(result, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(from_node, reply_context,
                                                             failure)
            else:
                node.reply(from_node, reply_context, DurableBeforeReply(
                    result if result is not None else DurableBefore.EMPTY))

        node.map_reduce_consume_local(None, node.topology.min_epoch, node.epoch(),
                                      map_fn, lambda a, b: a.merge(b)).begin(consume)

    def __repr__(self):
        return "QueryDurableBefore"
