"""Bootstrap data-fetch messages.

Capability parity with the reference's bootstrap streaming
(impl/AbstractFetchCoordinator.java FETCH_DATA_REQ handling, ListFetchCoordinator):
a replica newly adopting ranges pulls their current contents from a replica of the
previous epoch.  The source replies with its store contents for the ranges; entries
are (executeAt, value)-timestamped, so application on the destination is idempotent
and composes with concurrently-arriving Apply traffic.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Dict, Optional

from ..primitives.keys import Ranges
from ..primitives.timestamp import TxnId
from .base import MessageType, Reply, Request

if TYPE_CHECKING:
    from ..local.node import Node


class FetchStoreDataOk(Reply):
    """entries: key -> [(executeAt, value), ...] for every key in the ranges.
    ``partial`` marks a source that itself has stale (gapped) data on the
    ranges: its entries are merge-safe (committed writes, timestamp-ordered)
    but not individually complete — union-heal counts it toward the
    quorum-intersection bound instead of treating it as authoritative."""

    __slots__ = ("entries", "partial")

    def __init__(self, entries: Dict, partial: bool = False):
        self.entries = entries
        self.partial = partial

    @property
    def type(self):
        return MessageType.FETCH_DATA_RSP

    def __repr__(self):
        tag = ", partial" if self.partial else ""
        return f"FetchStoreDataOk({len(self.entries)} keys{tag})"


class FetchStoreData(Request):
    """Stream the data-store contents for ``ranges`` to a bootstrapping replica.
    The source first waits until the fencing sync point has applied LOCALLY
    (ApplyThenWaitUntilApplied semantics): a source lagging behind the fence
    would otherwise serve a snapshot missing quorum-applied writes.

    ``allow_stale``: union-heal mode (gap healing, not bootstrap) — a source
    whose own data is stale-marked still serves what it has, flagged partial.
    Any f+1 replicas' union contains every quorum-applied write (an apply
    quorum and f+1 responders must intersect), so the healer can clear its
    stale mark from enough partial snapshots even when EVERY replica of the
    range is gapped — without this, mutually-stale replicas deadlock refusing
    each other and the range stays read-unavailable forever (the chaos+churn
    burns stalled exactly there)."""

    __slots__ = ("ranges", "sync_txn_id", "sync_route", "allow_stale")

    def __init__(self, ranges: Ranges, sync_txn_id: Optional[TxnId] = None,
                 sync_route=None, allow_stale: bool = False):
        self.ranges = ranges
        self.sync_txn_id = sync_txn_id
        self.sync_route = sync_route
        self.allow_stale = allow_stale

    @property
    def type(self):
        return MessageType.FETCH_DATA_REQ

    def wait_for_epoch(self) -> int:
        return self.sync_txn_id.epoch if self.sync_txn_id is not None else 0

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        # a source that is ITSELF still bootstrapping any of these ranges has
        # incomplete data — refuse so the fetcher tries another source
        for cmd_store in node.command_stores.all_stores():
            if cmd_store.pending_bootstrap \
                    and cmd_store.pending_bootstrap.intersects(self.ranges):
                node.message_sink.reply_with_unknown_failure(
                    from_node, reply_context,
                    RuntimeError("source bootstrapping requested ranges"))
                return
        # a source with its OWN known data gaps on these ranges (stale marks):
        # a BOOTSTRAP fetch treats one source as authoritative and must refuse
        # (serving would 'heal' the fetcher with the same hole); a union-heal
        # fetch (allow_stale) serves what it has, flagged partial
        src_stale = getattr(node.data_store, "stale_ranges", None)
        is_partial = (src_stale is not None and len(src_stale)
                      and src_stale.intersects(self.ranges))
        if is_partial and not self.allow_stale:
            node.message_sink.reply_with_unknown_failure(
                from_node, reply_context,
                RuntimeError("source has stale (gapped) data on requested ranges"))
            return

        def serve(outcome=None, failure=None) -> None:
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(from_node,
                                                             reply_context, failure)
                return
            if outcome == "nack":
                node.message_sink.reply_with_unknown_failure(
                    from_node, reply_context,
                    RuntimeError("fence sync point invalidated"))
                return
            store = node.data_store
            entries: Dict = {}
            data = getattr(store, "data", None)
            if data is not None:
                for key, values in data.items():
                    rk = key.to_routing() if hasattr(key, "to_routing") else key
                    if self.ranges.contains(rk):
                        entries[key] = list(values)
            node.reply(from_node, reply_context,
                       FetchStoreDataOk(entries, partial=is_partial))

        if self.sync_txn_id is None or self.sync_route is None:
            serve()
            return
        from .txn_messages import await_applied_local
        # span ALL epochs this node knows: the source may hold the ranges only
        # at a PRIOR epoch (it is the replica the range is moving away from) —
        # the fence still applies there and must be awaited
        await_applied_local(node, self.sync_txn_id, self.sync_route,
                            node.topology.min_epoch,
                            max(self.sync_txn_id.epoch, node.epoch())) \
            .begin(lambda outcome, f: serve(outcome, f))

    def __repr__(self):
        return f"FetchStoreData({self.ranges!r})"
