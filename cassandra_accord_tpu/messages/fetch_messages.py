"""Bootstrap data-fetch messages.

Capability parity with the reference's bootstrap streaming
(impl/AbstractFetchCoordinator.java FETCH_DATA_REQ handling, ListFetchCoordinator):
a replica newly adopting ranges pulls their current contents from a replica of the
previous epoch.  The source replies with its store contents for the ranges; entries
are (executeAt, value)-timestamped, so application on the destination is idempotent
and composes with concurrently-arriving Apply traffic.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Dict, Optional

from ..primitives.keys import Ranges
from ..primitives.timestamp import TxnId
from .base import MessageType, Reply, Request

if TYPE_CHECKING:
    from ..local.node import Node


class FetchStoreDataOk(Reply):
    """entries: key -> [(executeAt, value), ...] for every key in the ranges."""

    __slots__ = ("entries",)

    def __init__(self, entries: Dict):
        self.entries = entries

    @property
    def type(self):
        return MessageType.FETCH_DATA_RSP

    def __repr__(self):
        return f"FetchStoreDataOk({len(self.entries)} keys)"


class FetchStoreData(Request):
    """Stream the data-store contents for ``ranges`` to a bootstrapping replica.
    The source first waits until the fencing sync point has applied LOCALLY
    (ApplyThenWaitUntilApplied semantics): a source lagging behind the fence
    would otherwise serve a snapshot missing quorum-applied writes."""

    __slots__ = ("ranges", "sync_txn_id", "sync_route")

    def __init__(self, ranges: Ranges, sync_txn_id: Optional[TxnId] = None,
                 sync_route=None):
        self.ranges = ranges
        self.sync_txn_id = sync_txn_id
        self.sync_route = sync_route

    @property
    def type(self):
        return MessageType.FETCH_DATA_REQ

    def wait_for_epoch(self) -> int:
        return self.sync_txn_id.epoch if self.sync_txn_id is not None else 0

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        # a source that is ITSELF still bootstrapping any of these ranges has
        # incomplete data — refuse so the fetcher tries another source
        for cmd_store in node.command_stores.all_stores():
            if cmd_store.pending_bootstrap \
                    and cmd_store.pending_bootstrap.intersects(self.ranges):
                node.message_sink.reply_with_unknown_failure(
                    from_node, reply_context,
                    RuntimeError("source bootstrapping requested ranges"))
                return
        # likewise a source with its OWN known data gaps on these ranges
        # (stale marks): serving its snapshot would 'heal' the fetcher with
        # the same hole and clear the fetcher's stale mark over an open gap
        src_stale = getattr(node.data_store, "stale_ranges", None)
        if src_stale is not None and len(src_stale) \
                and src_stale.intersects(self.ranges):
            node.message_sink.reply_with_unknown_failure(
                from_node, reply_context,
                RuntimeError("source has stale (gapped) data on requested ranges"))
            return

        def serve(outcome=None, failure=None) -> None:
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(from_node,
                                                             reply_context, failure)
                return
            if outcome == "nack":
                node.message_sink.reply_with_unknown_failure(
                    from_node, reply_context,
                    RuntimeError("fence sync point invalidated"))
                return
            store = node.data_store
            entries: Dict = {}
            data = getattr(store, "data", None)
            if data is not None:
                for key, values in data.items():
                    rk = key.to_routing() if hasattr(key, "to_routing") else key
                    if self.ranges.contains(rk):
                        entries[key] = list(values)
            node.reply(from_node, reply_context, FetchStoreDataOk(entries))

        if self.sync_txn_id is None or self.sync_route is None:
            serve()
            return
        from .txn_messages import await_applied_local
        # span ALL epochs this node knows: the source may hold the ranges only
        # at a PRIOR epoch (it is the replica the range is moving away from) —
        # the fence still applies there and must be awaited
        await_applied_local(node, self.sync_txn_id, self.sync_route,
                            node.topology.min_epoch,
                            max(self.sync_txn_id.epoch, node.epoch())) \
            .begin(lambda outcome, f: serve(outcome, f))

    def __repr__(self):
        return f"FetchStoreData({self.ranges!r})"
