"""Bootstrap data-fetch messages.

Capability parity with the reference's bootstrap streaming
(impl/AbstractFetchCoordinator.java FETCH_DATA_REQ handling, ListFetchCoordinator):
a replica newly adopting ranges pulls their current contents from a replica of the
previous epoch.  The source replies with its store contents for the ranges; entries
are (executeAt, value)-timestamped, so application on the destination is idempotent
and composes with concurrently-arriving Apply traffic.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Dict, List, Tuple

from ..primitives.keys import Ranges
from .base import MessageType, Reply, Request

if TYPE_CHECKING:
    from ..local.node import Node


class FetchStoreDataOk(Reply):
    """entries: key -> [(executeAt, value), ...] for every key in the ranges."""

    __slots__ = ("entries",)

    def __init__(self, entries: Dict):
        self.entries = entries

    @property
    def type(self):
        return MessageType.FETCH_DATA_RSP

    def __repr__(self):
        return f"FetchStoreDataOk({len(self.entries)} keys)"


class FetchStoreData(Request):
    """Stream the data-store contents for ``ranges`` to a bootstrapping replica."""

    __slots__ = ("ranges",)

    def __init__(self, ranges: Ranges):
        self.ranges = ranges

    @property
    def type(self):
        return MessageType.FETCH_DATA_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        # a source that is ITSELF still bootstrapping any of these ranges has
        # incomplete data — refuse so the fetcher tries another source
        for cmd_store in node.command_stores.all_stores():
            if cmd_store.pending_bootstrap \
                    and cmd_store.pending_bootstrap.intersects(self.ranges):
                node.message_sink.reply_with_unknown_failure(
                    from_node, reply_context,
                    RuntimeError("source bootstrapping requested ranges"))
                return
        store = node.data_store
        entries: Dict = {}
        data = getattr(store, "data", None)
        if data is not None:
            for key, values in data.items():
                rk = key.to_routing() if hasattr(key, "to_routing") else key
                if self.ranges.contains(rk):
                    entries[key] = list(values)
        node.reply(from_node, reply_context, FetchStoreDataOk(entries))

    def __repr__(self):
        return f"FetchStoreData({self.ranges!r})"
