"""Status interrogation and knowledge propagation.

Capability parity with ``accord.messages.CheckStatus`` / ``Propagate``
(CheckStatus.java:21-933, Propagate.java:1-546): ``CheckStatus`` reports everything a
replica knows about a txn — save status, ballots, executeAt, durability, route, and
(with ``include_info``) the partial txn/deps/writes/result — and the caller merges
replies field-wise into a single knowledge view.  ``propagate_knowledge`` applies a
merged view to the local stores, upgrading the local ``Known`` (the reference's
local-only Propagate message family).

Also: the hint messages ``InformOfTxn`` (tell the home shard an unwitnessed txn
exists, InformOfTxnId.java) and ``InformDurable`` (durability notice, InformDurable.java).
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Optional

from ..local import commands as C
from ..local.command_store import SafeCommandStore
from ..local.status import Durability, SaveStatus, Status
from ..primitives.deps import Deps
from ..primitives.route import Route
from ..primitives.timestamp import Ballot, Timestamp, TxnId
from ..primitives.txn import PartialTxn, Txn, Writes
from .base import Callback, MessageType, Reply, Request, TxnRequest

if TYPE_CHECKING:
    from ..local.node import Node


class CheckStatusOk(Reply):
    """One replica's (or a merge of several replicas') knowledge of a txn."""

    __slots__ = ("txn_id", "save_status", "promised", "accepted", "execute_at",
                 "durability", "route", "partial_txn", "partial_deps", "writes",
                 "result", "stable_for", "applied_for", "invalid_if_undecided")

    def __init__(self, txn_id: TxnId, save_status: SaveStatus, promised: Ballot,
                 accepted: Ballot, execute_at: Optional[Timestamp],
                 durability: Durability, route: Optional[Route],
                 partial_txn: Optional[PartialTxn], partial_deps: Optional[Deps],
                 writes: Optional[Writes], result,
                 stable_for=None, applied_for=None,
                 invalid_if_undecided: bool = False):
        from ..primitives.keys import Ranges
        self.txn_id = txn_id
        self.save_status = save_status
        self.promised = promised
        self.accepted = accepted
        self.execute_at = execute_at
        self.durability = durability
        self.route = route
        self.partial_txn = partial_txn
        self.partial_deps = partial_deps
        self.writes = writes
        self.result = result
        # coverage of this knowledge (the reference's FoundKnownMap / Known
        # sufficiency, CheckStatus.java): the ranges for which the carried deps
        # (>= STABLE) and writes (>= PRE_APPLIED) slices are known-complete
        self.stable_for = stable_for if stable_for is not None else Ranges.EMPTY
        self.applied_for = applied_for if applied_for is not None else Ranges.EMPTY
        # Infer hint (Infer.InvalidIfNot.IfUndecided, Infer.java:63-186): this
        # replica's majority-durability watermark passed txnId on its queried
        # ranges, so every lower txn is either durably applied or invalidated —
        # if a quorum says so and the txn is still undecided, it provably never
        # committed and can never commit (preaccept below the fence refuses)
        self.invalid_if_undecided = invalid_if_undecided

    @property
    def type(self):
        return MessageType.CHECK_STATUS_RSP

    @staticmethod
    def of(txn_id: TxnId, command, local_ranges=None) -> "CheckStatusOk":
        """``local_ranges`` must be the ranges this store's PAYLOAD slices
        actually cover — the ranges it owned at the txn's coordination epochs
        (``payload_coverage``), NOT its current ranges.  A store that adopted
        a range AFTER the txn's epochs holds deps/writes slices that never
        included it; claiming current ranges let a peer adopt a partial
        writes payload as if it covered the newly-adopted range and silently
        drop the missing key's write (seed-6 elastic trajectory: node 5's
        epoch-8 [k857]-only slice adopted at node 3 as covering k285 —
        replica divergence, v80.0 lost)."""
        from ..primitives.keys import Ranges
        local = local_ranges if local_ranges is not None else Ranges.EMPTY
        invalidated = command.save_status is SaveStatus.INVALIDATED
        stable_for = local if command.save_status.has_been(Status.STABLE) \
            and not command.save_status.is_truncated and not invalidated \
            else Ranges.EMPTY
        # applied_for asserts "the CARRIED writes cover these ranges" — it is
        # what gates outcome adoption at peers, so it must track the writes
        # payload: TRUNCATE_WITH_OUTCOME keeps its writes and still serves the
        # outcome; plain TRUNCATE/ERASE (writes dropped) claims nothing
        applied_for = local if command.writes is not None and not invalidated \
            and command.save_status.has_been(Status.PRE_APPLIED) else Ranges.EMPTY
        return CheckStatusOk(txn_id, command.save_status, command.promised,
                             command.accepted_or_committed, command.execute_at,
                             command.durability, command.route, command.partial_txn,
                             command.partial_deps, command.writes, command.result,
                             stable_for=stable_for, applied_for=applied_for)

    @staticmethod
    def payload_coverage(safe_store, txn_id: TxnId, command):
        """The ranges this store's txn/deps/writes slices can actually cover:
        the union of the ranges it owned over the txn's coordination window
        [txnId.epoch, executeAt.epoch] — what ``compute_scope`` sliced the
        payloads to when they were sent here.  Ranges adopted in LATER epochs
        are excluded: no payload for them ever arrived."""
        from ..primitives.keys import Ranges
        lo = txn_id.epoch
        hi = lo
        if command is not None and command.execute_at is not None:
            hi = max(hi, command.execute_at.epoch)
        covered = Ranges.EMPTY
        for e in range(lo, hi + 1):
            covered = covered.union(safe_store.ranges_at(e))
        return covered

    @staticmethod
    def infer_invalid_hint(safe_store, txn_id: TxnId, command) -> bool:
        """IfUndecided inference grounds (Infer.withInvalidIfNot,
        Infer.java:327-378): the store's majority-durability watermark covers
        txnId on every locally-owned participant — meaningless (False) once the
        command is decided locally."""
        from ..local.status import Status as S
        if command is not None and command.has_been(S.PRE_COMMITTED):
            return False
        local = safe_store.current_ranges()
        if not len(local):
            return False
        # an ABSENT command is ambiguous once the erase bound passed txnId:
        # GC physically deletes shard-redundant APPLIED commands
        # (command_store.run_gc), so a durably-applied txn would look exactly
        # like a never-committed one here — no hint below that bound.  (A
        # replica that erased it cannot claim the hint; one that still holds
        # it reports the applied status, which suppresses the inference.)
        bound = safe_store.redundant_before().min_shard_redundant_before(local)
        if bound is not None and txn_id < bound:
            return False
        from ..local.status import Durability as D
        return safe_store.durable_before().min_durability(
            txn_id, local) >= D.MAJORITY

    @staticmethod
    def empty(txn_id: TxnId) -> "CheckStatusOk":
        return CheckStatusOk(txn_id, SaveStatus.NOT_DEFINED, Ballot.ZERO, Ballot.ZERO,
                             None, Durability.NOT_DURABLE, None, None, None, None, None)

    def merge(self, other: "CheckStatusOk") -> "CheckStatusOk":
        """Field-wise knowledge merge (CheckStatus.CheckStatusOk.merge): the
        higher save status's decision fields win; routes union; txn/deps merge."""
        a, b = self, other
        if b.save_status.ordinal > a.save_status.ordinal:
            a, b = b, a
        route = a.route
        if route is None:
            route = b.route
        elif b.route is not None and b.route.home_key == route.home_key:
            route = route.union(b.route)
        partial_txn = a.partial_txn
        if partial_txn is None:
            partial_txn = b.partial_txn
        elif b.partial_txn is not None:
            partial_txn = partial_txn.with_merged(b.partial_txn)
        partial_deps = a.partial_deps
        if partial_deps is None:
            partial_deps = b.partial_deps
        elif b.partial_deps is not None and \
                ((a.save_status.has_been(Status.STABLE)
                  and b.save_status.has_been(Status.STABLE))
                 or a.save_status.ordinal == b.save_status.ordinal):
            # same knowledge tier (or both stable): deps slices from different
            # shards of the same decision merge
            partial_deps = partial_deps.with_merged(b.partial_deps)
        writes = a.writes
        if writes is None:
            writes = b.writes
        elif b.writes is not None:
            writes = writes.merge(b.writes)
        return CheckStatusOk(
            a.txn_id, a.save_status, a.promised.merge_max(b.promised),
            a.accepted.merge_max(b.accepted),
            a.execute_at if a.execute_at is not None else b.execute_at,
            max(a.durability, b.durability), route, partial_txn, partial_deps,
            writes,
            a.result if a.result is not None else b.result,
            stable_for=a.stable_for.union(b.stable_for),
            applied_for=a.applied_for.union(b.applied_for),
            # AND: the inference claim must hold at every contributor
            # (Infer.InvalidIfNot.reduce takes the weaker side)
            invalid_if_undecided=a.invalid_if_undecided and b.invalid_if_undecided)

    def full_txn(self) -> Optional[Txn]:
        """Reconstitute the complete txn if the merged partials cover the route."""
        if self.partial_txn is None or self.route is None:
            return None
        return self.partial_txn.reconstitute_or_none(self.route)

    def __repr__(self):
        return f"CheckStatusOk({self.txn_id!r}, {self.save_status.name}, dur={self.durability.name})"


class CheckStatus(TxnRequest):
    """Interrogate replicas' knowledge of ``txn_id`` (CheckStatus.java).  With
    ``include_info`` the reply carries the txn/deps/outcome payloads (the
    reference's IncludeInfo.All)."""

    __slots__ = ("include_info",)

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int,
                 include_info: bool = True):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.include_info = include_info

    @property
    def type(self):
        return MessageType.CHECK_STATUS_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id, include_info = self.txn_id, self.include_info

        def map_fn(safe_store: SafeCommandStore):
            command = safe_store.get_if_exists(txn_id)
            hint = CheckStatusOk.infer_invalid_hint(safe_store, txn_id, command)
            if command is None:
                ok = CheckStatusOk.empty(txn_id)
                ok.invalid_if_undecided = hint
                return ok
            ok = CheckStatusOk.of(
                txn_id, command,
                CheckStatusOk.payload_coverage(safe_store, txn_id, command))
            ok.invalid_if_undecided = hint
            if not include_info:
                from ..primitives.keys import Ranges
                ok.partial_txn = None
                ok.partial_deps = None
                ok.writes = None
                ok.result = None
                # coverage claims travel WITH the payloads they describe
                ok.stable_for = Ranges.EMPTY
                ok.applied_for = Ranges.EMPTY
            return ok

        def consume(result, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(from_node, reply_context, failure)
            else:
                node.reply(from_node, reply_context,
                           result if result is not None else CheckStatusOk.empty(txn_id))

        node.map_reduce_consume_local(self.scope, txn_id.epoch, txn_id.epoch,
                                      map_fn, lambda a, b: a.merge(b),
                                      preload=self.preload_ids()).begin(consume)

    def __repr__(self):
        return f"CheckStatus({self.txn_id!r})"


# ---------------------------------------------------------------------------
# local knowledge propagation (Propagate.java)
# ---------------------------------------------------------------------------

def propagate_knowledge(node: "Node", txn_id: TxnId, merged: CheckStatusOk):
    """Apply a merged knowledge view to the local stores, upgrading the local
    Known lattice: outcome -> apply; stable deps -> commit(STABLE); agreed
    executeAt -> precommit; definition -> preaccept; invalidation propagates.

    Returns the AsyncResult of the per-store application chain — with delayed
    stores the application defers, and callers (fetch_data) must not settle
    success over un-applied knowledge."""
    from ..utils import async_ as au
    route = merged.route
    if route is None:
        return au.success_result(None)
    # span through the node's CURRENT epoch, not just the execution epoch: a
    # store that adopted the footprint AFTER the txn's era (elastic joins,
    # churn re-adoptions) holds waiters on this txn but owns nothing at the
    # txn's own epochs — propagation targeted at [txn, exec] never visits
    # it and its waiters starve on knowledge every peer already has (the
    # seed-6 restart-matrix k428 hole).  Per-store slicing gates below keep
    # the application sound.
    max_epoch = merged.execute_at.epoch if merged.execute_at is not None else txn_id.epoch
    max_epoch = max(max_epoch, node.topology.current_epoch)

    def for_store(safe_store: SafeCommandStore) -> None:
        existing = safe_store.get_if_exists(txn_id)
        if (existing is None or not existing.listeners) \
                and C._is_shard_redundant(safe_store, txn_id, route):
            # GC physically erased this txn below the shard fence: late
            # knowledge propagation — including truncated-outcome adoption
            # onto a freshly-created stub — must not resurrect it (ballot
            # regression; the round-4 resurrection class).  EXCEPT when a
            # local waiter still lists it as a dependency (listeners): then
            # propagation is the HEAL that unblocks the waiter and lands
            # the write this lagging replica never applied — fending that
            # off wedged whole PRE_APPLIED chains behind one unwitnessed
            # dep (the seed-6 restart-matrix k428 hole).
            return
        status = merged.save_status
        if status is SaveStatus.INVALIDATED:
            C.commit_invalidate(safe_store, txn_id, scope=route)
            return
        if status.is_truncated:
            local_parts_t = route.participants().slice(safe_store.current_ranges())
            # the cluster truncated this txn after it applied; a lagging local
            # waiter would otherwise block forever (recovery nacks Truncated).
            # If the merged view still CARRIES the outcome
            # (TRUNCATE_WITH_OUTCOME), adopt it directly: writes land (the data
            # store is timestamp-ordered and idempotent) and the command becomes
            # a truncated tombstone (Propagate.java truncated handling / Infer)
            command = safe_store.get_if_exists(txn_id)
            if command is None or command.save_status.has_been(Status.PRE_APPLIED):
                return
            writes_free = not txn_id.is_write   # sync points / reads: applying
            if merged.execute_at is None:       # is a no-op
                # truncated with NO recoverable outcome (an ERASED tombstone —
                # e.g. every consulted peer quarantined the txn's corrupt
                # journal records): the cluster applied this write, this
                # replica never did, and the individual Apply will never
                # arrive.  The only remedy is the peer-snapshot heal (data
                # stores are timestamp-ordered and idempotent; at least one
                # replica past the durable fence holds the full set).
                # Returning silently left a permanent one-replica data hole
                # once GC erased the local stub below the shard watermark.
                if txn_id.is_write and len(local_parts_t):
                    _heal_store_gaps(node, safe_store, local_parts_t)
                return
            if writes_free or (merged.writes is not None
                               and merged.applied_for.contains_all(local_parts_t)):
                was_waiting = command.waiting_on is not None \
                    and command.waiting_on.is_waiting()
                never_initialised = command.waiting_on is None \
                    and not command.save_status.has_been(Status.STABLE)
                C.adopt_truncated_outcome(safe_store, command, route,
                                          merged.execute_at,
                                          None if writes_free else merged.writes,
                                          merged.result)
                if was_waiting or never_initialised:
                    # this replica adopted an outcome WITHOUT having applied
                    # the txn's (truncated-away) predecessors: their writes
                    # will never arrive individually — heal the data gap with
                    # a peer snapshot of the affected keys (timestamp-sorted,
                    # idempotent append: the union subsumes every missing
                    # predecessor; the hostile 1000-op burn caught replicas
                    # diverging with holes exactly here)
                    _heal_store_gaps(node, safe_store, local_parts_t)
            elif txn_id.is_write and len(local_parts_t):
                # truncated upstream with the WRITES STRIPPED (plain
                # TRUNCATE tier — executeAt still known): this replica can
                # neither adopt the outcome nor ever receive the individual
                # Apply — the same one-replica hole round 7 closed for the
                # executeAt-unknown case, found again on the seed-6 restart
                # trajectory (node 1's k428 epoch-9 cohort).  Heal the gap
                # from peer snapshots and collapse the local copy to an
                # ERASED tombstone so waiters stop waiting on an apply that
                # cannot happen (reads stay refused by the stale mark until
                # the heal lands).
                from ..local.durability import Cleanup
                if not command.has_been(Status.PRE_COMMITTED):
                    # pre-committed copies heal inside C.truncate's own
                    # data-gap guard; bare stubs need it launched here
                    _heal_store_gaps(node, safe_store, local_parts_t)
                C.truncate(safe_store, command, Cleanup.ERASE)
            return
        # gate each tier on the merged knowledge actually covering THIS store's
        # slice of the route (the reference's Known.sufficientFor per-store gate,
        # Propagate.java): deps/writes slices fetched from a subset of shards
        # must not be applied to stores they don't cover.
        local_parts = route.participants().slice(safe_store.current_ranges())
        if status.has_been(Status.PRE_APPLIED) and merged.writes is not None \
                and merged.partial_deps is not None and merged.partial_txn is not None \
                and merged.applied_for.contains_all(local_parts):
            C.apply_(safe_store, txn_id, route, merged.execute_at, merged.partial_deps,
                     merged.partial_txn, merged.writes, merged.result)
            return
        if status.has_been(Status.STABLE) and merged.partial_deps is not None \
                and merged.partial_txn is not None \
                and merged.stable_for.contains_all(local_parts):
            C.commit(safe_store, txn_id, SaveStatus.STABLE, merged.promised, route,
                     merged.partial_txn, merged.execute_at, merged.partial_deps)
            return
        if status.has_been(Status.PRE_COMMITTED) and merged.execute_at is not None:
            C.precommit(safe_store, txn_id, merged.execute_at)
            return
        if status.has_been(Status.PRE_ACCEPTED) and merged.partial_txn is not None:
            C.preaccept(safe_store, txn_id, merged.partial_txn, route)

    return node.for_each_local(route, txn_id.epoch, max_epoch, for_store,
                               preload=(txn_id,))


def _heal_store_gaps(node: "Node", safe_store: SafeCommandStore,
                     participants) -> None:
    """Snapshot-fetch ``participants``' data from peer replicas and merge
    (idempotent, timestamp-ordered).  Sources may themselves lag — merging
    every reply is safe, and at least one replica past the durable fence
    (the one whose truncated evidence triggered this) holds the full set."""
    from ..primitives.keys import Ranges as _Rs
    from .fetch_messages import FetchStoreData, FetchStoreDataOk
    rngs = participants if isinstance(participants, _Rs) \
        else participants.to_ranges()
    if not len(rngs):
        return
    store = node.data_store

    def current_plan(open_rngs):
        """PER-SHARD fetch plan against the CURRENT topology: the stale mark
        may only clear when EVERY shard slice of the footprint was healed by
        replicas of THAT shard (an Ok from a different shard's peer says
        nothing about this slice).  Recomputed each retry round — replicas
        replaced under topology churn must not leave the heal retrying a
        stale peer list forever.  Each entry carries the union-heal bound:
        enough responders (self included) that any apply quorum intersects
        them."""
        topology = node.config_service.current_topology()
        plan = []
        for shard in topology.shards:
            sub = open_rngs.intersection(_Rs.of(shard.range))
            if len(sub):
                peers = sorted(n for n in shard.nodes if n != node.id)
                if peers:
                    # UNION-HEAL soundness: every write below the durable
                    # fence applied at a slow-path quorum q of n; any
                    # responder set of size >= n - q + 1 intersects every
                    # such quorum, so the union of responders' snapshots
                    # (self included — its data is already local) contains
                    # every fenced write.  Stale/partial sources count:
                    # their entries are committed writes, merge-safe.
                    # Floor at one PEER response so a gapped replica never
                    # self-certifies.
                    need_peers = max(1, len(shard.nodes)
                                     - shard.slow_path_quorum_size + 1
                                     - 1)   # minus self
                    plan.append((sub, peers, min(need_peers, len(peers))))
        return plan

    if not current_plan(rngs):
        return   # no peer can heal (lone replica): marking stale would
                 # permanently refuse reads with nothing to redirect to
    token = store.mark_stale(rngs)   # reads redirect until the gap heals
    state = {"open": rngs, "rounds": 0}
    command_store = safe_store.store

    def escalate() -> None:
        """Bootstrap-grade catch-up (Bootstrap.java:83-494 re-run for stale
        ranges / RedundantBefore.staleUntilAtLeast): after the paced
        peer-snapshot heal has failed several rounds (sustained partition,
        vanished peers), stop pacing and re-enter the full bootstrap ladder —
        coordinate a fresh exclusive sync point over the open footprint,
        stream the data (complete up to that NEW fence, so writes committed
        DURING the outage are covered too), and advance bootstrapped_at.  The
        ladder retries with its own backoff until peers return; the stale
        mark clears only on completion.

        The LAUNCH itself is paced by the store's unapplied pressure
        (refence_backoff): a catch-up bootstrap re-fences the footprint with
        a fresh exclusive sync point, and firing it while decided txns sit
        unapplied (the seed-6 slo.unapplied condition) re-fences faster
        than the wedged reads can assemble coverage — the exact cadence the
        truncation/staleness ladder must back off."""
        from ..local.bootstrap import Bootstrap, refence_backoff

        def on_done(_v, failure) -> None:
            if failure is None:
                store.clear_stale(token)

        def launch() -> None:
            Bootstrap(node, command_store, state["open"], node.epoch(),
                      catch_up=True).start().add_listener(on_done)

        delay = refence_backoff(node, command_store, 0.0)
        if delay > 0.0:
            node.scheduler.once(delay, launch)
        else:
            launch()

    def attempt(delay: float) -> None:
        """One heal round over the still-open footprint; unhealed remainder
        retries with capped backoff — partitions re-roll and churn replaces
        replicas, so availability returns without re-exposing the hole.
        After several failed rounds the heal escalates to the bootstrap
        fetch ladder (see ``escalate``)."""
        state["rounds"] += 1
        if state["rounds"] > 5:
            escalate()
            return
        next_delay = min(delay * 2, 16.0)
        plan = current_plan(state["open"])
        if not plan:
            node.scheduler.once(delay, lambda: attempt(next_delay))
            return
        round_ = {"pending": len(plan)}

        def slice_done(sub, healed: bool) -> None:
            if healed:
                state["open"] = state["open"].without(sub)
            round_["pending"] -= 1
            if round_["pending"] == 0:
                if not len(state["open"]):
                    store.clear_stale(token)
                else:
                    node.scheduler.once(delay, lambda: attempt(next_delay))

        def slice_attempt(sub, peers, need: int) -> None:
            st = {"pending": len(peers), "got": 0}

            class HealCallback(Callback):
                def on_success(self, from_node: int, reply) -> None:
                    st["pending"] -= 1
                    if isinstance(reply, FetchStoreDataOk):
                        # a NON-partial snapshot (source past the fence with
                        # no gaps of its own) is authoritative alone; partial
                        # (gapped-source) snapshots count toward the
                        # quorum-intersection bound
                        st["got"] += need if not reply.partial else 1
                        for key, entries in reply.entries.items():
                            for ts, value in entries:
                                store.append(key, ts, value)
                    if st["pending"] == 0:
                        slice_done(sub, st["got"] >= need)

                def on_failure(self, from_node: int, failure: BaseException) -> None:
                    st["pending"] -= 1
                    if st["pending"] == 0:
                        slice_done(sub, st["got"] >= need)

            callback = HealCallback()
            for to in peers:
                node.send(to, FetchStoreData(sub, allow_stale=True), callback)

        for sub, peers, need in plan:
            slice_attempt(sub, peers, need)

    attempt(2.0)


# ---------------------------------------------------------------------------
# hint messages
# ---------------------------------------------------------------------------

class InformOfTxn(TxnRequest):
    """Tell the home shard a txn exists so its progress log starts monitoring it
    (InformOfTxnId.java)."""

    __slots__ = ()

    @property
    def type(self):
        return MessageType.INFORM_OF_TXN_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id, scope = self.txn_id, self.scope

        def for_store(safe_store: SafeCommandStore) -> None:
            command = safe_store.get_or_create(txn_id)
            if command.route is None:
                command.route = scope
            # only the store owning the home key takes on coordination-progress
            # monitoring (the reference's progress-shard discipline)
            progress_shard = safe_store.current_ranges().contains(scope.home_key)
            safe_store.progress_log().unwitnessed(txn_id, scope.home_key, progress_shard)

        node.for_each_local(scope, txn_id.epoch, txn_id.epoch, for_store,
                            preload=(txn_id,))

    def __repr__(self):
        return f"InformOfTxn({self.txn_id!r})"


class FindRoute(Request):
    """Route discovery for a txn known only by id (FindRoute.java /
    FindSomeRoute.java capability): ask a node whether ANY of its stores
    witnessed the txn, and reply with the route (and how much it knows).
    Unlike every Txn request this is NOT scope-sliced — the asker has no
    route to slice by; the whole point is to learn one."""

    __slots__ = ("txn_id",)

    def __init__(self, txn_id: TxnId):
        self.txn_id = txn_id

    @property
    def type(self):
        return MessageType.FIND_ROUTE_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        from ..utils import async_ as au
        txn_id = self.txn_id

        def map_fn(safe_store: SafeCommandStore):
            cmd = safe_store.get_if_exists(txn_id)
            if cmd is not None and cmd.route is not None:
                return (cmd.route, cmd.save_status.ordinal)
            return None

        def reduce(a, b):
            if a is None:
                return b
            if b is None:
                return a
            route = a[0] if _route_wider(a[0], b[0]) else b[0]
            return (route, max(a[1], b[1]))

        def consume(result, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(
                    from_node, reply_context, failure)
                return
            route, ordinal = result if result is not None else (None, 0)
            node.reply(from_node, reply_context,
                       FindRouteOk(txn_id, route, ordinal))

        chains = [s.submit(map_fn) for s in node.command_stores.all_stores()]

        def reduce_all(results):
            acc = None
            for r in results:
                acc = reduce(acc, r)
            return acc

        au.all_of(chains).map(reduce_all).begin(consume)

    def __repr__(self):
        return f"FindRoute({self.txn_id!r})"


def _route_wider(a: Route, b: Route) -> bool:
    """Prefer full routes, then more participants."""
    if a.full != b.full:
        return a.full
    return len(a.participants()) >= len(b.participants())


class FindRouteOk(Reply):
    __slots__ = ("txn_id", "route", "status_ordinal")

    def __init__(self, txn_id: TxnId, route: Optional[Route], status_ordinal: int):
        self.txn_id = txn_id
        self.route = route
        self.status_ordinal = status_ordinal

    @property
    def type(self):
        return MessageType.FIND_ROUTE_RSP

    def __repr__(self):
        return f"FindRouteOk({self.txn_id!r}, {self.route!r})"


def find_some_route(node: "Node", txn_id: TxnId) -> "au.AsyncResult":
    """Ask EVERY node in the current topology for the txn's route
    (FindSomeRoute semantics: any replica that witnessed it suffices).
    Resolves with the widest Route found, or None if nobody knows."""
    from ..utils import async_ as au
    result = au.settable()
    targets = sorted(node.config_service.current_topology().nodes())
    state = {"pending": len(targets), "route": None}

    class RouteCallback(Callback):
        def on_success(self, from_node: int, reply) -> None:
            if isinstance(reply, FindRouteOk) and reply.route is not None:
                if state["route"] is None \
                        or _route_wider(reply.route, state["route"]):
                    state["route"] = reply.route
            self._one()

        def on_failure(self, from_node: int, failure: BaseException) -> None:
            self._one()

        def _one(self) -> None:
            state["pending"] -= 1
            if state["pending"] == 0 and not result.is_done():
                result.set_success(state["route"])

    callback = RouteCallback()
    for to in targets:
        node.send(to, FindRoute(txn_id), callback)
    if not targets:
        result.set_success(None)
    return result


class InformDurable(TxnRequest):
    """Durability notice (InformDurable.java): mark the txn durable at the
    given level so progress logs stand down."""

    __slots__ = ("execute_at", "durability")

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int,
                 execute_at: Optional[Timestamp], durability: Durability):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.execute_at = execute_at
        self.durability = durability

    @property
    def type(self):
        return MessageType.INFORM_DURABLE_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id, scope, execute_at, durability = \
            self.txn_id, self.scope, self.execute_at, self.durability

        def for_store(safe_store: SafeCommandStore) -> None:
            C.set_durability(safe_store, txn_id, durability, scope, execute_at)

        node.for_each_local(scope, txn_id.epoch, txn_id.epoch, for_store,
                            preload=(txn_id,))

    def __repr__(self):
        return f"InformDurable({self.txn_id!r}, {self.durability.name})"


class InformHomeDurable(TxnRequest):
    """Durability notice to the HOME shard specifically
    (InformHomeDurable.java): the home shard owns global progress
    responsibility for the txn (MaybeRecover/home-shard progress state), so
    it learns durably-applied status even when it holds no data for the txn —
    standing its progress machinery down."""

    __slots__ = ("execute_at", "durability")

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int,
                 execute_at: Optional[Timestamp], durability: Durability):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.execute_at = execute_at
        self.durability = durability

    @property
    def type(self):
        return MessageType.INFORM_HOME_DURABLE_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id, scope, execute_at, durability = \
            self.txn_id, self.scope, self.execute_at, self.durability

        def for_store(safe_store: SafeCommandStore) -> None:
            # durability mark only: the progress log consults durability on
            # its own cadence.  An EXPLICIT stand-down here would kill local
            # progress driving on a home replica that has not itself applied
            # yet (quorum-durable elsewhere ≠ locally complete) — that
            # variant stalled hostile burns to the probe cap.
            C.set_durability(safe_store, txn_id, durability, scope, execute_at)

        node.for_each_local(scope, txn_id.epoch, txn_id.epoch, for_store,
                            preload=(txn_id,))

    def __repr__(self):
        return f"InformHomeDurable({self.txn_id!r}, {self.durability.name})"


class Propagate(Request):
    """Knowledge propagation as a FIRST-CLASS local request
    (Propagate.java:1-546): the merged CheckStatusOk a fetch produced, applied
    to the local stores by self-delivery through the normal receive path — so
    it is serializable, shows up in message traces with its own PROPAGATE_*
    type, and is replayable like any other request."""

    __slots__ = ("txn_id", "merged")

    MESSAGE_TYPES = (MessageType.PROPAGATE_PRE_ACCEPT_MSG,
                     MessageType.PROPAGATE_STABLE_MSG,
                     MessageType.PROPAGATE_APPLY_MSG,
                     MessageType.PROPAGATE_OTHER_MSG)

    def __init__(self, txn_id: TxnId, merged: CheckStatusOk):
        self.txn_id = txn_id
        self.merged = merged

    @property
    def type(self):
        ss = self.merged.save_status
        if ss.ordinal >= SaveStatus.PRE_APPLIED.ordinal and not ss.is_truncated:
            return MessageType.PROPAGATE_APPLY_MSG
        if ss.has_been(Status.STABLE) and not ss.is_truncated:
            return MessageType.PROPAGATE_STABLE_MSG
        if ss.has_been(Status.PRE_ACCEPTED):
            return MessageType.PROPAGATE_PRE_ACCEPT_MSG
        return MessageType.PROPAGATE_OTHER_MSG

    def process(self, node: "Node", from_node: int, reply_context):
        """Returns the propagation AsyncResult so a direct caller (fetch_data)
        can settle on actual application; the normal receive path ignores it."""
        return propagate_knowledge(node, self.txn_id, self.merged)

    def __repr__(self):
        return f"Propagate({self.txn_id!r}, {self.merged.save_status.name})"
