"""Message base plumbing: Message/Request/Reply, the MessageType registry,
scope-sliced TxnRequest, and executor-pinned callbacks.

Capability parity with ``accord.messages`` base types (MessageType.java:36-82,
TxnRequest.java:1-310, Callback.java, Reply.java): every request knows how to process
itself replica-side against a Node; replies correlate to callers via an opaque
ReplyContext; TxnRequests carry a topology-sliced scope plus ``wait_for_epoch`` so a
replica defers processing until it has adopted the epoch.
"""
from __future__ import annotations

import enum
from typing import TYPE_CHECKING, Optional

from ..primitives.keys import Ranges
from ..primitives.route import Route
from ..primitives.timestamp import TxnId
from ..utils.invariants import check_state

if TYPE_CHECKING:
    from ..local.node import Node
    from ..topology.topology import Topologies


class MessageType(enum.Enum):
    """Registry of remote message types + local PROPAGATE types
    (MessageType.java:36-82). ``has_side_effects`` marks messages whose processing
    may mutate durable replica state."""

    SIMPLE_RSP = ("SIMPLE_RSP", False)
    FAILURE_RSP = ("FAILURE_RSP", False)
    PRE_ACCEPT_REQ = ("PRE_ACCEPT_REQ", True)
    PRE_ACCEPT_RSP = ("PRE_ACCEPT_RSP", False)
    ACCEPT_REQ = ("ACCEPT_REQ", True)
    ACCEPT_RSP = ("ACCEPT_RSP", False)
    ACCEPT_INVALIDATE_REQ = ("ACCEPT_INVALIDATE_REQ", True)
    GET_DEPS_REQ = ("GET_DEPS_REQ", False)
    GET_DEPS_RSP = ("GET_DEPS_RSP", False)
    GET_EPHEMERAL_READ_DEPS_REQ = ("GET_EPHEMERAL_READ_DEPS_REQ", False)
    GET_EPHEMERAL_READ_DEPS_RSP = ("GET_EPHEMERAL_READ_DEPS_RSP", False)
    GET_MAX_CONFLICT_REQ = ("GET_MAX_CONFLICT_REQ", False)
    GET_MAX_CONFLICT_RSP = ("GET_MAX_CONFLICT_RSP", False)
    COMMIT_SLOW_PATH_REQ = ("COMMIT_SLOW_PATH_REQ", True)
    COMMIT_MAXIMAL_REQ = ("COMMIT_MAXIMAL_REQ", True)
    STABLE_FAST_PATH_REQ = ("STABLE_FAST_PATH_REQ", True)
    STABLE_SLOW_PATH_REQ = ("STABLE_SLOW_PATH_REQ", True)
    STABLE_MAXIMAL_REQ = ("STABLE_MAXIMAL_REQ", True)
    COMMIT_INVALIDATE_REQ = ("COMMIT_INVALIDATE_REQ", True)
    APPLY_MINIMAL_REQ = ("APPLY_MINIMAL_REQ", True)
    APPLY_MAXIMAL_REQ = ("APPLY_MAXIMAL_REQ", True)
    APPLY_RSP = ("APPLY_RSP", False)
    READ_REQ = ("READ_REQ", False)
    READ_EPHEMERAL_REQ = ("READ_EPHEMERAL_REQ", False)
    READ_RSP = ("READ_RSP", False)
    BEGIN_RECOVER_REQ = ("BEGIN_RECOVER_REQ", True)
    BEGIN_RECOVER_RSP = ("BEGIN_RECOVER_RSP", False)
    BEGIN_INVALIDATE_REQ = ("BEGIN_INVALIDATE_REQ", True)
    BEGIN_INVALIDATE_RSP = ("BEGIN_INVALIDATE_RSP", False)
    WAIT_ON_COMMIT_REQ = ("WAIT_ON_COMMIT_REQ", False)
    WAIT_ON_COMMIT_RSP = ("WAIT_ON_COMMIT_RSP", False)
    WAIT_UNTIL_APPLIED_REQ = ("WAIT_UNTIL_APPLIED_REQ", False)
    APPLY_THEN_WAIT_UNTIL_APPLIED_REQ = ("APPLY_THEN_WAIT_UNTIL_APPLIED_REQ", True)
    RECOVER_AWAIT_REQ = ("RECOVER_AWAIT_REQ", False)
    CHECK_STATUS_REQ = ("CHECK_STATUS_REQ", False)
    CHECK_STATUS_RSP = ("CHECK_STATUS_RSP", False)
    FETCH_DATA_REQ = ("FETCH_DATA_REQ", False)
    FETCH_DATA_RSP = ("FETCH_DATA_RSP", False)
    SET_SHARD_DURABLE_REQ = ("SET_SHARD_DURABLE_REQ", True)
    SET_GLOBALLY_DURABLE_REQ = ("SET_GLOBALLY_DURABLE_REQ", True)
    QUERY_DURABLE_BEFORE_REQ = ("QUERY_DURABLE_BEFORE_REQ", False)
    QUERY_DURABLE_BEFORE_RSP = ("QUERY_DURABLE_BEFORE_RSP", False)
    INFORM_OF_TXN_REQ = ("INFORM_OF_TXN_REQ", True)
    FIND_ROUTE_REQ = ("FIND_ROUTE_REQ", False)
    FIND_ROUTE_RSP = ("FIND_ROUTE_RSP", False)
    INFORM_DURABLE_REQ = ("INFORM_DURABLE_REQ", True)
    INFORM_HOME_DURABLE_REQ = ("INFORM_HOME_DURABLE_REQ", True)
    # local-only message types (Propagate family)
    PROPAGATE_PRE_ACCEPT_MSG = ("PROPAGATE_PRE_ACCEPT_MSG", True)
    PROPAGATE_STABLE_MSG = ("PROPAGATE_STABLE_MSG", True)
    PROPAGATE_APPLY_MSG = ("PROPAGATE_APPLY_MSG", True)
    PROPAGATE_OTHER_MSG = ("PROPAGATE_OTHER_MSG", True)

    def __init__(self, _name: str, has_side_effects: bool):
        self.has_side_effects = has_side_effects


class Message:
    __slots__ = ()

    @property
    def type(self) -> MessageType:
        raise NotImplementedError


class Request(Message):
    """A message processed replica-side via ``process(node, from_node, reply_ctx)``."""

    __slots__ = ()

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        raise NotImplementedError

    def wait_for_epoch(self) -> int:
        """Replica must have adopted this epoch before processing (TxnRequest)."""
        return 0

    def prefetch_specs(self, node: "Node"):
        """Deps queries this request WILL issue when processed, as
        (command_store, impl.resolver.QuerySpec) pairs — lets a coalesced
        delivery window answer a whole batch's queries in one device launch
        (TpuDepsResolver.prefetch).  Best-effort: over- or under-declaring is
        harmless (unused answers are dropped; undeclared queries launch
        individually)."""
        return None


class _LocalNoReply:
    """Reply context for self-delivered LOCAL requests (Propagate family):
    sinks drop any reply addressed to it."""
    __slots__ = ()

    def __repr__(self):
        return "LOCAL_NO_REPLY"


LOCAL_NO_REPLY = _LocalNoReply()


class Reply(Message):
    __slots__ = ()

    @property
    def is_final(self) -> bool:
        """Non-final replies keep the callback registered (e.g. ReadOk streaming)."""
        return True


class FailureReply(Reply):
    __slots__ = ("failure",)

    def __init__(self, failure: BaseException):
        self.failure = failure

    @property
    def type(self) -> MessageType:
        return MessageType.FAILURE_RSP

    def __repr__(self) -> str:
        return f"FailureReply({self.failure!r})"


class Callback:
    """Coordinator-side reply handler; the harness pins each callback to the
    originating executor (Callback.java / SafeCallback semantics)."""

    __slots__ = ()

    def on_success(self, from_node: int, reply: Reply) -> None:
        raise NotImplementedError

    def on_failure(self, from_node: int, failure: BaseException) -> None:
        raise NotImplementedError

    def on_callback_failure(self, from_node: int, failure: BaseException) -> None:
        raise failure


class TxnRequest(Request):
    """A request scoped to one replica's intersection with a route
    (TxnRequest.java:1-310): ``scope`` is the route sliced to the ranges the
    recipient owns over the relevant epochs; ``wait_for_epoch`` gates processing."""

    __slots__ = ("txn_id", "scope", "_wait_for_epoch", "min_epoch")

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int, min_epoch: int = 0):
        self.txn_id = txn_id
        self.scope = scope
        self._wait_for_epoch = wait_for_epoch
        self.min_epoch = min_epoch or wait_for_epoch

    def wait_for_epoch(self) -> int:
        return self._wait_for_epoch

    def preload_ids(self):
        """PreLoadContext declaration (PreLoadContext.java): the txn ids this
        request's in-store processing touches.  Evicted ones are loaded
        asynchronously BEFORE the operation task runs; subclasses whose
        handlers walk dependencies (Commit, Apply) extend this."""
        return (self.txn_id,)

    @staticmethod
    def compute_scope(to_node: int, topologies: "Topologies", route: Route) -> Optional[Route]:
        """Slice ``route`` to the ranges ``to_node`` replicates across the given
        epochs (latest-epoch-first union, TxnRequest.computeScope)."""
        ranges = Ranges.EMPTY
        for topology in topologies:
            ranges = ranges.union(topology.ranges_for_node(to_node))
        sliced = route.slice(ranges)
        return None if sliced.is_empty() else sliced

    @staticmethod
    def compute_wait_for_epoch(to_node: int, topologies: "Topologies") -> int:
        """Highest epoch in which ``to_node`` participates (TxnRequest
        .computeWaitForEpoch) — no point waiting for epochs it has no ranges in."""
        wait = topologies.oldest_epoch
        for topology in topologies:
            if topology.ranges_for_node(to_node):
                wait = max(wait, topology.epoch)
        return wait
