"""The transaction-pipeline wire messages and their replica-side handlers.

Capability parity with ``accord.messages`` PreAccept/Accept/Commit/Apply/ReadData
(PreAccept.java:37-354, Accept.java:50-296, Commit.java:61-409, Apply.java:47-246,
ReadData.java:53-538): each Request processes itself against the receiving Node by
map-reducing over the intersecting CommandStores, and replies exactly once.

``Commit`` supports the reference's Stable+Read fusion (Commit.stableAndRead,
Commit.java:176): a Commit carrying ``read=True`` executes the txn's read once the
command becomes ReadyToExecute and replies ReadOk instead of a plain ack.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Dict, List, Optional

from ..local import commands as C
from ..local.cfk import InternalStatus
from ..local.command_store import SafeCommandStore
from ..local.status import SaveStatus, Status
from ..primitives.deps import Deps, DepsBuilder
from ..primitives.keys import Keys, Range, Ranges
from ..primitives.route import Route
from ..primitives.timestamp import Ballot, Timestamp, TxnId
from ..primitives.txn import PartialTxn, Writes
from ..utils import async_ as au
from .base import MessageType, Reply, Request, TxnRequest

if TYPE_CHECKING:
    from ..local.node import Node


# ---------------------------------------------------------------------------
# deps calculation (PreAccept.calculatePartialDeps, PreAccept.java:245-267)
# ---------------------------------------------------------------------------

def worst_outcome(a, b):
    """Reduce CommitOutcomes to the most severe across stores."""
    order = [C.CommitOutcome.INSUFFICIENT, C.CommitOutcome.REJECTED_BALLOT,
             C.CommitOutcome.REDUNDANT, C.CommitOutcome.SUCCESS]
    return a if order.index(a) < order.index(b) else b


def _txn_query_specs(node, txn_id: TxnId, keys_or_ranges, before: Timestamp,
                     want_max: bool):
    """Declare the deps queries a PreAccept/Accept handler will issue, per
    intersecting store (for delivery-window prefetch).  Key-domain only: range
    txns use the host-side range table, not the resolver's device index."""
    from ..impl.resolver import QuerySpec
    if isinstance(keys_or_ranges, Ranges):
        return None
    rks = []
    seen = set()
    for key in keys_or_ranges:
        rk = key.to_routing() if hasattr(key, "to_routing") else key
        if rk not in seen:
            seen.add(rk)
            rks.append(rk)
    out = []
    for store in node.command_stores.all_stores():
        local = store.current_ranges()
        local_rks = [rk for rk in rks if local.contains(rk)]
        if not local_rks:
            continue
        out.append((store, QuerySpec("kc", txn_id, local_rks, before)))
        if want_max:
            # commands.preaccept passes the UNFILTERED key list to max_conflict
            out.append((store, QuerySpec("mc", None, rks, None)))
    return out


def calculate_partial_deps(safe_store: SafeCommandStore, txn_id: TxnId,
                           keys_or_ranges, before: Timestamp) -> Deps:
    """All active conflicting txns with txnId < before, witnessed by txn_id's kind."""
    builder = DepsBuilder()
    keys = None if isinstance(keys_or_ranges, Ranges) else keys_or_ranges
    ranges = keys_or_ranges if isinstance(keys_or_ranges, Ranges) else None

    def visit(key_or_range, dep_id: TxnId):
        if dep_id != txn_id:
            builder.add(key_or_range, dep_id)

    safe_store.map_reduce_active(keys, ranges, before, txn_id, visit)
    # floor deps: the fence txns standing in for everything elided below them
    # (RedundantBefore.collectDeps, PreAccept.java:264)
    safe_store.redundant_before().collect_deps(keys, ranges, visit)
    return builder.build()


# ---------------------------------------------------------------------------
# replies
# ---------------------------------------------------------------------------

class SimpleOk(Reply):
    __slots__ = ()

    @property
    def type(self):
        return MessageType.SIMPLE_RSP

    def __repr__(self):
        return "Ok"


SIMPLE_OK = SimpleOk()


class PreAcceptOk(Reply):
    __slots__ = ("txn_id", "witnessed_at", "deps")

    def __init__(self, txn_id: TxnId, witnessed_at: Timestamp, deps: Deps):
        self.txn_id = txn_id
        self.witnessed_at = witnessed_at
        self.deps = deps

    @property
    def type(self):
        return MessageType.PRE_ACCEPT_RSP

    @property
    def witnessed_fast_path(self) -> bool:
        return self.witnessed_at == self.txn_id.as_timestamp()

    def __repr__(self):
        return f"PreAcceptOk({self.txn_id!r}@{self.witnessed_at!r})"


class PreAcceptNack(Reply):
    __slots__ = ()

    @property
    def type(self):
        return MessageType.PRE_ACCEPT_RSP

    def __repr__(self):
        return "PreAcceptNack"


class AcceptOk(Reply):
    __slots__ = ("txn_id", "deps")

    def __init__(self, txn_id: TxnId, deps: Deps):
        self.txn_id = txn_id
        self.deps = deps

    @property
    def type(self):
        return MessageType.ACCEPT_RSP

    def __repr__(self):
        return f"AcceptOk({self.txn_id!r})"


class AcceptNack(Reply):
    __slots__ = ("txn_id", "supersceded_by")

    def __init__(self, txn_id: TxnId, supersceded_by: Ballot):
        self.txn_id = txn_id
        self.supersceded_by = supersceded_by

    @property
    def type(self):
        return MessageType.ACCEPT_RSP

    def __repr__(self):
        return f"AcceptNack({self.supersceded_by!r})"


class CommitOk(Reply):
    __slots__ = ()

    @property
    def type(self):
        return MessageType.SIMPLE_RSP

    def __repr__(self):
        return "CommitOk"


COMMIT_OK = CommitOk()


class StableAck(Reply):
    """Immediate non-final ack of the Stable state for a Commit that also carries a
    read: the stable quorum must not wait on read execution (the read legitimately
    blocks on dependencies). The final ReadOk follows on the same correlation id."""
    __slots__ = ()

    @property
    def type(self):
        return MessageType.SIMPLE_RSP

    @property
    def is_final(self):
        return False

    def __repr__(self):
        return "StableAck"


STABLE_ACK = StableAck()


class CommitNack(Reply):
    __slots__ = ("outcome",)

    def __init__(self, outcome):
        self.outcome = outcome

    @property
    def type(self):
        return MessageType.SIMPLE_RSP

    def __repr__(self):
        return f"CommitNack({self.outcome})"


class ReadOk(Reply):
    __slots__ = ("unavailable", "data")

    def __init__(self, data, unavailable: Optional[Ranges] = None):
        self.data = data
        self.unavailable = unavailable

    @property
    def type(self):
        return MessageType.READ_RSP

    def __repr__(self):
        return f"ReadOk(unavailable={self.unavailable})"


class ReadNack(Reply):
    """Invalid / obsolete / redundant read (ReadData.ReadNack)."""
    __slots__ = ("reason",)

    def __init__(self, reason: str):
        self.reason = reason

    @property
    def type(self):
        return MessageType.READ_RSP

    def __repr__(self):
        return f"ReadNack({self.reason})"


class ApplyOk(Reply):
    __slots__ = ()

    @property
    def type(self):
        return MessageType.APPLY_RSP

    def __repr__(self):
        return "ApplyOk"


APPLY_OK = ApplyOk()


# ---------------------------------------------------------------------------
# PreAccept
# ---------------------------------------------------------------------------

class PreAccept(TxnRequest):
    __slots__ = ("partial_txn", "max_epoch", "route")

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int,
                 partial_txn: PartialTxn, max_epoch: int, route: Optional[Route] = None):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.partial_txn = partial_txn
        self.max_epoch = max_epoch
        # the FULL route: replicas store it so recovery/progress machinery can
        # reconstitute the txn footprint (BeginRecovery.java route field /
        # CheckStatus FoundRoute semantics)
        self.route = route if route is not None else scope

    @property
    def type(self):
        return MessageType.PRE_ACCEPT_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id, partial_txn, scope = self.txn_id, self.partial_txn, self.scope

        route = self.route

        def map_fn(safe_store: SafeCommandStore):
            outcome = C.preaccept(safe_store, txn_id, partial_txn, route)
            if outcome in (C.AcceptOutcome.REJECTED_BALLOT, C.AcceptOutcome.TRUNCATED):
                return None
            command = safe_store.get_if_exists(txn_id)
            if command.save_status is SaveStatus.INVALIDATED \
                    or command.execute_at is None:
                # invalidated (or otherwise undecidable) — an Ok here could
                # feed a fast-path decision for a txn that can never commit
                return None
            deps = calculate_partial_deps(safe_store, txn_id, partial_txn.keys,
                                          txn_id.as_timestamp())
            return (command.execute_at, deps)

        def reduce_fn(a, b):
            if a is None or b is None:
                return None
            return (a[0].merge_max(b[0]), a[1].with_merged(b[1]))

        def consume(result, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(from_node, reply_context, failure)
            elif result is None:
                node.reply(from_node, reply_context, PreAcceptNack())
            else:
                witnessed_at, deps = result
                node.reply(from_node, reply_context, PreAcceptOk(txn_id, witnessed_at, deps))

        node.map_reduce_consume_local(scope, node.topology.min_epoch, self.max_epoch,
                                      map_fn, reduce_fn,
                                      preload=self.preload_ids()).begin(consume)

    def prefetch_specs(self, node):
        # mirrors the handler's two consults: max_conflict over ALL the txn's
        # keys (commands.preaccept) and the deps walk over the store-local keys
        # (map_reduce_active's by_rk filter), before = txnId
        return _txn_query_specs(node, self.txn_id, self.partial_txn.keys,
                                self.txn_id.as_timestamp(), want_max=True)

    def __repr__(self):
        return f"PreAccept({self.txn_id!r}, {self.scope!r})"


# ---------------------------------------------------------------------------
# Accept (slow path)
# ---------------------------------------------------------------------------

class Accept(TxnRequest):
    __slots__ = ("ballot", "execute_at", "partial_deps", "keys", "route")

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int, ballot: Ballot,
                 execute_at: Timestamp, keys, partial_deps: Deps,
                 route: Optional[Route] = None):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.ballot = ballot
        self.execute_at = execute_at
        self.keys = keys
        self.partial_deps = partial_deps
        self.route = route if route is not None else scope

    @property
    def type(self):
        return MessageType.ACCEPT_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id, ballot, execute_at = self.txn_id, self.ballot, self.execute_at
        scope, keys, partial_deps = self.scope, self.keys, self.partial_deps
        route = self.route

        def map_fn(safe_store: SafeCommandStore):
            outcome = C.accept(safe_store, txn_id, ballot, route, execute_at, partial_deps)
            if outcome is C.AcceptOutcome.REJECTED_BALLOT:
                command = safe_store.get_if_exists(txn_id)
                return ("nack", command.promised)
            if outcome is C.AcceptOutcome.TRUNCATED:
                return ("nack", Ballot.MAX)
            if outcome is C.AcceptOutcome.REDUNDANT:
                # already (pre)committed — possibly at a DIFFERENT executeAt by a
                # recovery coordinator — or invalidated: acking would let the
                # proposer commit a second, conflicting decision (split brain).
                # Reply Redundant→nack so the proposer fails Preempted and the
                # true outcome is learned via CheckStatus
                # (Accept.java:102, Propose.java:104-107)
                return ("nack", Ballot.MAX)
            # collect deps newly witnessed up to executeAt (Accept.java:84-118)
            deps = calculate_partial_deps(safe_store, txn_id, keys, execute_at)
            return ("ok", deps)

        def reduce_fn(a, b):
            if a is None or b is None:
                return None
            if a[0] == "nack":
                return a
            if b[0] == "nack":
                return b
            return ("ok", a[1].with_merged(b[1]))

        def consume(result, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(from_node, reply_context, failure)
            elif result is None or result[0] == "nack":
                superseded = result[1] if result is not None else Ballot.MAX
                node.reply(from_node, reply_context, AcceptNack(txn_id, superseded))
            else:
                node.reply(from_node, reply_context, AcceptOk(txn_id, result[1]))

        node.map_reduce_consume_local(scope, node.topology.min_epoch,
                                      execute_at.epoch, map_fn, reduce_fn,
                                      preload=self.preload_ids()).begin(consume)

    def prefetch_specs(self, node):
        # the Accept deps walk runs AFTER the self-registration, whose effect
        # on its own answer is nil (the walk excludes txn_id) — the resolver's
        # self-exemption makes the prefetched answer exact
        return _txn_query_specs(node, self.txn_id, self.keys, self.execute_at,
                                want_max=False)

    def __repr__(self):
        return f"Accept({self.txn_id!r}@{self.execute_at!r})"


# ---------------------------------------------------------------------------
# Commit (slow-path commit / stable, optionally fused with the read)
# ---------------------------------------------------------------------------

class Commit(TxnRequest):
    __slots__ = ("kind_status", "ballot", "partial_txn", "execute_at", "partial_deps",
                 "read", "route")

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int,
                 kind_status: SaveStatus, execute_at: Timestamp,
                 partial_txn: Optional[PartialTxn], partial_deps: Deps,
                 read: bool = False, ballot: Ballot = Ballot.ZERO,
                 route: Optional[Route] = None):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.kind_status = kind_status    # SaveStatus.COMMITTED or SaveStatus.STABLE
        self.ballot = ballot
        self.partial_txn = partial_txn
        self.execute_at = execute_at
        self.partial_deps = partial_deps
        self.read = read
        self.route = route if route is not None else scope

    def preload_ids(self):
        if self.partial_deps is None:
            return (self.txn_id,)
        return (self.txn_id, *self.partial_deps.txn_ids())

    @property
    def type(self):
        return MessageType.STABLE_FAST_PATH_REQ if self.kind_status is SaveStatus.STABLE \
            else MessageType.COMMIT_SLOW_PATH_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id = self.txn_id

        def map_fn(safe_store: SafeCommandStore):
            return C.commit(safe_store, txn_id, self.kind_status, self.ballot, self.route,
                            self.partial_txn, self.execute_at, self.partial_deps)

        def consume(result, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(from_node, reply_context, failure)
                return
            if result not in (C.CommitOutcome.SUCCESS, C.CommitOutcome.REDUNDANT):
                node.reply(from_node, reply_context, CommitNack(result))
                return
            if self.read:
                node.reply(from_node, reply_context, STABLE_ACK)
                execute_read(node, from_node, reply_context, txn_id, self.scope,
                             self.execute_at, fallback_txn=self.partial_txn)
            else:
                node.reply(from_node, reply_context, COMMIT_OK)

        node.map_reduce_consume_local(self.scope, node.topology.min_epoch,
                                      self.execute_at.epoch,
                                      map_fn, worst_outcome,
                                      preload=self.preload_ids()).begin(consume)

    def __repr__(self):
        tag = "+read" if self.read else ""
        return f"Commit[{self.kind_status.name}]({self.txn_id!r}{tag})"


# ---------------------------------------------------------------------------
# ReadData / ReadTxnData (standalone read of a committed txn)
# ---------------------------------------------------------------------------

class ReadTxnData(TxnRequest):
    __slots__ = ("execute_at_epoch",)

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int,
                 execute_at_epoch: int):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.execute_at_epoch = execute_at_epoch

    @property
    def type(self):
        return MessageType.READ_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        execute_read(node, from_node, reply_context, self.txn_id, self.scope,
                     None)

    def __repr__(self):
        return f"ReadTxnData({self.txn_id!r})"


def execute_read(node: "Node", from_node: int, reply_context, txn_id: TxnId,
                 scope: Route, execute_at_hint: Optional[Timestamp],
                 fallback_txn=None) -> None:
    """Wait per-store for ReadyToExecute, run the read, merge Data, reply ReadOk
    (ReadData.java:57-260 state machine, collapsed to the wait->execute->reply path)."""
    exec_epoch = execute_at_hint.epoch if execute_at_hint is not None else txn_id.epoch
    stores = node.command_stores.intersecting_stores(scope, txn_id.epoch, exec_epoch)
    if not stores:
        node.reply(from_node, reply_context, ReadNack("no intersecting store"))
        return
    # a replica that no longer owns ANY of the scope at the execution epoch
    # cannot serve this read (topology moved on) — ReadData unavailable
    covered = Ranges.EMPTY
    for store in stores:
        covered = covered.union(store.ranges_at(exec_epoch))
    parts = scope.participants()
    if not covered.intersects(parts):
        node.reply(from_node, reply_context, ReadNack("unavailable"))
        return

    chains = [store.submit(
        lambda s: _read_when_ready(s, txn_id, fallback_txn),
        preload=(txn_id,)).flat_map(lambda c: c)
              for store in stores]

    def consume(datas, failure):
        if failure is not None:
            node.message_sink.reply_with_unknown_failure(from_node, reply_context, failure)
            return
        if any(d == "nack" for d in datas):
            node.reply(from_node, reply_context, ReadNack("invalidated"))
            return
        if any(d == "obsolete" for d in datas):
            node.reply(from_node, reply_context, ReadNack("obsolete"))
            return
        if any(d == "unavailable" for d in datas):
            node.reply(from_node, reply_context, ReadNack("unavailable"))
            return
        merged = None
        unavailable = Ranges.EMPTY
        for d in datas:
            if d is None:
                continue
            if isinstance(d, tuple) and d and d[0] == "partial":
                _tag, data, un = d
                unavailable = unavailable.union(un)
                d = data
                if d is None:
                    continue
            merged = d if merged is None else merged.merge(d)
        node.reply(from_node, reply_context, ReadOk(
            merged, unavailable=unavailable if len(unavailable) else None))

    au.all_of(chains).begin(consume)


class _ExclusiveSnapshotView:
    """DataStore view whose ``get_at`` excludes the entry at exactly
    ``execute_at`` — used when serving a read from a copy that already
    APPLIED the txn, where the store contains the txn's OWN write at
    ts == execute_at (executeAts are unique, so the exclusive bound strips
    exactly that entry and nothing else)."""
    __slots__ = ("_ds",)

    def __init__(self, ds):
        self._ds = ds

    def __getattr__(self, name):
        return getattr(self._ds, name)

    def get_at(self, key, execute_at):
        return self._ds.get_at(key, execute_at, exclusive=True)


def _unresolved_elision_cover(s: SafeCommandStore, command):
    """(hard, soft, whole): the slices still at risk for ``command``'s read —
    the footprints of its elided-without-local-apply write deps that have
    STILL not landed here.  Resolved entries are pruned (monotone — a dep
    never un-applies, and a completed fetch never un-delivers).

    ``hard`` slices refuse REGARDLESS of pending-bootstrap/stale marks:
    their dep is locally WITNESSED and still IN FLIGHT (below APPLIED, not
    truncated) — its Apply is coming, and the elision bound that dropped it
    was the lie (seed-8: a fence that never witnessed a minority-witnessed
    in-flight write advanced locally_applied_before past it with NO pending
    mark covering the gap; op 201's range read then served k750 while
    op 150 was still mid-recovery — v150.0 missing).  The refusal
    self-heals when the dep's Apply lands.

    ``soft`` slices (dep absent / truncated-era / unknown here) refuse only
    where a fetch is OUTSTANDING — the caller intersects with the pending
    marks; their data story is the bootstrap fetch, and refusing them
    unconditionally rebuilt the seed-6 wedge (ancient elided deps never
    "resolve" in command state: their writes arrived by fetch).

    ``whole`` is True when some unresolved dep's footprint cannot be
    derived (no partial_deps participants): fully conservative fallback."""
    from ..local.commands import _dep_applied_locally
    from ..local.status import SaveStatus as _SS
    from ..primitives.keys import _Successor
    elided = command.elided_unapplied
    if not elided:
        return Ranges.EMPTY, Ranges.EMPTY, False
    store = s.store
    deps = command.partial_deps
    hard: list = []
    soft: list = []
    whole = False
    unresolved = set()
    for dep_id in elided:
        parts = deps.participants(dep_id) if deps is not None else None
        if _dep_applied_locally(store, dep_id) \
                or _fetch_covered(s, dep_id, parts):
            continue   # landed/fetched since: pruned below (assign-only)
        unresolved.add(dep_id)
        if parts is None:
            whole = True
            continue
        dep = store.commands.get(dep_id)
        in_flight = dep is not None \
            and not dep.save_status.is_truncated \
            and dep.save_status is not _SS.INVALIDATED \
            and dep.save_status.ordinal < _SS.APPLIED.ordinal
        out = hard if in_flight else soft
        keys, rngs = parts
        out.extend(Range(rk, _Successor(rk)) for rk in keys)
        out.extend(rngs)
    if len(unresolved) != len(elided):
        # prune resolved entries with a FRESH set — the journal's identity-
        # diff skip keys on object identity (harness/journal.py _FIELDS)
        command.elided_unapplied = unresolved or None
    return (Ranges.of(*hard) if hard else Ranges.EMPTY,
            Ranges.of(*soft) if soft else Ranges.EMPTY,
            whole)


def _fetch_covered(s: SafeCommandStore, dep_id, parts) -> bool:
    """Was an elided dep's write DELIVERED BY A COMPLETED BOOTSTRAP FETCH?
    True when the dep provably executes below the ``bootstrapped_at`` fence
    on every part of its footprint this store owns, and no fetch is
    outstanding there (no pending-bootstrap/stale mark): the fetch snapshot
    was complete up to the fence, so the write is in the data even though
    the dep's Apply never ran here.  An unknown executeAt, a part above the
    fence, or an outstanding fetch stays unresolved — the seed-8 lie was a
    fence advancing past an in-flight write it never witnessed, whose
    executeAt landed ABOVE the fence."""
    store = s.store
    cmd = store.commands.get(dep_id)
    if cmd is None:
        cmd = store.cold_summaries.get(dep_id)
    exec_at = getattr(cmd, "execute_at", None) if cmd is not None else None
    if exec_at is None:
        return False
    owned = store.all_ranges()
    pending = store.pending_bootstrap or Ranges.EMPTY
    stale = getattr(s.data_store(), "stale_ranges", None)
    if stale is not None and len(stale):
        pending = pending.union(stale)
    rb = store.redundant_before
    if parts is None:
        return False
    keys, rngs = parts
    checked = False

    def point_ok(rk) -> bool:
        if pending.contains(rk):
            return False
        e = rb.entry(rk)
        b = e.bootstrapped_at if e is not None else None
        return b is not None and exec_at < b.as_timestamp()

    for key in keys:
        rk = key.to_routing() if hasattr(key, "to_routing") else key
        if not owned.contains(rk):
            continue
        checked = True
        if not point_ok(rk):
            return False
    for rng in rngs:
        probe = Ranges.of(rng)
        sliced = owned.intersection(probe)
        for piece in sliced:
            checked = True
            if pending.intersects(Ranges.of(piece)):
                return False
            for e in rb.map.values_over(piece.start, piece.end):
                b = e.bootstrapped_at if e is not None else None
                if b is None or not exec_at < b.as_timestamp():
                    return False
    return checked


def _serve_read(s: SafeCommandStore, command, result, fallback_txn,
                applied: bool = False) -> bool:
    """Serve the executeAt snapshot from this store: read the CLEAN slice and
    report pending-bootstrap / stale (heal in flight) ranges as unavailable so
    the coordinator can assemble full coverage across replicas (partial reads;
    ReadData unavailable semantics + ReadCoordinator).  Refusing whole reads
    on ANY overlap deadlocked chaos+churn burns cluster-wide.

    ``fallback_txn``: truncated copies have their partial_txn stripped — the
    fused Stable+Read request carries the definition, so the read still runs.
    """
    ptxn = command.partial_txn if command.partial_txn is not None else fallback_txn
    if ptxn is None:
        result.set_success("obsolete")   # no definition to read with
        return True
    # read against the ranges owned at the EXECUTION epoch (they may have
    # been dropped in a later one; the data is still here)
    ranges = s.store.ranges_at(command.execute_at.epoch) \
        if command.execute_at is not None else s.store.current_ranges()
    pending = s.store.pending_bootstrap
    stale = getattr(s.data_store(), "stale_ranges", None)
    if stale is not None and len(stale):
        pending = pending.union(stale) if pending else stale
    if command.waiting_on is not None \
            and (command.save_status in (SaveStatus.READY_TO_EXECUTE,
                                         SaveStatus.APPLYING,
                                         SaveStatus.APPLIED)
                 or command.applied_locally):
        # GRANDFATHERED SERVE (the seed-6 bootstrap-refencing wedge): where
        # this command's WaitingOn drained through LOCAL applies, its stable
        # deps — which cover every conflicting write below executeAt — all
        # landed in this store's MVCC snapshot, so the snapshot at executeAt
        # is COMPLETE there regardless of pending-bootstrap/stale marks a
        # LATER re-fence added.  The unavailable set therefore becomes the
        # slices touched by UNRESOLVED elisions only: hard (in-flight local
        # dep — refuses regardless of pending; the seed-8 unwitnessed-write
        # fence advance) union soft-within-pending (fetch-story deps gate on
        # an outstanding fetch).  Pending WITHOUT elisions is forgiven —
        # refusing the whole footprint is what raced coverage assembly
        # against the re-fencing cadence until every replica of a slice was
        # simultaneously fenced: the seed-6 circular wait.
        hard, soft, whole = _unresolved_elision_cover(s, command)
        if whole:
            pending = pending.union(hard) if pending else hard
        else:
            pending = hard.union(pending.intersection(soft)) if pending \
                else hard
    unavailable = Ranges.EMPTY
    if pending:
        k = ptxn.keys
        if isinstance(k, Ranges):
            unavailable = k.intersection(ranges).intersection(pending)
        else:
            hit = [rk for rk in (
                key.to_routing() if hasattr(key, "to_routing") else key
                for key in k)
                if ranges.contains(rk) and pending.contains(rk)]
            if hit:
                unavailable = ranges.intersection(pending)
        if len(unavailable):
            ranges = ranges.without(pending)
    read_keys = ptxn.keys.intersection(ranges) \
        if isinstance(ptxn.keys, Ranges) \
        else [k for k in ptxn.keys
              if ranges.contains(k.to_routing() if hasattr(k, "to_routing") else k)]

    def done(data, f, unavailable=unavailable):
        if f is not None:
            result.set_failure(f)
        elif isinstance(data, str):
            # sentinel ("obsolete"): the store cannot serve this read
            result.set_success(data)
        elif len(unavailable):
            result.set_success(("partial", data, unavailable))
        else:
            result.set_success(data)

    ds = _ExclusiveSnapshotView(s.data_store()) if applied else None
    if not applied and command.execute_at is not None \
            and not isinstance(read_keys, Ranges):
        # normal-path committed read: advance the per-key execution registers
        # for the keys the Read DECLARES (read_keys here is the full txn
        # footprint; write-only keys are registered by _apply_writes).
        # Validated; historical applied-copy serves skip this — their
        # snapshot is below the store's execution frontier by design.
        declared = ptxn.read.keys() if ptxn.read is not None else None
        tfk = s.store.timestamps_for_key
        for key in read_keys:
            if declared is None or isinstance(declared, Ranges) \
                    or declared.contains(key):
                tfk.update_last_execution(s, key, command.execute_at, False)
    ptxn.read_chain(s, command.execute_at, read_keys, data_store=ds).begin(done)
    return True


def _read_when_ready(safe_store: SafeCommandStore, txn_id: TxnId,
                     fallback_txn=None) -> au.AsyncChain:
    """Returns a chain yielding the Data read at executeAt (or 'nack')."""
    result = au.settable()
    store = safe_store.store

    def try_read(s: SafeCommandStore, command) -> bool:
        if command.save_status is SaveStatus.INVALIDATED:
            result.set_success("nack")
            return True
        def _gap_fenced(s: SafeCommandStore, cmd) -> bool:
            """A TRUNCATED_APPLY copy that never ran the dependency-ordered
            apply may be missing predecessor writes.  Serving it is sound
            only when the possibly-gappy footprint is stale/bootstrap-fenced
            (then _serve_read reports those slices unavailable).  The fencing
            paths have escape hatches — lone-replica heal, route-less
            truncation — where nothing was fenced: refuse there."""
            if cmd.route is None:
                return False
            parts = cmd.route.participants().slice(s.current_ranges())
            if not len(parts):
                return True   # nothing of the footprint lives here
            fenced = s.store.pending_bootstrap or Ranges.EMPTY
            stale = getattr(s.data_store(), "stale_ranges", None)
            if stale is not None and len(stale):
                fenced = fenced.union(stale)
            if isinstance(parts, Ranges):
                return not len(parts.without(fenced))
            return all(fenced.contains(p) for p in parts)

        if (command.save_status is SaveStatus.APPLIED
            or (command.save_status is SaveStatus.TRUNCATED_APPLY
                and (command.applied_locally or _gap_fenced(s, command)))) \
                and command.execute_at is not None:
            # the command raced past ReadyToExecute here (an Apply — possibly
            # a recovery's Maximal — or a with-outcome truncation won).  The
            # store is a timestamped MVCC snapshot, so unlike the reference
            # (ReadData.java:57-260 nacks obsolete — Cassandra's store has no
            # per-executeAt snapshot) the read CAN still be served: APPLIED
            # means every dependency's write landed locally, any known data
            # gap (truncated-without-local-apply) is stale-fenced and reported
            # as unavailable slices by _serve_read, and the EXCLUSIVE snapshot
            # bound strips the txn's own write at ts == executeAt.  Without
            # this, sustained-chaos recoveries livelock: every replica's copy
            # races to APPLIED before the recovery's read round arrives and
            # the read phase exhausts on obsolete nacks (seed-4 churn stall).
            return _serve_read(s, command, result, fallback_txn, applied=True)
        if command.save_status.is_truncated:
            # ERASED (no executeAt left to snapshot at): genuinely obsolete —
            # the coordinator reads elsewhere; stale-marking covers any gap
            result.set_success("obsolete")
            return True
        if command.save_status is SaveStatus.READY_TO_EXECUTE:
            return _serve_read(s, command, result, fallback_txn)
        if command.save_status in (SaveStatus.PRE_APPLIED, SaveStatus.APPLYING):
            # deps not yet locally applied — the executeAt snapshot is
            # incomplete here.  NACK immediately (the reference's obsolete,
            # ReadData.java:57-260): the coordinator's retry loop must stay
            # in control.  PRE_APPLIED/APPLYING are TRANSIENT — the local
            # drain reaches APPLIED, where the read serves from the MVCC
            # snapshot — so the coordinator treats this as retry-later, not
            # failure (see _ExecuteTxn's delayed read re-round).  Replica-
            # side waiting (bounded or not) was tried and LIVELOCKED hostile
            # burns: the wait pushes read replies past the coordinator's
            # own timeout/preemption windows, so no recovery attempt ever
            # completes (seed 1).
            result.set_success("obsolete")
            return True
        return False

    command = safe_store.get_or_create(txn_id)
    if not try_read(safe_store, command):
        def listener(s: SafeCommandStore, cmd):
            if result.is_done() or try_read(s, cmd):
                s.remove_transient_listener(txn_id, listener)
        safe_store.add_transient_listener(txn_id, listener)
    return result.to_chain()


# ---------------------------------------------------------------------------
# Apply
# ---------------------------------------------------------------------------

class Apply(TxnRequest):
    __slots__ = ("kind", "execute_at", "partial_deps", "partial_txn", "writes", "result",
                 "route")

    MINIMAL = "minimal"
    MAXIMAL = "maximal"

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int, kind: str,
                 execute_at: Timestamp, partial_deps: Deps,
                 partial_txn: Optional[PartialTxn], writes: Optional[Writes], result,
                 route: Optional[Route] = None):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.kind = kind
        self.execute_at = execute_at
        self.partial_deps = partial_deps
        self.partial_txn = partial_txn
        self.writes = writes
        self.result = result
        self.route = route if route is not None else scope

    def preload_ids(self):
        if self.partial_deps is None:
            return (self.txn_id,)
        return (self.txn_id, *self.partial_deps.txn_ids())

    @property
    def type(self):
        return MessageType.APPLY_MAXIMAL_REQ if self.kind == Apply.MAXIMAL \
            else MessageType.APPLY_MINIMAL_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id = self.txn_id

        def map_fn(safe_store: SafeCommandStore):
            return C.apply_(safe_store, txn_id, self.route, self.execute_at,
                            self.partial_deps, self.partial_txn, self.writes, self.result)

        def consume(result, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(from_node, reply_context, failure)
            elif result is C.CommitOutcome.INSUFFICIENT:
                node.reply(from_node, reply_context, ReadNack("insufficient"))
            else:
                # Apply acks once the outcome is durably RECORDED (Apply.java
                # ApplyReply.Applied); callers needing execution completion use
                # WaitUntilApplied / ApplyThenWaitUntilApplied instead
                node.reply(from_node, reply_context, APPLY_OK)

        node.map_reduce_consume_local(self.scope, node.topology.min_epoch,
                                      self.execute_at.epoch,
                                      map_fn, worst_outcome,
                                      preload=self.preload_ids()).begin(consume)

    def __repr__(self):
        return f"Apply[{self.kind}]({self.txn_id!r})"


class ApplyThenWaitUntilApplied(Apply):
    """Apply (Maximal) and reply only once the txn has actually APPLIED in every
    intersecting local store — the blocking-sync-point execution message
    (ApplyThenWaitUntilApplied.java; ExecuteSyncPoint.ExecuteBlocking sends it
    so its quorum means "executed", not merely "recorded")."""

    __slots__ = ()

    @property
    def type(self):
        return MessageType.APPLY_THEN_WAIT_UNTIL_APPLIED_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id, scope, execute_at = self.txn_id, self.scope, self.execute_at

        def map_fn(safe_store: SafeCommandStore):
            return C.apply_(safe_store, txn_id, self.route, execute_at,
                            self.partial_deps, self.partial_txn, self.writes,
                            self.result)

        def consume(result, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(from_node, reply_context, failure)
            elif result is C.CommitOutcome.INSUFFICIENT:
                node.reply(from_node, reply_context, ReadNack("insufficient"))
            else:
                def done(outcome, f2):
                    if f2 is not None:
                        node.message_sink.reply_with_unknown_failure(
                            from_node, reply_context, f2)
                    elif outcome == "nack":
                        node.reply(from_node, reply_context, ReadNack("invalidated"))
                    else:
                        node.reply(from_node, reply_context, APPLY_OK)
                await_applied_local(node, txn_id, scope, txn_id.epoch,
                                    execute_at.epoch).begin(done)

        node.map_reduce_consume_local(self.scope, node.topology.min_epoch,
                                      self.execute_at.epoch,
                                      map_fn, worst_outcome,
                                      preload=self.preload_ids()).begin(consume)

    def __repr__(self):
        return f"ApplyThenWaitUntilApplied({self.txn_id!r})"


# ---------------------------------------------------------------------------
# WaitUntilApplied (WaitUntilApplied.java): blocking wait used by sync-point
# execution, recovery, and bootstrap streaming — replies once the txn has
# Applied in every intersecting local store (or nacks if invalidated).
# ---------------------------------------------------------------------------

class WaitUntilApplied(TxnRequest):
    __slots__ = ("execute_at_hint",)

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int,
                 execute_at_hint: Optional[Timestamp] = None):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.execute_at_hint = execute_at_hint

    @property
    def type(self):
        return MessageType.WAIT_UNTIL_APPLIED_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id = self.txn_id
        max_epoch = self.execute_at_hint.epoch if self.execute_at_hint is not None \
            else txn_id.epoch

        def consume(outcome, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(
                    from_node, reply_context, failure)
            elif outcome == "nack":
                node.reply(from_node, reply_context, ReadNack("invalidated"))
            else:
                node.reply(from_node, reply_context, APPLY_OK)

        await_applied_local(node, txn_id, self.scope, txn_id.epoch,
                            max_epoch).begin(consume)

    def __repr__(self):
        return f"WaitUntilApplied({self.txn_id!r})"


def await_applied_local(node: "Node", txn_id: TxnId, unseekables,
                        min_epoch: int, max_epoch: int) -> au.AsyncChain:
    """Chain resolving "ok"/"nack" once ``txn_id`` is Applied (or truncated /
    invalidated) in every intersecting LOCAL store.  Shared by WaitUntilApplied
    and local barriers."""
    stores = node.command_stores.intersecting_stores(unseekables, min_epoch,
                                                     max_epoch)
    if not stores:
        return au.done("ok")
    chains = [store.submit(lambda s: await_applied(s, txn_id),
                           preload=(txn_id,))
              .flat_map(lambda c: c) for store in stores]
    return au.all_of(chains).map(
        lambda results: "nack" if any(r == "nack" for r in results) else "ok")


def await_applied(safe_store: SafeCommandStore, txn_id: TxnId) -> au.AsyncChain:
    """Chain resolving once ``txn_id`` is Applied (or truncated) in this store."""
    result = au.settable()

    def check(s: SafeCommandStore, command) -> bool:
        if command.save_status is SaveStatus.INVALIDATED:
            result.set_success("nack")
            return True
        if command.save_status.ordinal >= SaveStatus.APPLIED.ordinal \
                or command.save_status.is_truncated:
            result.set_success("ok")
            return True
        return False

    command = safe_store.get_or_create(txn_id)
    if not check(safe_store, command):
        def listener(s: SafeCommandStore, cmd):
            if check(s, cmd):
                s.remove_transient_listener(txn_id, listener)
        safe_store.add_transient_listener(txn_id, listener)
    return result.to_chain()
