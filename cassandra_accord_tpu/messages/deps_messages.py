"""GetDeps — the standalone dependency-calculation round.

Capability parity with ``accord.messages.GetDeps`` (GetDeps.java:39-125): for a
txn whose executeAt is already decided but whose deps are unknown on some of
its footprint (an interrupted commit being recovered — Recover.java:384-400 —
or a sync point collecting deps), ask each replica to calculate deps fresh at
``before = executeAt`` and merge per shard at a quorum
(coordinate/collect_deps.py = CollectDeps.java).
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Optional

from ..primitives.deps import Deps
from ..primitives.keys import Ranges
from ..primitives.route import Route
from ..primitives.timestamp import Timestamp, TxnId
from .base import MessageType, Reply, TxnRequest
from .txn_messages import calculate_partial_deps

if TYPE_CHECKING:
    from ..local.node import Node


class GetDepsOk(Reply):
    __slots__ = ("deps",)

    def __init__(self, deps: Deps):
        self.deps = deps

    @property
    def type(self):
        return MessageType.GET_DEPS_RSP

    def __repr__(self):
        return f"GetDepsOk({self.deps!r})"


class GetDeps(TxnRequest):
    __slots__ = ("keys", "execute_at")

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int,
                 keys, execute_at: Timestamp):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.keys = keys
        self.execute_at = execute_at

    @property
    def type(self):
        return MessageType.GET_DEPS_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id, keys, execute_at, scope = \
            self.txn_id, self.keys, self.execute_at, self.scope

        def map_fn(safe_store):
            return calculate_partial_deps(safe_store, txn_id, keys, execute_at)

        def reduce_fn(a, b):
            return a.with_merged(b)

        def consume(result, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(
                    from_node, reply_context, failure)
            else:
                node.reply(from_node, reply_context,
                           GetDepsOk(result if result is not None else Deps.NONE))

        node.map_reduce_consume_local(scope, node.topology.min_epoch,
                                      execute_at.epoch, map_fn, reduce_fn) \
            .begin(consume)

    def prefetch_specs(self, node):
        from .txn_messages import _txn_query_specs
        return _txn_query_specs(node, self.txn_id, self.keys, self.execute_at,
                                want_max=False)

    def __repr__(self):
        return f"GetDeps({self.txn_id!r}, @{self.execute_at!r})"


class GetMaxConflictOk(Reply):
    __slots__ = ("max_conflict",)

    def __init__(self, max_conflict: Optional[Timestamp]):
        self.max_conflict = max_conflict

    @property
    def type(self):
        return MessageType.GET_MAX_CONFLICT_RSP

    def __repr__(self):
        return f"GetMaxConflictOk({self.max_conflict!r})"


class GetMaxConflict(TxnRequest):
    """The standalone MaxConflicts consult (GetMaxConflict.java): the highest
    txnId/executeAt witnessed intersecting a footprint — lets an exclusive
    sync point (or any coordinator that only needs an ordering bound) learn a
    safe timestamp floor without a full PreAccept round."""

    __slots__ = ("keys",)

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int, keys):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.keys = keys

    @property
    def type(self):
        return MessageType.GET_MAX_CONFLICT_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        keys, scope = self.keys, self.scope

        def map_fn(safe_store):
            ks = None if isinstance(keys, Ranges) else keys
            rs = keys if isinstance(keys, Ranges) else None
            return safe_store.max_conflict(ks, rs)

        def reduce_fn(a, b):
            if a is None:
                return b
            if b is None:
                return a
            return a if a > b else b

        def consume(result, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(
                    from_node, reply_context, failure)
            else:
                node.reply(from_node, reply_context, GetMaxConflictOk(result))

        node.map_reduce_consume_local(scope, node.topology.min_epoch,
                                      self.txn_id.epoch, map_fn, reduce_fn) \
            .begin(consume)

    def __repr__(self):
        return f"GetMaxConflict({self.txn_id!r})"
