"""Ephemeral (non-durable, 1-round) read messages.

Capability parity with ``accord.messages`` GetEphemeralReadDeps /
ReadEphemeralTxnData (GetEphemeralReadDeps.java, ReadEphemeralTxnData.java):
an EphemeralRead is never witnessed by other transactions and leaves no durable
state — a quorum per shard reports the writes it must be ordered after
(plus the latest epoch, so the read executes against current topology), then one
replica per shard waits for those writes to apply locally and serves the read.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Optional

from ..local.command_store import SafeCommandStore
from ..local.status import SaveStatus
from ..primitives.deps import Deps
from ..primitives.route import Route
from ..primitives.timestamp import Timestamp, TxnId
from ..primitives.txn import PartialTxn
from ..utils import async_ as au
from .base import MessageType, Reply, TxnRequest
from .txn_messages import ReadNack, ReadOk, calculate_partial_deps

if TYPE_CHECKING:
    from ..local.node import Node


class GetEphemeralReadDepsOk(Reply):
    __slots__ = ("deps", "latest_epoch")

    def __init__(self, deps: Deps, latest_epoch: int):
        self.deps = deps
        self.latest_epoch = latest_epoch

    @property
    def type(self):
        return MessageType.GET_EPHEMERAL_READ_DEPS_RSP

    def __repr__(self):
        return f"GetEphemeralReadDepsOk(epoch={self.latest_epoch})"


class GetEphemeralReadDeps(TxnRequest):
    """Report every witnessed txn the ephemeral read must be ordered after
    (writes and sync points on its keys), plus the node's latest epoch."""

    __slots__ = ("keys",)

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int, keys):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.keys = keys

    @property
    def type(self):
        return MessageType.GET_EPHEMERAL_READ_DEPS_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id, keys = self.txn_id, self.keys

        def map_fn(safe_store: SafeCommandStore) -> Deps:
            # ALL conflicting witnessed txns (not just < txnId): the read
            # executes after everything it may be concurrent with
            return calculate_partial_deps(safe_store, txn_id, keys, Timestamp.MAX)

        def consume(result, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(from_node, reply_context,
                                                             failure)
            else:
                node.reply(from_node, reply_context, GetEphemeralReadDepsOk(
                    result if result is not None else Deps.NONE, node.epoch()))

        node.map_reduce_consume_local(self.scope, txn_id.epoch, node.epoch(),
                                      map_fn, lambda a, b: a.with_merged(b)) \
            .begin(consume)

    def __repr__(self):
        return f"GetEphemeralReadDeps({self.txn_id!r})"


class ReadEphemeralTxnData(TxnRequest):
    """Wait for the given deps to apply locally, then serve the read
    (ReadEphemeralTxnData.java; no durable command state is created)."""

    __slots__ = ("partial_txn", "partial_deps", "execute_at_epoch")

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int,
                 partial_txn: PartialTxn, partial_deps: Deps, execute_at_epoch: int):
        super().__init__(txn_id, scope, wait_for_epoch)
        self.partial_txn = partial_txn
        self.partial_deps = partial_deps
        self.execute_at_epoch = execute_at_epoch

    @property
    def type(self):
        return MessageType.READ_EPHEMERAL_REQ

    def process(self, node: "Node", from_node: int, reply_context) -> None:
        txn_id = self.txn_id
        partial_txn, partial_deps = self.partial_txn, self.partial_deps
        stores = node.command_stores.intersecting_stores(
            self.scope, txn_id.epoch, max(txn_id.epoch, self.execute_at_epoch))
        if not stores:
            node.reply(from_node, reply_context, ReadNack("no intersecting store"))
            return

        chains = [store.submit(
            lambda s: _read_after_deps(s, txn_id, partial_txn, partial_deps))
            .flat_map(lambda c: c) for store in stores]

        def consume(datas, failure):
            if failure is not None:
                node.message_sink.reply_with_unknown_failure(from_node, reply_context,
                                                             failure)
                return
            # string sentinels from the data plane: "unavailable" (bootstrap)
            # and "obsolete" (stale-marked key — read_chain propagates it so
            # a gapped replica never silently serves a non-prefix snapshot)
            for sentinel, reason in (("unavailable", "unavailable"),
                                     ("obsolete", "obsolete")):
                if any(d == sentinel for d in datas):
                    node.reply(from_node, reply_context, ReadNack(reason))
                    return
            merged = None
            for d in datas:
                if d is None:
                    continue
                merged = d if merged is None else merged.merge(d)
            node.reply(from_node, reply_context, ReadOk(merged))

        au.all_of(chains).begin(consume)

    def __repr__(self):
        return f"ReadEphemeralTxnData({self.txn_id!r})"


def _read_after_deps(safe_store: SafeCommandStore, txn_id: TxnId,
                     partial_txn: PartialTxn, partial_deps: Deps) -> au.AsyncChain:
    """Chain yielding the Data once every local dep has applied (or been
    truncated/invalidated)."""
    store = safe_store.store
    local_ranges = store.all_ranges()
    deps = partial_deps.slice(local_ranges)
    redundant = safe_store.redundant_before()
    pending = set()
    result = au.settable()

    def do_read(s: SafeCommandStore):
        # data for bootstrapping ranges is incomplete here: refuse so the
        # coordinator reads another replica (same guard as _read_when_ready)
        if s.store.pending_bootstrap \
                and partial_txn.intersects(s.store.pending_bootstrap):
            result.set_success("unavailable")
            return
        read_keys = [key for key in partial_txn.keys
                     if local_ranges.contains(key.to_routing()
                                              if hasattr(key, "to_routing") else key)]
        # ephemeral reads are never witnessed: the per-key registers are the
        # ONLY record that this key was snapshotted at this timestamp — a
        # later write landing below it is a deps-completeness violation the
        # registers alone can catch (impl/TimestampsForKey.java)
        tfk = s.store.timestamps_for_key
        snapshot_at = txn_id.as_timestamp()
        for key in read_keys:
            tfk.record_ephemeral_read(key, snapshot_at)
        partial_txn.read_chain(s, snapshot_at, read_keys).begin(
            lambda data, f: result.set_failure(f) if f is not None
            else result.set_success(data))

    def dep_done(s: SafeCommandStore, dep_cmd) -> bool:
        return dep_cmd.save_status.ordinal >= SaveStatus.APPLIED.ordinal \
            or dep_cmd.save_status.is_truncated \
            or dep_cmd.save_status is SaveStatus.INVALIDATED

    for dep_id in deps.txn_ids():
        parts = deps.participants(dep_id)
        if parts is not None and redundant.is_locally_redundant(dep_id, parts):
            continue
        dep = safe_store.get_or_create(dep_id)
        if not dep_done(safe_store, dep):
            pending.add(dep_id)

    if not pending:
        do_read(safe_store)
        return result.to_chain()

    for dep_id in list(pending):
        def listener(s: SafeCommandStore, cmd, dep_id=dep_id):
            if dep_done(s, cmd):
                s.remove_transient_listener(dep_id, listener)
                pending.discard(dep_id)
                if not pending and not result.is_done():
                    do_read(s)
        safe_store.add_transient_listener(dep_id, listener)
    return result.to_chain()
