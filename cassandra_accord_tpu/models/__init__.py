"""Device-resident "models": pytree state + jittable step pipelines."""
from .conflict_graph import (
    TxnBatch, preaccept_step, stabilise_step, execute_step, gc_step, txn_step, txn_step_scan,
)

__all__ = ["TxnBatch", "preaccept_step", "stabilise_step", "execute_step",
           "gc_step", "txn_step", "txn_step_scan"]
