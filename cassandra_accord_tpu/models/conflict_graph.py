"""ConflictGraphModel — the flagship device-resident deps-resolution pipeline.

This is the "model" of the framework in the ML-framework sense: a pytree of
device state (ops.graph_state.GraphState) plus jittable step functions that
advance it.  One ``txn_step`` is the TPU analog of everything the reference
does per transaction between PreAccept and Apply on the metadata plane:

  reference (per txn, scalar Java)              here (per BATCH, one launch)
  ------------------------------------------    ----------------------------
  CommandsForKey.mapReduceActive deps scan      overlap_join (MXU matmul)
  MaxConflicts lookup + proposal                max_conflict_ts (+ host HLC)
  Commands.commit -> initialiseWaitingOn        insert_batch adjacency rows
  Commands.maybeExecute / updateWaitingOn       kahn_frontier
  apply + listener cascade                      set_status_batch(APPLIED)

The control plane (coordination, messages, recovery) stays on the host and
calls these steps through the DepsResolver boundary (impl/tpu_resolver.py);
this module is deliberately ignorant of the protocol — it is pure array
programs, which is what makes it shardable over a Mesh (parallel/mesh.py).
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops import graph_state as gs
from ..ops import deps_kernels as dk


class TxnBatch(NamedTuple):
    """A batch of incoming transactions (host-assembled, device-consumed)."""
    slots: jax.Array     # [B] int32 — target slot per txn (host-assigned)
    key_inc: jax.Array   # [B, K] int8
    txn_id: jax.Array    # [B, 5] int32 packed lanes
    kind: jax.Array      # [B] int8
    valid: jax.Array     # [B] bool — allows padding a partial batch


@jax.jit
def preaccept_step(state: gs.GraphState, batch: TxnBatch
                   ) -> Tuple[gs.GraphState, jax.Array, jax.Array, jax.Array]:
    """Witness a batch: compute deps, propose conflict-max, insert rows.

    Returns (state', deps [B, T] bool, conflict_max [B, 5], any_dep [B]).
    Invalid (padding) lanes insert nothing."""
    deps = dk.overlap_join(state.key_inc, state.txn_id, state.kind,
                           state.status, state.active,
                           batch.key_inc, batch.txn_id, batch.kind)
    deps = deps & batch.valid[:, None]
    conflict_max, any_dep = dk.max_conflict_ts(state.ts, deps)
    # padding rows scatter to slot T-1 with valid=False -> masked writes
    safe_slots = jnp.where(batch.valid, batch.slots, state.txn_slots - 1)
    state = gs.GraphState(
        key_inc=state.key_inc.at[safe_slots].set(
            jnp.where(batch.valid[:, None], batch.key_inc,
                      state.key_inc[safe_slots])),
        ts=state.ts.at[safe_slots].set(
            jnp.where(batch.valid[:, None], batch.txn_id,
                      state.ts[safe_slots])),
        txn_id=state.txn_id.at[safe_slots].set(
            jnp.where(batch.valid[:, None], batch.txn_id,
                      state.txn_id[safe_slots])),
        kind=state.kind.at[safe_slots].set(
            jnp.where(batch.valid, batch.kind, state.kind[safe_slots])),
        status=state.status.at[safe_slots].set(
            jnp.where(batch.valid, jnp.int8(gs.PREACCEPTED),
                      state.status[safe_slots])),
        adj=state.adj.at[safe_slots].set(
            jnp.where(batch.valid[:, None], deps.astype(jnp.int8),
                      state.adj[safe_slots])),
        active=state.active.at[safe_slots].set(
            batch.valid | state.active[safe_slots]),
    )
    return state, deps, conflict_max, any_dep


@jax.jit
def stabilise_step(state: gs.GraphState, slots: jax.Array,
                   execute_at: jax.Array, valid: jax.Array) -> gs.GraphState:
    """Fix executeAt + deps for a batch (the Commit/Stable transition:
    Commands.commit -> initialiseWaitingOn, Commands.java:289,688)."""
    safe_slots = jnp.where(valid, slots, state.txn_slots - 1)
    status = jnp.where(valid, jnp.int8(gs.STABLE), state.status[safe_slots])
    ts = jnp.where(valid[:, None], execute_at, state.ts[safe_slots])
    return state._replace(
        ts=state.ts.at[safe_slots].set(ts),
        status=state.status.at[safe_slots].set(status))


@jax.jit
def execute_step(state: gs.GraphState) -> Tuple[gs.GraphState, jax.Array]:
    """One execution wave: find the ready frontier and apply it
    (Commands.maybeExecute -> Applied, Commands.java:617-666).

    Returns (state', applied_mask [T] bool)."""
    ready = dk.kahn_frontier(state.adj, state.status, state.active)
    status = jnp.where(ready, jnp.int8(gs.APPLIED), state.status)
    return state._replace(status=status), ready


@jax.jit
def gc_step(state: gs.GraphState, redundant_before: jax.Array) -> gs.GraphState:
    """Evict applied txns with txnId below the GC watermark
    (RedundantBefore.java:49-529): their slots become reusable.

    redundant_before: [5] int32 packed lanes."""
    applied = (state.status == gs.APPLIED) | (state.status == gs.INVALIDATED)
    below = gs.ts_less(state.txn_id, redundant_before[None, :])
    return gs.evict_mask(state, ~(applied & below & state.active))


@partial(jax.jit, donate_argnums=(0,))
def txn_step(state: gs.GraphState, batch: TxnBatch
             ) -> Tuple[gs.GraphState, jax.Array, jax.Array]:
    """The flagship full step: witness a batch, stabilise it at its proposed
    timestamps (fast path: executeAt == txnId when no conflict is later; slow
    path: conflict_max.next — the device analog of unique_now_at_least when
    the conflict dominates the clock), then run one execution wave.  This is
    what ``__graft_entry__.entry`` compiles and what the bench drives in a
    loop.  The full protocol uses the host-finalised proposal through the
    DepsResolver boundary instead; this fused step is the benchable
    device-only pipeline.

    Returns (state', deps [B, T], applied_mask [T])."""
    state, deps, conflict_max, any_dep = preaccept_step(state, batch)
    # fast-path: no conflict later than us -> executeAt = txnId
    fast = ~any_dep | gs.ts_less(conflict_max, batch.txn_id)
    exec_at = jnp.where(fast[:, None], batch.txn_id,
                        gs.ts_next(conflict_max, 0))
    state = stabilise_step(state, batch.slots, exec_at, batch.valid)
    state, applied = execute_step(state)
    return state, deps, applied


@partial(jax.jit, donate_argnums=(0,))
def txn_step_scan(state: gs.GraphState, batches: TxnBatch
                  ) -> Tuple[gs.GraphState, jax.Array]:
    """Run txn_step over a whole stacked sequence of batches in ONE device
    dispatch (lax.scan) — amortises host->device launch latency, the main
    cost when the control plane streams small batches.  ``batches`` fields
    carry a leading iteration axis [N, ...].

    Returns (state', applied_count [N] int32)."""

    def body(st, b):
        st, _deps, applied = txn_step(st, b)
        return st, jnp.sum(applied.astype(jnp.int32))

    return jax.lax.scan(body, state, batches)
