"""The reference progress log: home-shard liveness monitoring + blocked-dependency
resolution.

Capability parity with ``accord.impl.SimpleProgressLog`` (SimpleProgressLog.java:78-729):

- **CoordinateState** (home shard only): every txn whose progress shard is this
  store is monitored until durably settled.  Each poll compares the txn's
  ProgressToken against the last poll; no advancement means the coordinator may
  have died, so escalate through ``maybe_recover`` (CheckStatus probe, then full
  recovery / invalidation).  Polls are staggered by the owning node's scheduler.

- **BlockingState**: when a Stable command reports it is waiting on a dependency
  (``waiting`` callback), the blocking txn is monitored; if it stays undecided
  locally, fetch its state from its participants' replicas (FetchData -> local
  Propagate upgrade); if the whole cluster has nothing committed for it, recovery
  of the *blocking* txn is escalated the same way (it was pre-accepted by our
  PreAccept round, so its home shard may know nothing — recovery invalidates it).
"""
from __future__ import annotations

import enum
from typing import TYPE_CHECKING, Dict, Optional

from ..api.interfaces import ProgressLog
from ..local.status import SaveStatus, Status
from ..protocol_batch.columns import ENGAGE_FLOOR
from ..primitives.route import Route
from ..primitives.timestamp import TxnId
from ..utils.invariants import check_state

if TYPE_CHECKING:
    from ..local.command_store import CommandStore


class Progress(enum.Enum):
    EXPECTED = 0        # progress expected from elsewhere; check next poll
    NO_PROGRESS = 1     # nothing moved since last poll; escalate now
    INVESTIGATING = 2   # a probe/recovery is in flight
    DONE = 3


class _MonitorState:
    """Per-txn monitoring record, used both for home-shard coordination
    monitoring and for blocked-dependency resolution.

    ``backoff``/``cooldown``: after a failed investigation (preempted, quorum
    unreachable) the monitor sits out an exponentially growing number of polls
    before escalating again — without this, several nodes monitoring the same
    stuck txn perpetually preempt each other's recovery/invalidation ballots
    (the reference staggers its retries through randomized requeue delays,
    SimpleProgressLog.java)."""
    __slots__ = ("txn_id", "route", "progress", "token", "backoff", "cooldown")

    def __init__(self, txn_id: TxnId, route: Route):
        self.txn_id = txn_id
        self.route = route
        self.progress = Progress.EXPECTED
        self.token = None
        self.backoff = 0
        self.cooldown = 0

    def investigation_failed(self) -> None:
        # ballot-only movement no longer resets the backoff (material-advance
        # gating), so mutual preemption decays on its own — the cap can stay
        # low for fast retries once the cluster heals
        self.backoff = min(self.backoff * 2 + 1, 8)
        self.cooldown = self.backoff
        self.progress = Progress.NO_PROGRESS

    def investigation_progressed(self) -> None:
        self.backoff = 0
        self.cooldown = 0

    def in_cooldown(self) -> bool:
        if self.cooldown > 0:
            self.cooldown -= 1
            return True
        return False


_CoordinateState = _MonitorState
_BlockingState = _MonitorState


class _NonHomeState:
    """A txn pre-accepted here whose home shard is elsewhere: if it stays
    undecided, tell the home shard it exists (InformHomeOfTxn semantics) so its
    progress log starts monitoring."""
    __slots__ = ("txn_id", "route", "polls")

    def __init__(self, txn_id: TxnId, route: Route):
        self.txn_id = txn_id
        self.route = route
        self.polls = 0


class SimpleProgressLog(ProgressLog):
    """One instance per CommandStore; all callbacks arrive inside the store."""

    def __init__(self, store: "CommandStore", poll_interval_s: float = 0.5):
        self.store = store
        self.node = store.node
        self.coordinating: Dict[TxnId, _CoordinateState] = {}
        self.blocking: Dict[TxnId, _BlockingState] = {}
        self.non_home: Dict[TxnId, _NonHomeState] = {}
        # jittered cadence: [0.6, 1.6) × base, resampled per cycle, so polls of
        # different stores/nodes never stay aligned (cross-node recovery
        # collisions would otherwise livelock on mutual preemption)
        rng = self.node.random.fork()
        interval = lambda: poll_interval_s * (0.6 + rng.next_float())  # noqa: E731
        self._scheduled = self.node.scheduler.recurring(interval, self._poll)
        # retry budget (local/overload.py): a deterministic token bucket
        # bounding investigation/blocked-fetch launches per sim-second.  The
        # stagger window spreads a herd WITHIN a poll tick; the budget bounds
        # the rate ACROSS ticks — under sustained overload the backlog would
        # otherwise relaunch wholesale every poll.  None when the knob is off.
        self._budget = None
        cfg = getattr(self.node, "config", None)
        if cfg is not None and cfg.retry_budget_enabled:
            from ..local.overload import TokenBucket
            self._budget = TokenBucket(
                cfg.retry_budget_rate_s, cfg.retry_budget_burst,
                cfg.retry_budget_jitter,
                salt=(self.node.id << 16) ^ (store.id + 1),
                now_s=self.node.now_micros() / 1e6)

    def close(self) -> None:
        self._scheduled.cancel()

    def resume_after_restart(self) -> None:
        """Post-restart re-investigation seeding (crash-restart nemesis): the
        crash destroyed every monitor, so scan the journal-rebuilt store and
        re-register them.  Undecided home-shard txns resume CoordinateState
        monitoring — a crashed COORDINATOR's own in-flight txns land here, and
        if nothing advances them the next polls escalate through maybe_recover
        so peers supersede the dead coordination (commit or invalidate).
        Non-home undecided txns resume the InformHomeOfTxn path.  Blocked
        STABLE/PRE_APPLIED txns re-enter blocking monitoring separately, via
        the replay pass's maybe_execute -> waiting() callbacks."""
        store = self.store
        for txn_id, command in list(store.commands.items()):
            if command.save_status.ordinal >= SaveStatus.APPLIED.ordinal \
                    or command.save_status is SaveStatus.INVALIDATED \
                    or command.save_status.is_truncated:
                continue
            if command.durability.is_durable or command.route is None:
                continue
            progress_shard = store.current_ranges().contains(
                command.route.home_key)
            self._track(command, progress_shard)

    # -- lifecycle callbacks (home shard monitoring) -------------------------
    def _track(self, command, progress_shard: bool) -> None:
        if command.route is None:
            return
        if not progress_shard:
            if command.txn_id not in self.non_home and command.route.full:
                self.non_home[command.txn_id] = _NonHomeState(command.txn_id, command.route)
            return
        state = self.coordinating.get(command.txn_id)
        if state is None:
            self.coordinating[command.txn_id] = _CoordinateState(command.txn_id, command.route)

    def unwitnessed(self, txn_id, home_key, progress_shard) -> None:
        if progress_shard and txn_id not in self.coordinating:
            cmd = self.store.lookup(txn_id)
            if cmd is not None and cmd.route is not None:
                self.coordinating[txn_id] = _CoordinateState(txn_id, cmd.route)

    def pre_accepted(self, command, progress_shard) -> None:
        self._track(command, progress_shard)

    def accepted(self, command, progress_shard) -> None:
        self._track(command, progress_shard)

    def precommitted(self, command) -> None:
        pass

    def stable(self, command, progress_shard) -> None:
        self._track(command, progress_shard)

    def ready_to_execute(self, command) -> None:
        pass

    def executed(self, command, progress_shard) -> None:
        # outcome reached locally: the home shard's liveness duty is discharged
        # (durability scheduling handles global durability)
        self._done(command.txn_id)

    def durable(self, command) -> None:
        # durability discharges home-shard monitoring, but NOT blocked-dependency
        # tracking: a dep durable elsewhere may still need its writes applied HERE
        self.coordinating.pop(command.txn_id, None)
        self.non_home.pop(command.txn_id, None)

    def invalidated(self, command, progress_shard) -> None:
        self._done(command.txn_id)

    def clear(self, txn_id) -> None:
        self._done(txn_id)

    def _done(self, txn_id: TxnId) -> None:
        self.coordinating.pop(txn_id, None)
        self.blocking.pop(txn_id, None)
        self.non_home.pop(txn_id, None)

    # -- blocked-dependency callbacks ----------------------------------------
    def waiting(self, blocked_by, blocked_until, blocked_on_route,
                blocked_on_participants) -> None:
        if blocked_by in self.blocking:
            return
        route = _route_for_participants(blocked_by, blocked_on_route,
                                        blocked_on_participants)
        # route=None: a txn known only by id (InformOfTxnId-class knowledge) —
        # still monitored; _resolve_blocked discovers the route first
        # (FindSomeRoute/RecoverWithSomeRoute capability)
        self.blocking[blocked_by] = _BlockingState(blocked_by, route)
        self._observe("blocked_monitors")

    # -- the poll loop (SimpleProgressLog.run) --------------------------------
    def _poll(self) -> None:
        self.store.execute(lambda _safe_store: self._poll_in_store())

    def _launch_staggered(self, launch) -> None:
        """Spread investigation launches across the poll window instead of
        firing the whole backlog at one tick: with hundreds of blocked txns a
        same-tick herd of recoveries ballot-preempts itself faster than any
        attempt completes (the sustained-chaos livelock class; the reference
        staggers via randomized requeue delays, SimpleProgressLog.java)."""
        if not hasattr(self, "_stagger_rng"):
            self._stagger_rng = self.node.random.fork()
        window = getattr(self.node, "config", None)
        window = window.investigation_stagger_s if window is not None else 0.5
        delay = window * self._stagger_rng.next_float()
        self.node.scheduler.once(
            delay, lambda: self.store.execute(lambda _s: launch()))

    def _poll_in_store(self) -> None:
        if not self.coordinating and not self.blocking and not self.non_home:
            return   # nothing monitored: skip the import + scan setup
        from ..coordinate.maybe_recover import ProgressToken

        # columnar settlement pre-scan (protocol_batch/): ONE vectorized
        # gather answers "settled / outcome known / resident" for every
        # monitored id instead of a per-txn lookup + attribute chase.  Only
        # RESIDENT rows are decided from the mirror — for those the scalar
        # ``store.lookup`` is a pure dict hit, so skipping it skips no
        # fault-in; non-resident ids take the scalar path unchanged (their
        # lookup may fault evicted state in, which is observable).
        engine = self.store.batch_engine
        coord_ids = list(self.coordinating.keys())
        done_m = outcome_m = resident_m = None
        if engine is not None and len(coord_ids) >= ENGAGE_FLOOR:
            done_m, outcome_m, resident_m = engine.settled_partition(coord_ids)
        for i, txn_id in enumerate(coord_ids):
            state = self.coordinating.get(txn_id)
            if state is None or state.progress is Progress.INVESTIGATING:
                continue
            if resident_m is not None and resident_m[i]:
                if done_m[i]:
                    self._done(txn_id)
                    continue
                if outcome_m[i]:
                    continue
                command = self.store.commands.get(txn_id)  # resident: dict hit
            else:
                command = self.store.lookup(txn_id)
                if command is not None and (
                        command.save_status.ordinal
                        >= SaveStatus.APPLIED.ordinal):
                    self._done(txn_id)
                    continue
                if command is not None and command.save_status.ordinal \
                        >= SaveStatus.PRE_APPLIED.ordinal:
                    # the OUTCOME is already known locally: nothing to
                    # recover — the txn is waiting on its deps' applies,
                    # which the blocked-dep machinery drives.  Launching
                    # recoveries here is what starves applies behind
                    # recovery churn (the PRE_APPLIED-backlog livelock
                    # class: each recovery preempts coordinators actually
                    # draining the chain; the reference's ladder gates
                    # investigation while a txn is advancing,
                    # SimpleProgressLog.java:228-340)
                    continue
            local_token = None if command is None else ProgressToken(
                command.durability, command.save_status.ordinal, command.promised)
            if state.token is None or (local_token is not None
                                       and local_token.advanced_from(state.token)):
                # first poll / local progress since last poll: give it a cycle
                state.token = local_token
                state.progress = Progress.EXPECTED
                continue
            if state.in_cooldown():
                continue
            if not self._budget_ok():
                state.cooldown = max(state.cooldown, 1)
                continue
            state.progress = Progress.INVESTIGATING
            self._launch_staggered(lambda state=state: self._investigate(state))

        # blocking map: the resolved check is the only consumer of the
        # command object, so resident rows answer it entirely from the
        # mirror — no per-txn lookup at all for the (typically large under
        # chaos) still-blocked majority
        block_ids = list(self.blocking.keys())
        resolved_m = bresident_m = None
        if engine is not None and len(block_ids) >= ENGAGE_FLOOR:
            resolved_m, bresident_m = engine.resolved_partition(block_ids)
        for i, txn_id in enumerate(block_ids):
            state = self.blocking.get(txn_id)
            if state is None or state.progress is Progress.INVESTIGATING:
                continue
            if bresident_m is not None and bresident_m[i]:
                if resolved_m[i]:
                    self.blocking.pop(txn_id, None)
                    continue
            else:
                command = self.store.lookup(txn_id)
                if command is not None and self._locally_resolved(command):
                    self.blocking.pop(txn_id, None)
                    continue
            if state.progress is Progress.EXPECTED:
                # freshly blocked: give the normal pipeline one poll cycle
                state.progress = Progress.NO_PROGRESS
                continue
            if state.in_cooldown():
                continue
            if not self._budget_ok():
                state.cooldown = max(state.cooldown, 1)
                continue
            state.progress = Progress.INVESTIGATING
            self._launch_staggered(
                lambda state=state: self._resolve_blocked(state))

        for txn_id in list(self.non_home.keys()):
            state = self.non_home.get(txn_id)
            command = self.store.lookup(txn_id)
            if command is None or command.has_been(Status.PRE_COMMITTED):
                self.non_home.pop(txn_id, None)
                continue
            state.polls += 1
            if state.polls >= 2:
                self._inform_home(state)
                self.non_home.pop(txn_id, None)

    @staticmethod
    def _locally_resolved(command) -> bool:
        """A blocking dep no longer blocks anyone here: applied locally, or will
        never execute."""
        return (command.save_status.ordinal >= SaveStatus.APPLIED.ordinal
                or command.save_status is SaveStatus.INVALIDATED
                or command.save_status.is_truncated)

    def _observe(self, kind: str) -> None:
        obs = getattr(self.node, "observer", None)
        if obs is not None:
            obs.on_progress(kind, self.node.id, self.store.id)

    def _budget_ok(self) -> bool:
        """Retry-budget gate for a monitor launch.  A denial defers the txn to
        the next poll cycle (its monitor state is untouched beyond a one-poll
        cooldown) — the backlog drains at the budgeted rate instead of
        relaunching wholesale every tick."""
        if self._budget is None:
            return True
        if self._budget.try_acquire(self.node.now_micros() / 1e6):
            return True
        counters = getattr(self.node, "overload_counters", None)
        if counters is not None:
            counters["budget_denied"] += 1
        obs = getattr(self.node, "observer", None)
        if obs is not None:
            obs.registry.counter("overload.budget_denied", node=self.node.id,
                                 store=self.store.id).inc()
        return False

    def _investigate(self, state: _CoordinateState) -> None:
        from ..coordinate.maybe_recover import maybe_recover
        self._observe("investigations")

        def on_done(outcome, failure):
            current = self.coordinating.get(state.txn_id)
            if failure is not None:
                if current is not None:
                    current.investigation_failed()
                return
            if outcome.settled:
                self._done(state.txn_id)
            elif current is not None:
                # ballot-only movement is NOT progress: it means competing
                # recovery attempts are preempting each other — back off so
                # one of them eventually runs uncontended to completion
                if outcome.token.advanced_materially_from(current.token):
                    current.investigation_progressed()
                else:
                    current.investigation_failed()
                current.token = outcome.token
                current.progress = Progress.EXPECTED

        maybe_recover(self.node, state.txn_id, state.route, state.token) \
            .add_listener(on_done)

    def _resolve_blocked(self, state: _BlockingState) -> None:
        """One CheckStatus quorum probe (fetch_data, which also propagates any
        knowledge found into our stores); if the blocking txn is undecided
        cluster-wide AND made no progress since the last probe, drive it to a
        decision: recover when the definition reconstitutes, invalidate when it
        cannot (it was never durably witnessed)."""
        from ..coordinate.errors import Invalidated
        from ..coordinate.fetch_data import fetch_data
        from ..coordinate.maybe_recover import ProgressToken
        from ..coordinate.recover import invalidate as do_invalidate, recover as do_recover
        from ..utils import async_ as au
        self._observe("blocked_probes")

        if state.route is None:
            # route unknown (the txn was learned by id only): discover it
            # before anything else — RecoverWithSomeRoute (FindSomeRoute ->
            # RecoverWithRoute, RecoverWithRoute.java:1-242)
            from ..messages.status_messages import find_some_route

            def on_route(route, failure):
                current = self.blocking.get(state.txn_id)
                if current is None:
                    return
                if failure is not None or route is None:
                    # nobody in the cluster knows it yet: back off and retry
                    # (an InformOfTxn may still be in flight)
                    current.investigation_failed()
                    return
                current.route = route
                current.progress = Progress.NO_PROGRESS  # escalate next poll

            find_some_route(self.node, state.txn_id).add_listener(on_route)
            return

        def on_fetched(merged, failure):
            current = self.blocking.get(state.txn_id)
            if current is None:
                return
            if failure is not None:
                current.investigation_failed()
                return
            # fetch_data propagated any knowledge found; resolved iff the dep is
            # now APPLIED (or settled) *locally* — being merely (pre)committed
            # cluster-wide doesn't unblock local execution
            command = self.store.lookup(state.txn_id)
            if command is not None and self._locally_resolved(command):
                self.blocking.pop(state.txn_id, None)
                return
            token = ProgressToken.of(merged) if merged is not None else None
            if token is not None and token.advanced_from(current.token):
                # real (status/durability) advance: reset the backoff; a
                # ballot-only advance stands down (a competing attempt is in
                # flight — preempting it helps nobody) but GROWS the backoff
                if token.advanced_materially_from(current.token):
                    current.investigation_progressed()
                    current.progress = Progress.NO_PROGRESS  # escalate next poll if stalled
                else:
                    current.investigation_failed()
                current.token = token
                return

            # stalled and undecided: settle it
            rec = au.settable()
            txn = merged.full_txn() if merged is not None else None
            if merged is not None and merged.route is not None and merged.route.full:
                full_route = merged.route
            elif txn is not None:
                # reconstituted definition: recover over the txn's REAL
                # footprint — a partial hint would slice recovery to one shard
                # and stall it forever (empty partials at the others)
                full_route = self.node.compute_route(txn)
            else:
                full_route = state.route
            if txn is not None:
                do_recover(self.node, state.txn_id, txn, full_route, rec)
            else:
                do_invalidate(self.node, state.txn_id, full_route, rec)

            def on_settled(_value, rec_failure):
                from ..coordinate.errors import Truncated
                cur = self.blocking.get(state.txn_id)
                if cur is not None:
                    if rec_failure is None or isinstance(rec_failure,
                                                        (Invalidated, Truncated)):
                        self.blocking.pop(state.txn_id, None)
                    else:
                        cur.investigation_failed()
            rec.add_listener(on_settled)

        fetch_data(self.node, state.txn_id, state.route).add_listener(on_fetched)

    def _inform_home(self, state: _NonHomeState) -> None:
        """Send InformOfTxn to the home-shard replicas (InformHomeOfTxn)."""
        from ..messages.status_messages import InformOfTxn
        topology = self.node.topology.topology_for_epoch(state.txn_id.epoch)
        shard = topology.for_key(state.route.home_key)
        if shard is None:
            return
        for to in shard.nodes:
            if to != self.node.id:
                self.node.send(to, InformOfTxn(state.txn_id, state.route,
                                               state.txn_id.epoch))


def _route_for_participants(txn_id: TxnId, waiter_route: Optional[Route],
                            participants) -> Optional[Route]:
    """Best route hint for a blocking txn: its participants from the waiter's
    deps — a SUBSET of its true route, so the hint is a partial route (it must
    never be mistaken for the full footprint by txn reconstitution)."""
    if participants is not None:
        keys, ranges = participants
        if len(keys):
            return Route(keys[0], keys, full=False)
        if len(ranges):
            return Route(ranges[0].start, ranges, full=False)
    return waiter_route


def progress_log_factory(poll_interval_s: float = 0.5):
    """Factory suitable for Node(progress_log_factory=...)."""
    def make(store: "CommandStore") -> SimpleProgressLog:
        return SimpleProgressLog(store, poll_interval_s)
    return make
