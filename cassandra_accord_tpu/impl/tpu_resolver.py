"""TpuDepsResolver — the device-resident conflict-index data plane.

The per-store conflict index (the reference's CommandsForKey sorted arrays +
MaxConflicts map, cfk/CommandsForKey.java:615-628, MaxConflicts.java:32) lives
on-device as an ``ops.graph_state.GraphState``: a key-incidence matrix, packed
timestamp lanes, kind/status codes and an active mask over fixed txn slots.

Every dependency query (``SafeCommandStore.map_reduce_active`` →
``calculate_partial_deps``, PreAccept.java:245-267) and timestamp-proposal
consult (``max_conflict``) is answered by a batched MXU join
(ops.deps_kernels.overlap_join / max_conflict_keys) instead of the reference's
scalar per-key scans (cfk/CommandsForKey.java:925-1000).

Host/device split:
- the host keeps O(1)-per-txn bookkeeping: TxnId ↔ slot maps, per-txn key
  sets (for result attribution), status/executeAt mirrors (for monotonic
  update rules and capacity-growth rebuilds);
- the device holds the O(T×K) index and does all O(T) scan work.

Mutations (register / prune) are buffered host-side and flushed to the device
as batched scatters immediately before the next query, so a burst of
concurrent PreAccepts between queries becomes one fused device update — the
batching the dense per-txn Java scan cannot do.

Slot lifecycle: slots are recycled once a txn is fully pruned from every key
it touched (the cfk prune protocol driven by RedundantBefore GC,
command_store._prune_below_fences / run_gc); capacity doubles by host rebuild
when the free list empties.
"""
from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Set, Tuple, TYPE_CHECKING

import numpy as np

from ..primitives.keys import Range, RoutingKey
from ..primitives.timestamp import Timestamp, TxnId
from ..utils.invariants import check_state
from .resolver import DepsResolver

if TYPE_CHECKING:
    from ..local.command_store import CommandStore
    from ..local.cfk import InternalStatus


def _pack_before(before: Timestamp) -> Tuple[int, int, int, int, int]:
    """Pack a query bound, saturating out-of-device-range bounds (e.g. the
    ephemeral-read Timestamp.MAX sentinel) to lanes above every real packed
    timestamp (all real lanes are < 2^31-1)."""
    try:
        return before.pack_lanes()
    except Exception:  # noqa: BLE001 — bound exceeds device packing range
        m = 0x7FFFFFFF
        return (m, m, m, m, m)


class _TxnMirror:
    """Host bookkeeping for one indexed txn (rebuilds + attribution)."""
    __slots__ = ("slot", "kind_code", "status", "execute_at", "keys")

    def __init__(self, slot: int, kind_code: int, status: int,
                 execute_at: Timestamp, keys: Set[RoutingKey]):
        self.slot = slot
        self.kind_code = kind_code
        self.status = status
        self.execute_at = execute_at
        self.keys = keys


class TpuDepsResolver(DepsResolver):
    def __init__(self, store: "CommandStore", txn_capacity: int = 64,
                 key_capacity: int = 64):
        self.store = store
        self.txns: Dict[TxnId, _TxnMirror] = {}
        self.txn_at: Dict[int, TxnId] = {}          # slot -> txn (attribution)
        self.key_slot: Dict[RoutingKey, int] = {}
        self.key_refs: Dict[RoutingKey, int] = {}   # live incidences per key
        self.free_slots: List[int] = list(range(txn_capacity))
        heapq.heapify(self.free_slots)
        self.free_key_slots: List[int] = list(range(key_capacity))
        heapq.heapify(self.free_key_slots)
        # pending (txn_id) inserts/updates and (slot, key_slot) bit ops
        self._dirty_txns: Set[TxnId] = set()
        self._clear_bits: List[Tuple[int, int]] = []
        self._deactivate: List[int] = []
        self._state = None          # lazy: GraphState built on first flush
        self._t = txn_capacity
        self._k = key_capacity

    # -- registration (cfk.update semantics) ---------------------------------
    def register(self, txn_id: TxnId, status, execute_at, keys) -> None:
        from ..local.cfk import InternalStatus as IS
        status_i = int(status)
        m = self.txns.get(txn_id)
        if m is None:
            slot = self._alloc_slot()
            ea = execute_at if execute_at is not None else txn_id.as_timestamp()
            m = _TxnMirror(slot, int(txn_id.kind), status_i, ea, set())
            self.txns[txn_id] = m
            self.txn_at[slot] = txn_id
        else:
            # monotonic status; executeAt moves on upgrade or while ACCEPTED
            if status_i > m.status:
                m.status = status_i
                if execute_at is not None:
                    m.execute_at = execute_at
            elif status_i == m.status and execute_at is not None \
                    and status_i == int(IS.ACCEPTED):
                m.execute_at = execute_at
        for rk in keys:
            if rk not in m.keys:
                # allocate the key slot BEFORE recording the incidence: growth
                # rebuilds iterate txn key sets and need every slot assigned
                if rk not in self.key_slot:
                    self.key_slot[rk] = self._alloc_key_slot()
                m.keys.add(rk)
                self.key_refs[rk] = self.key_refs.get(rk, 0) + 1
        self._dirty_txns.add(txn_id)

    def on_pruned(self, key: RoutingKey, txn_ids) -> None:
        ks = self.key_slot.get(key)
        if ks is None:
            return
        for txn_id in txn_ids:
            m = self.txns.get(txn_id)
            if m is None or key not in m.keys:
                continue
            m.keys.discard(key)
            self._clear_bits.append((m.slot, ks))
            self._release_key(key)
            if not m.keys:
                # fully pruned: recycle the slot
                self._deactivate.append(m.slot)
                del self.txns[txn_id]
                del self.txn_at[m.slot]
                self._dirty_txns.discard(txn_id)
                heapq.heappush(self.free_slots, m.slot)

    def _release_key(self, key: RoutingKey) -> None:
        """Drop a live incidence; recycle the key slot when none remain (the
        device column is already zeroed by the per-incidence clears)."""
        n = self.key_refs.get(key, 0) - 1
        if n > 0:
            self.key_refs[key] = n
        else:
            self.key_refs.pop(key, None)
            ks = self.key_slot.pop(key, None)
            if ks is not None:
                heapq.heappush(self.free_key_slots, ks)

    # -- queries -------------------------------------------------------------
    def key_conflicts(self, by: TxnId, keys, before: Timestamp):
        import jax.numpy as jnp
        from ..ops import deps_kernels as dk
        known = [rk for rk in keys if rk in self.key_slot]
        if not known or not self.txns:
            return []
        self._flush()
        q = np.zeros((1, self._k), dtype=np.int8)
        for rk in known:
            q[0, self.key_slot[rk]] = 1
        before_lanes = np.asarray([_pack_before(before)], dtype=np.int32)
        kind = np.asarray([int(by.kind)], dtype=np.int8)
        s = self._state
        mask = np.asarray(dk.overlap_join(
            s.key_inc, s.txn_id, s.kind, s.status, s.active,
            jnp.asarray(q), jnp.asarray(before_lanes), jnp.asarray(kind)))[0]
        return self._attribute(mask, set(known))

    def range_conflicts(self, by: TxnId, rng: Range, before: Timestamp):
        keys = [rk for rk in self.key_slot if rng.contains(rk)]
        return self.key_conflicts(by, keys, before)

    def max_conflict_keys(self, keys) -> Optional[Timestamp]:
        import jax.numpy as jnp
        from ..ops import deps_kernels as dk
        known = [rk for rk in keys if rk in self.key_slot]
        if not known or not self.txns:
            return None
        self._flush()
        q = np.zeros((1, self._k), dtype=np.int8)
        for rk in known:
            q[0, self.key_slot[rk]] = 1
        s = self._state
        lanes = np.asarray(dk.max_conflict_keys(
            s.key_inc, s.ts, s.txn_id, s.active, jnp.asarray(q)))[0]
        ts = Timestamp.unpack_lanes(tuple(int(v) for v in lanes))
        return None if ts == Timestamp.NONE else ts

    def max_conflict_range(self, rng: Range) -> Optional[Timestamp]:
        keys = [rk for rk in self.key_slot if rng.contains(rk)]
        return self.max_conflict_keys(keys)

    # -- device state management ---------------------------------------------
    def _attribute(self, mask: np.ndarray, queried: Set[RoutingKey]
                   ) -> List[Tuple[RoutingKey, TxnId]]:
        """Map a [T] slot mask back to (key, TxnId) incidences.  O(|result|):
        the device did the O(T) scan; the host only touches hits."""
        out: List[Tuple[RoutingKey, TxnId]] = []
        for slot in np.nonzero(mask)[0]:
            tid = self.txn_at.get(int(slot))
            if tid is None:
                continue
            for rk in self.txns[tid].keys & queried:
                out.append((rk, tid))
        return out

    def _alloc_slot(self) -> int:
        if not self.free_slots:
            self._grow(txns=True)
        return heapq.heappop(self.free_slots)

    def _alloc_key_slot(self) -> int:
        if not self.free_key_slots:
            self._grow(txns=False)
        return heapq.heappop(self.free_key_slots)

    def _grow(self, txns: bool) -> None:
        """Double capacity and rebuild the device state from host mirrors."""
        if txns:
            self.free_slots = list(range(self._t, self._t * 2))
            heapq.heapify(self.free_slots)
            self._t *= 2
        else:
            self.free_key_slots = list(range(self._k, self._k * 2))
            heapq.heapify(self.free_key_slots)
            self._k *= 2
        self._rebuild()

    def _rebuild(self) -> None:
        """Full host->device rebuild (capacity growth only — rare, amortised)."""
        from ..ops import graph_state as gs
        import jax.numpy as jnp
        t, k = self._t, self._k
        key_inc = np.zeros((t, k), dtype=np.int8)
        ts = np.zeros((t, gs.TS_LANES), dtype=np.int32)
        txn_id = np.zeros((t, gs.TS_LANES), dtype=np.int32)
        kind = np.zeros((t,), dtype=np.int8)
        status = np.zeros((t,), dtype=np.int8)
        active = np.zeros((t,), dtype=np.bool_)
        for tid, m in self.txns.items():
            key_inc[m.slot, [self.key_slot[rk] for rk in m.keys]] = 1
            ts[m.slot] = m.execute_at.pack_lanes()
            txn_id[m.slot] = tid.pack_lanes()
            kind[m.slot] = m.kind_code
            status[m.slot] = m.status
            active[m.slot] = True
        self._state = gs.GraphState(
            key_inc=jnp.asarray(key_inc), ts=jnp.asarray(ts),
            txn_id=jnp.asarray(txn_id), kind=jnp.asarray(kind),
            status=jnp.asarray(status),
            adj=jnp.zeros((t, t), dtype=jnp.int8),
            active=jnp.asarray(active))
        self._dirty_txns.clear()
        self._clear_bits.clear()
        self._deactivate.clear()

    def _flush(self) -> None:
        """Push buffered mutations to the device as batched scatters (eager
        jnp ops: no per-batch-size recompilation; one fused update per burst)."""
        from ..ops import graph_state as gs
        import jax.numpy as jnp
        if self._state is None:
            self._rebuild()
            return
        if not (self._dirty_txns or self._clear_bits or self._deactivate):
            return
        s = self._state
        # order matters: clears and deactivations target OLD occupants of a
        # slot; inserts (which may recycle that same slot) must land last
        if self._clear_bits:
            rows = np.asarray([r for r, _ in self._clear_bits], dtype=np.int32)
            cols = np.asarray([c for _, c in self._clear_bits], dtype=np.int32)
            s = s._replace(key_inc=s.key_inc.at[rows, cols].set(0))
            self._clear_bits.clear()
        if self._deactivate:
            d = jnp.asarray(np.asarray(self._deactivate, dtype=np.int32))
            s = s._replace(active=s.active.at[d].set(False),
                           key_inc=s.key_inc.at[d].set(0),
                           status=s.status.at[d].set(0))
            self._deactivate.clear()
        if self._dirty_txns:
            dirty = sorted(self._dirty_txns)   # deterministic flush order
            n = len(dirty)
            slots = np.empty((n,), dtype=np.int32)
            key_inc = np.zeros((n, self._k), dtype=np.int8)
            ts = np.empty((n, gs.TS_LANES), dtype=np.int32)
            txn_id = np.empty((n, gs.TS_LANES), dtype=np.int32)
            kind = np.empty((n,), dtype=np.int8)
            status = np.empty((n,), dtype=np.int8)
            for i, tid in enumerate(dirty):
                m = self.txns[tid]
                slots[i] = m.slot
                key_inc[i, [self.key_slot[rk] for rk in m.keys]] = 1
                ts[i] = m.execute_at.pack_lanes()
                txn_id[i] = tid.pack_lanes()
                kind[i] = m.kind_code
                status[i] = m.status
            js = jnp.asarray(slots)
            s = gs.GraphState(
                key_inc=s.key_inc.at[js].set(jnp.asarray(key_inc)),
                ts=s.ts.at[js].set(jnp.asarray(ts)),
                txn_id=s.txn_id.at[js].set(jnp.asarray(txn_id)),
                kind=s.kind.at[js].set(jnp.asarray(kind)),
                status=s.status.at[js].set(jnp.asarray(status)),
                adj=s.adj,
                active=s.active.at[js].set(True))
            self._dirty_txns.clear()
        self._state = s

    # -- introspection (tests / bench) ---------------------------------------
    def device_state(self):
        self._flush()
        return self._state

    def indexed_count(self) -> int:
        return len(self.txns)
