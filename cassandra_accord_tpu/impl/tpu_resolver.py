"""TpuDepsResolver — the accelerator conflict-index data plane.

The per-store conflict index (the reference's CommandsForKey sorted arrays +
MaxConflicts map, cfk/CommandsForKey.java:615-628, MaxConflicts.java:32) is a
fixed-capacity ARRAY index — a key-incidence matrix, packed timestamp lanes,
kind/status codes and an active mask over txn slots — instead of the
reference's per-key pointer-chased sorted arrays.

Every dependency query (``SafeCommandStore.map_reduce_active`` →
``calculate_partial_deps``, PreAccept.java:245-267) and timestamp-proposal
consult (``max_conflict``) is ONE fused join over that index
(ops.deps_kernels.consult): key-overlap matmul × started-before lex compare ×
kind-witness mask, plus the masked lexicographic max for the timestamp
proposal — not the reference's scalar per-key scans
(cfk/CommandsForKey.java:925-1000).

TRANSITIVE ELISION (cfk/CommandsForKey.java:144-157; local/cfk.py
map_reduce_active is the oracle): committed txns executing before the latest
committed WRITE below the query bound are covered by it and excluded from
deps answers.  The index maintains this incrementally as per-incidence
COVERED bits (monotone while the per-key covering bound E_k = max committed
write executeAt only grows; prune of a covering write un-covers survivors):

- ``live`` incidence matrix = full incidence minus covered bits → one matmul
  answers an elided deps query EXACTLY for any bound above E_k (the common
  case: PreAccept/Accept bounds are fresh timestamps);
- bounds at-or-below E_k take a per-key vectorized pass that recomputes the
  covering write for that bound (rare; exact);
- max-conflict always uses the FULL incidence (elision never applies to the
  timestamp proposal).

Two execution tiers answer the SAME join bit-identically, picked per call by
a cost model (the accelerator-native split: dispatch to the MXU only when the
work amortizes launch+transfer):

- host tier  — one vectorized numpy pass (BLAS f32 matmuls + lane-wise lex
               compares).  No launch overhead; serves small windows.
- device tier — ops.deps_kernels.consult on the TPU: bf16 MXU matmul over
               [B, K] × [K, T].  Serves large batches / deep indexes
               (bench.py kernel_scaling).

The canonical index lives in host numpy (mutations are in-place row writes);
the device copy is synced lazily when the device tier is chosen.  Tier choice
never affects answers (both tiers are parity-checked against the cfk walk by
VerifyDepsResolver), only speed.

Queries batch across messages: a coalesced delivery window
(harness/cluster.py ``batch_window_us``) declares its upcoming
PreAccept/Accept consults via ``prefetch``, which answers ALL of them in one
fused consult.  Live queries are then served from the cached answers with
EXACT sequential semantics:

- every index mutation since the prefetch marks its keys dirty;
- a clean cached answer is served only when no dirty key intersects it;
- dirt from txns NEW since the prefetch is PATCHED in from the (always
  current, therefore sequentially exact) host mirrors — including the
  querying txn itself, whose own registration precedes its deps walk;
- a WRITE entering the committed lattice mid-window marks its keys HARD
  (its arrival moves the covering bound for arbitrary bounds on those keys);
  hard keys always fall back;
- patching is only attempted for bounds above E_k (below it the covered bits
  are not the right elision set), and upgrades of pre-existing txns always
  fall back.

Anything unprovable falls back to an individual consult, so batching is a
pure fast path.

Slot lifecycle: slots are recycled once a txn is fully pruned from every key
it touched (the cfk prune protocol driven by RedundantBefore GC,
command_store._prune_below_fences / run_gc); capacity doubles by host rebuild
when the free list empties.
"""
from __future__ import annotations

import heapq
import time
from typing import Dict, List, Optional, Set, Tuple, TYPE_CHECKING

import numpy as np

from ..device_service.service import AsyncResult
from ..primitives.keys import Range, RoutingKey
from ..primitives.timestamp import Timestamp, TxnId, TxnKind
from ..utils.invariants import check_state
from .resolver import DepsResolver

if TYPE_CHECKING:
    from ..local.command_store import CommandStore
    from ..local.cfk import InternalStatus

TS_LANES = 5
_WRITE = int(TxnKind.WRITE)

_WITNESSES: Optional[np.ndarray] = None
_INVALIDATED: Optional[int] = None
_COMMITTED: Optional[int] = None


def _witnesses() -> np.ndarray:
    global _WITNESSES
    if _WITNESSES is None:
        from ..ops.deps_kernels import _witness_table
        _WITNESSES = _witness_table()
    return _WITNESSES


def _status_codes() -> Tuple[int, int]:
    """(COMMITTED, INVALIDATED) from the one source of truth (local.cfk), so
    the host tier's masks can never diverge from the cfk walk or the kernel."""
    global _COMMITTED, _INVALIDATED
    if _COMMITTED is None:
        from ..local.cfk import InternalStatus
        _COMMITTED = int(InternalStatus.COMMITTED)
        _INVALIDATED = int(InternalStatus.INVALIDATED)
    return _COMMITTED, _INVALIDATED


_APPLIED: Optional[int] = None


def _applied_code() -> int:
    global _APPLIED
    if _APPLIED is None:
        from ..local.cfk import InternalStatus
        _APPLIED = int(InternalStatus.APPLIED)
    return _APPLIED


def _pack_before(before: Timestamp) -> Tuple[int, int, int, int, int]:
    """Pack a query bound, saturating out-of-device-range bounds (e.g. the
    ephemeral-read Timestamp.MAX sentinel) to lanes above every real packed
    timestamp (all real lanes are < 2^31-1)."""
    try:
        return before.pack_lanes()
    except Exception:  # noqa: BLE001 — bound exceeds device packing range
        m = 0x7FFFFFFF
        return (m, m, m, m, m)


def _post_mc(raw):
    """Deferred-mc post-processor: unpack the service's max lanes."""
    ts = Timestamp.unpack_lanes(tuple(int(v) for v in raw[1]))
    return None if ts == Timestamp.NONE else ts


def _lex_less(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Lexicographic a < b over packed lanes [..., 5] (numpy; mirrors
    ops.graph_state.ts_less exactly)."""
    lt = a[..., TS_LANES - 1] < b[..., TS_LANES - 1]
    for lane in range(TS_LANES - 2, -1, -1):
        lt = (a[..., lane] < b[..., lane]) | ((a[..., lane] == b[..., lane]) & lt)
    return lt


def _lex_max_rows(rows: np.ndarray) -> np.ndarray:
    """Lexicographic max over rows [N, 5] (N >= 1)."""
    sel = np.ones(rows.shape[0], dtype=bool)
    for lane in range(TS_LANES):
        best = rows[sel, lane].max()
        sel = sel & (rows[:, lane] == best)
    return rows[np.flatnonzero(sel)[0]]


class _TxnMirror:
    """Host bookkeeping for one indexed txn (rebuilds + attribution + the
    covered-key set for transitive elision)."""
    __slots__ = ("slot", "kind_code", "status", "execute_at", "keys", "covered",
                 "durable")

    def __init__(self, slot: int, kind_code: int, status: int,
                 execute_at: Timestamp, keys: Set[RoutingKey]):
        self.slot = slot
        self.kind_code = kind_code
        self.status = status
        self.execute_at = execute_at
        self.keys = keys
        self.covered: Set[RoutingKey] = set()
        self.durable = False   # per-txn UNIVERSAL durability (elision gate)


class TpuDepsResolver(DepsResolver):
    def __init__(self, store: "CommandStore", txn_capacity: Optional[int] = None,
                 key_capacity: Optional[int] = None, config=None):
        from ..config import LocalConfig
        cfg = config if config is not None else LocalConfig.from_env()
        self.config = cfg
        # initial capacities: growth doubles them (a host rebuild + a new jit
        # shape each time), so long-running/bench deployments start big
        if txn_capacity is None:
            txn_capacity = cfg.tpu_txn_slots
        if key_capacity is None:
            key_capacity = cfg.tpu_key_slots
        self.store = store
        self.txns: Dict[TxnId, _TxnMirror] = {}
        self.txn_at: Dict[int, TxnId] = {}          # slot -> txn (attribution)
        self.key_slot: Dict[RoutingKey, int] = {}
        self.key_refs: Dict[RoutingKey, int] = {}   # live incidences per key
        self.free_slots: List[int] = list(range(txn_capacity))
        heapq.heapify(self.free_slots)
        self.free_key_slots: List[int] = list(range(key_capacity))
        heapq.heapify(self.free_key_slots)
        # transitive-elision bookkeeping (mirrors cfk._committed_writes +
        # the covering bound per key)
        self.key_maxw: Dict[RoutingKey, Timestamp] = {}      # E_k (max cw ea)
        self.key_maxw_tid: Dict[RoutingKey, TxnId] = {}      # that write's tid
        self.key_cw: Dict[RoutingKey, Dict[TxnId, Timestamp]] = {}
        self.key_uncovered: Dict[RoutingKey, Set[TxnId]] = {}
        self.key_covered: Dict[RoutingKey, Set[TxnId]] = {}
        # max-conflict floor per key over PRUNED incidences (mirror of
        # cfk._pruned_max): a timestamp proposal must exceed every txn the
        # key ever witnessed, resident in the index or not
        self.key_mc_floor: Dict[RoutingKey, Timestamp] = {}
        # elision soundness gate (cfk.map_reduce_active doc): a txn may only
        # be covered once below the key's MAJORITY-durable watermark; the
        # store bumps durable_gen on watermark advances and we re-sweep lazily
        self._durable_gen_seen = -1
        # pending (txn_id) inserts/updates, (slot, key_slot) bit ops, and
        # chronological live-matrix ops (cover=0 / uncover=1)
        self._dirty_txns: Set[TxnId] = set()
        self._clear_bits: List[Tuple[int, int]] = []
        self._deactivate: List[int] = []
        self._live_ops: List[Tuple[int, int, int]] = []
        self._t = txn_capacity
        self._k = key_capacity
        self._h: Optional[dict] = None   # canonical numpy index (lazy)
        self._device = None              # device copy (lazy, synced on use)
        self._device_clean = False
        # tier selection: 'auto' cost model, or forced for tests/benches
        self.tier = cfg.tpu_tier
        self._threshold_elems: Optional[float] = None
        # below this many indexed txns the per-key scalar walk (the cfk
        # oracle itself) beats the vectorized tiers' fixed overhead — the
        # third rung of the cost ladder: walk / host-vector / MXU
        self._walk_max = cfg.tpu_walk_max
        # narrow-query walk routing past _walk_max (flat-cost walks)
        self._walk_width = cfg.tpu_walk_width
        # above this capacity the persistent f32 host-tier mirrors (2 × K×T×4
        # bytes) are not worth their memory — the canonical index stays int8
        # (2 × T×K bytes) and the host tier casts per call (rare: the cost
        # model prefers the device tier at that scale anyway)
        self._f32_max = cfg.tpu_f32_max
        self._walk: Optional[DepsResolver] = None
        # consult counters: ONE increment PER SUBMITTED CONSULT (a batched
        # launch of B queries counts B — the r03-comparable bookkeeping; the
        # old per-launch counting understated device traffic by the batch
        # factor and made device-vs-host ratios incomparable)
        self.walk_consults = 0
        self.host_consults = 0
        self.native_consults = 0
        self.device_consults = 0
        # total wall seconds inside fused-consult tier dispatch (whichever
        # tier answered) — the wall profiler's device-consult-wait line.
        # WALL-clock: never enters the deterministic registry or burn stats
        self.consult_wall_s = 0.0
        # persistent batched device consult service (device_service/): owns
        # the device-resident index (incremental double-buffered refresh),
        # the ragged batching window, and the futures submission API.  The
        # device tier routes through it unless tpu_service == "off" (legacy
        # one-shot dispatch, kept as the bench baseline).
        self.service_enabled = cfg.tpu_service != "off"
        self._service_obj = None
        # rows of the canonical index touched since the service last
        # refreshed its buffers (None = full upload needed: first sight,
        # capacity growth, host rebuild)
        self._dirty_rows: Optional[Set[int]] = None
        # slot high-watermarks: min-heap allocation keeps live slots a
        # prefix, so these bound the service's occupancy-view extents
        self._max_slot = -1
        self._max_key_slot = -1
        # host-tier engine: 'auto' uses the native C++ consult when built and
        # the query key-counts are sparse (its O(B*T*k_q) walk beats the
        # dense BLAS pass), 'numpy'/'native' force a rung
        self._host_engine = cfg.tpu_host_engine
        # execute-phase wait-graph mirror (Commands WaitingOn edges), the input
        # to the kernel-computed execution frontier
        self.edges: Dict[TxnId, Set[TxnId]] = {}
        # prefetched-answer cache for the current delivery window (None = no
        # window active): sig -> answer, plus keys dirtied/hardened since
        self._cache: Optional[Dict[tuple, object]] = None
        self._cache_dirty: Dict[RoutingKey, Set[TxnId]] = {}
        self._cache_hard: Set[RoutingKey] = set()
        self._prefetch_preexisting: Set[TxnId] = set()
        self.prefetch_hits = 0
        self.prefetch_patched = 0
        self.prefetch_misses = 0

    # -- the persistent device consult service --------------------------------
    def service(self):
        """This resolver's DeviceConsultService (lazy; one per store)."""
        if self._service_obj is None:
            from ..device_service.service import DeviceConsultService
            self._service_obj = DeviceConsultService(self, config=self.config)
        return self._service_obj

    def take_dirty_rows(self) -> Optional[Set[int]]:
        """Rows changed since the service's last buffer refresh (None = the
        whole index must re-upload).  Consumes the tracking set."""
        rows = self._dirty_rows
        self._dirty_rows = set()
        return rows

    @property
    def service_submitted(self) -> int:
        return self._service_obj.submitted if self._service_obj else 0

    @property
    def service_batches(self) -> int:
        return self._service_obj.batches if self._service_obj else 0

    # -- registration (cfk.update semantics) ---------------------------------
    def register(self, txn_id: TxnId, status, execute_at, keys) -> None:
        from ..local.cfk import InternalStatus as IS
        status_i = int(status)
        committed_i, invalidated_i = _status_codes()
        m = self.txns.get(txn_id)
        was: Optional[int] = None if m is None else m.status
        if m is None:
            slot = self._alloc_slot()
            ea = execute_at if execute_at is not None else txn_id.as_timestamp()
            m = _TxnMirror(slot, int(txn_id.kind), status_i, ea, set())
            self.txns[txn_id] = m
            self.txn_at[slot] = txn_id
        elif status_i == invalidated_i and committed_i <= m.status \
                and m.status != invalidated_i:
            # committed txns can never be invalidated (cfk.update's guard):
            # ignore the registration ENTIRELY — adding its keys while
            # refusing its status would split the cfk and resolver planes
            return
        else:
            # monotonic status; executeAt moves on upgrade or while ACCEPTED,
            # and is FINAL from COMMITTED on (cfk.update's invariant)
            if status_i > m.status:
                if execute_at is not None and m.status < committed_i:
                    m.execute_at = execute_at
                m.status = status_i
            elif status_i == m.status and execute_at is not None \
                    and status_i == int(IS.ACCEPTED):
                m.execute_at = execute_at
        added_key = False
        for rk in keys:
            if rk not in m.keys:
                # allocate the key slot BEFORE recording the incidence: growth
                # rebuilds iterate txn key sets and need every slot assigned
                if rk not in self.key_slot:
                    self.key_slot[rk] = self._alloc_key_slot()
                m.keys.add(rk)
                added_key = True
                self.key_refs[rk] = self.key_refs.get(rk, 0) + 1
        self._dirty_txns.add(txn_id)
        if self._cache is not None and added_key \
                and txn_id in self._prefetch_preexisting:
            # a PRE-EXISTING txn grew its footprint mid-window: its base
            # contributions in cached answers are un-patchable — drop the cache
            # (rare: routes only widen on cross-epoch re-contact)
            self._cache = None
        if self._cache is not None:
            # conservatively dirty the txn's WHOLE footprint: a status/executeAt
            # upgrade changes its contribution on every key it touches
            for rk in m.keys:
                self._cache_dirty.setdefault(rk, set()).add(txn_id)
        if (was is None or was < committed_i) \
                and committed_i <= m.status != invalidated_i:
            self._on_committed(txn_id, m)
        elif added_key and m.status != invalidated_i and committed_i <= m.status:
            # already-committed txn gained keys: index its committed presence
            # on the new keys too (same lattice-entry handling, new keys only)
            self._on_committed(txn_id, m)

    def _coverable_now(self, rk: RoutingKey, txn_id: TxnId, m: _TxnMirror,
                       e_k: Optional[Timestamp] = None,
                       bound: Optional[TxnId] = None) -> bool:
        """Cover condition = below the covering write AND below the key's
        majority-durable watermark (the elision soundness gate).  Callers in
        loops pass the hoisted per-key e_k/bound."""
        if e_k is None:
            e_k = self.key_maxw.get(rk)
        if e_k is None or not m.execute_at < e_k:
            return False
        if m.durable:
            # the flag path additionally needs the covering write to have
            # WITNESSED the entry (tid below the cover's tid): a reordered
            # cover (ea above, tid below) never chained through it, and
            # eliding it would break local-apply transitivity (cfk
            # map_reduce_active's maxcw_tid condition)
            tid_k = self.key_maxw_tid.get(rk)
            if tid_k is not None and txn_id < tid_k:
                return True
        if bound is None:
            bound = self._durable_majority(rk)
        return bound is not None and txn_id < bound

    def _on_committed(self, txn_id: TxnId, m: _TxnMirror) -> None:
        """The txn entered the committed lattice (executeAt now final):
        maintain the covering bounds and covered bits (cfk elision mirror)."""
        coverable = TxnKind.WRITE.witnesses(TxnKind(m.kind_code))
        is_w = m.kind_code == _WRITE
        for rk in m.keys:
            cw = self.key_cw.get(rk)
            if cw is not None and txn_id in cw:
                continue    # this key already processed (added-keys re-entry)
            if rk in self.key_covered and txn_id in self.key_covered[rk]:
                continue
            if coverable and self._coverable_now(rk, txn_id, m):
                self._cover(rk, txn_id, m)
            elif coverable:
                self.key_uncovered.setdefault(rk, set()).add(txn_id)
            e_k = self.key_maxw.get(rk)
            if is_w:
                self.key_cw.setdefault(rk, {})[txn_id] = m.execute_at
                if self._cache is not None:
                    # a new committed write moves the covering bound for
                    # arbitrary query bounds on this key: cached answers there
                    # are unservable for the rest of the window
                    self._cache_hard.add(rk)
                if e_k is None or m.execute_at > e_k:
                    old_tid = self.key_maxw_tid.get(rk)
                    self.key_maxw[rk] = m.execute_at
                    self.key_maxw_tid[rk] = txn_id
                    if old_tid is not None and txn_id < old_tid:
                        # REORDERED cover (ea up, tid down): flag-covered
                        # entries above the new tid are no longer provably
                        # witnessed by the cover — re-expose, then re-cover
                        # whatever the watermark still allows
                        self._unc_over_tid(rk, txn_id)
                    self._sweep(rk)

    def _unc_over_tid(self, rk: RoutingKey, new_tid: TxnId) -> None:
        """Un-cover entries whose cover validity depended on a higher frontier
        tid; the follow-up _sweep re-covers any that remain eligible (e.g.
        via the watermark path).  Un-covering is always safe — it only
        re-emits."""
        ks = self.key_slot.get(rk)
        cov = self.key_covered.get(rk)
        if ks is None or not cov:
            return
        self._cache = None
        for t in [t for t in cov if not t < new_tid]:
            mt = self.txns.get(t)
            if mt is None:
                continue
            cov.discard(t)
            mt.covered.discard(rk)
            self.key_uncovered.setdefault(rk, set()).add(t)
            self._live_ops.append((mt.slot, ks, 1))

    def _sweep(self, rk: RoutingKey) -> None:
        """The covering bound (E_k or the durability gate) advanced: cover
        every committed coverable txn now below both."""
        unc = self.key_uncovered.get(rk)
        if not unc:
            return
        e_k = self.key_maxw.get(rk)
        bound = self._durable_majority(rk)       # loop-invariant: hoisted
        if e_k is None:
            return
        for t in list(unc):
            mt = self.txns.get(t)
            if mt is not None and self._coverable_now(rk, t, mt, e_k, bound):
                unc.discard(t)
                self._cover(rk, t, mt)

    def _maybe_resweep_durable(self) -> None:
        """The store's durability watermarks advanced since we last looked:
        the elision gate may have widened — re-sweep keys with uncovered
        committed entries (lazy, amortised against durable_gen)."""
        gen = getattr(self.store, "durable_gen", None)
        if gen is None or gen == self._durable_gen_seen:
            return
        self._durable_gen_seen = gen
        self._cache = None   # cached answers predate the wider gate
        for rk in list(self.key_uncovered):
            if self.key_uncovered.get(rk):
                self._sweep(rk)

    def _cover(self, rk: RoutingKey, txn_id: TxnId, m: _TxnMirror) -> None:
        m.covered.add(rk)
        self.key_covered.setdefault(rk, set()).add(txn_id)
        self._live_ops.append((m.slot, self.key_slot[rk], 0))

    def mark_durable(self, txn_id: TxnId) -> None:
        """Per-txn UNIVERSAL durability (every Apply acked): the elision
        gate widens for this txn on every key it touches (the device-plane
        mirror of cfk.mark_durable)."""
        m = self.txns.get(txn_id)
        if m is None:
            return
        if not m.durable:
            # the flag changes per-bound answers (the walk/_slow_hits flag
            # path) even when no covered bit flips here — cached window
            # answers computed before it are unservable
            self._cache = None
        m.durable = True
        self._dirty_txns.add(txn_id)   # h["durable"] row updates on flush
        committed_i, invalidated_i = _status_codes()
        if m.status < committed_i or m.status == invalidated_i \
                or not TxnKind.WRITE.witnesses(TxnKind(m.kind_code)):
            return
        covered_any = False
        for rk in list(m.keys - m.covered):
            unc = self.key_uncovered.get(rk)
            if unc is None or txn_id not in unc:
                continue
            if self._coverable_now(rk, txn_id, m):
                unc.discard(txn_id)
                self._cover(rk, txn_id, m)
                covered_any = True
        if covered_any:
            self._cache = None   # cached answers predate the wider gate

    def on_pruned(self, key: RoutingKey, txn_ids) -> None:
        self._cache = None   # prunes mid-window are rare: drop the whole cache
        ks = self.key_slot.get(key)
        if ks is None:
            return
        cw = self.key_cw.get(key)
        cw_removed = False
        for txn_id in txn_ids:
            m = self.txns.get(txn_id)
            if m is None or key not in m.keys:
                continue
            c = m.execute_at if not m.execute_at < txn_id.as_timestamp() \
                else txn_id.as_timestamp()
            f = self.key_mc_floor.get(key)
            if f is None or c > f:
                self.key_mc_floor[key] = c
            m.keys.discard(key)
            m.covered.discard(key)
            self._clear_bits.append((m.slot, ks))
            if cw is not None and cw.pop(txn_id, None) is not None:
                cw_removed = True
            u = self.key_uncovered.get(key)
            if u is not None:
                u.discard(txn_id)
            c = self.key_covered.get(key)
            if c is not None:
                c.discard(txn_id)
            self._release_key(key)
            if not m.keys:
                # fully pruned: recycle the slot — purging any buffered
                # cover/uncover ops for it, which must never replay onto a
                # future occupant of the same slot
                self._deactivate.append(m.slot)
                if self._live_ops:
                    self._live_ops = [op for op in self._live_ops
                                      if op[0] != m.slot]
                del self.txns[txn_id]
                del self.txn_at[m.slot]
                self._dirty_txns.discard(txn_id)
                self.edges.pop(txn_id, None)
                heapq.heappush(self.free_slots, m.slot)
        if cw_removed and key in self.key_slot:
            # the covering bound may have receded: un-cover survivors whose
            # cover no longer holds (cfk recomputes per query; we re-expose)
            if cw:
                new_tid, new_e = max(cw.items(), key=lambda kv: (kv[1], kv[0]))
            else:
                new_tid, new_e = None, None
            if new_e is None:
                self.key_maxw.pop(key, None)
                self.key_maxw_tid.pop(key, None)
            else:
                self.key_maxw[key] = new_e
                self.key_maxw_tid[key] = new_tid
            bound = self._durable_majority(key)
            for t in list(self.key_covered.get(key, ())):
                mt = self.txns.get(t)
                if mt is None:
                    continue
                if not self._coverable_now(key, t, mt, new_e, bound):
                    self.key_covered[key].discard(t)
                    mt.covered.discard(key)
                    self.key_uncovered.setdefault(key, set()).add(t)
                    self._live_ops.append((mt.slot, ks, 1))

    def _release_key(self, key: RoutingKey) -> None:
        """Drop a live incidence; recycle the key slot when none remain (the
        index column is already zeroed by the per-incidence clears)."""
        n = self.key_refs.get(key, 0) - 1
        if n > 0:
            self.key_refs[key] = n
        else:
            self.key_refs.pop(key, None)
            for d in (self.key_maxw, self.key_cw, self.key_uncovered,
                      self.key_covered):
                d.pop(key, None)
            ks = self.key_slot.pop(key, None)
            if ks is not None:
                # purge buffered cover/uncover ops on the recycled COLUMN —
                # they must never replay onto a future key in this slot
                if self._live_ops:
                    self._live_ops = [op for op in self._live_ops
                                      if op[1] != ks]
                heapq.heappush(self.free_key_slots, ks)

    # -- batched prefetch (delivery-window coalescing) ------------------------
    def prefetch(self, specs) -> None:
        """Answer every declared query in ONE fused consult and cache the
        answers for the window (see module doc for the exactness rules).
        Specs whose bound is at/below a queried key's covering bound take the
        exact per-key slow path instead of the batched matmul."""
        self._maybe_resweep_durable()   # BEFORE the cache is built
        widest = max((len(s.keys) for s in specs), default=0)
        if self._use_walk(width=widest):
            # below the vectorization threshold — or a window of uniformly
            # narrow queries against a big index, where per-query walks beat
            # a dense batch pass — the walk answers each query cheaper than
            # batch + cache bookkeeping
            self._cache = None
            return
        self._cache = {}
        self._cache_dirty = {}
        self._cache_hard = set()
        # ids indexed as of the prefetch: mutations by NEW txns can be patched
        # into cached answers exactly; upgrades of these force a fallback
        self._prefetch_preexisting = set(self.txns)
        live: List[Tuple[tuple, str, List[RoutingKey], object]] = []
        slow: List[Tuple[tuple, List[RoutingKey], object, TxnId]] = []
        for spec in specs:
            known = [rk for rk in spec.keys if rk in self.key_slot]
            if spec.op == "kc":
                sig = ("kc", spec.by, frozenset(known), spec.before)
                if not known or not self.txns:
                    self._cache[sig] = []
                elif self._all_fast(known, spec.before):
                    live.append((sig, "kc", known, spec.before))
                else:
                    slow.append((sig, known, spec.before, spec.by))
            else:
                sig = ("mc", frozenset(known))
                if not known or not self.txns:
                    self._cache[sig] = None
                else:
                    live.append((sig, "mc", known, None))
        for sig, known, before, by in slow:
            self._cache[sig] = self._slow_hits(by, known, before)
        if not live:
            return
        b = len(live)
        if self._use_service(b):
            # futures path: the window's consults submit as ONE ragged batch
            # into the persistent service; nothing dispatches until the first
            # cached answer is DEMANDED (a fully-invalidated window costs
            # zero launches), and every answer is computed against the index
            # snapshot pinned here (service.begin_window) — byte-identical
            # to the eager path, since the cache exactness rules only serve
            # answers whose inputs did not change since the prefetch
            self._flush()
            svc = self.service()
            svc.begin_window()
            for sig, op, known, before in live:
                cols = [self.key_slot[rk] for rk in known]
                if op == "kc":
                    # txn_lanes: the querying TxnId in the ConsultBatch's
                    # txn_rows attribution lanes — the field the ragged
                    # ingress contract reserved for the columnar protocol
                    # batches (device_service/batch.py doc; the kernel does
                    # not read it, so answers are unchanged)
                    self._cache[sig] = svc.submit(
                        cols, _pack_before(before), int(sig[1].kind),
                        txn_lanes=sig[1].pack_lanes(),
                        post=self._post_kc(known))
                else:
                    self._cache[sig] = svc.submit(
                        cols, (0, 0, 0, 0, 0), 0, post=_post_mc)
            if not svc.deferred:
                # host fallback: no pinned snapshot — answer the window NOW
                # so mid-window mutations cannot leak into (and duplicate
                # against) the cache's delta patching
                svc.flush_window()
            return
        q = np.zeros((b, self._k), dtype=np.int8)
        before_lanes = np.zeros((b, TS_LANES), dtype=np.int32)
        kind = np.zeros((b,), dtype=np.int8)
        for i, (sig, op, known, before) in enumerate(live):
            for rk in known:
                q[i, self.key_slot[rk]] = 1
            if op == "kc":
                before_lanes[i] = _pack_before(before)
                kind[i] = int(sig[1].kind)
        deps, max_lanes = self._consult(q, before_lanes, kind)
        for i, (sig, op, known, _before) in enumerate(live):
            if op == "kc":
                self._cache[sig] = self._attribute(deps[i], set(known))
            else:
                ts = Timestamp.unpack_lanes(tuple(int(v) for v in max_lanes[i]))
                self._cache[sig] = None if ts == Timestamp.NONE else ts

    def end_batch(self) -> None:
        self._cache = None
        self._cache_dirty = {}
        self._cache_hard = set()
        if self._service_obj is not None:
            self._service_obj.end_window()

    def _use_service(self, b: int) -> bool:
        """Route this window/batch through the persistent device service?
        Same cost gate as the device tier (the service IS the device tier
        when enabled)."""
        if not self.service_enabled:
            return False
        if self.tier == "device":
            return True
        return self.tier == "auto" \
            and b * self._t * self._k >= self._device_threshold()

    def _post_kc(self, known):
        """Attribution post-processor for a deferred kc consult (applied at
        demand time; exactness of demand-time attribution is guaranteed by
        the cache dirty/hard rules — any input change forces a fallback)."""
        known_set = set(known)

        def post(raw):
            return self._attribute(raw[0], known_set)
        return post

    def _fast(self, rk: RoutingKey, before: Timestamp) -> bool:
        """Covered bits implement elision exactly for this (key, bound) iff
        the bound is above the covering bound E_k."""
        e_k = self.key_maxw.get(rk)
        return e_k is None or e_k < before

    def _all_fast(self, known, before: Timestamp) -> bool:
        return all(self._fast(rk, before) for rk in known)

    def _cached(self, sig, known, exempt: Optional[TxnId],
                before: Optional[Timestamp]):
        """A cached answer, made exact against mutations since the prefetch
        (module doc): hard keys and pre-existing upgrades fall back; NEW-txn
        dirt is patched; patching requires the bound above E_k (kc only;
        before=None means mc, where elision never applies).

        Returns (hit, answer, delta_ids); (False, None, None) on fallback."""
        if self._cache is None:
            return False, None, None
        if sig not in self._cache:
            self.prefetch_misses += 1
            return False, None, None
        delta_ids: Set[TxnId] = set()
        dirty = self._cache_dirty
        hard = self._cache_hard
        if dirty or hard:
            pre = self._prefetch_preexisting
            for rk in known:
                if rk in hard:
                    self.prefetch_misses += 1
                    return False, None, None
                for d in dirty.get(rk, ()):
                    if d == exempt and d in pre:
                        # upgrade of the querying txn itself: kc-invariant
                        # (txn_id/kind static, stays eligible; key additions
                        # to pre-existing txns nuke the cache in register)
                        continue
                    if d in pre or d not in self.txns:
                        self.prefetch_misses += 1
                        return False, None, None
                    if before is not None and not self._fast(rk, before):
                        self.prefetch_misses += 1
                        return False, None, None
                    # NEW txns — including the querying txn itself, which the
                    # CPU oracle's cfk walk also reports when txn_id < before
                    # (the Accept deps walk at before=executeAt) — are patched
                    # from the mirrors under the exact same predicates
                    delta_ids.add(d)
        ans = self._cache[sig]
        if isinstance(ans, AsyncResult):
            # deferred service consult: first demand dispatches the whole
            # window in one launch; memoize so repeated hits stay O(1)
            ans = ans.result()
            if ans is None:
                # superseded-window safety net fired: no answer — fall back
                self.prefetch_misses += 1
                return False, None, None
            self._cache[sig] = ans
        if delta_ids:
            self.prefetch_patched += 1
        else:
            self.prefetch_hits += 1
        return True, ans, delta_ids

    # -- execution-frontier plane ---------------------------------------------
    def is_indexed(self, txn_id: TxnId) -> bool:
        return txn_id in self.txns

    def register_waiting(self, waiter: TxnId, deps) -> None:
        self.edges[waiter] = set(deps)

    def remove_waiting(self, waiter: TxnId, dep: TxnId) -> None:
        s = self.edges.get(waiter)
        if s is not None:
            s.discard(dep)

    def note_terminal(self, txn_id: TxnId, invalidated: bool = False) -> None:
        """Terminal-transition mirror update, DECOUPLED from key indexing
        (see DepsResolver.note_terminal).  The live witness path misses
        terminal transitions in three shapes — cfk refuses demoted-cold /
        pruned entries, truncation never calls register_witness, and GC's
        physical erase deletes the command outright — each of which left the
        mirror status at STABLE so the kernel frontier reported the slot
        ready forever (the KNOWN_ISSUES device-only parity violation).

        Only frontier-relevant state moves: the status code and the txn's
        own wait edges.  Deps-plane answers are untouched — APPLIED has the
        same join eligibility as STABLE, and INVALIDATED is gated by
        cfk.update's committed-never-invalidated rule exactly like
        ``register`` (it only fires where the cfk walk also excludes the
        entry: never-committed, or already demoted/pruned out of the hot
        set), so cpu/tpu query parity is preserved."""
        self.edges.pop(txn_id, None)   # a terminal txn is no longer a waiter
        m = self.txns.get(txn_id)
        if m is None:
            return
        committed_i, invalidated_i = _status_codes()
        if invalidated:
            if m.status < committed_i:
                m.status = invalidated_i
                self._dirty_txns.add(txn_id)
                # eligibility changed mid-window: cached prefetch answers
                # predate it (rare — only cfk-refused invalidations land here)
                self._cache = None
        else:
            applied_i = _applied_code()
            if m.status < applied_i:
                m.status = applied_i
                self._dirty_txns.add(txn_id)

    def frontier_ready(self) -> Set[TxnId]:
        """The execution frontier as ONE kernel pass
        (ops.frontier_kernels.kahn_frontier_edges over the mirrored wait
        graph): every indexed STABLE txn whose remaining wait edges all
        point at done/evicted slots.  Edges to txns outside the index (range
        txns, cross-epoch deps) conservatively block their waiter.  This is
        the batch-executor view of the same frontier the event-driven
        WaitingOn drains one notification at a time (Commands.java:617-775);
        the burn harness asserts the two agree at quiescent points.

        The wait graph is COMPACTED to the slots that participate in edges
        and handed to the frontier tier as CSR edge arrays — the previous
        dense formulation materialized a pow2 [n, n] adjacency per release
        tick and ran a matmul over it, quadratic in the involved set for a
        graph that is sparse by construction (elision bounds deps to
        concurrency)."""
        from ..ops import frontier_kernels as fk
        self._flush()
        h = self._h
        stable_i = 4   # ops.graph_state.STABLE == cfk.InternalStatus.STABLE
        involved: List[int] = []
        pos: Dict[int, int] = {}

        def slot_of(tid: TxnId) -> Optional[int]:
            m = self.txns.get(tid)
            return None if m is None else m.slot

        edge_pairs: List[Tuple[int, int]] = []
        external_waiters: Set[int] = set()
        for waiter, deps in self.edges.items():
            ws = slot_of(waiter)
            if ws is None or not deps:
                continue
            for d in deps:
                ds = slot_of(d)
                if ds is None:
                    external_waiters.add(ws)
                else:
                    edge_pairs.append((ws, ds))
        for a, b in edge_pairs:
            for s in (a, b):
                if s not in pos:
                    pos[s] = len(involved)
                    involved.append(s)
        for s in external_waiters:
            if s not in pos:
                pos[s] = len(involved)
                involved.append(s)
        ready_ids: Set[TxnId] = set()
        # stable slots with no wait edges at all are ready outright
        waiting_slots = {a for a, _ in edge_pairs} | external_waiters
        for s in np.nonzero(h["active"] & (h["status"] == stable_i))[0]:
            s = int(s)
            if s not in waiting_slots and s in self.txn_at:
                ready_ids.add(self.txn_at[s])
        if involved:
            idx = np.asarray(involved)
            src = np.fromiter((pos[a] for a, _ in edge_pairs),
                              dtype=np.int32, count=len(edge_pairs))
            dst = np.fromiter((pos[b] for _, b in edge_pairs),
                              dtype=np.int32, count=len(edge_pairs))
            ready = fk.frontier_ready_from_edges(
                src, dst, h["status"][idx], h["active"][idx])
            for i in np.nonzero(ready)[0]:
                s = involved[int(i)]
                if s not in external_waiters and s in self.txn_at:
                    ready_ids.add(self.txn_at[s])
        return ready_ids

    def _use_walk(self, width: Optional[int] = None) -> bool:
        if self.tier == "auto":
            if len(self.txns) <= self._walk_max:
                return True
            # the flat-cost redesign (cold-tier demotion) makes the scalar
            # cfk walk O(hot-set) per key REGARDLESS of index size: narrow
            # queries always walk; only wide footprints amortize a dense
            # O(T*K) vectorized pass (measured: at T=65k the dense host pass
            # collapses to ~60 q/s while the walk holds thousands)
            return width is not None and width <= self._walk_width
        return self.tier == "walk"

    def _walk_tier(self) -> DepsResolver:
        """The scalar per-key cfk walk (the oracle itself) as the smallest
        rung of the cost ladder — at shallow indexes its near-zero constant
        factor beats any vectorized pass."""
        if self._walk is None:
            from .resolver import CpuDepsResolver
            self._walk = CpuDepsResolver(self.store)
        self.walk_consults += 1
        return self._walk

    # -- queries -------------------------------------------------------------
    def key_conflicts(self, by: TxnId, keys, before: Timestamp):
        # O(1) gen probe BEFORE any cached hit: a mid-window durability
        # advance widens the elision gate and invalidates prefetched answers
        self._maybe_resweep_durable()
        known = [rk for rk in keys if rk in self.key_slot]
        if not known or not self.txns:
            return []
        if by.kind.is_sync_point:
            # fence queries exclude the per-txn durable elision flag (see
            # CpuDepsResolver.key_conflicts) — the covered bits bake it in,
            # so sync points always take the exact walk
            return self._walk_tier().key_conflicts(by, keys, before)
        if self._use_walk(width=len(known)):
            return self._walk_tier().key_conflicts(by, keys, before)
        hit, ans, delta = self._cached(("kc", by, frozenset(known), before),
                                       known, by, before)
        if hit:
            out = list(ans)
            if delta:
                known_set = set(known)
                wit = by.kind.witnesses
                _, invalidated_i = _status_codes()
                for d in sorted(delta):
                    m = self.txns[d]
                    if m.status == invalidated_i or not wit(TxnKind(m.kind_code)) \
                            or not d.as_timestamp() < before:
                        continue
                    # a NEW committed txn below the covering bound is elided
                    # by the cfk walk too: honor its covered set
                    for rk in (m.keys - m.covered) & known_set:
                        out.append((rk, d))
            return out
        if self._all_fast(known, before):
            q = np.zeros((1, self._k), dtype=np.int8)
            for rk in known:
                q[0, self.key_slot[rk]] = 1
            before_lanes = np.asarray([_pack_before(before)], dtype=np.int32)
            kind = np.asarray([int(by.kind)], dtype=np.int8)
            deps, _ = self._consult(q, before_lanes, kind, want_max=False)
            return self._attribute(deps[0], set(known))
        return self._slow_hits(by, known, before)

    def range_conflicts(self, by: TxnId, rng: Range, before: Timestamp):
        keys = [rk for rk in self.key_slot if rng.contains(rk)]
        return self.key_conflicts(by, keys, before)

    def max_conflict_keys(self, keys) -> Optional[Timestamp]:
        self._maybe_resweep_durable()   # see key_conflicts
        floor: Optional[Timestamp] = None
        for rk in keys:
            f = self.key_mc_floor.get(rk)
            if f is not None and (floor is None or f > floor):
                floor = f

        def with_floor(ts: Optional[Timestamp]) -> Optional[Timestamp]:
            if ts is None:
                return floor
            return ts if floor is None or ts > floor else floor

        known = [rk for rk in keys if rk in self.key_slot]
        if not known or not self.txns:
            return floor
        if self._use_walk(width=len(known)):
            # the walk tier (cfk) carries its own pruned floor already
            return self._walk_tier().max_conflict_keys(keys)
        hit, ans, delta = self._cached(("mc", frozenset(known)), known, None,
                                       None)
        if hit:
            if delta:
                known_set = set(known)
                for d in delta:
                    m = self.txns[d]
                    if m.keys & known_set:
                        c = m.execute_at if not m.execute_at < d.as_timestamp() \
                            else d.as_timestamp()
                        if ans is None or ans < c:
                            ans = c
            return with_floor(ans)
        q = np.zeros((1, self._k), dtype=np.int8)
        for rk in known:
            q[0, self.key_slot[rk]] = 1
        _, lanes = self._consult(q, np.zeros((1, TS_LANES), dtype=np.int32),
                                 np.zeros((1,), dtype=np.int8), want_deps=False)
        ts = Timestamp.unpack_lanes(tuple(int(v) for v in lanes[0]))
        return with_floor(None if ts == Timestamp.NONE else ts)

    def max_conflict_range(self, rng: Range) -> Optional[Timestamp]:
        keys = {rk for rk in self.key_slot if rng.contains(rk)}
        keys |= {rk for rk in self.key_mc_floor if rng.contains(rk)}
        return self.max_conflict_keys(sorted(keys))

    # -- the fused consult: tier dispatch ------------------------------------
    def _consult(self, q: np.ndarray, before: np.ndarray, kind: np.ndarray,
                 want_deps: bool = True, want_max: bool = True):
        """Answer a [B]-query batch: (deps [B, T] bool over the LIVE index,
        max_lanes [B, 5] over the FULL index).  Callers guarantee every deps
        row's bound is above its keys' covering bounds (fast rows).  Host and
        device tiers compute the identical join; the cost model picks by
        B·T·K vs the calibrated launch-amortization threshold."""
        self._flush()
        b = q.shape[0]
        t0 = time.perf_counter()
        try:
            if self.tier == "device" or (
                    self.tier == "auto"
                    and b * self._t * self._k >= self._device_threshold()):
                if self.service_enabled:
                    # the persistent service: incremental buffer refresh +
                    # ragged launch (vs the legacy one-shot whole-index
                    # re-upload below)
                    return self.service().consult_rows(q, before, kind)
                return self._consult_device(q, before, kind)
            return self._consult_host(q, before, kind, want_deps, want_max)
        finally:
            self.consult_wall_s += time.perf_counter() - t0

    def _device_threshold(self) -> float:
        """elems = B·T·K above which the device tier wins: calibrated once
        from a measured launch round-trip and the host tier's element rate."""
        if self._threshold_elems is None:
            override = getattr(self, "config", None)
            override = override.tpu_dispatch_elems if override else None
            if override is not None:
                self._threshold_elems = override
            else:
                self._threshold_elems = _calibrate_threshold()
        return self._threshold_elems

    def _consult_host(self, q, before, kind, want_deps=True, want_max=True):
        """The join as one vectorized numpy pass (BLAS f32 matmuls — exact for
        0/1 values — + lane-wise lex compares).  Mirrors ops.deps_kernels.
        consult bit-for-bit.  Sparse query batches route to the native C++
        engine (native/consult.cpp) when it is built: protocol queries touch
        1-3 keys, where its O(B·T·k_q) column walk beats the dense O(B·T·K)
        BLAS pass with zero temporaries."""
        if self._host_engine != "numpy":
            from .. import native
            if native.available():   # cached bool: free when not built
                qcols = [np.nonzero(row)[0] for row in q]
                nnz = sum(len(c) for c in qcols)
                if self._host_engine == "native" or nnz <= 8 * len(qcols):
                    self.native_consults += len(qcols)
                    _, invalidated_i = _status_codes()
                    deps, max_lanes = native.consult_batch(
                        self._h, qcols, before, kind, invalidated_i,
                        want_deps=want_deps, want_max=want_max)
                    return deps, max_lanes
        self.host_consults += q.shape[0]
        h = self._h
        if "key_inc_f32" not in h:
            # above the f32-mirror bound: cast per call (the cost model rarely
            # routes here at that scale — device tier amortizes far better)
            h = dict(h)
            h["key_inc_f32"] = np.ascontiguousarray(
                h["key_inc"].T.astype(np.float32))
            h["live_f32"] = np.ascontiguousarray(
                h["live_inc"].T.astype(np.float32))
        committed_i, invalidated_i = _status_codes()
        deps = None
        if want_deps:
            share_live = (q.astype(np.float32) @ h["live_f32"]) > 0.0       # [B,T]
            started = _lex_less(h["txn_id"][None, :, :], before[:, None, :])
            wit = _witnesses()[kind[:, None].astype(np.int64),
                               h["kind"][None, :].astype(np.int64)]
            eligible = h["active"] & (h["status"] != invalidated_i)
            deps = share_live & started & wit & eligible[None, :]
        max_lanes = None
        if want_max:
            share_full = (q.astype(np.float32) @ h["key_inc_f32"]) > 0.0    # [B,T]
            mc_mask = share_full & h["active"][None, :]
            per_slot = np.where(_lex_less(h["ts"], h["txn_id"])[:, None],
                                h["txn_id"], h["ts"])                       # [T,5]
            b = q.shape[0]
            tie = mc_mask
            max_lanes = np.zeros((b, TS_LANES), dtype=np.int64)
            for lane in range(TS_LANES):
                vals = np.where(tie, per_slot[None, :, lane], -1)
                best = vals.max(axis=1)
                tie = tie & (per_slot[None, :, lane] == best[:, None])
                max_lanes[:, lane] = np.maximum(best, 0)
        return deps, max_lanes

    def _consult_device(self, q, before, kind):
        """ops.deps_kernels.consult on the TPU — one fused MXU launch for the
        whole batch.  The batch dim pads to a power of two so jit compiles
        once per shape bucket, not once per window size."""
        import jax
        import jax.numpy as jnp
        from ..ops import deps_kernels as dk
        self.device_consults += q.shape[0]
        self._sync_device()
        b = q.shape[0]
        b_pad = 1 << max(0, b - 1).bit_length()
        if b_pad != b:
            q = np.concatenate(
                [q, np.zeros((b_pad - b, q.shape[1]), dtype=q.dtype)])
            before = np.concatenate(
                [before, np.zeros((b_pad - b, TS_LANES), dtype=before.dtype)])
            kind = np.concatenate(
                [kind, np.zeros((b_pad - b,), dtype=kind.dtype)])
        s = self._device
        if self._t >= 32768:
            # transfer-bound regime: bit-pack the deps mask on device (8×
            # smaller result) and unpack host-side
            packed, max_lanes = jax.device_get(dk.consult_packed(
                s["live_inc"], s["key_inc"], s["ts"], s["txn_id"], s["kind"],
                s["status"], s["active"], jnp.asarray(q), jnp.asarray(before),
                jnp.asarray(kind)))
            deps = np.unpackbits(packed, axis=1, bitorder="little") \
                .astype(bool)[:, :self._t]
        else:
            deps, max_lanes = jax.device_get(dk.consult(
                s["live_inc"], s["key_inc"], s["ts"], s["txn_id"], s["kind"],
                s["status"], s["active"], jnp.asarray(q), jnp.asarray(before),
                jnp.asarray(kind)))
        return deps[:b], max_lanes[:b]

    def _sync_device(self) -> None:
        """Upload the canonical host index to the device if stale (lazy: only
        the device tier pays for device residency)."""
        if self._device_clean and self._device is not None:
            return
        import jax.numpy as jnp
        h = self._h
        self._device = {
            "key_inc": jnp.asarray(h["key_inc"]),
            "live_inc": jnp.asarray(h["live_inc"]),
            "ts": jnp.asarray(h["ts"]),
            "txn_id": jnp.asarray(h["txn_id"]),
            "kind": jnp.asarray(h["kind"]),
            "status": jnp.asarray(h["status"]),
            "active": jnp.asarray(h["active"]),
        }
        self._device_clean = True

    # -- the exact per-key path (bounds at/below the covering bound) ---------
    def _slow_hits(self, by: TxnId, known, before: Timestamp
                   ) -> List[Tuple[RoutingKey, TxnId]]:
        """Per-key vectorized recompute of the covering write FOR THIS BOUND —
        the exact analog of cfk.map_reduce_active's maxCommittedWriteBefore
        search (rare: only bounds at/below E_k take this)."""
        self._flush()
        self.host_consults += 1
        h = self._h
        committed_i, invalidated_i = _status_codes()
        bl = np.asarray(_pack_before(before), dtype=np.int64)
        started = _lex_less(h["txn_id"], bl)                    # [T]
        wit = _witnesses()[int(by.kind), h["kind"].astype(np.int64)]
        eligible = h["active"] & (h["status"] != invalidated_i)
        committed = (h["status"] >= committed_i) & (h["status"] != invalidated_i)
        write_wit = _witnesses()[_WRITE, h["kind"].astype(np.int64)]
        is_w = h["kind"] == _WRITE
        ea_before = _lex_less(h["ts"], bl)                      # [T]
        out: List[Tuple[RoutingKey, TxnId]] = []
        for rk in known:
            col = h["key_inc"][:, self.key_slot[rk]] != 0
            cand = col & started & wit & eligible
            cw = col & committed & is_w & ea_before
            bound = self._durable_majority(rk)
            if cw.any():
                # the covering write = lexicographic max (executeAt, txnId)
                # among committed writes before the bound — BOTH coordinates,
                # matching cfk._covering_write_before (vectorized lexsort:
                # np.lexsort keys are least-significant FIRST)
                idx = np.nonzero(cw)[0]
                combined = np.concatenate([h["ts"][idx], h["txn_id"][idx]],
                                          axis=1)
                best = idx[np.lexsort(combined.T[::-1])[-1]]
                maxcw = h["ts"][best]
                maxcw_tid = h["txn_id"][best]
                # durability gate: (per-txn flag AND witnessed by the cover)
                # OR below the key's majority watermark — bit-identical to
                # cfk.map_reduce_active
                gate = h["durable"] & _lex_less(h["txn_id"], maxcw_tid)
                if bound is not None:
                    bound_lanes = np.asarray(_pack_before(bound), dtype=np.int64)
                    gate = gate | _lex_less(h["txn_id"], bound_lanes)
                elide = committed & _lex_less(h["ts"], maxcw) & write_wit & gate
                cand = cand & ~elide
            for slot in np.nonzero(cand)[0]:
                tid = self.txn_at.get(int(slot))
                if tid is not None:
                    out.append((rk, tid))
        return out

    # -- host index maintenance ----------------------------------------------
    def _attribute(self, mask: np.ndarray, queried: Set[RoutingKey]
                   ) -> List[Tuple[RoutingKey, TxnId]]:
        """Map a [T] slot mask (over the LIVE index) back to (key, TxnId)
        incidences, excluding covered keys.  O(|result|): the array pass did
        the O(T) scan; the host only touches hits."""
        out: List[Tuple[RoutingKey, TxnId]] = []
        for slot in np.nonzero(mask)[0]:
            tid = self.txn_at.get(int(slot))
            if tid is None:
                continue
            m = self.txns[tid]
            keys = (m.keys - m.covered) if m.covered else m.keys
            for rk in keys & queried:
                out.append((rk, tid))
        return out

    def _alloc_slot(self) -> int:
        if not self.free_slots:
            self._grow(txns=True)
        slot = heapq.heappop(self.free_slots)
        if slot > self._max_slot:
            self._max_slot = slot   # occupancy watermark (service view extent)
        return slot

    def _alloc_key_slot(self) -> int:
        if not self.free_key_slots:
            self._grow(txns=False)
        slot = heapq.heappop(self.free_key_slots)
        if slot > self._max_key_slot:
            self._max_key_slot = slot
        return slot

    def _grow(self, txns: bool) -> None:
        """Double capacity and rebuild the index arrays from host mirrors."""
        if txns:
            self.free_slots = list(range(self._t, self._t * 2))
            heapq.heapify(self.free_slots)
            self._t *= 2
        else:
            self.free_key_slots = list(range(self._k, self._k * 2))
            heapq.heapify(self.free_key_slots)
            self._k *= 2
        self._rebuild()

    def _rebuild(self) -> None:
        """Full rebuild of the canonical arrays (capacity growth only — rare,
        amortised)."""
        t, k = self._t, self._k
        key_inc = np.zeros((t, k), dtype=np.int8)
        live_inc = np.zeros((t, k), dtype=np.int8)
        ts = np.zeros((t, TS_LANES), dtype=np.int32)
        txn_id = np.zeros((t, TS_LANES), dtype=np.int32)
        kind = np.zeros((t,), dtype=np.int8)
        status = np.zeros((t,), dtype=np.int8)
        active = np.zeros((t,), dtype=np.bool_)
        durable = np.zeros((t,), dtype=np.bool_)
        for tid, m in self.txns.items():
            cols = [self.key_slot[rk] for rk in m.keys]
            key_inc[m.slot, cols] = 1
            live_cols = [self.key_slot[rk] for rk in m.keys - m.covered]
            live_inc[m.slot, live_cols] = 1
            ts[m.slot] = m.execute_at.pack_lanes()
            txn_id[m.slot] = tid.pack_lanes()
            kind[m.slot] = m.kind_code
            status[m.slot] = m.status
            active[m.slot] = True
            durable[m.slot] = m.durable
        self._h = {"key_inc": key_inc, "live_inc": live_inc,
                   "ts": ts, "txn_id": txn_id, "kind": kind, "status": status,
                   "active": active, "durable": durable}
        if t <= self._f32_max:
            # persistent transposed f32 mirrors for the BLAS host tier; above
            # the bound the host tier casts per call (memory budget: the
            # canonical index stays 2 × T×K int8 bytes)
            # C-contiguous: the native engine streams these rows (a .T view
            # would make astype F-contiguous and force a full copy per call)
            self._h["key_inc_f32"] = np.ascontiguousarray(
                key_inc.T.astype(np.float32))
            self._h["live_f32"] = np.ascontiguousarray(
                live_inc.T.astype(np.float32))
        self._device_clean = False
        self._dirty_rows = None   # shapes changed: the service re-uploads
        self._dirty_txns.clear()
        self._clear_bits.clear()
        self._deactivate.clear()
        self._live_ops.clear()

    def _flush(self) -> None:
        """Apply buffered mutations to the canonical host arrays (in-place row
        writes — O(rows changed)); the device copy goes stale and re-syncs
        lazily if/when the device tier is next chosen."""
        self._maybe_resweep_durable()
        if self._h is None:
            self._rebuild()
            return
        if not (self._dirty_txns or self._clear_bits or self._deactivate
                or self._live_ops):
            return
        if self._dirty_rows is not None:
            # row provenance for the service's incremental buffer refresh
            # (collected BEFORE the buffers below are consumed)
            self._dirty_rows.update(row for row, _ in self._clear_bits)
            self._dirty_rows.update(self._deactivate)
            self._dirty_rows.update(self.txns[tid].slot
                                    for tid in self._dirty_txns)
            self._dirty_rows.update(op[0] for op in self._live_ops)
        h = self._h
        f32 = "key_inc_f32" in h
        # order matters: clears and deactivations target OLD occupants of a
        # slot; inserts (which may recycle that same slot) must land last
        for row, col in self._clear_bits:
            h["key_inc"][row, col] = 0
            h["live_inc"][row, col] = 0
            if f32:
                h["key_inc_f32"][col, row] = 0.0
                h["live_f32"][col, row] = 0.0
        self._clear_bits.clear()
        if self._deactivate:
            d = np.asarray(self._deactivate, dtype=np.int32)
            h["active"][d] = False
            h["key_inc"][d] = 0
            h["live_inc"][d] = 0
            if f32:
                h["key_inc_f32"][:, d] = 0.0
                h["live_f32"][:, d] = 0.0
            h["status"][d] = 0
            h["durable"][d] = False
            self._deactivate.clear()
        for tid in sorted(self._dirty_txns):    # deterministic flush order
            m = self.txns[tid]
            row = m.slot
            h["key_inc"][row] = 0
            h["live_inc"][row] = 0
            cols = [self.key_slot[rk] for rk in m.keys]
            h["key_inc"][row, cols] = 1
            live_cols = [self.key_slot[rk] for rk in m.keys - m.covered]
            h["live_inc"][row, live_cols] = 1
            if f32:
                h["key_inc_f32"][:, row] = 0.0
                h["live_f32"][:, row] = 0.0
                h["key_inc_f32"][cols, row] = 1.0
                h["live_f32"][live_cols, row] = 1.0
            h["ts"][row] = m.execute_at.pack_lanes()
            h["txn_id"][row] = tid.pack_lanes()
            h["kind"][row] = m.kind_code
            h["status"][row] = m.status
            h["active"][row] = True
            h["durable"][row] = m.durable
        self._dirty_txns.clear()
        # chronological cover/uncover flips: rows written above already carry
        # the final covered state, so replaying (whose last op per incidence
        # IS the final state) is consistent; flips on un-dirty rows apply here
        for row, col, val in self._live_ops:
            if h["key_inc"][row, col]:      # incidence may have pruned since
                h["live_inc"][row, col] = val
                if f32:
                    h["live_f32"][col, row] = float(val)
        self._live_ops.clear()
        self._device_clean = False

    # -- introspection (tests / bench) ---------------------------------------
    def host_index(self) -> dict:
        self._flush()
        return self._h

    def indexed_count(self) -> int:
        return len(self.txns)


_CALIBRATED: Optional[float] = None


def _calibrate_threshold() -> float:
    """Measure one device launch round-trip and the host tier's element rate;
    the device tier is worth it above elems ≈ host_rate × launch_rtt.
    Process-wide (one measurement serves every store's resolver)."""
    global _CALIBRATED
    if _CALIBRATED is not None:
        return _CALIBRATED
    try:
        import jax
        import jax.numpy as jnp
        from ..ops import deps_kernels as dk
        t, k, b = 256, 64, 8
        args = (jnp.zeros((t, k), jnp.int8), jnp.zeros((t, k), jnp.int8),
                jnp.zeros((t, TS_LANES), jnp.int32),
                jnp.zeros((t, TS_LANES), jnp.int32), jnp.zeros((t,), jnp.int8),
                jnp.zeros((t,), jnp.int8), jnp.zeros((t,), jnp.bool_),
                jnp.zeros((b, k), jnp.int8), jnp.zeros((b, TS_LANES), jnp.int32),
                jnp.zeros((b,), jnp.int8))
        jax.block_until_ready(dk.consult(*args))      # compile
        t0 = time.perf_counter()
        jax.block_until_ready(dk.consult(*args))
        rtt = time.perf_counter() - t0
        # host tier: ~f32 BLAS matmul; measure a representative pass
        hq = np.random.default_rng(0)
        a = (hq.random((256, 512)) < 0.1).astype(np.float32)
        m = (hq.random((512, 4096)) < 0.1).astype(np.float32)
        t0 = time.perf_counter()
        for _ in range(3):
            _ = a @ m
        host_rate = 3 * a.shape[0] * a.shape[1] * m.shape[1] \
            / (time.perf_counter() - t0)
        _CALIBRATED = max(1e6, host_rate * rtt)
    except Exception:  # noqa: BLE001 — no device: host tier only
        _CALIBRATED = float("inf")
    return _CALIBRATED
