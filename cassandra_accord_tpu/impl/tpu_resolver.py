"""TpuDepsResolver — the accelerator conflict-index data plane.

The per-store conflict index (the reference's CommandsForKey sorted arrays +
MaxConflicts map, cfk/CommandsForKey.java:615-628, MaxConflicts.java:32) is a
fixed-capacity ARRAY index — a key-incidence matrix, packed timestamp lanes,
kind/status codes and an active mask over txn slots — instead of the
reference's per-key pointer-chased sorted arrays.

Every dependency query (``SafeCommandStore.map_reduce_active`` →
``calculate_partial_deps``, PreAccept.java:245-267) and timestamp-proposal
consult (``max_conflict``) is ONE fused join over that index
(ops.deps_kernels.consult): key-overlap matmul × started-before lex compare ×
kind-witness mask, plus the masked lexicographic max for the timestamp
proposal — not the reference's scalar per-key scans
(cfk/CommandsForKey.java:925-1000).

Two execution tiers answer the SAME join bit-identically, picked per call by
a cost model (the accelerator-native split: dispatch to the MXU only when the
work amortizes launch+transfer):

- host tier  — the join as one vectorized numpy pass over the index arrays
               (BLAS f32 matmul + lane-wise lex compares).  No launch
               overhead; serves small windows.
- device tier — ops.deps_kernels.consult on the TPU: bf16 MXU matmul over
               [B, K] × [K, T].  Serves large batches / deep indexes, where
               it is 30-80× the host tier (bench.py kernel_scaling).

The canonical index lives in host numpy (mutations are in-place row writes);
the device copy is synced lazily when the device tier is chosen.  The cost
model self-calibrates: it measures one launch round-trip and the host tier's
element throughput, then dispatches by B·T·K.  Tier choice never affects
answers (both tiers are parity-checked against the cfk walk by
VerifyDepsResolver), only speed.

Queries batch across messages: a coalesced delivery window
(harness/cluster.py ``batch_window_us``) declares its upcoming
PreAccept/Accept consults via ``prefetch``, which answers ALL of them in one
fused consult (one numpy pass or one MXU launch).  Live queries are then
served from the cached answers with EXACT sequential semantics: every index
mutation since the prefetch marks its keys dirty, and a cached answer is only
used when no dirty key intersects the query — except the querying txn's own
registration, which provably cannot change its own answer (the deps walk
excludes ``by`` host-side, and the timestamp consult runs before the
self-registration).  Anything else falls back to an individual consult, so
batching is a pure fast path.

Slot lifecycle: slots are recycled once a txn is fully pruned from every key
it touched (the cfk prune protocol driven by RedundantBefore GC,
command_store._prune_below_fences / run_gc); capacity doubles by host rebuild
when the free list empties.
"""
from __future__ import annotations

import heapq
import os
import time
from typing import Dict, List, Optional, Set, Tuple, TYPE_CHECKING

import numpy as np

from ..primitives.keys import Range, RoutingKey
from ..primitives.timestamp import Timestamp, TxnId
from ..utils.invariants import check_state
from .resolver import DepsResolver

if TYPE_CHECKING:
    from ..local.command_store import CommandStore
    from ..local.cfk import InternalStatus

TS_LANES = 5

_INVALIDATED: Optional[int] = None


def _invalidated_code() -> int:
    """InternalStatus.INVALIDATED, resolved lazily from the one source of
    truth (local.cfk) so the host tier's eligibility mask can never diverge
    from the cfk walk or the device kernel."""
    global _INVALIDATED
    if _INVALIDATED is None:
        from ..local.cfk import InternalStatus
        _INVALIDATED = int(InternalStatus.INVALIDATED)
    return _INVALIDATED

_WITNESSES: Optional[np.ndarray] = None


def _witnesses() -> np.ndarray:
    global _WITNESSES
    if _WITNESSES is None:
        from ..ops.deps_kernels import _witness_table
        _WITNESSES = _witness_table()
    return _WITNESSES


def _pack_before(before: Timestamp) -> Tuple[int, int, int, int, int]:
    """Pack a query bound, saturating out-of-device-range bounds (e.g. the
    ephemeral-read Timestamp.MAX sentinel) to lanes above every real packed
    timestamp (all real lanes are < 2^31-1)."""
    try:
        return before.pack_lanes()
    except Exception:  # noqa: BLE001 — bound exceeds device packing range
        m = 0x7FFFFFFF
        return (m, m, m, m, m)


def _lex_less(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Lexicographic a < b over packed lanes [..., 5] (numpy; mirrors
    ops.graph_state.ts_less exactly)."""
    lt = a[..., TS_LANES - 1] < b[..., TS_LANES - 1]
    for lane in range(TS_LANES - 2, -1, -1):
        lt = (a[..., lane] < b[..., lane]) | ((a[..., lane] == b[..., lane]) & lt)
    return lt


class _TxnMirror:
    """Host bookkeeping for one indexed txn (rebuilds + attribution)."""
    __slots__ = ("slot", "kind_code", "status", "execute_at", "keys")

    def __init__(self, slot: int, kind_code: int, status: int,
                 execute_at: Timestamp, keys: Set[RoutingKey]):
        self.slot = slot
        self.kind_code = kind_code
        self.status = status
        self.execute_at = execute_at
        self.keys = keys


class TpuDepsResolver(DepsResolver):
    def __init__(self, store: "CommandStore", txn_capacity: Optional[int] = None,
                 key_capacity: Optional[int] = None):
        # initial capacities: growth doubles them (a host rebuild + a new jit
        # shape each time), so long-running/bench deployments start big
        if txn_capacity is None:
            txn_capacity = int(os.environ.get("ACCORD_TPU_TXN_SLOTS", "64"))
        if key_capacity is None:
            key_capacity = int(os.environ.get("ACCORD_TPU_KEY_SLOTS", "64"))
        self.store = store
        self.txns: Dict[TxnId, _TxnMirror] = {}
        self.txn_at: Dict[int, TxnId] = {}          # slot -> txn (attribution)
        self.key_slot: Dict[RoutingKey, int] = {}
        self.key_refs: Dict[RoutingKey, int] = {}   # live incidences per key
        self.free_slots: List[int] = list(range(txn_capacity))
        heapq.heapify(self.free_slots)
        self.free_key_slots: List[int] = list(range(key_capacity))
        heapq.heapify(self.free_key_slots)
        # pending (txn_id) inserts/updates and (slot, key_slot) bit ops
        self._dirty_txns: Set[TxnId] = set()
        self._clear_bits: List[Tuple[int, int]] = []
        self._deactivate: List[int] = []
        self._t = txn_capacity
        self._k = key_capacity
        self._h: Optional[dict] = None   # canonical numpy index (lazy)
        self._device = None              # device copy (lazy, synced on use)
        self._device_clean = False
        # tier selection: 'auto' cost model, or forced for tests/benches
        self.tier = os.environ.get("ACCORD_TPU_TIER", "auto")
        self._threshold_elems: Optional[float] = None
        self.host_consults = 0
        self.device_consults = 0
        # prefetched-answer cache for the current delivery window (None = no
        # window active): sig -> answer, plus keys dirtied since the prefetch
        self._cache: Optional[Dict[tuple, object]] = None
        self._cache_dirty: Dict[RoutingKey, Set[TxnId]] = {}
        self._prefetch_preexisting: Set[TxnId] = set()
        self.prefetch_hits = 0
        self.prefetch_patched = 0
        self.prefetch_misses = 0

    # -- registration (cfk.update semantics) ---------------------------------
    def register(self, txn_id: TxnId, status, execute_at, keys) -> None:
        from ..local.cfk import InternalStatus as IS
        status_i = int(status)
        m = self.txns.get(txn_id)
        if m is None:
            slot = self._alloc_slot()
            ea = execute_at if execute_at is not None else txn_id.as_timestamp()
            m = _TxnMirror(slot, int(txn_id.kind), status_i, ea, set())
            self.txns[txn_id] = m
            self.txn_at[slot] = txn_id
        else:
            # monotonic status; executeAt moves on upgrade or while ACCEPTED
            if status_i > m.status:
                m.status = status_i
                if execute_at is not None:
                    m.execute_at = execute_at
            elif status_i == m.status and execute_at is not None \
                    and status_i == int(IS.ACCEPTED):
                m.execute_at = execute_at
        added_key = False
        for rk in keys:
            if rk not in m.keys:
                # allocate the key slot BEFORE recording the incidence: growth
                # rebuilds iterate txn key sets and need every slot assigned
                if rk not in self.key_slot:
                    self.key_slot[rk] = self._alloc_key_slot()
                m.keys.add(rk)
                added_key = True
                self.key_refs[rk] = self.key_refs.get(rk, 0) + 1
        self._dirty_txns.add(txn_id)
        if self._cache is not None and added_key \
                and txn_id in self._prefetch_preexisting:
            # a PRE-EXISTING txn grew its footprint mid-window: its base
            # contributions in cached answers are un-patchable — drop the cache
            # (rare: routes only widen on cross-epoch re-contact)
            self._cache = None
        if self._cache is not None:
            # conservatively dirty the txn's WHOLE footprint: a status/executeAt
            # upgrade changes its contribution on every key it touches
            for rk in m.keys:
                self._cache_dirty.setdefault(rk, set()).add(txn_id)

    def on_pruned(self, key: RoutingKey, txn_ids) -> None:
        self._cache = None   # prunes mid-window are rare: drop the whole cache
        ks = self.key_slot.get(key)
        if ks is None:
            return
        for txn_id in txn_ids:
            m = self.txns.get(txn_id)
            if m is None or key not in m.keys:
                continue
            m.keys.discard(key)
            self._clear_bits.append((m.slot, ks))
            self._release_key(key)
            if not m.keys:
                # fully pruned: recycle the slot
                self._deactivate.append(m.slot)
                del self.txns[txn_id]
                del self.txn_at[m.slot]
                self._dirty_txns.discard(txn_id)
                heapq.heappush(self.free_slots, m.slot)

    def _release_key(self, key: RoutingKey) -> None:
        """Drop a live incidence; recycle the key slot when none remain (the
        index column is already zeroed by the per-incidence clears)."""
        n = self.key_refs.get(key, 0) - 1
        if n > 0:
            self.key_refs[key] = n
        else:
            self.key_refs.pop(key, None)
            ks = self.key_slot.pop(key, None)
            if ks is not None:
                heapq.heappush(self.free_key_slots, ks)

    # -- batched prefetch (delivery-window coalescing) ------------------------
    def prefetch(self, specs) -> None:
        """Answer every declared query in ONE fused consult and cache the
        answers for the window (see module doc for the exactness rule)."""
        self._cache = {}
        self._cache_dirty = {}
        # ids indexed as of the prefetch: mutations by NEW txns can be patched
        # into cached answers exactly; upgrades of these force a fallback
        self._prefetch_preexisting = set(self.txns)
        live: List[Tuple[tuple, str, List[RoutingKey], object]] = []
        for spec in specs:
            known = [rk for rk in spec.keys if rk in self.key_slot]
            if spec.op == "kc":
                sig = ("kc", spec.by, frozenset(known), spec.before)
                if not known or not self.txns:
                    self._cache[sig] = []
                    continue
            else:
                sig = ("mc", frozenset(known))
                if not known or not self.txns:
                    self._cache[sig] = None
                    continue
            live.append((sig, spec.op, known,
                         spec.before if spec.op == "kc" else None))
        if not live:
            return
        b = len(live)
        q = np.zeros((b, self._k), dtype=np.int8)
        before_lanes = np.zeros((b, TS_LANES), dtype=np.int32)
        kind = np.zeros((b,), dtype=np.int8)
        for i, (sig, op, known, before) in enumerate(live):
            for rk in known:
                q[i, self.key_slot[rk]] = 1
            if op == "kc":
                before_lanes[i] = _pack_before(before)
                kind[i] = int(sig[1].kind)
        deps, max_lanes = self._consult(q, before_lanes, kind)
        for i, (sig, op, known, _before) in enumerate(live):
            if op == "kc":
                self._cache[sig] = self._attribute(deps[i], set(known))
            else:
                ts = Timestamp.unpack_lanes(tuple(int(v) for v in max_lanes[i]))
                self._cache[sig] = None if ts == Timestamp.NONE else ts

    def end_batch(self) -> None:
        self._cache = None
        self._cache_dirty = {}

    def _cached(self, sig, known, exempt: Optional[TxnId]):
        """A cached answer, made exact against mutations since the prefetch:

        - keys dirtied only by ``exempt`` (the querying txn itself — excluded
          from its own deps answer host-side) need nothing;
        - keys dirtied by txns NEW since the prefetch are patched with those
          txns' exact contributions from the (always-current) host mirrors —
          at call time the mirrors ARE the sequential state, so the patched
          answer equals a live query's;
        - keys dirtied by an UPGRADE of a pre-existing txn force a fallback
          (its base contribution is already folded in and cannot be unpicked).

        Returns (hit, answer, delta_ids) — delta_ids the new txns to patch in
        (empty on clean hits); (False, None, None) on miss/fallback."""
        if self._cache is None:
            return False, None, None
        if sig not in self._cache:
            self.prefetch_misses += 1
            return False, None, None
        delta_ids: Set[TxnId] = set()
        dirty = self._cache_dirty
        if dirty:
            pre = self._prefetch_preexisting
            for rk in known:
                for d in dirty.get(rk, ()):
                    if d == exempt and d in pre:
                        # upgrade of the querying txn itself: kc-invariant
                        # (txn_id/kind static, stays eligible; key additions
                        # to pre-existing txns nuke the cache in register)
                        continue
                    if d in pre or d not in self.txns:
                        self.prefetch_misses += 1
                        return False, None, None
                    # NEW txns — including the querying txn itself, which the
                    # CPU oracle's cfk walk also reports when txn_id < before
                    # (the Accept deps walk at before=executeAt) — are patched
                    # from the mirrors under the exact same predicates
                    delta_ids.add(d)
        if delta_ids:
            self.prefetch_patched += 1
        else:
            self.prefetch_hits += 1
        return True, self._cache[sig], delta_ids

    # -- queries -------------------------------------------------------------
    def key_conflicts(self, by: TxnId, keys, before: Timestamp):
        known = [rk for rk in keys if rk in self.key_slot]
        if not known or not self.txns:
            return []
        hit, ans, delta = self._cached(("kc", by, frozenset(known), before),
                                       known, by)
        if hit:
            out = list(ans)
            if delta:
                known_set = set(known)
                wit = by.kind.witnesses
                from ..local.cfk import InternalStatus as IS
                inval = int(IS.INVALIDATED)
                for d in sorted(delta):
                    m = self.txns[d]
                    if m.status == inval or not wit(d.kind) \
                            or not d.as_timestamp() < before:
                        continue
                    for rk in m.keys & known_set:
                        out.append((rk, d))
            return out
        q = np.zeros((1, self._k), dtype=np.int8)
        for rk in known:
            q[0, self.key_slot[rk]] = 1
        before_lanes = np.asarray([_pack_before(before)], dtype=np.int32)
        kind = np.asarray([int(by.kind)], dtype=np.int8)
        deps, _ = self._consult(q, before_lanes, kind, want_max=False)
        return self._attribute(deps[0], set(known))

    def range_conflicts(self, by: TxnId, rng: Range, before: Timestamp):
        keys = [rk for rk in self.key_slot if rng.contains(rk)]
        return self.key_conflicts(by, keys, before)

    def max_conflict_keys(self, keys) -> Optional[Timestamp]:
        known = [rk for rk in keys if rk in self.key_slot]
        if not known or not self.txns:
            return None
        hit, ans, delta = self._cached(("mc", frozenset(known)), known, None)
        if hit:
            if delta:
                known_set = set(known)
                for d in delta:
                    m = self.txns[d]
                    if m.keys & known_set:
                        c = m.execute_at if not m.execute_at < d.as_timestamp() \
                            else d.as_timestamp()
                        if ans is None or ans < c:
                            ans = c
            return ans
        q = np.zeros((1, self._k), dtype=np.int8)
        for rk in known:
            q[0, self.key_slot[rk]] = 1
        _, lanes = self._consult(q, np.zeros((1, TS_LANES), dtype=np.int32),
                                 np.zeros((1,), dtype=np.int8), want_deps=False)
        ts = Timestamp.unpack_lanes(tuple(int(v) for v in lanes[0]))
        return None if ts == Timestamp.NONE else ts

    def max_conflict_range(self, rng: Range) -> Optional[Timestamp]:
        keys = [rk for rk in self.key_slot if rng.contains(rk)]
        return self.max_conflict_keys(keys)

    # -- the fused consult: tier dispatch ------------------------------------
    def _consult(self, q: np.ndarray, before: np.ndarray, kind: np.ndarray,
                 want_deps: bool = True, want_max: bool = True):
        """Answer a [B]-query batch: (deps [B, T] bool, max_lanes [B, 5]).
        Host and device tiers compute the identical join; the cost model picks
        by B·T·K vs the calibrated launch-amortization threshold."""
        self._flush()
        b = q.shape[0]
        if self.tier == "device" or (
                self.tier == "auto"
                and b * self._t * self._k >= self._device_threshold()):
            return self._consult_device(q, before, kind)
        return self._consult_host(q, before, kind, want_deps, want_max)

    def _device_threshold(self) -> float:
        """elems = B·T·K above which the device tier wins: calibrated once
        from a measured launch round-trip and the host tier's element rate."""
        if self._threshold_elems is None:
            env = os.environ.get("ACCORD_TPU_DISPATCH_ELEMS")
            if env is not None:
                self._threshold_elems = float(env)
            else:
                self._threshold_elems = _calibrate_threshold()
        return self._threshold_elems

    def _consult_host(self, q, before, kind, want_deps=True, want_max=True):
        """The join as one vectorized numpy pass (BLAS f32 matmul — exact for
        0/1 values — + lane-wise lex compares).  Mirrors ops.deps_kernels.
        consult bit-for-bit."""
        self.host_consults += 1
        h = self._h
        share = (q.astype(np.float32) @ h["key_inc_f32"]) > 0.0          # [B,T]
        deps = None
        if want_deps:
            started = _lex_less(h["txn_id"][None, :, :], before[:, None, :])
            wit = _witnesses()[kind[:, None].astype(np.int64),
                               h["kind"][None, :].astype(np.int64)]
            eligible = h["active"] & (h["status"] != _invalidated_code())
            deps = share & started & wit & eligible[None, :]
        max_lanes = None
        if want_max:
            mc_mask = share & h["active"][None, :]
            per_slot = np.where(_lex_less(h["ts"], h["txn_id"])[:, None],
                                h["txn_id"], h["ts"])                    # [T,5]
            b = q.shape[0]
            tie = mc_mask
            max_lanes = np.zeros((b, TS_LANES), dtype=np.int64)
            for lane in range(TS_LANES):
                vals = np.where(tie, per_slot[None, :, lane], -1)
                best = vals.max(axis=1) if vals.shape[1] else \
                    np.full((b,), -1, dtype=np.int64)
                tie = tie & (per_slot[None, :, lane] == best[:, None])
                max_lanes[:, lane] = np.maximum(best, 0)
        return deps, max_lanes

    def _consult_device(self, q, before, kind):
        """ops.deps_kernels.consult on the TPU — one fused MXU launch for the
        whole batch.  The batch dim pads to a power of two so jit compiles
        once per shape bucket, not once per window size."""
        import jax
        import jax.numpy as jnp
        from ..ops import deps_kernels as dk
        self.device_consults += 1
        self._sync_device()
        b = q.shape[0]
        b_pad = 1 << max(0, b - 1).bit_length()
        if b_pad != b:
            q = np.concatenate(
                [q, np.zeros((b_pad - b, q.shape[1]), dtype=q.dtype)])
            before = np.concatenate(
                [before, np.zeros((b_pad - b, TS_LANES), dtype=before.dtype)])
            kind = np.concatenate(
                [kind, np.zeros((b_pad - b,), dtype=kind.dtype)])
        s = self._device
        deps, max_lanes = jax.device_get(dk.consult(
            s["key_inc"], s["ts"], s["txn_id"], s["kind"], s["status"],
            s["active"], jnp.asarray(q), jnp.asarray(before),
            jnp.asarray(kind)))
        return deps[:b], max_lanes[:b]

    def _sync_device(self) -> None:
        """Upload the canonical host index to the device if stale (lazy: only
        the device tier pays for device residency)."""
        if self._device_clean and self._device is not None:
            return
        import jax.numpy as jnp
        h = self._h
        self._device = {
            "key_inc": jnp.asarray(h["key_inc"]),
            "ts": jnp.asarray(h["ts"]),
            "txn_id": jnp.asarray(h["txn_id"]),
            "kind": jnp.asarray(h["kind"]),
            "status": jnp.asarray(h["status"]),
            "active": jnp.asarray(h["active"]),
        }
        self._device_clean = True

    # -- host index maintenance ----------------------------------------------
    def _attribute(self, mask: np.ndarray, queried: Set[RoutingKey]
                   ) -> List[Tuple[RoutingKey, TxnId]]:
        """Map a [T] slot mask back to (key, TxnId) incidences.  O(|result|):
        the array pass did the O(T) scan; the host only touches hits."""
        out: List[Tuple[RoutingKey, TxnId]] = []
        for slot in np.nonzero(mask)[0]:
            tid = self.txn_at.get(int(slot))
            if tid is None:
                continue
            for rk in self.txns[tid].keys & queried:
                out.append((rk, tid))
        return out

    def _alloc_slot(self) -> int:
        if not self.free_slots:
            self._grow(txns=True)
        return heapq.heappop(self.free_slots)

    def _alloc_key_slot(self) -> int:
        if not self.free_key_slots:
            self._grow(txns=False)
        return heapq.heappop(self.free_key_slots)

    def _grow(self, txns: bool) -> None:
        """Double capacity and rebuild the index arrays from host mirrors."""
        if txns:
            self.free_slots = list(range(self._t, self._t * 2))
            heapq.heapify(self.free_slots)
            self._t *= 2
        else:
            self.free_key_slots = list(range(self._k, self._k * 2))
            heapq.heapify(self.free_key_slots)
            self._k *= 2
        self._rebuild()

    def _rebuild(self) -> None:
        """Full rebuild of the canonical arrays (capacity growth only — rare,
        amortised)."""
        t, k = self._t, self._k
        key_inc = np.zeros((t, k), dtype=np.int8)
        ts = np.zeros((t, TS_LANES), dtype=np.int32)
        txn_id = np.zeros((t, TS_LANES), dtype=np.int32)
        kind = np.zeros((t,), dtype=np.int8)
        status = np.zeros((t,), dtype=np.int8)
        active = np.zeros((t,), dtype=np.bool_)
        for tid, m in self.txns.items():
            key_inc[m.slot, [self.key_slot[rk] for rk in m.keys]] = 1
            ts[m.slot] = m.execute_at.pack_lanes()
            txn_id[m.slot] = tid.pack_lanes()
            kind[m.slot] = m.kind_code
            status[m.slot] = m.status
            active[m.slot] = True
        self._h = {"key_inc": key_inc, "key_inc_f32": key_inc.T.astype(np.float32),
                   "ts": ts, "txn_id": txn_id, "kind": kind, "status": status,
                   "active": active}
        self._device_clean = False
        self._dirty_txns.clear()
        self._clear_bits.clear()
        self._deactivate.clear()

    def _flush(self) -> None:
        """Apply buffered mutations to the canonical host arrays (in-place row
        writes — O(rows changed)); the device copy goes stale and re-syncs
        lazily if/when the device tier is next chosen."""
        if self._h is None:
            self._rebuild()
            return
        if not (self._dirty_txns or self._clear_bits or self._deactivate):
            return
        h = self._h
        # order matters: clears and deactivations target OLD occupants of a
        # slot; inserts (which may recycle that same slot) must land last
        for row, col in self._clear_bits:
            h["key_inc"][row, col] = 0
            h["key_inc_f32"][col, row] = 0.0
        self._clear_bits.clear()
        if self._deactivate:
            d = np.asarray(self._deactivate, dtype=np.int32)
            h["active"][d] = False
            h["key_inc"][d] = 0
            h["key_inc_f32"][:, d] = 0.0
            h["status"][d] = 0
            self._deactivate.clear()
        for tid in sorted(self._dirty_txns):    # deterministic flush order
            m = self.txns[tid]
            row = m.slot
            h["key_inc"][row] = 0
            h["key_inc_f32"][:, row] = 0.0
            cols = [self.key_slot[rk] for rk in m.keys]
            h["key_inc"][row, cols] = 1
            h["key_inc_f32"][cols, row] = 1.0
            h["ts"][row] = m.execute_at.pack_lanes()
            h["txn_id"][row] = tid.pack_lanes()
            h["kind"][row] = m.kind_code
            h["status"][row] = m.status
            h["active"][row] = True
        self._dirty_txns.clear()
        self._device_clean = False

    # -- introspection (tests / bench) ---------------------------------------
    def host_index(self) -> dict:
        self._flush()
        return self._h

    def indexed_count(self) -> int:
        return len(self.txns)


_CALIBRATED: Optional[float] = None


def _calibrate_threshold() -> float:
    """Measure one device launch round-trip and the host tier's element rate;
    the device tier is worth it above elems ≈ host_rate × launch_rtt.
    Process-wide (one measurement serves every store's resolver)."""
    global _CALIBRATED
    if _CALIBRATED is not None:
        return _CALIBRATED
    try:
        import jax
        import jax.numpy as jnp
        from ..ops import deps_kernels as dk
        t, k, b = 256, 64, 8
        args = (jnp.zeros((t, k), jnp.int8), jnp.zeros((t, TS_LANES), jnp.int32),
                jnp.zeros((t, TS_LANES), jnp.int32), jnp.zeros((t,), jnp.int8),
                jnp.zeros((t,), jnp.int8), jnp.zeros((t,), jnp.bool_),
                jnp.zeros((b, k), jnp.int8), jnp.zeros((b, TS_LANES), jnp.int32),
                jnp.zeros((b,), jnp.int8))
        jax.block_until_ready(dk.consult(*args))      # compile
        t0 = time.perf_counter()
        jax.block_until_ready(dk.consult(*args))
        rtt = time.perf_counter() - t0
        # host tier: ~f32 BLAS matmul; measure a representative pass
        hq = np.random.default_rng(0)
        a = (hq.random((256, 512)) < 0.1).astype(np.float32)
        m = (hq.random((512, 4096)) < 0.1).astype(np.float32)
        t0 = time.perf_counter()
        for _ in range(3):
            _ = a @ m
        host_rate = 3 * a.shape[0] * a.shape[1] * m.shape[1] \
            / (time.perf_counter() - t0)
        _CALIBRATED = max(1e6, host_rate * rtt)
    except Exception:  # noqa: BLE001 — no device: host tier only
        _CALIBRATED = float("inf")
    return _CALIBRATED
