"""List-append KV model store for the harness and Maelstrom adapter.

Capability parity with the reference's ``accord.impl.list`` test model
(ListStore.java 599 LoC, ListRead/ListUpdate/ListQuery/ListData/ListResult): every key
holds a list of appended values with their apply timestamps; writes append a value,
reads return the list contents.  The burn test's strict-serializability verifier
consumes exactly this read/append observation model.
"""
from __future__ import annotations

from bisect import insort
from typing import Dict, List, Optional, Tuple

from ..api.interfaces import Data, DataStore, Query, Read, Result, Update, Write
from ..primitives.keys import Key, Keys, Ranges
from ..primitives.timestamp import Timestamp, TxnId
from ..utils import async_ as au


class ListStore(DataStore):
    """In-memory per-node storage: key -> sorted list of (executeAt, value)."""

    def __init__(self, node_id: int = 0):
        self.node_id = node_id
        self.data: Dict[Key, List[Tuple[Timestamp, object]]] = {}
        # ranges with KNOWN data holes (truncated-outcome adoption landed a
        # txn whose truncated-away predecessors are absent): reads here are
        # refused (obsolete-nack -> coordinator retries another replica)
        # until a peer snapshot heals the gap
        # a MULTISET of marks: overlapping gaps from independent heals must
        # not clear each other's coverage (each heal clears only its token)
        self._stale_marks: list = []

    def mark_stale(self, rngs):
        """Returns the token to pass to clear_stale."""
        token = rngs
        self._stale_marks.append(token)
        return token

    def clear_stale(self, token) -> None:
        try:
            self._stale_marks.remove(token)
        except ValueError:
            pass

    @property
    def stale_ranges(self):
        from ..primitives.keys import Ranges as _Ranges
        out = _Ranges.EMPTY
        for r in self._stale_marks:
            out = out.union(r)
        return out

    def is_stale(self, key) -> bool:
        if not self._stale_marks:
            return False
        rk = key.to_routing() if hasattr(key, "to_routing") else key
        return any(r.contains(rk) for r in self._stale_marks)

    def get(self, key: Key) -> Tuple[object, ...]:
        return tuple(v for _, v in self.data.get(key, ()))

    def get_at(self, key: Key, execute_at: Timestamp,
               exclusive: bool = False) -> Tuple[object, ...]:
        """Snapshot read: entries applied at-or-before ``execute_at`` only.
        Keeps reads correct even when a write with a LATER executeAt landed
        early (truncated-outcome adoption applies out of dependency order).

        ``exclusive`` drops the entry at exactly ``execute_at``: executeAts
        are unique per txn, so when a read is served from a copy that already
        APPLIED the txn, the exclusive bound removes exactly the txn's OWN
        write — reconstructing the pre-apply snapshot the read semantics
        require."""
        if exclusive:
            return tuple(v for ts, v in self.data.get(key, ()) if ts < execute_at)
        return tuple(v for ts, v in self.data.get(key, ()) if ts <= execute_at)

    def append(self, key: Key, execute_at: Timestamp, value: object) -> None:
        entries = self.data.setdefault(key, [])
        # idempotent: the same (executeAt, value) may be applied once
        for ts, _ in entries:
            if ts == execute_at:
                return
        insort(entries, (execute_at, value))

    def keys_in(self, rng) -> List[Key]:
        """All stored keys within a Range, sorted (range-read enumeration)."""
        return sorted(k for k in self.data if rng.contains(k.to_routing()))

    def fetch(self, node, safe_store, ranges, sync_point, fetch_ranges,
              catch_up: bool = False):
        """Pull ``ranges``' contents from a source replica (bootstrap
        streaming; impl/AbstractFetchCoordinator.java).  Sources have applied
        the fencing sync point, so their data is complete up to it; entries are
        timestamped so concurrent Apply traffic composes idempotently.

        ``catch_up=False`` (topology-change adoption): sources are the
        PRIOR-epoch shard replicas — they held the data before the move; no
        prior topology means fresh key-space (trivially complete).
        ``catch_up=True`` (stale-range bootstrap-grade heal): this store
        already owns the ranges and is refetching IN PLACE — sources are the
        fence-epoch shard PEERS, and a slice with no reachable peer fails the
        attempt (never 'trivially complete': the data exists, we lost it)."""
        from ..messages.base import Callback
        from ..messages.fetch_messages import FetchStoreData, FetchStoreDataOk

        # fetch plan: per source-topology SHARD slice, from that shard's
        # replicas — a single source need not cover all the ranges (they may
        # span shards with disjoint replica sets)
        epoch = sync_point.txn_id.epoch
        source_topo = None
        if catch_up:
            if node.topology.has_epoch(epoch):
                source_topo = node.topology.topology_for_epoch(epoch)
        else:
            for e in range(epoch - 1, node.topology.min_epoch - 1, -1):
                if node.topology.has_epoch(e):
                    source_topo = node.topology.topology_for_epoch(e)
                    break
        plan = []   # (sub_ranges, [candidate sources])
        if source_topo is not None:
            for shard in source_topo.shards:
                sub = ranges.intersection(Ranges.of(shard.range))
                if not sub:
                    continue
                candidates = [n for n in shard.nodes if n != node.id]
                if candidates:
                    plan.append((sub, candidates))
                elif node.id in shard.nodes and not catch_up:
                    # we were the shard's only replica: our local copy IS the
                    # data, complete up to the fence by construction
                    pass
                else:
                    # a needed slice has NO source — reporting it fetched would
                    # let bootstrapped_at cover data we never obtained; fail the
                    # attempt so bootstrap retries (ListStore.fetch contract,
                    # impl/list/ListStore.java)
                    fetch_ranges.fail(RuntimeError(
                        f"no fetch source for {sub!r} "
                        f"(epoch {source_topo.epoch}, catch_up={catch_up})"))
                    return au.success_result()
        if not plan:
            if catch_up:
                # catch-up must never claim completeness without a source
                fetch_ranges.fail(RuntimeError(
                    f"no catch-up sources for {ranges!r} at epoch {epoch}"))
                return au.success_result()
            # anything the prior topology did not replicate is fresh
            # key-space: trivially complete
            fetch_ranges.fetched(ranges)
            return au.success_result()

        store = self
        remaining = {"n": len(plan)}
        # the fence shipment (Apply.Maximal of the sync point) depends only on
        # the sync point — build it once for all slices/failover retries
        from ..messages.txn_messages import Apply, ApplyOk, ApplyThenWaitUntilApplied
        from ..primitives.timestamp import TxnKind
        from ..primitives.txn import Txn
        fence_parts = sync_point.route.participants()
        fence_txn = Txn.empty(TxnKind.EXCLUSIVE_SYNC_POINT, fence_parts)
        fence_partial = fence_txn.slice(fence_parts, include_query=False)
        fence_writes = fence_txn.execute(sync_point.txn_id,
                                         sync_point.execute_at, None)

        def fetch_slice(sub: Ranges, candidates, i: int) -> None:
            class FetchCallback(Callback):
                def on_success(self, from_node: int, reply) -> None:
                    if not isinstance(reply, FetchStoreDataOk):
                        self.on_failure(from_node,
                                        RuntimeError(f"bad reply {reply!r}"))
                        return
                    for key, entries in reply.entries.items():
                        for ts, value in entries:
                            store.append(key, ts, value)
                    remaining["n"] -= 1
                    if remaining["n"] == 0:
                        fetch_ranges.fetched(ranges)

                def on_failure(self, from_node: int, failure: BaseException) -> None:
                    if i + 1 < len(candidates):
                        fetch_slice(sub, candidates, i + 1)
                    else:
                        fetch_ranges.fail(failure)

            # ship the fence to the source FIRST (Apply.Maximal + wait-applied):
            # a source outside the fence's current-epoch topology (the replica
            # the range is moving AWAY from) never hears of it otherwise, and
            # data is only complete up to an APPLIED fence
            # (impl/AbstractFetchCoordinator.java — ApplyThenWaitUntilApplied)
            fetch_cb = FetchCallback()

            class FenceCallback(Callback):
                def on_success(self, from_node: int, reply) -> None:
                    if not isinstance(reply, ApplyOk):
                        self.on_failure(from_node,
                                        RuntimeError(f"fence not applied: {reply!r}"))
                        return
                    node.send(from_node,
                              FetchStoreData(sub, sync_point.txn_id,
                                             sync_point.route),
                              fetch_cb)

                def on_failure(self, from_node: int, failure: BaseException) -> None:
                    fetch_cb.on_failure(from_node, failure)

            node.send(candidates[i], ApplyThenWaitUntilApplied(
                sync_point.txn_id, sync_point.route, sync_point.txn_id.epoch,
                Apply.MAXIMAL, sync_point.execute_at,
                sync_point.deps, fence_partial,
                fence_writes, None, route=sync_point.route), FenceCallback())

        for sub, candidates in plan:
            fetch_slice(sub, candidates, 0)
        return au.success_result()


class ListData(Data):
    """key -> tuple of values observed by the read."""

    def __init__(self, entries: Optional[Dict[Key, Tuple]] = None):
        self.entries: Dict[Key, Tuple] = entries or {}

    def merge(self, other: "Data") -> "Data":
        if not isinstance(other, ListData):
            return self
        merged = dict(self.entries)
        merged.update(other.entries)
        return ListData(merged)

    def __repr__(self):
        return f"ListData({self.entries})"


class ListRead(Read):
    def __init__(self, keys: Keys):
        self._keys = keys

    def keys(self):
        return self._keys

    def read(self, key, safe_store, execute_at, data_store) -> au.AsyncChain:
        if getattr(data_store, "is_stale", lambda _k: False)(key):
            return au.done("obsolete")   # gapped here: serve from a peer
        return au.done(ListData({key: data_store.get_at(key, execute_at)}))

    def slice(self, ranges: Ranges) -> "ListRead":
        return ListRead(self._keys.slice(ranges))

    def merge(self, other: "Read") -> "ListRead":
        return ListRead(self._keys.union(other._keys))


class ListRangeRead(Read):
    """Range-domain read: reads every stored key inside the ranges
    (the reference's range queries, BurnTest.java:208-240 / ListRead over ranges)."""

    def __init__(self, ranges: Ranges):
        self._ranges = ranges

    def keys(self):
        return self._ranges

    def read(self, rng, safe_store, execute_at, data_store) -> au.AsyncChain:
        stale = getattr(data_store, "stale_ranges", None)
        if stale is not None and len(stale) and stale.intersects(rng):
            return au.done("obsolete")   # gapped here: serve from a peer
        entries = {key: data_store.get_at(key, execute_at)
                   for key in data_store.keys_in(rng)}
        return au.done(ListData(entries))

    def slice(self, ranges: Ranges) -> "ListRangeRead":
        return ListRangeRead(self._ranges.intersection(ranges))

    def merge(self, other: "Read") -> "ListRangeRead":
        return ListRangeRead(self._ranges.union(other._ranges))


class ListWrite(Write):
    """Computed appends: key -> value."""

    def __init__(self, appends: Dict[Key, object]):
        self.appends = appends

    def apply(self, store: ListStore, key, execute_at) -> au.AsyncChain:
        if key in self.appends:
            store.append(key, execute_at, self.appends[key])
        return au.done(None)

    def merge(self, other: "ListWrite") -> "ListWrite":
        merged = dict(self.appends)
        merged.update(other.appends)
        return ListWrite(merged)


class ListUpdate(Update):
    """key -> value to append."""

    def __init__(self, appends: Dict[Key, object]):
        self.appends = appends

    def keys(self):
        return Keys.of(self.appends.keys())

    def apply(self, execute_at, data) -> ListWrite:
        return ListWrite(dict(self.appends))

    def slice(self, ranges: Ranges) -> "ListUpdate":
        return ListUpdate({k: v for k, v in self.appends.items()
                           if ranges.contains(k.to_routing())})

    def merge(self, other: "Update") -> "ListUpdate":
        merged = dict(self.appends)
        merged.update(other.appends)
        return ListUpdate(merged)


class ListResult(Result):
    """Client-visible result: what the txn read (key -> tuple) and wrote."""

    def __init__(self, txn_id: TxnId, execute_at, reads: Dict[Key, Tuple],
                 writes: Dict[Key, object]):
        self.txn_id = txn_id
        self.execute_at = execute_at
        self.reads = reads
        self.writes = writes

    def __repr__(self):
        return f"ListResult({self.txn_id!r}, reads={self.reads}, writes={self.writes})"


class ListQuery(Query):
    def __init__(self):
        pass

    def compute(self, txn_id, execute_at, keys, data, read, update) -> ListResult:
        reads = dict(data.entries) if isinstance(data, ListData) else {}
        writes = dict(update.appends) if isinstance(update, ListUpdate) else {}
        return ListResult(txn_id, execute_at, reads, writes)


def list_txn(keys_read: List[Key], appends: Dict[Key, object]):
    """Build a list-model Txn: read ``keys_read``, append ``appends``."""
    from ..primitives.txn import Txn
    all_keys = Keys.of(list(keys_read) + list(appends.keys()))
    read = ListRead(Keys.of(keys_read))
    update = ListUpdate(appends) if appends else None
    return Txn.of(all_keys, read, update, ListQuery())


def range_read_txn(ranges: Ranges):
    """Build a range-domain read Txn over ``ranges`` (reference range queries)."""
    from ..primitives.txn import Txn
    return Txn.of(ranges, ListRangeRead(ranges), None, ListQuery())


def ephemeral_read_txn(keys_read: List[Key]):
    """Build an ephemeral (1-round, non-durable) read Txn (Txn.Kind.EphemeralRead)."""
    from ..primitives.timestamp import TxnKind
    from ..primitives.txn import Txn
    keys = Keys.of(keys_read)
    return Txn(TxnKind.EPHEMERAL_READ, keys, ListRead(keys), None, ListQuery())
