"""No-op Read/Query/Result used by empty system txns (sync points, barriers).

Parity: the reference constructs empty txns via ``Agent.emptySystemTxn``
(Agent.java:88-97) with reads that touch nothing.
"""
from __future__ import annotations

from ..api.interfaces import Data, Query, Read, Result
from ..utils import async_ as au


class NoopData(Data):
    def merge(self, other):
        return other if other is not None else self


class NoopRead(Read):
    def __init__(self, keys):
        self._keys = keys

    def keys(self):
        return self._keys

    def read(self, key, safe_store, execute_at, data_store):
        return au.done(None)

    def slice(self, ranges):
        from ..primitives.keys import Keys, Ranges
        if isinstance(self._keys, Ranges):
            return NoopRead(self._keys.intersection(ranges))
        return NoopRead(self._keys.slice(ranges))

    def merge(self, other):
        if isinstance(other, NoopRead):
            return NoopRead(self._keys.union(other._keys))
        return other


class NoopResult(Result):
    def __repr__(self):
        return "NoopResult"


class NoopQuery(Query):
    def compute(self, txn_id, execute_at, keys, data, read, update):
        return NOOP_RESULT


NOOP_RESULT = NoopResult()
NOOP_QUERY = NoopQuery()
