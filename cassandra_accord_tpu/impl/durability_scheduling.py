"""Background durability scheduling.

Capability parity with ``accord.impl.CoordinateDurabilityScheduling``
(CoordinateDurabilityScheduling.java:78-350): each node periodically rotates a
``CoordinateShardDurable`` round over successive sub-ranges of the ranges it
replicates (completing a full cycle every ``shard_cycle_time``), and — staggered by
node index so nodes take turns — runs ``CoordinateGloballyDurable`` every
``global_cycle_time``.  Together these advance every replica's DurableBefore /
RedundantBefore watermarks, enabling truncation GC cluster-wide.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, List, Optional

from ..coordinate.durability import (coordinate_globally_durable,
                                     coordinate_shard_durable)
from ..primitives.keys import Range, Ranges

if TYPE_CHECKING:
    from ..local.node import Node


class CoordinateDurabilityScheduling:
    """One per node; start() registers recurring tasks on the node scheduler."""

    def __init__(self, node: "Node", shard_cycle_time_s: float = 30.0,
                 global_cycle_time_s: float = 60.0, splits_per_range: int = 1):
        self.node = node
        self.shard_cycle_time_s = shard_cycle_time_s
        self.global_cycle_time_s = global_cycle_time_s
        self.splits_per_range = max(1, splits_per_range)
        self._cursor = 0
        self._in_flight = False
        self._scheduled: List = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        step = self._shard_step_interval_s()
        self._scheduled.append(
            self.node.scheduler.recurring(step, self._shard_round))
        # stagger global rounds by node index so nodes take turns
        # (CoordinateDurabilityScheduling.java:57-78)
        topology = self.node.topology.current()
        nodes = sorted(topology.nodes()) if topology is not None else [self.node.id]
        idx = nodes.index(self.node.id) if self.node.id in nodes else 0
        offset = (idx / max(1, len(nodes))) * self.global_cycle_time_s
        self._scheduled.append(self.node.scheduler.once(
            offset, lambda: self._scheduled.append(self.node.scheduler.recurring(
                self.global_cycle_time_s, self._global_round))))

    def stop(self) -> None:
        for s in self._scheduled:
            try:
                s.cancel()
            except Exception:  # noqa: BLE001
                pass
        self._scheduled.clear()

    # -- rounds --------------------------------------------------------------
    def _sub_ranges(self) -> List[Ranges]:
        """The rotation: each of this node's replicated ranges, split into
        ``splits_per_range`` slices."""
        topology = self.node.topology.current()
        if topology is None:
            return []
        my = topology.ranges_for_node(self.node.id)
        out: List[Ranges] = []
        for rng in my:
            for piece in _split(rng, self.splits_per_range):
                out.append(Ranges.of(piece))
        return out

    def _shard_step_interval_s(self) -> float:
        n = max(1, len(self._sub_ranges()))
        return max(0.05, self.shard_cycle_time_s / n)

    def _shard_round(self) -> None:
        if self._in_flight:
            return  # previous round still running; keep the cadence, skip
        subs = self._sub_ranges()
        if not subs:
            return
        ranges = subs[self._cursor % len(subs)]
        self._cursor += 1
        self._in_flight = True

        def done(_v, _f):
            self._in_flight = False

        coordinate_shard_durable(self.node, ranges).add_listener(done)

    def _global_round(self) -> None:
        coordinate_globally_durable(self.node).add_listener(lambda _v, _f: None)


def _split(rng: Range, pieces: int) -> List[Range]:
    """Split a range into up to ``pieces`` sub-ranges when the key type supports
    interpolation (IntKey-style ``value``); otherwise return it whole
    (ShardDistributor.EvenSplit delegates to a pluggable Splitter the same way)."""
    if pieces <= 1:
        return [rng]
    start, end = rng.start, rng.end
    sv = getattr(start, "value", None)
    ev = getattr(end, "value", None)
    if sv is None or ev is None or not isinstance(sv, int) or not isinstance(ev, int) \
            or ev - sv < pieces \
            or getattr(start, "prefix", 0) != getattr(end, "prefix", 0):
        return [rng]
    out = []
    width = (ev - sv) // pieces
    cls = type(start)
    prefix = getattr(start, "prefix", 0)
    for i in range(pieces):
        s = sv + i * width
        e = ev if i == pieces - 1 else s + width
        out.append(Range(cls(s, prefix), cls(e, prefix)))
    return out
