"""The DepsResolver boundary — the pluggable per-store conflict-index data plane.

The reference hides its dependency calculation behind
``SafeCommandStore.mapReduceActive`` (SafeCommandStore.java:292) + the per-key
``CommandsForKey`` indexes (cfk/CommandsForKey.java:925-1000) and its timestamp
proposal behind ``MaxConflicts`` (MaxConflicts.java:32) + per-key maxima.  This
module makes that boundary explicit so the SAME protocol code runs against:

- ``CpuDepsResolver``  — the host reference data plane: walks the store's
  CommandsForKey lists (exactly the reference's scalar scan shape);
- ``TpuDepsResolver``  — the device data plane (impl/tpu_resolver.py): the
  store's conflict index lives on-device as a GraphState and every query is a
  batched MXU join (ops.deps_kernels.overlap_join / max_conflict_keys).  Its
  device tier routes through the PERSISTENT batched consult service
  (device_service/: incrementally-refreshed double-buffered index, ragged
  batching windows, ``submit(txn_keys) -> AsyncResult`` futures) unless
  ``tpu_service=off`` selects the legacy one-shot dispatch;
- ``VerifyDepsResolver`` — runs both and asserts bit-identical results on
  every query ("deps-graph parity"); used by tests and the burn harness.

Select per-node via ``Node(resolver=...)`` or globally via the environment
variable ``ACCORD_RESOLVER`` in {cpu, tpu, verify} (default cpu).

Scope: the resolver owns the KEY-domain conflict index (the hot path).
Range-domain transactions (sync points; InMemoryCommandStore.rangeCommands
scan, :814-900) remain a host-side side table in SafeCommandStore — they are
rare control transactions, not data-plane load.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple, TYPE_CHECKING

from ..primitives.keys import Range, RoutingKey
from ..primitives.timestamp import Timestamp, TxnId
from ..utils.invariants import check_state

if TYPE_CHECKING:
    from ..local.command_store import CommandStore
    from ..local.cfk import InternalStatus


def check_resolver_kind(kind: str) -> str:
    check_state(kind in ("cpu", "tpu", "verify"),
                "resolver kind must be cpu|tpu|verify, got %s", kind)
    return kind


def make_resolver(kind: str, store: "CommandStore",
                  config=None) -> "DepsResolver":
    if kind == "cpu":
        return CpuDepsResolver(store)
    if kind == "tpu":
        from .tpu_resolver import TpuDepsResolver
        return TpuDepsResolver(store, config=config)
    if kind == "verify":
        from .tpu_resolver import TpuDepsResolver
        return VerifyDepsResolver(CpuDepsResolver(store),
                                  TpuDepsResolver(store, config=config))
    raise ValueError(f"unknown resolver kind {kind!r}")


class QuerySpec:
    """A declared upcoming query, for batched prefetch (resolver.prefetch).

    ``op`` is 'kc' (key_conflicts) or 'mc' (max_conflict_keys).  ``keys`` are
    the keys the caller WILL pass (pre key-slot filtering — the resolver
    applies its own known-key filter, exactly as the live query does, so the
    cached answer's signature matches the call-time signature)."""
    __slots__ = ("op", "by", "keys", "before")

    def __init__(self, op: str, by: Optional[TxnId], keys, before: Optional[Timestamp]):
        self.op = op
        self.by = by
        self.keys = tuple(keys)
        self.before = before


class DepsResolver:
    """Interface.  All queries are pure reads of the index; registration and
    pruning are the only mutations, and both are driven by the owning
    SafeCommandStore (single-logical-thread discipline applies)."""

    def prefetch(self, specs: List["QuerySpec"]) -> None:
        """Hint: the declared queries are about to be issued (a coalesced
        delivery window).  A device resolver answers them all in ONE launch
        and serves the live queries from the cached answers — falling back to
        an individual launch whenever an index mutation since the prefetch
        could change the answer (exact sequential semantics).  Host resolvers
        ignore it."""

    def end_batch(self) -> None:
        """The delivery window ended: drop any prefetched answers."""

    # -- execution-frontier plane (Commands WaitingOn mirror) -----------------
    def is_indexed(self, txn_id: TxnId) -> bool:
        """Does the device index hold this txn (frontier-exec eligibility)?
        Host-only resolvers index nothing."""
        return False

    def register_waiting(self, waiter: TxnId, deps) -> None:
        """The execute-phase wait graph: ``waiter`` blocks on ``deps``
        (Commands.initialiseWaitingOn, Commands.java:688).  Device resolvers
        mirror the edges so the execution frontier can be computed as one
        kernel pass (ops.deps_kernels.kahn_frontier); host resolvers rely on
        the event-driven WaitingOn and ignore this."""

    def remove_waiting(self, waiter: TxnId, dep: TxnId) -> None:
        """An edge drained (dep applied/invalidated/truncated or provably
        ordered after the waiter — Commands.java:704-775)."""

    def note_terminal(self, txn_id: TxnId, invalidated: bool = False) -> None:
        """The host command reached a TERMINAL SaveStatus (applied /
        invalidated / truncated / erased) — regardless of whether any cfk
        accepted a witness update for the transition.  ``register`` alone
        cannot carry this: it is gated behind the cfk key-indexing loop,
        which refuses demoted-cold and pruned entries, truncation never
        re-registers at all, and topology churn can drop key ownership
        between STABLE and APPLIED.  Device resolvers must still advance
        their mirror's status (a stale STABLE row reports the txn
        execution-ready forever — the one-sided device mirror leak) and drop
        the txn's wait edges; host resolvers keep no mirror and ignore this."""

    def mark_durable(self, txn_id: TxnId) -> None:
        """Per-txn UNIVERSAL durability (Commands.set_durability crossing
        UNIVERSAL — the coordinator saw every Apply ack): device-plane
        resolvers widen their elision mirrors; host resolvers rely on the cfk
        flag (cfk.mark_durable) and ignore this.  Majority durability is NOT
        sufficient for elision — see commands.set_durability."""

    def _durable_majority(self, rk: RoutingKey) -> Optional[TxnId]:
        """The key's majority-durable watermark — the elision soundness gate
        (cfk.map_reduce_active doc).  Shared by BOTH data planes: the gate
        semantics must stay bit-identical for verify parity."""
        db = getattr(self.store, "durable_before", None)
        if db is None:
            return None
        e = db.entry(rk)
        return e.majority_before if e is not None else None

    def register(self, txn_id: TxnId, status: "InternalStatus",
                 execute_at: Optional[Timestamp],
                 keys: Tuple[RoutingKey, ...]) -> None:
        """Witness/upgrade a key-domain managed txn on ``keys``
        (CommandsForKey.update semantics: status monotonic)."""
        raise NotImplementedError

    def on_pruned(self, key: RoutingKey, txn_ids: List[TxnId]) -> None:
        """The per-key index dropped ``txn_ids`` below a prune bound — evict
        the (txn, key) incidences so late queries match (cfk pruning)."""
        raise NotImplementedError

    def key_conflicts(self, by: TxnId, keys: List[RoutingKey], before: Timestamp
                      ) -> List[Tuple[RoutingKey, TxnId]]:
        """Active (non-invalidated) indexed txns with txnId < before on any of
        ``keys`` that ``by``'s kind witnesses; (key, dep) per incidence.
        == mapReduceActive over the cfk indexes (cfk/CommandsForKey.java:925)."""
        raise NotImplementedError

    def range_conflicts(self, by: TxnId, rng: Range, before: Timestamp
                        ) -> List[Tuple[RoutingKey, TxnId]]:
        """Same, for every indexed key inside ``rng``."""
        raise NotImplementedError

    def max_conflict_keys(self, keys: List[RoutingKey]) -> Optional[Timestamp]:
        """Lexicographic max of max(executeAt, txnId) over indexed txns touching
        ``keys`` (the per-key half of the MaxConflicts consult)."""
        raise NotImplementedError

    def max_conflict_range(self, rng: Range) -> Optional[Timestamp]:
        raise NotImplementedError


class CpuDepsResolver(DepsResolver):
    """Reference host resolver: delegates to the store's CommandsForKey lists.
    This IS the reference algorithm (scalar per-key scans); it owns no state of
    its own, so cfk registration doubles as resolver registration."""

    def __init__(self, store: "CommandStore"):
        self.store = store

    # cfk.update is already performed by SafeCommandStore.register_witness —
    # the cfk lists are this resolver's index.
    def register(self, txn_id, status, execute_at, keys) -> None:
        pass

    def on_pruned(self, key, txn_ids) -> None:
        pass

    def key_conflicts(self, by, keys, before):
        out: List[Tuple[RoutingKey, TxnId]] = []
        # sync points are local-apply FENCES: their deps must wait on every
        # txn not yet provably applied at EVERY replica, so the per-txn
        # MAJORITY-durable elision flag does not apply to them (only the
        # universal-grade watermark does) — eliding a merely-majority-applied
        # txn from an exclusive sync point's deps lets mark_shard_durable
        # claim universal application the barrier never proved, advancing
        # truncation fences past unapplied txns (the round-5 stale-cascade)
        flag = not by.kind.is_sync_point
        for rk in keys:
            cfk = self.store.cfks.get(rk)
            if cfk is not None:
                cfk.map_reduce_active(before, by.witnesses,
                                      lambda t, _rk=rk: out.append((_rk, t)),
                                      durable_majority=self._durable_majority(rk),
                                      flag_elision=flag)
        return out

    def range_conflicts(self, by, rng, before):
        out: List[Tuple[RoutingKey, TxnId]] = []
        flag = not by.kind.is_sync_point   # see key_conflicts
        for rk in sorted(self.store.cfks):
            if rng.contains(rk):
                cfk = self.store.cfks[rk]
                cfk.map_reduce_active(before, by.witnesses,
                                      lambda t, _rk=rk: out.append((_rk, t)),
                                      durable_majority=self._durable_majority(rk),
                                      flag_elision=flag)
        return out

    def max_conflict_keys(self, keys):
        out: Optional[Timestamp] = None
        for rk in keys:
            cfk = self.store.cfks.get(rk)
            if cfk is not None:
                ts = cfk.max_timestamp()
                if ts is not None and (out is None or ts > out):
                    out = ts
        return out

    def max_conflict_range(self, rng):
        out: Optional[Timestamp] = None
        for rk in sorted(self.store.cfks):
            if rng.contains(rk):
                ts = self.store.cfks[rk].max_timestamp()
                if ts is not None and (out is None or ts > out):
                    out = ts
        return out


class VerifyDepsResolver(DepsResolver):
    """Runs the CPU and TPU resolvers side by side and asserts every query
    agrees — the continuous deps-graph parity check (BASELINE.md metric).
    Comparison is set-level (Deps construction is order-independent)."""

    def __init__(self, cpu: CpuDepsResolver, tpu: DepsResolver):
        self.cpu = cpu
        self.tpu = tpu
        self.queries = 0

    def prefetch(self, specs) -> None:
        # only the device side batches; the cpu side stays the live oracle the
        # cached answers are checked against on every query
        self.tpu.prefetch(specs)

    def end_batch(self) -> None:
        self.tpu.end_batch()

    def register_waiting(self, waiter, deps) -> None:
        self.tpu.register_waiting(waiter, deps)

    def remove_waiting(self, waiter, dep) -> None:
        self.tpu.remove_waiting(waiter, dep)

    def note_terminal(self, txn_id, invalidated: bool = False) -> None:
        self.tpu.note_terminal(txn_id, invalidated=invalidated)

    def is_indexed(self, txn_id) -> bool:
        return self.tpu.is_indexed(txn_id)

    def register(self, txn_id, status, execute_at, keys) -> None:
        self.cpu.register(txn_id, status, execute_at, keys)
        self.tpu.register(txn_id, status, execute_at, keys)

    def on_pruned(self, key, txn_ids) -> None:
        self.cpu.on_pruned(key, txn_ids)
        self.tpu.on_pruned(key, txn_ids)

    def mark_durable(self, txn_id) -> None:
        self.cpu.mark_durable(txn_id)
        self.tpu.mark_durable(txn_id)

    def _check(self, what, a, b):
        check_state(a == b, "deps parity violation in %s: cpu=%s tpu=%s",
                    what, a, b)
        self.queries += 1
        return a

    def key_conflicts(self, by, keys, before):
        return self._check(
            "key_conflicts",
            sorted(self.cpu.key_conflicts(by, keys, before)),
            sorted(self.tpu.key_conflicts(by, keys, before)))

    def range_conflicts(self, by, rng, before):
        return self._check(
            "range_conflicts",
            sorted(self.cpu.range_conflicts(by, rng, before)),
            sorted(self.tpu.range_conflicts(by, rng, before)))

    def max_conflict_keys(self, keys):
        return self._check("max_conflict_keys",
                           self.cpu.max_conflict_keys(keys),
                           self.tpu.max_conflict_keys(keys))

    def max_conflict_range(self, rng):
        return self._check("max_conflict_range",
                           self.cpu.max_conflict_range(rng),
                           self.tpu.max_conflict_range(rng))
