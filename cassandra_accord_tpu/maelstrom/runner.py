"""In-process Maelstrom simulator: seeded queues, random delays, periodic
partitions.

Capability parity with ``accord-maelstrom``'s test-tree ``Cluster``/``Runner``
(maelstrom/Cluster.java:70-330, Runner.java): runs the full Maelstrom packet
protocol (init / txn / accord wrappers) between in-process MaelstromNodes over a
simulated-time queue, with random delivery delays and periodic random network
partitions, and validates client results.
"""
from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional

from ..harness.cluster import PendingQueue, SimScheduler
from ..utils.random import RandomSource
from .node import MaelstromNode, node_num


class MaelstromCluster:
    """N MaelstromNodes exchanging JSON packets over a seeded queue."""

    def __init__(self, n_nodes: int, seed: int = 1,
                 min_latency_us: int = 500, max_latency_us: int = 10_000,
                 partition_interval_s: Optional[float] = 2.0,
                 partition_duration_s: float = 0.5):
        self.rng = RandomSource(seed)
        self.queue = PendingQueue()
        self.scheduler = SimScheduler(self.queue)
        self.min_latency_us = min_latency_us
        self.max_latency_us = max_latency_us
        self.names = [f"n{i}" for i in range(1, n_nodes + 1)]
        self.partitioned: set = set()   # node names on the minority side
        self.errors: List[BaseException] = []
        self.client_replies: List[dict] = []
        self._reply_handlers: Dict[int, Callable[[dict], None]] = {}
        self._next_client_msg = [0]
        self.nodes: Dict[str, MaelstromNode] = {}
        for name in self.names:
            self.nodes[name] = MaelstromNode(
                name, list(self.names),
                emit=lambda packet: self._route(packet),
                scheduler=self.scheduler,
                now_micros=lambda: self.queue.now_micros,
                on_error=self.errors.append)
        if partition_interval_s:
            self.scheduler.recurring(partition_interval_s,
                                     lambda: self._random_partition(partition_duration_s))

    # -- network -------------------------------------------------------------
    def _random_partition(self, duration_s: float) -> None:
        """Partition a random minority for ``duration_s`` (Cluster.java:143-215)."""
        k = self.rng.next_int(1, max(2, len(self.names) // 2 + 1))
        side = set(self.rng.pick(self.names) for _ in range(k))
        self.partitioned = side
        self.scheduler.once(duration_s, lambda: self._heal(side))

    def _heal(self, side: set) -> None:
        if self.partitioned == side:
            self.partitioned = set()

    def _dropped(self, src: str, dest: str) -> bool:
        return (src in self.partitioned) != (dest in self.partitioned)

    def _route(self, packet: dict) -> None:
        # serialize/deserialize for wire fidelity (catches codec gaps)
        packet = json.loads(json.dumps(packet))
        src, dest = packet["src"], packet["dest"]
        if dest.startswith("c"):
            self._deliver_client(packet)
            return
        if self._dropped(src, dest):
            return
        delay = self.rng.next_int(self.min_latency_us, self.max_latency_us)
        self.queue.add_after(delay, lambda: self.nodes[dest].handle(
            packet, self._client_reply))
        # note: node->node packets never need client_reply, but txn packets
        # delivered via submit() do

    def _deliver_client(self, packet: dict) -> None:
        self.client_replies.append(packet)
        handler = self._reply_handlers.pop(packet["body"].get("in_reply_to"), None)
        if handler is not None:
            handler(packet)

    def _client_reply(self, request_packet: dict, body: dict) -> None:
        self._next_client_msg[0] += 1
        body = dict(body)
        body["msg_id"] = self._next_client_msg[0]
        if "msg_id" in request_packet["body"]:
            body["in_reply_to"] = request_packet["body"]["msg_id"]
        self._route({"src": request_packet["dest"], "dest": request_packet["src"],
                     "body": body})

    # -- clients -------------------------------------------------------------
    def submit_txn(self, to: str, ops: List[list], msg_id: int,
                   on_reply: Callable[[dict], None]) -> None:
        self._reply_handlers[msg_id] = on_reply
        packet = {"src": "c1", "dest": to,
                  "body": {"type": "txn", "msg_id": msg_id, "txn": ops}}
        delay = self.rng.next_int(self.min_latency_us, self.max_latency_us)
        self.queue.add_after(delay, lambda: self.nodes[to].handle(
            packet, self._client_reply))

    # -- execution -----------------------------------------------------------
    def run_until(self, predicate: Callable[[], bool],
                  max_tasks: int = 1_000_000) -> bool:
        n = 0
        while n < max_tasks:
            if predicate():
                return True
            task = self.queue.pop()
            if task is None:
                return predicate()
            task()
            n += 1
            if self.errors:
                raise self.errors[0]
        return predicate()


def run_workload(seed: int, n_nodes: int = 3, ops: int = 50,
                 partition_interval_s: Optional[float] = 2.0,
                 check: bool = True) -> Dict:
    """Seeded list-append workload (SimpleRandomTest): every txn must eventually
    get txn_ok (retrying on error/timeout), and every read must observe a
    prefix-consistent list per key.

    With ``check=True`` the adapter also records the full client-visible
    history (every attempt: an errored attempt may still have committed, so
    it is recorded as an info op and the retry uses FRESH values — reusing
    values would break the unique-write attribution the checker relies on),
    reads back every touched key for an authoritative final state, and runs
    the protocol-blind oracle (observe/checker.py) over it — the Elle-style
    cross-check of the Maelstrom path (ROADMAP item 4d)."""
    from ..observe.checker import check_history
    from ..observe.history import HistoryRecorder
    cluster = MaelstromCluster(n_nodes, seed=seed,
                               partition_interval_s=partition_interval_s)
    rng = RandomSource(seed * 31 + 1)
    history = HistoryRecorder()
    results: Dict[int, dict] = {}
    state = {"msg": 0, "done": 0, "submitted": 0, "val": 0}
    pending: Dict[int, tuple] = {}
    touched: set = set()

    def submit(op_id: int, shape: List[tuple], attempt: int = 0) -> None:
        state["msg"] += 1
        msg_id = state["msg"]
        ops_list: List[list] = []
        reads: List[int] = []
        writes: Dict[int, list] = {}
        for kind, key in shape:
            if kind == "r":
                ops_list.append(["r", key, None])
                reads.append(key)
            else:
                state["val"] += 1
                v = state["val"]
                ops_list.append(["append", key, v])
                writes.setdefault(key, []).append(v)
                touched.add(key)
        pending[msg_id] = (op_id, shape, attempt)
        hid = (op_id, attempt)
        history.invoke(hid, None, cluster.queue.now_micros,
                       tuple(reads), writes)

        def handler(packet: dict, _msg_id=msg_id, _hid=hid,
                    _writes=writes) -> None:
            op_id2, shape2, attempt2 = pending.pop(_msg_id)
            body = packet["body"]
            now = cluster.queue.now_micros
            if body["type"] == "txn_ok":
                observed = {k: tuple(v or ()) for op, k, v in body["txn"]
                            if op == "r"}
                history.resolve(_hid, "ok", now, reads=observed,
                                writes=_writes)
                results[op_id2] = body
                state["done"] += 1
            else:
                # outcome unknown — the txn may still have committed: an
                # info op, then retry on a (possibly different) node with
                # fresh values — client-side liveness (ListRequest retry
                # semantics)
                history.resolve(_hid, "lost", now)
                submit(op_id2, shape2, attempt2 + 1)

        to = f"n{1 + rng.next_int(n_nodes)}"
        cluster.submit_txn(to, ops_list, msg_id, handler)

    for i in range(ops):
        key = rng.next_int(8)
        shape: List[tuple] = []
        if rng.next_boolean():
            shape.append(("r", key))
        shape.append(("append", key))
        if rng.next_float() < 0.3:
            shape.append(("append", rng.next_int(8)))
        submit(i, shape)
        state["submitted"] += 1

    ok = cluster.run_until(lambda: state["done"] >= ops, max_tasks=3_000_000)
    assert ok, f"only {state['done']}/{ops} maelstrom txns completed"

    # prefix consistency per key across all observed reads
    longest: Dict[int, list] = {}
    for op_id in sorted(results):
        for op, key, value in results[op_id]["txn"]:
            if op != "r":
                continue
            prev = longest.setdefault(key, [])
            shorter, longer = sorted([prev, value], key=len)
            assert longer[: len(shorter)] == shorter, \
                f"non-prefix reads on {key}: {prev} vs {value}"
            longest[key] = longer
    out = {"ok": state["done"],
           "reads_checked": sum(len(v) for v in longest.values())}

    if check:
        # authoritative final state: read back every touched key (retrying
        # through partitions), then hand the whole history to the oracle
        final_state: Dict[int, tuple] = {}

        def read_back(key: int, attempt: int = 0) -> None:
            state["msg"] += 1
            msg_id = state["msg"]
            hid = ("final", key, attempt)
            history.invoke(hid, None, cluster.queue.now_micros, (key,), None)

            def handler(packet: dict, _hid=hid, _key=key,
                        _attempt=attempt) -> None:
                body = packet["body"]
                now = cluster.queue.now_micros
                if body["type"] == "txn_ok":
                    val = tuple(body["txn"][0][2] or ())
                    history.resolve(_hid, "ok", now, reads={_key: val})
                    final_state[_key] = val
                else:
                    history.resolve(_hid, "lost", now)
                    read_back(_key, _attempt + 1)

            to = f"n{1 + rng.next_int(n_nodes)}"
            cluster.submit_txn(to, [["r", key, None]], msg_id, handler)

        for key in sorted(touched):
            read_back(key)
        ok2 = cluster.run_until(
            lambda: len(final_state) >= len(touched), max_tasks=3_000_000)
        assert ok2, f"final-state read-back stalled: " \
                    f"{len(final_state)}/{len(touched)} keys"
        report = check_history(history.ops, final_state=final_state)
        out["history_ops"] = len(history)
        out["final_keys"] = len(final_state)
        out["history"] = {k: report[k] for k in ("ops", "ok", "keys", "edges")}
    return out
