"""Maelstrom node core: the protocol adapter between Maelstrom JSON packets and
the accord Node, independent of transport (stdio Main and the in-process Runner
both drive it).

Capability parity with ``accord-maelstrom`` Main/MaelstromRequest/TopologyFactory
(Main.java:60-244, MaelstromRequest.java, TopologyFactory.java): ``init`` builds the
Node with a static topology computed from the node list; ``txn`` bodies carry
Maelstrom micro-op lists (``[["r", k, null], ["append", k, v]]`` — the list-append
workload) executed as one accord transaction; accord's own wire messages travel
wrapped in ``accord``/``accord_reply`` bodies via the codec.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..api.interfaces import Agent, ConfigurationService, MessageSink, Scheduler
from ..impl.list_store import (ListData, ListQuery, ListRead, ListResult,
                               ListStore, ListUpdate)
from ..local.node import Node
from ..primitives.keys import IntKey, Keys, Range, SentinelKey
from ..primitives.txn import Txn
from ..topology.topology import Shard, Topology
from ..utils import async_ as au
from ..utils.random import RandomSource
from . import codec

MULTI = "$multi"


def node_num(name: str) -> int:
    """Maelstrom node id ("n3") -> accord node id (3).  Arbitrary names map via
    crc32 (process-stable; Python's str hash is salted per process)."""
    import zlib
    stripped = name.lstrip("n")
    return int(stripped) if stripped.isdigit() \
        else (zlib.crc32(name.encode()) % 10**6) + 10**6


class TopologyFactory:
    """Static topology from the init node list (TopologyFactory.java): the int
    key space split contiguously into one shard per node, each replicated rf-way
    (simplification of the reference's hash-split; same shard/replica shape)."""

    @staticmethod
    def build(node_names: List[str], rf: Optional[int] = None,
              key_bound: int = 1 << 16) -> Topology:
        ids = sorted(node_num(n) for n in node_names)
        n = len(ids)
        rf = rf if rf is not None else min(3, n)
        shards = []
        lo = SentinelKey.min(0)
        for i in range(n):
            hi = SentinelKey.max(0) if i == n - 1 \
                else IntKey(((i + 1) * key_bound) // n)
            replicas = [ids[(i + j) % n] for j in range(rf)]
            shards.append(Shard(Range(lo, hi), replicas))
            lo = hi
        return Topology(1, shards)


class StaticConfigService(ConfigurationService):
    """SimpleConfigService: one static topology, everyone synced."""

    def __init__(self, topology: Topology, node_id: int, peers: List[int],
                 send_sync: Callable[[int, int], None]):
        self.topology = topology
        self.node_id = node_id
        self.peers = peers
        self.send_sync = send_sync
        self.listeners: List[ConfigurationService.Listener] = []

    def register_listener(self, listener) -> None:
        self.listeners.append(listener)

    def current_topology(self) -> Topology:
        return self.topology

    def get_topology_for_epoch(self, epoch: int) -> Optional[Topology]:
        return self.topology if epoch == self.topology.epoch else None

    def fetch_topology_for_epoch(self, epoch: int) -> None:
        pass

    def acknowledge_epoch(self, ready, start_sync: bool) -> None:
        for peer in self.peers:
            self.send_sync(peer, ready.epoch)


class MaelstromAgent(Agent):
    def __init__(self, on_error: Callable[[BaseException], None]):
        self._on_error = on_error

    def on_uncaught_exception(self, failure: BaseException) -> None:
        self._on_error(failure)

    def on_handled_exception(self, failure: BaseException) -> None:
        pass


def parse_txn(ops: List[list]) -> Tuple[Txn, List[list]]:
    """Build an accord Txn from Maelstrom micro-ops.  Multiple appends to one
    key coalesce into one tagged multi-value (flattened again in replies)."""
    reads: List[IntKey] = []
    appends: Dict[IntKey, list] = {}
    for op, key, value in ops:
        k = IntKey(int(key))
        if op == "r":
            if k not in reads:
                reads.append(k)
        elif op == "append":
            appends.setdefault(k, []).append(value)
        else:
            raise ValueError(f"unsupported op {op!r}")
    upd = {k: (v[0] if len(v) == 1 else [MULTI] + v) for k, v in appends.items()}
    all_keys = Keys.of(list(reads) + list(upd.keys()))
    txn = Txn.of(all_keys, ListRead(Keys.of(reads)),
                 ListUpdate(upd) if upd else None, ListQuery())
    return txn, ops


def flatten(values: tuple) -> list:
    out = []
    for v in values:
        if isinstance(v, (list, tuple)) and len(v) > 0 and v[0] == MULTI:
            out.extend(v[1:])
        else:
            out.append(v)
    return out


def fill_results(ops: List[list], result: ListResult) -> List[list]:
    """Fill the read ops with observed values (MaelstromReply txn_ok body).
    Reads report the pre-transaction state, appends are echoed as-is — exactly
    the reference's reply shape (MaelstromReply.writeBody)."""
    out = []
    for op, key, value in ops:
        if op == "r":
            got = flatten(result.reads.get(IntKey(int(key)), ()))
            out.append(["r", key, got])
        else:
            out.append([op, key, value])
    return out


class PacketSink(MessageSink):
    """MessageSink over Maelstrom packets (StdoutSink, Main.java:86-143):
    requests carry a fresh ``amsg_id`` for reply correlation; callbacks time out
    after ``timeout_s`` (swept by the transport's scheduler)."""

    def __init__(self, me: str, emit: Callable[[dict], None],
                 now_s: Callable[[], float], timeout_s: float = 1.0):
        self.me = me
        self.emit = emit
        self.now_s = now_s
        self.timeout_s = timeout_s
        self.next_id = 0
        self.callbacks: Dict[int, Tuple[object, float]] = {}

    def _packet(self, to: int, body: dict) -> dict:
        return {"src": self.me, "dest": f"n{to}", "body": body}

    def send(self, to: int, request) -> None:
        self.next_id += 1
        self.emit(self._packet(to, {"type": "accord", "amsg_id": self.next_id,
                                    "payload": codec.encode_message(request)}))

    def send_with_callback(self, to: int, request, callback) -> None:
        self.next_id += 1
        self.callbacks[self.next_id] = (callback, self.now_s() + self.timeout_s, to)
        self.emit(self._packet(to, {"type": "accord", "amsg_id": self.next_id,
                                    "payload": codec.encode_message(request)}))

    def reply(self, to: int, reply_context, reply) -> None:
        from ..messages.base import LOCAL_NO_REPLY
        if reply_context is LOCAL_NO_REPLY:
            return   # self-delivered local request (Propagate): no reply
        amsg_id = reply_context
        self.emit(self._packet(to, {"type": "accord_reply", "in_reply_to_a": amsg_id,
                                    "payload": codec.encode_message(reply)}))

    def deliver_reply(self, from_node: int, amsg_id: int, reply) -> None:
        entry = self.callbacks.get(amsg_id)
        if entry is None:
            return
        callback = entry[0]
        if getattr(reply, "is_final", True):
            del self.callbacks[amsg_id]
        from ..messages.base import FailureReply
        if isinstance(reply, FailureReply):
            callback.on_failure(from_node, reply.failure)
        else:
            callback.on_success(from_node, reply)

    def sweep_timeouts(self) -> None:
        now = self.now_s()
        for amsg_id in [i for i, e in self.callbacks.items() if e[1] <= now]:
            callback, _deadline, to = self.callbacks.pop(amsg_id)
            callback.on_failure(to, TimeoutError(f"no reply to {amsg_id}"))


class MaelstromNode:
    """One Maelstrom process: wires Node + ListStore + PacketSink and handles
    every packet type."""

    def __init__(self, name: str, node_names: List[str],
                 emit: Callable[[dict], None], scheduler: Scheduler,
                 now_micros: Callable[[], int],
                 on_error: Optional[Callable[[BaseException], None]] = None,
                 rf: Optional[int] = None):
        self.name = name
        self.id = node_num(name)
        self.errors: List[BaseException] = []
        self.scheduler = scheduler

        def emit_or_loopback(packet: dict) -> None:
            if packet["dest"] == name:
                # self-sends dispatch in-process, not over the wire
                scheduler.now(lambda: self.handle(packet, lambda *_: None))
            else:
                emit(packet)

        self.sink = PacketSink(name, emit_or_loopback, lambda: now_micros() / 1e6)
        topology = TopologyFactory.build(node_names, rf=rf)
        peers = sorted(node_num(n) for n in node_names if n != name)
        self.store = ListStore(self.id)
        config = StaticConfigService(topology, self.id, peers, self._send_sync)
        self.node = Node(self.id, self.sink, config,
                         MaelstromAgent(on_error or self.errors.append),
                         scheduler, self.store, RandomSource(self.id),
                         now_micros=now_micros)
        scheduler.recurring(0.25, self.sink.sweep_timeouts)

    def _send_sync(self, peer: int, epoch: int) -> None:
        self.sink.emit(self.sink._packet(peer, {"type": "accord_sync",
                                                "epoch": epoch}))

    # -- packet handling (Main.java:207-232) ---------------------------------
    def handle(self, packet: dict, client_reply: Callable[[dict, dict], None]) -> None:
        body = packet["body"]
        btype = body.get("type")
        if btype == "txn":
            self._handle_txn(packet, body, client_reply)
        elif btype == "accord":
            request = codec.decode_message(body["payload"])
            self.node.receive(request, node_num(packet["src"]), body["amsg_id"])
        elif btype == "accord_reply":
            reply = codec.decode_message(body["payload"])
            self.sink.deliver_reply(node_num(packet["src"]),
                                    body["in_reply_to_a"], reply)
        elif btype == "accord_sync":
            self.node.on_remote_sync_complete(node_num(packet["src"]), body["epoch"])
        elif btype in ("init", "init_ok"):
            pass  # init handled by the transport constructing this object
        else:
            client_reply(packet, {"type": "error", "code": 10,
                                  "text": f"unsupported {btype}"})

    def _handle_txn(self, packet: dict, body: dict,
                    client_reply: Callable[[dict, dict], None]) -> None:
        try:
            txn, ops = parse_txn(body["txn"])
        except Exception as e:  # noqa: BLE001
            client_reply(packet, {"type": "error", "code": 12, "text": str(e)})
            return

        def on_done(value, failure):
            if failure is not None or not isinstance(value, ListResult):
                client_reply(packet, {"type": "error", "code": 11,
                                      "text": f"txn failed: {failure}"})
            else:
                client_reply(packet, {"type": "txn_ok",
                                      "txn": fill_results(ops, value)})

        self.node.coordinate(txn).add_listener(on_done)
