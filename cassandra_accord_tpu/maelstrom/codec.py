"""JSON wire codec for every accord message and primitive.

Capability parity with ``accord-maelstrom``'s ``Json.java`` (Json.java:1-300+, the
reference's only complete serialization codec): every Request/Reply and every
primitive they carry (timestamps, txn ids, keys/ranges/routes, deps, txn bodies,
writes, durability maps) round-trips through JSON for the Maelstrom stdio protocol.

Design: instead of a hand-written adapter per type (GSON-style), a single tagged
recursive codec over ``__slots__`` state, with a registry of serializable classes.
Containers and numpy arrays are tagged; enums encode by value; caches are skipped
and rebuilt lazily after decode.
"""
from __future__ import annotations

import enum
import json
from typing import Any, Dict, Tuple, Type

import numpy as np

_CLASSES: Dict[str, Tuple[Type, Tuple[str, ...]]] = {}
# derived caches: never on the wire; rebuilt at decode (None for the lazy
# ones, __wire_rebuild__ for the eager ones like Timestamp._k)
_SKIP_SLOTS = {"_inverted", "_k", "_kind_c", "_memo", "_h", "_tk", "_rk"}


def _all_slots(cls: Type) -> Tuple[str, ...]:
    out = []
    for klass in reversed(cls.__mro__):
        for s in getattr(klass, "__slots__", ()):
            if s not in out and s not in _SKIP_SLOTS:
                out.append(s)
    return tuple(out)


def register(cls: Type) -> Type:
    _CLASSES[cls.__name__] = (cls, _all_slots(cls))
    return cls


def _register_all() -> None:
    from ..api import interfaces as api
    from ..impl import list_store as ls
    from ..impl import noop_execution as noop
    from ..local import durability as dur
    from ..local.status import Durability, SaveStatus, Status
    from ..local import commands as C
    from ..messages import base as mb
    from ..messages import deps_messages as gdm
    from ..messages import durability_messages as dm
    from ..messages import ephemeral_messages as em
    from ..messages import fetch_messages as fm
    from ..messages import recovery_messages as rm
    from ..messages import status_messages as sm
    from ..messages import txn_messages as tm
    from ..primitives import deps as d
    from ..primitives import keys as k
    from ..primitives import latest_deps as ld
    from ..primitives import route as r
    from ..primitives import sync_point as spp
    from ..primitives import timestamp as t
    from ..primitives import txn as tx
    from ..utils.interval_map import ReducingIntervalMap

    for mod, names in (
        (t, ["Timestamp", "TxnId", "Ballot"]),
        (k, ["IntKey", "SentinelKey", "_Successor", "Range", "Keys",
             "RoutingKeys", "Ranges"]),
        (r, ["Route"]),
        (d, ["KeyDeps", "RangeDeps", "Deps"]),
        (ld, ["LatestDeps", "LatestEntry"]),
        (gdm, ["GetDeps", "GetDepsOk", "GetMaxConflict", "GetMaxConflictOk"]),
        (tx, ["Txn", "PartialTxn", "Writes"]),
        (spp, ["SyncPoint"]),
        (ls, ["ListRead", "ListRangeRead", "ListUpdate", "ListWrite",
              "ListQuery", "ListData", "ListResult"]),
        (noop, ["NoopRead", "NoopQuery", "NoopData", "NoopResult"]),
        (dur, ["RedundantBefore", "DurableBefore"]),
        (mb, ["FailureReply"]),
        (tm, ["SimpleOk", "PreAcceptOk", "PreAcceptNack", "AcceptOk", "AcceptNack",
              "CommitOk", "StableAck", "CommitNack", "ReadOk", "ReadNack",
              "ApplyOk", "PreAccept", "Accept", "Commit", "ReadTxnData", "Apply",
              "WaitUntilApplied"]),
        (rm, None),
        (sm, ["CheckStatusOk", "CheckStatus", "InformOfTxn", "InformDurable",
              "InformHomeDurable", "Propagate", "FindRoute", "FindRouteOk"]),
        (dm, ["SetShardDurable", "SetGloballyDurable", "DurableBeforeReply",
              "QueryDurableBefore"]),
        (em, ["GetEphemeralReadDepsOk", "GetEphemeralReadDeps",
              "ReadEphemeralTxnData"]),
        (fm, ["FetchStoreDataOk", "FetchStoreData"]),
    ):
        if names is None:
            # register every public class in the module
            names = [n for n in dir(mod)
                     if isinstance(getattr(mod, n), type) and not n.startswith("_")
                     and getattr(getattr(mod, n), "__module__", None) == mod.__name__]
        for name in names:
            cls = getattr(mod, name, None)
            if cls is not None:
                register(cls)

    from ..local.cfk import InternalStatus
    from ..primitives.latest_deps import KnownDeps
    for e in (t.TxnKind, t.Domain, SaveStatus, Status, Durability,
              C.AcceptOutcome, C.CommitOutcome, InternalStatus, KnownDeps):
        _CLASSES[e.__name__] = (e, ())

    # ReducingIntervalMap + DurableEntry/RedundantEntry (NamedTuples)
    register(ReducingIntervalMap)
    _CLASSES["DurableEntry"] = (dur.DurableEntry, ())
    _CLASSES["RedundantEntry"] = (dur.RedundantEntry, ())


def encode_value(obj: Any):
    if isinstance(obj, enum.Enum):
        # BEFORE the primitive branch: IntEnums (TxnKind, InternalStatus) are
        # ints and would otherwise lose their type on the wire.  By NAME:
        # enum values may be arbitrary tuples (SaveStatus ordinal+status)
        return {"$": type(obj).__name__, "v": obj.name, "e": 1}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, np.ndarray):
        return {"$": "nd", "dt": str(obj.dtype), "v": obj.tolist()}
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):    # NamedTuple
        return {"$": type(obj).__name__, "nt": 1,
                "v": [encode_value(x) for x in obj]}
    if isinstance(obj, list):
        return {"$": "L", "v": [encode_value(x) for x in obj]}
    if isinstance(obj, tuple):
        return {"$": "T", "v": [encode_value(x) for x in obj]}
    if isinstance(obj, (set, frozenset)):
        return {"$": "S", "v": [encode_value(x) for x in obj]}
    if isinstance(obj, dict):
        return {"$": "D", "v": [[encode_value(k), encode_value(v)]
                                for k, v in obj.items()]}
    if isinstance(obj, BaseException):
        return {"$": "exc", "t": type(obj).__name__, "m": str(obj)}
    name = type(obj).__name__
    if name not in _CLASSES:
        raise TypeError(f"unregistered wire type: {name}")
    _cls, slots = _CLASSES[name]
    out = {"$": name}
    for s in slots:
        out[s] = encode_value(getattr(obj, s))
    # plain-__dict__ classes (and mixed slots+dict)
    for s, v in getattr(obj, "__dict__", {}).items():
        if s not in out and s not in _SKIP_SLOTS:
            out[s] = encode_value(v)
    return out


def decode_value(obj: Any):
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, list):
        return [decode_value(x) for x in obj]
    assert isinstance(obj, dict), obj
    tag = obj["$"]
    if tag == "L":
        return [decode_value(x) for x in obj["v"]]
    if tag == "T":
        return tuple(decode_value(x) for x in obj["v"])
    if tag == "S":
        return set(decode_value(x) for x in obj["v"])
    if tag == "D":
        return {decode_value(k): decode_value(v) for k, v in obj["v"]}
    if tag == "nd":
        return np.asarray(obj["v"], dtype=obj["dt"])
    if tag == "exc":
        return RuntimeError(f"{obj['t']}: {obj['m']}")
    cls, slots = _CLASSES[tag]
    if obj.get("e"):
        return cls[obj["v"]]
    if obj.get("nt"):
        return cls(*[decode_value(x) for x in obj["v"]])
    inst = cls.__new__(cls)
    for s, v in obj.items():
        if s in ("$", "e", "nt"):
            continue
        setattr(inst, s, decode_value(v))
    for s in _SKIP_SLOTS:
        if s in getattr(cls, "__slots__", ()) or any(
                s in getattr(k, "__slots__", ()) for k in cls.__mro__):
            try:
                setattr(inst, s, None)
            except AttributeError:
                pass
    rebuild = getattr(inst, "__wire_rebuild__", None)
    if rebuild is not None:
        rebuild()
    return inst


def encode_message(message) -> dict:
    return encode_value(message)


def decode_message(payload: dict):
    return decode_value(payload)


def dumps(message) -> str:
    return json.dumps(encode_message(message), separators=(",", ":"))


def loads(s: str):
    return decode_message(json.loads(s))


_register_all()
