"""Maelstrom stdio node binary.

Capability parity with ``accord-maelstrom``'s ``Main`` (Main.java:60-244): reads
JSON packets from stdin, answers ``init`` with ``init_ok``, then serves ``txn``
client bodies and accord wrapper messages until EOF.  Run under the Maelstrom
workbench as::

    maelstrom test -w txn-list-append --bin ./maelstrom-node ...

where ``maelstrom-node`` execs ``python -m cassandra_accord_tpu.maelstrom``.

Real time drives the scheduler: timers are serviced between stdin lines (stdin
reads use a small poll timeout so timeouts/retries fire while idle).
"""
from __future__ import annotations

import heapq
import json
import select
import sys
import time
from typing import Callable, List, Optional

from ..api.interfaces import Scheduler
from .node import MaelstromNode


class RealTimeScheduler(Scheduler):
    """Monotonic-clock task queue serviced by the stdio loop."""

    def __init__(self):
        self.heap: List = []
        self.seq = 0

    def _push(self, at: float, run: Callable[[], None], interval: Optional[float],
              state: Optional[dict] = None):
        self.seq += 1
        state = state if state is not None else {"cancelled": False}
        heapq.heappush(self.heap, [at, self.seq, run, interval, state])

        class _S(Scheduler.Scheduled):
            def cancel(self_inner):
                state["cancelled"] = True
        return _S()

    def once(self, delay_s: float, run: Callable[[], None]):
        return self._push(time.monotonic() + delay_s, run, None)

    def recurring(self, interval_s: float, run: Callable[[], None]):
        return self._push(time.monotonic() + interval_s, run, interval_s)

    def now(self, run: Callable[[], None]):
        return self._push(time.monotonic(), run, None)

    def service(self) -> float:
        """Run everything due; return seconds until the next task (or 0.2)."""
        while self.heap and self.heap[0][0] <= time.monotonic():
            at, _seq, run, interval, state = heapq.heappop(self.heap)
            if state["cancelled"]:
                continue
            if interval is not None:
                # re-arm sharing the SAME cancellation state: the handle
                # returned at registration keeps working after every fire
                self._push(time.monotonic() + interval, run, interval, state)
            run()
        if not self.heap:
            return 0.2
        return max(0.0, min(0.2, self.heap[0][0] - time.monotonic()))


def emit(packet: dict) -> None:
    sys.stdout.write(json.dumps(packet, separators=(",", ":")) + "\n")
    sys.stdout.flush()


def main() -> None:
    import os
    scheduler = RealTimeScheduler()
    node: Optional[MaelstromNode] = None
    next_msg_id = [0]

    def client_reply(packet: dict, body: dict) -> None:
        next_msg_id[0] += 1
        body = dict(body)
        body["msg_id"] = next_msg_id[0]
        if "msg_id" in packet["body"]:
            body["in_reply_to"] = packet["body"]["msg_id"]
        emit({"src": packet["dest"], "dest": packet["src"], "body": body})

    def handle_line(line: str) -> None:
        nonlocal node
        packet = json.loads(line)
        body = packet.get("body", {})
        if body.get("type") == "init":
            node = MaelstromNode(
                body["node_id"], body["node_ids"], emit, scheduler,
                now_micros=lambda: int(time.time() * 1e6))
            client_reply(packet, {"type": "init_ok"})
        elif node is not None:
            node.handle(packet, client_reply)
        else:
            client_reply(packet, {"type": "error", "code": 10,
                                  "text": "not initialised"})

    # raw non-blocking reads + own line buffer: several lines can arrive in one
    # read, and buffered readline + select would strand all but the first
    fd = sys.stdin.fileno()
    os.set_blocking(fd, False)
    buf = b""
    eof = False
    while not eof:
        timeout = scheduler.service()
        ready, _, _ = select.select([fd], [], [], timeout)
        if not ready:
            continue
        try:
            chunk = os.read(fd, 1 << 16)
        except BlockingIOError:
            continue
        if chunk == b"":
            eof = True
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            line = line.strip()
            if line:
                handle_line(line.decode())
        if eof and buf.strip():
            # final line without a trailing newline still counts
            handle_line(buf.strip().decode())
            buf = b""


if __name__ == "__main__":
    main()
