"""Maelstrom (Jepsen workbench) adapter: JSON codec for all wire types, stdio
node binary, and an in-process simulator with partitions (accord-maelstrom)."""
from . import codec
from .node import MaelstromNode, TopologyFactory, parse_txn
from .runner import MaelstromCluster, run_workload

__all__ = ["codec", "MaelstromNode", "TopologyFactory", "parse_txn",
           "MaelstromCluster", "run_workload"]
