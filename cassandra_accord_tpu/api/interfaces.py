"""SPI interface definitions (abstract base classes).

Each mirrors a reference interface; file:line citations point at the contract source:
- Agent                   accord/api/Agent.java:34-97
- DataStore               accord/api/DataStore.java:39-113
- MessageSink             accord/api/MessageSink.java
- ConfigurationService    accord/api/ConfigurationService.java:60-183
- ProgressLog             accord/api/ProgressLog.java:59-213
- Scheduler               accord/api/Scheduler.java
- Read/Update/Query/...   accord/api/{Read,Update,Query,Write,Data,Result}.java
- TopologySorter          accord/api/TopologySorter.java
- EventsListener          accord/api/EventsListener.java:26-60
- BarrierType             accord/api/BarrierType.java
- LocalConfig             accord/config/LocalConfig.java
"""
from __future__ import annotations

import abc
import enum
from typing import TYPE_CHECKING, Callable, Iterable, List, Optional

if TYPE_CHECKING:
    from ..primitives.keys import Key, Keys, Ranges, RoutingKey
    from ..primitives.timestamp import Timestamp, TxnId
    from ..utils.async_ import AsyncChain, AsyncResult


class Agent(abc.ABC):
    """Policy + failure callbacks injected into the Node."""

    def on_recover(self, node, success, fail) -> None:
        pass

    def on_inconsistent_timestamp(self, command, prev: "Timestamp", next_: "Timestamp") -> None:
        raise AssertionError(f"inconsistent timestamp on {command}: {prev} vs {next_}")

    def on_failed_bootstrap(self, phase: str, ranges: "Ranges", retry: Callable[[], None],
                            failure: BaseException) -> None:
        retry()

    def on_stale(self, stale_since: "Timestamp", ranges: "Ranges") -> None:
        pass

    def on_uncaught_exception(self, failure: BaseException) -> None:
        raise failure

    def on_handled_exception(self, failure: BaseException) -> None:
        pass

    def pre_accept_timeout(self) -> float:
        """Seconds a coordinator waits for PreAccept before invalidating."""
        return 1.0

    def cfk_hlc_prune_delta(self) -> int:
        """How far behind the max HLC a CommandsForKey entry must be to prune."""
        return 1000

    def cfk_prune_interval(self) -> int:
        return 32

    def is_expired(self, initiated_micros: int, now_micros: int) -> bool:
        return now_micros - initiated_micros > int(self.pre_accept_timeout() * 1_000_000)

    def empty_system_txn(self, kind, keys_or_ranges):
        """An empty Txn of the given kind (used by sync points)."""
        from ..primitives.txn import Txn
        return Txn.empty(kind, keys_or_ranges)

    def metrics_events_listener(self) -> "EventsListener":
        return EventsListener.NOOP


class EventsListener:
    """Metrics hooks (EventsListener.java:26-60)."""

    NOOP: "EventsListener"

    def on_committed(self, command) -> None: ...
    def on_stable(self, command) -> None: ...
    def on_executed(self, command) -> None: ...
    def on_applied(self, command, t0_micros: int) -> None: ...
    def on_fast_path_taken(self, txn_id, deps) -> None: ...
    def on_slow_path_taken(self, txn_id, deps) -> None: ...
    def on_recover(self, txn_id, ballot) -> None: ...
    def on_preempted(self, txn_id) -> None: ...
    def on_timeout(self, txn_id) -> None: ...


EventsListener.NOOP = EventsListener()


class Data(abc.ABC):
    """Result of reading one or more keys; mergeable (Data.java)."""

    @abc.abstractmethod
    def merge(self, other: "Data") -> "Data": ...


class Result:
    """Opaque client-visible txn result (Result.java)."""


class Write(abc.ABC):
    """The computed effect of an Update on one key (Write.java)."""

    @abc.abstractmethod
    def apply(self, store: "DataStore", key, execute_at: "Timestamp") -> "AsyncChain":
        ...

    def merge(self, other: "Write") -> "Write":
        """Union of two per-shard slices of the same txn's write effect.
        Implementations whose slices can differ MUST override; silently keeping
        one slice would lose the other's effects."""
        if other is not self and other is not None:
            raise NotImplementedError(
                f"{type(self).__name__}.merge: per-shard write slices cannot be "
                "combined without an implementation-specific merge")
        return self


class Read(abc.ABC):
    """Read hook (Read.java): executed replica-side at executeAt."""

    @abc.abstractmethod
    def keys(self):
        """Seekables this read touches."""

    @abc.abstractmethod
    def read(self, key, safe_store, execute_at: "Timestamp", data_store: "DataStore") -> "AsyncChain[Data]":
        ...

    @abc.abstractmethod
    def slice(self, ranges: "Ranges") -> "Read": ...

    @abc.abstractmethod
    def merge(self, other: "Read") -> "Read": ...


class Update(abc.ABC):
    """Update hook (Update.java): turns read Data into Writes at execution time."""

    @abc.abstractmethod
    def keys(self): ...

    @abc.abstractmethod
    def apply(self, execute_at: "Timestamp", data: Optional[Data]) -> "Write | dict":
        """Compute per-key writes from the read data."""

    @abc.abstractmethod
    def slice(self, ranges: "Ranges") -> "Update": ...

    @abc.abstractmethod
    def merge(self, other: "Update") -> "Update": ...


class Query(abc.ABC):
    """Computes the client Result from read Data (Query.java)."""

    @abc.abstractmethod
    def compute(self, txn_id: "TxnId", execute_at: "Timestamp", keys,
                data: Optional[Data], read: Optional[Read], update: Optional[Update]) -> Result:
        ...


class FetchRanges(abc.ABC):
    """Callbacks a DataStore.fetch implementation drives (DataStore.java:39-113)."""

    @abc.abstractmethod
    def starting(self, ranges: "Ranges"):
        """Declare a fetch of ``ranges`` is starting; returns a StartingRangeFetch
        handle with started()/cancel() controls."""

    @abc.abstractmethod
    def fetched(self, ranges: "Ranges") -> None: ...

    @abc.abstractmethod
    def fail(self, ranges: "Ranges", failure: BaseException) -> None: ...


class DataStore(abc.ABC):
    """Storage hook; also the bootstrap fetch API."""

    class FetchResult:
        """AsyncResult of a fetch with abort()."""

    def fetch(self, node, safe_store, ranges: "Ranges", sync_point,
              fetch_ranges: FetchRanges, catch_up: bool = False):
        """Fetch data for newly-adopted ranges up to ``sync_point``; default impl for
        in-memory stores completes immediately (harness ListStore overrides)."""
        raise NotImplementedError

    def snapshot(self, ranges: "Ranges", before) -> object:
        raise NotImplementedError


class MessageSink(abc.ABC):
    @abc.abstractmethod
    def send(self, to: int, request) -> None: ...

    @abc.abstractmethod
    def send_with_callback(self, to: int, request, callback) -> None: ...

    @abc.abstractmethod
    def reply(self, to: int, reply_context, reply) -> None: ...

    def reply_with_unknown_failure(self, to: int, reply_context, failure: BaseException) -> None:
        from ..messages.base import FailureReply
        self.reply(to, reply_context, FailureReply(failure))


class ProgressLog(abc.ABC):
    """Per-store liveness driver (ProgressLog.java:59-213). All callbacks are invoked
    from inside the owning CommandStore."""

    def unwitnessed(self, txn_id, home_key, progress_shard) -> None: ...
    def pre_accepted(self, command, progress_shard) -> None: ...
    def accepted(self, command, progress_shard) -> None: ...
    def precommitted(self, command) -> None: ...
    def stable(self, command, progress_shard) -> None: ...
    def ready_to_execute(self, command) -> None: ...
    def executed(self, command, progress_shard) -> None: ...
    def durable(self, command) -> None: ...
    def invalidated(self, command, progress_shard) -> None: ...
    def durable_global(self, txn_id, durability) -> None: ...
    def waiting(self, blocked_by, blocked_until, blocked_on_route, blocked_on_participants) -> None: ...
    def clear(self, txn_id) -> None: ...

    NOOP: "ProgressLog"


class _NoopProgressLog(ProgressLog):
    pass


ProgressLog.NOOP = _NoopProgressLog()


class Scheduler(abc.ABC):
    """Time-based callbacks (Scheduler.java). Times in seconds."""

    class Scheduled:
        def cancel(self) -> None: ...

    @abc.abstractmethod
    def once(self, delay_s: float, run: Callable[[], None]) -> "Scheduler.Scheduled": ...

    @abc.abstractmethod
    def recurring(self, interval_s: float, run: Callable[[], None]) -> "Scheduler.Scheduled": ...

    def now(self, run: Callable[[], None]) -> None:
        self.once(0.0, run)


class TopologySorter(abc.ABC):
    """Replica contact preference order (TopologySorter.java)."""

    @abc.abstractmethod
    def compare(self, a: int, b: int, shards) -> int: ...

    @staticmethod
    def identity():
        return _IdentitySorter()


class _IdentitySorter(TopologySorter):
    def compare(self, a: int, b: int, shards) -> int:
        return -1 if a < b else (1 if a > b else 0)


class BarrierType(enum.Enum):
    """BarrierType.java: local waits for any covering applied txn; global coordinates
    a SyncPoint (async returns before application, sync after)."""
    LOCAL = "local"
    GLOBAL_ASYNC = "global_async"
    GLOBAL_SYNC = "global_sync"

    @property
    def is_global(self) -> bool:
        return self is not BarrierType.LOCAL

    @property
    def wait_on_global_application(self) -> bool:
        return self is BarrierType.GLOBAL_SYNC


class ConfigurationService(abc.ABC):
    """Epoch/topology feed (ConfigurationService.java:60-183)."""

    class Listener(abc.ABC):
        def on_topology_update(self, topology, start_sync: bool) -> "AsyncResult":
            ...

        def on_remote_sync_complete(self, node_id: int, epoch: int) -> None: ...
        def truncate_topology_until(self, epoch: int) -> None: ...
        def on_epoch_closed(self, ranges: "Ranges", epoch: int) -> None: ...
        def on_epoch_redundant(self, ranges: "Ranges", epoch: int) -> None: ...

    @abc.abstractmethod
    def register_listener(self, listener: "ConfigurationService.Listener") -> None: ...

    @abc.abstractmethod
    def current_topology(self): ...

    def current_epoch(self) -> int:
        return self.current_topology().epoch

    @abc.abstractmethod
    def get_topology_for_epoch(self, epoch: int): ...

    @abc.abstractmethod
    def fetch_topology_for_epoch(self, epoch: int) -> None: ...

    def acknowledge_epoch(self, ready, start_sync: bool) -> None:
        pass

    def report_epoch_closed(self, ranges: "Ranges", epoch: int) -> None:
        pass

    def report_epoch_redundant(self, ranges: "Ranges", epoch: int) -> None:
        pass


class LocalConfig:
    """Epoch-fetch timeouts / watchdog intervals (config/LocalConfig.java)."""

    epoch_fetch_initial_timeout_s: float = 0.05
    epoch_fetch_increased_timeout_s: float = 1.0

    DEFAULT: "LocalConfig"


LocalConfig.DEFAULT = LocalConfig()
