"""The SPI boundary: interfaces the embedding system implements.

Capability parity with ``accord.api`` (SURVEY.md §2.2): storage, networking,
scheduling, progress, configuration and txn-execution hooks are all injected —
the protocol core never talks to a real network, disk, clock or thread pool directly.
This is the property that makes the deterministic simulation harness possible.
"""
from .interfaces import (
    Agent,
    BarrierType,
    ConfigurationService,
    Data,
    DataStore,
    EventsListener,
    FetchRanges,
    LocalConfig,
    MessageSink,
    ProgressLog,
    Query,
    Read,
    Result,
    Scheduler,
    TopologySorter,
    Update,
    Write,
)

__all__ = [
    "Agent", "BarrierType", "ConfigurationService", "Data", "DataStore",
    "EventsListener", "FetchRanges", "LocalConfig", "MessageSink", "ProgressLog",
    "Query", "Read", "Result", "Scheduler", "TopologySorter", "Update", "Write",
]
