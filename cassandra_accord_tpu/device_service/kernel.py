"""The service's fused ragged consult kernel (pre-transposed operands).

Same join semantics as ``ops.deps_kernels.consult`` — key-overlap matmul ×
started-before lex compare × kind-witness mask, plus the masked lex max for
the timestamp proposal — but consuming the DoubleBufferedIndex layout:

- incidence comes in PRE-TRANSPOSED and PRE-CAST ([K, T] in the matmul
  dtype): the one-shot kernel casts its int8 [T, K] operands per call, which
  at replay scale is a multi-GB cast PER CONSULT (the dominant term of the
  r05 wedge on the CPU backend, and wasted HBM bandwidth on the MXU);
- the ragged query batch densifies ON DEVICE: flat key columns + row ids +
  weights scatter into the [B, K] mask (weight 0 = padding, scatters
  nothing; duplicate keys accumulate >1, consumed only as nonzero).

Bit-identical answers to the one-shot kernel and the host tiers (the parity
property tests drive all of them over the same randomized ragged batches).
"""
from __future__ import annotations

from functools import partial

_CONSULT_T = None


def consult_t():
    """The jitted kernel (lazy: importing jax only when a dispatch happens)."""
    global _CONSULT_T
    if _CONSULT_T is not None:
        return _CONSULT_T

    import jax
    import jax.numpy as jnp
    from ..ops.deps_kernels import WITNESSES, _lex_max_masked
    from ..ops.graph_state import INVALIDATED, ts_less

    @partial(jax.jit, static_argnames=("packed",))
    def ragged_consult_t(live_T, key_T, ts, txn_id, kind, status, active,
                         flat_cols, row_ids, weights, before, qkind,
                         packed=False):
        b = before.shape[0]
        k, t = live_T.shape
        dt = live_T.dtype
        q = jnp.zeros((b, k), dtype=dt) \
            .at[row_ids, flat_cols].add(weights.astype(dt))
        dn = (((1,), (0,)), ((), ()))
        share_live = jax.lax.dot_general(
            q, live_T, dn, preferred_element_type=jnp.float32) > 0.0   # [B, T]
        share_full = jax.lax.dot_general(
            q, key_T, dn, preferred_element_type=jnp.float32) > 0.0    # [B, T]
        started = ts_less(txn_id[None, :, :], before[:, None, :])      # [B, T]
        wit = WITNESSES[qkind[:, None].astype(jnp.int32),
                        kind[None, :].astype(jnp.int32)]               # [B, T]
        eligible = active & (status != INVALIDATED)                    # [T]
        deps = share_live & started & wit & eligible[None, :]
        mc_mask = share_full & active[None, :]
        per_slot = jnp.where(ts_less(ts, txn_id)[:, None], txn_id, ts)  # [T,5]
        max_lanes = _lex_max_masked(
            jnp.broadcast_to(per_slot[None, :, :],
                             mc_mask.shape + (per_slot.shape[-1],)), mc_mask)
        if packed:
            # transfer-bound regime: bit-pack the deps mask before it leaves
            # HBM (8× smaller result; hosts unpack with np.unpackbits)
            bits = deps.reshape(b, t // 8, 8).astype(jnp.uint32)
            w8 = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], dtype=jnp.uint32)
            deps = jnp.sum(bits * w8, axis=-1).astype(jnp.uint8)
        return deps, max_lanes

    _CONSULT_T = ragged_consult_t
    return _CONSULT_T
