"""Device-resident conflict index with double-buffered incremental refresh.

The r05 replay post-mortem (the ``truncated_at_event: 36`` wedge): the
one-shot dispatch path (``TpuDepsResolver._sync_device``) re-uploaded the
WHOLE canonical index whenever ANY mutation had landed since the last
consult — on the protocol path mutations interleave with every query, so at
T=32k every consult paid a full host→device transfer of two T×K incidence
matrices plus, per capacity tier, a fresh XLA compile; and the kernel then
joined against the full CAPACITY extent even when a handful of txns were
live.  Measured: 2 queries in 263 s.

This module is the fix — the index lives ON the device persistently, in the
layout the consult kernel actually consumes, sized to what is actually
occupied:

- **Pre-transposed, pre-cast incidence**: ``live_T``/``key_T`` are [K, T]
  in the matmul dtype (bf16 on accelerators for the MXU; f32 on the CPU
  backend, where emulated-bf16 and the per-call int8 cast of a multi-GB
  operand are exactly what made one launch cost seconds).  The cast+
  transpose happens ONCE per refresh, not once per consult.
- **Occupancy views, not capacity**: slot allocation is min-heap ordered,
  so live rows/columns are a PREFIX of the arrays; buffers cover
  pow2-bucketed views of the high-watermark slot, and the join cost tracks
  what the index holds, not what it could hold.  The view widens by
  doubling (bounded compile variants); it never shrinks.
- **Double-buffered row refresh**: ``refresh`` builds the next buffer from
  the serving one by scattering only the dirty rows (``.at[...].set``),
  row-count padded to pow2 buckets, then swaps the front reference.  XLA
  dispatches the scatter asynchronously — the host never blocks on the
  update; a consult submitted right after queues behind it on the device
  stream.  An open batching window that pinned the OLD front (its
  submission-time snapshot) keeps it alive — that pinned-old / serving-new
  pair is the double buffer.
- Full uploads only when cheaper than row traffic (dirty fraction above
  ``full_fraction``) or when the view/capacity changed.

Everything here also runs on the CPU jax backend (tests, hostless CI): the
"device" is wherever jax put the buffers.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Set, Tuple

import numpy as np

from .batch import pow2_bucket

# row-refresh chunk cap: one refresh compiles at most log2(1024/8)+1 shape
# variants per view; a bigger dirty set loops over capped chunks (no new
# shapes) or tips into a full upload via full_fraction
ROW_REFRESH_FLOOR = 8
ROW_REFRESH_CAP = 1024

T_VIEW_FLOOR = 64
K_VIEW_FLOOR = 16

_ROW_FIELDS = ("ts", "txn_id", "kind", "status", "active")

_APPLY_ROWS = None


def _apply_rows_fn():
    import jax

    @jax.jit
    def apply_rows(bufs, rows, live_t, key_t, vals):
        out = {name: bufs[name].at[rows].set(vals[name])
               for name in _ROW_FIELDS}
        out["live_T"] = bufs["live_T"].at[:, rows].set(live_t)
        out["key_T"] = bufs["key_T"].at[:, rows].set(key_t)
        return out
    return apply_rows


def mm_dtype():
    """The matmul operand dtype: bf16 feeds the MXU on accelerators; the CPU
    backend emulates bf16 (measured ~6× slower than its native f32 GEMM), so
    tests and hostless runs use f32.  Results are identical — operands are
    0/1-ish counts consumed only as nonzero."""
    import jax
    import jax.numpy as jnp
    return jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16


class DoubleBufferedIndex:
    """The persistent device copy of one resolver's canonical host index."""

    def __init__(self, full_fraction: float = 0.25):
        self.front: Optional[Dict[str, object]] = None
        self.view: Tuple[int, int] = (0, 0)          # (t_view, k_view)
        self.full_fraction = full_fraction
        self.generation = 0
        # telemetry: refresh traffic + the jit-shape ledger (the bounded-
        # compilation contract both tests and the bench introspect)
        self.full_uploads = 0
        self.incremental_refreshes = 0
        self.rows_uploaded = 0
        self.jit_shapes: Set[tuple] = set()

    def drop(self) -> None:
        self.front = None
        self.view = (0, 0)

    @property
    def t_view(self) -> int:
        return self.view[0]

    def _full_upload(self, host: Dict[str, np.ndarray],
                     t_view: int, k_view: int) -> None:
        import jax.numpy as jnp
        dt = mm_dtype()
        live = host["live_inc"][:t_view, :k_view]
        key = host["key_inc"][:t_view, :k_view]
        self.front = {
            "live_T": jnp.asarray(np.ascontiguousarray(live.T).astype(dt)),
            "key_T": jnp.asarray(np.ascontiguousarray(key.T).astype(dt)),
            "ts": jnp.asarray(host["ts"][:t_view]),
            "txn_id": jnp.asarray(host["txn_id"][:t_view]),
            "kind": jnp.asarray(host["kind"][:t_view]),
            "status": jnp.asarray(host["status"][:t_view]),
            "active": jnp.asarray(host["active"][:t_view]),
        }
        self.view = (t_view, k_view)
        self.generation += 1
        self.full_uploads += 1
        self.jit_shapes.add(("full", t_view, k_view))

    def refresh(self, host: Dict[str, np.ndarray],
                dirty_rows: Optional[Iterable[int]],
                t_used: int, k_used: int) -> None:
        """Bring the device copy up to date with the canonical host arrays.
        ``dirty_rows=None`` means unknown provenance (first sight / capacity
        growth / host rebuild): full upload.  ``t_used``/``k_used`` are the
        resolver's slot high-watermarks; the view covers their pow2 buckets."""
        t_cap, k_cap = host["key_inc"].shape
        t_view = pow2_bucket(max(t_used, 1), T_VIEW_FLOOR, t_cap)
        k_view = pow2_bucket(max(k_used, 1), K_VIEW_FLOOR, k_cap)
        # views never shrink: shrinking would churn compiles on sawtooth
        # occupancy, and padding rows are inactive anyway
        t_view = max(t_view, self.view[0]) if self.view[0] <= t_cap else t_view
        k_view = max(k_view, self.view[1]) if self.view[1] <= k_cap else k_view
        rows = None if dirty_rows is None else sorted(dirty_rows)
        if (self.front is None or self.view != (t_view, k_view) or rows is None
                or len(rows) >= max(1, int(t_view * self.full_fraction))):
            self._full_upload(host, t_view, k_view)
            return
        if not rows:
            return
        import jax.numpy as jnp
        global _APPLY_ROWS
        if _APPLY_ROWS is None:
            _APPLY_ROWS = _apply_rows_fn()
        dt = mm_dtype()
        bufs = self.front
        for lo in range(0, len(rows), ROW_REFRESH_CAP):
            chunk = rows[lo:lo + ROW_REFRESH_CAP]
            r_pad = pow2_bucket(len(chunk), ROW_REFRESH_FLOOR, ROW_REFRESH_CAP)
            idx = np.full((r_pad,), chunk[0], dtype=np.int32)
            idx[:len(chunk)] = chunk
            # padding repeats row chunk[0] with row chunk[0]'s values:
            # duplicate same-value writes are idempotent under .at[].set
            live_t = np.ascontiguousarray(
                host["live_inc"][idx, :k_view].T).astype(dt)
            key_t = np.ascontiguousarray(
                host["key_inc"][idx, :k_view].T).astype(dt)
            vals = {name: jnp.asarray(host[name][idx])
                    for name in _ROW_FIELDS}
            bufs = _APPLY_ROWS(bufs, jnp.asarray(idx), jnp.asarray(live_t),
                               jnp.asarray(key_t), vals)
            self.jit_shapes.add(("rows", r_pad, t_view, k_view))
            self.rows_uploaded += len(chunk)
        self.front = bufs          # swap: consults from here on see the update
        self.generation += 1
        self.incremental_refreshes += 1
