"""Persistent batched device consult service (the protocol-path device tier).

``DeviceConsultService`` (service.py) owns a persistent, incrementally
refreshed device-resident conflict index (index.py), a ragged batching
window with jit-stable bucket shapes (batch.py), and a futures-based
submission API the resolver routes protocol consults through.  See each
module's docstring; README "Device consult service" for the operator view.
"""
from .batch import ConsultBatch, build_batch, pow2_bucket, split_rows
from .index import DoubleBufferedIndex
from .service import AsyncResult, DeviceConsultService

__all__ = ["AsyncResult", "ConsultBatch", "DeviceConsultService",
           "DoubleBufferedIndex", "build_batch", "pow2_bucket", "split_rows"]
