"""The ragged batch-ingress contract: flat keys + row offsets + txn-id rows.

Per-txn consult key sets are RAGGED — a PreAccept touches 1-3 keys, a range
txn hundreds — and the device wants fixed shapes.  This module is the shared
wire format between everything that produces consult batches (the resolver's
delivery-window prefetch today, the columnar protocol batches of ROADMAP
item 2 tomorrow) and the device service that consumes them:

- ``flat_cols``  [N]   int32 — every row's key-slot columns, concatenated;
- ``offsets``    [B+1] int32 — row i occupies flat_cols[offsets[i]:offsets[i+1]]
                               (empty rows are legal: offsets[i] == offsets[i+1]);
- ``before``     [B,5] int32 — per-row started-before bound (packed lanes);
- ``kind``       [B]   int8  — per-row querying-txn kind code;
- ``txn_rows``   [B,5] int32 — per-row querying TxnId lanes (zero = none).
                               RESERVED for the columnar protocol batches of
                               ROADMAP item 2 (on-device self-exclusion /
                               attribution); the current kernel does not read
                               it — attribution happens host-side.

This is the same flattened-tokens + row-offsets shape ragged paged attention
uses for variable-length sequences (PAPERS: "Ragged Paged Attention"): the
ragged dimension rides in ONE dense vector and the row structure in a small
offsets vector, so a single kernel serves every mixture of row widths.

Shape discipline (the jit-stability contract): both the row count B and the
flat length N pad UP to power-of-two buckets with a floor and a cap, so a
steady-state workload compiles O(log(max_rows) * log(max_flat)) kernel
variants TOTAL, not one per window size (the r05 replay failure mode).
Padding rows have offsets[i] == offsets[i+1] (width 0) and a saturated
started-before of 0, so they match nothing; padding flat elements carry
weight 0 and scatter nowhere.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

TS_LANES = 5

# bucket floors keep tiny windows from compiling one kernel per size 1..8;
# caps keep one window from compiling unboundedly wide shapes — a window
# larger than the cap splits into multiple dispatches of capped shape
ROW_BUCKET_FLOOR = 8
FLAT_BUCKET_FLOOR = 16


def pow2_bucket(n: int, floor: int, cap: Optional[int] = None) -> int:
    """The power-of-two shape bucket for ``n`` elements (>= floor, <= cap)."""
    b = max(floor, 1 << max(0, n - 1).bit_length())
    return min(b, cap) if cap is not None else b


class ConsultBatch:
    """One ragged consult batch, padded to jit-stable bucket shapes.

    ``rows`` is the REAL row count (pre-padding); arrays are bucket-shaped.
    ``row_ids``/``weights`` are the scatter companions of ``flat_cols``:
    element j lands in dense row ``row_ids[j]`` with weight ``weights[j]``
    (0 for padding, so padding scatters no incidence)."""

    __slots__ = ("rows", "flat", "flat_cols", "row_ids", "weights",
                 "offsets", "before", "kind", "txn_rows")

    def __init__(self, rows: int, flat: int, flat_cols: np.ndarray,
                 row_ids: np.ndarray, weights: np.ndarray,
                 offsets: np.ndarray, before: np.ndarray, kind: np.ndarray,
                 txn_rows: np.ndarray):
        self.rows = rows
        self.flat = flat
        self.flat_cols = flat_cols
        self.row_ids = row_ids
        self.weights = weights
        self.offsets = offsets
        self.before = before
        self.kind = kind
        self.txn_rows = txn_rows

    @property
    def shape_signature(self) -> Tuple[int, int]:
        """(row bucket, flat bucket) — the jit compile key of this batch."""
        return (self.before.shape[0], self.flat_cols.shape[0])

    def densify(self, k: int) -> np.ndarray:
        """The dense [rows, K] int8 key mask (host fallback / parity checks).
        Duplicate columns in a row collapse to 1, exactly as the device
        scatter's >0 consumption does."""
        q = np.zeros((self.rows, k), dtype=np.int8)
        for j in range(self.flat):
            if self.weights[j]:
                q[self.row_ids[j], self.flat_cols[j]] = 1
        return q


def build_batch(row_cols: Sequence[Sequence[int]],
                before_lanes: Sequence[Tuple[int, ...]],
                kind_codes: Sequence[int],
                txn_lanes: Optional[Sequence[Optional[Tuple[int, ...]]]] = None,
                row_cap: Optional[int] = None,
                flat_cap: Optional[int] = None) -> ConsultBatch:
    """Pack ragged per-row key-slot column lists into one ConsultBatch.

    Empty rows, duplicate columns within a row, and max-width rows are all
    legal; callers cap rows per batch BEFORE building (the window splits),
    so ``row_cap``/``flat_cap`` only bound the padding buckets."""
    b = len(row_cols)
    n = sum(len(c) for c in row_cols)
    b_pad = pow2_bucket(b, ROW_BUCKET_FLOOR, row_cap)
    n_pad = pow2_bucket(max(n, 1), FLAT_BUCKET_FLOOR, flat_cap)
    if b > b_pad or n > n_pad:
        raise ValueError(f"batch exceeds its shape cap: rows {b}>{b_pad} "
                         f"or flat {n}>{n_pad} — split before building")
    flat_cols = np.zeros((n_pad,), dtype=np.int32)
    row_ids = np.zeros((n_pad,), dtype=np.int32)
    weights = np.zeros((n_pad,), dtype=np.int8)
    offsets = np.zeros((b_pad + 1,), dtype=np.int32)
    before = np.zeros((b_pad, TS_LANES), dtype=np.int32)
    kind = np.zeros((b_pad,), dtype=np.int8)
    txn_rows = np.zeros((b_pad, TS_LANES), dtype=np.int32)
    at = 0
    for i, cols in enumerate(row_cols):
        offsets[i] = at
        for c in cols:
            flat_cols[at] = c
            row_ids[at] = i
            weights[at] = 1
            at += 1
        before[i] = before_lanes[i]
        kind[i] = kind_codes[i]
        if txn_lanes is not None and txn_lanes[i] is not None:
            txn_rows[i] = txn_lanes[i]
    offsets[b:] = at   # real tail + every padding row: width 0
    return ConsultBatch(b, n, flat_cols, row_ids, weights, offsets,
                        before, kind, txn_rows)


def split_rows(items: List, row_cap: int) -> List[List]:
    """Split a window's items into row_cap-bounded chunks (shape-cap policy:
    an oversized window becomes several capped dispatches, never a new jit
    shape)."""
    return [items[i:i + row_cap] for i in range(0, max(len(items), 1), row_cap)] \
        if items else []
