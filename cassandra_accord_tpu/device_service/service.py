"""DeviceConsultService — the persistent asynchronous batched consult tier.

One service instance serves one resolver's conflict index (one command
store).  It owns the THREE pieces the one-shot dispatch path lacked, which
is why BENCH_r03 recorded zero device consults on the real protocol path and
the r05 replay wedged:

1. a PERSISTENT device index (index.DoubleBufferedIndex): mutations ship as
   incremental row refreshes, not whole-index re-uploads;
2. a RAGGED BATCHING WINDOW: concurrent per-txn key-set consults coalesce
   into one flattened-keys + row-offsets batch (batch.ConsultBatch), padded
   to jit-stable pow2 buckets, so the ~10 ms dispatch RTT (BENCH_r03)
   amortizes across the whole window;
3. a FUTURES submission API: ``submit(txn_keys, ...) -> AsyncResult``.
   Submissions accumulate; the first ``result()`` demand dispatches the
   whole window in ONE launch (per capped chunk) and fulfils every future.
   A window whose answers are never demanded (the resolver's exactness
   machinery invalidated them) costs ZERO launches.

Snapshot discipline: ``begin_window`` pins the index buffers as of the
window's opening; mid-window registrations mutate only the host mirrors (and
the resolver's dirty-key tracking decides what is still servable), so every
answer is exact with respect to its submission point — byte-identical to the
eager path the burn tests reconcile against.

Backend: ``jax`` runs the fused consult kernel wherever jax placed the
buffers (TPU in production, CPU backend in tests — both count as
``device_consults``: it is the kernel tier).  ``host`` is the deterministic
fallback — the resolver's own vectorized numpy pass, same answers
bit-for-bit, dispatched EAGERLY per window (no device snapshot exists to
defer against).  ``auto`` picks jax whenever a usable jax runtime exists
and falls back to host only when jax itself is unavailable.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from .batch import ConsultBatch, build_batch, pow2_bucket, split_rows
from .index import DoubleBufferedIndex

TS_LANES = 5


class AsyncResult:
    """Future for one submitted consult.  ``result()`` forces the owning
    window's dispatch (one batched launch) on first demand."""
    __slots__ = ("_window", "_post", "_value", "done")

    def __init__(self, window: "_Window", post: Optional[Callable] = None):
        self._window = window
        self._post = post
        self._value = None
        self.done = False

    def _fulfil(self, raw) -> None:
        # raw=None is the superseded-window safety net: no answer exists, so
        # the post-processor must not run (it dereferences the raw tuple);
        # consumers treat a None result as a cache miss and fall back
        self._value = self._post(raw) \
            if self._post is not None and raw is not None else raw
        self._post = None
        self.done = True

    def result(self):
        if not self.done:
            self._window.service._demand(self._window)
        return self._value


class _Window:
    """One batching window: pending submissions + the pinned index snapshot
    they must be answered against."""
    __slots__ = ("service", "buffers", "generation", "pending", "dispatched")

    def __init__(self, service: "DeviceConsultService", buffers, generation):
        self.service = service
        self.buffers = buffers          # pinned front (None = host fallback)
        self.generation = generation
        # (cols, before_lanes, kind_code, txn_lanes, future)
        self.pending: List[tuple] = []
        self.dispatched = False


class DeviceConsultService:
    def __init__(self, resolver, config=None):
        from ..config import LocalConfig
        cfg = config if config is not None else getattr(
            resolver, "config", None) or LocalConfig.from_env()
        self.resolver = resolver
        self.backend = cfg.tpu_service_backend
        self.max_window = cfg.tpu_service_max_window
        self.index = DoubleBufferedIndex(
            full_fraction=cfg.tpu_service_refresh_full_frac)
        self._window: Optional[_Window] = None
        self._use_jax: Optional[bool] = None
        # -- service-level telemetry (observe/device.py collects these) ------
        self.submitted = 0              # consults submitted (futures created)
        self.answered = 0               # futures fulfilled
        self.oneshot_rows = 0           # immediate consult_rows consults
        self.batches = 0                # device/host dispatches (launches)
        self.dropped_windows = 0        # windows whose answers went undemanded
        self.batch_size_hist: Dict[int, int] = {}   # real rows -> count
        self.dispatch_seconds = 0.0     # wall time inside dispatches
        self.dispatch_count = 0
        self.dispatch_max_seconds = 0.0
        self.occupancy_sum = 0          # real rows per dispatch vs max_window
        self.jit_shapes: set = set()    # (rows_bucket, flat_bucket, t, k, packed)
        # bounded (ts, queue_depth, batch_rows) samples for the Chrome-trace
        # counter track; ts is sim-micros when the store has a clock, else a
        # dispatch ordinal.  Appending is deterministic and touches no RNG /
        # scheduling, so the zero-observer-effect contract holds.
        self._sample_cap = 4096
        self.samples: Deque[Tuple[int, int, int]] = \
            deque(maxlen=self._sample_cap)
        self.samples_dropped = 0
        # wall-clock profiler (observe.WallProfiler) — resolved lazily from
        # the owning node at first dispatch; False = probed, none attached
        self._profiler = None

    # -- clock (sim time when available) -------------------------------------
    def _now(self) -> Optional[int]:
        node = getattr(getattr(self.resolver, "store", None), "node", None)
        if node is not None:
            try:
                return int(node.now_micros())
            except Exception:  # noqa: BLE001 — clockless stand-in stores
                return None
        return None

    def _jax_backed(self) -> bool:
        if self._use_jax is None:
            if self.backend == "jax":
                self._use_jax = True
            elif self.backend == "host":
                self._use_jax = False
            else:
                # auto: the kernel tier runs wherever jax placed the buffers
                # (TPU in production, the CPU backend in tests — same as the
                # legacy _consult_device semantics); host fallback only when
                # there is no usable jax runtime at all
                try:
                    import jax
                    jax.devices()
                    self._use_jax = True
                except Exception:  # noqa: BLE001 — no jax runtime at all
                    self._use_jax = False
        return self._use_jax

    # -- index refresh --------------------------------------------------------
    def _refresh(self) -> None:
        """Bring the persistent buffers up to date (incremental rows against
        the occupancy-view extent — slot allocation is min-heap ordered, so
        the resolver's high-watermarks bound every live row/column)."""
        h = self.resolver.host_index()
        self.index.refresh(h, self.resolver.take_dirty_rows(),
                           getattr(self.resolver, "_max_slot", -1) + 1,
                           getattr(self.resolver, "_max_key_slot", -1) + 1)

    # -- the batching window --------------------------------------------------
    def begin_window(self) -> None:
        """Open a new window: refresh the index and pin the snapshot every
        submission in this window is answered against."""
        if self._window is not None and self._window.pending \
                and not self._window.dispatched:
            self.dropped_windows += 1
        if self._jax_backed():
            self._refresh()
            self._window = _Window(self, self.index.front,
                                   self.index.generation)
        else:
            self._window = _Window(self, None, 0)

    def end_window(self) -> None:
        if self._window is not None and self._window.pending \
                and not self._window.dispatched:
            self.dropped_windows += 1
        self._window = None

    def flush_window(self) -> None:
        """Dispatch the current window NOW (eager).  The host fallback has no
        pinned device snapshot, so deferring to demand time would answer
        against a post-mutation index and break byte-identity with the eager
        path — the resolver forces this right after submitting a host-backed
        window."""
        if self._window is not None and self._window.pending:
            self._demand(self._window)

    @property
    def deferred(self) -> bool:
        """Whether windows may defer dispatch to demand time (only the jax
        path has the pinned snapshot that makes deferral exact)."""
        return self._jax_backed()

    def submit(self, txn_key_cols, before_lanes, kind_code,
               txn_lanes=None, post: Optional[Callable] = None) -> AsyncResult:
        """Enqueue one ragged consult (key-slot columns; empty is legal) into
        the current window; returns its future.  No dispatch happens until a
        result is demanded — then the WHOLE window goes in one launch."""
        if self._window is None:
            self.begin_window()
        w = self._window
        fut = AsyncResult(w, post)
        w.pending.append((tuple(txn_key_cols), tuple(before_lanes),
                          int(kind_code), txn_lanes, fut))
        self.submitted += 1
        self._sample(len(w.pending), 0)
        return fut

    def _demand(self, window: "_Window") -> None:
        """First ``result()`` on an undispatched window: answer EVERYTHING
        pending in capped ragged batches against the pinned snapshot."""
        if window.dispatched:
            return
        window.dispatched = True
        if window is not self._window:
            # the window was superseded before any demand (exactness machinery
            # dropped the cache): its futures resolve to None — callers that
            # could still demand them hold a live cache, so this cannot happen
            # for a served answer; it is a safety net, not a code path
            for *_ignore, fut in window.pending:
                fut._fulfil(None)
            return
        for chunk in split_rows(window.pending, self.max_window):
            batch = build_batch(
                [p[0] for p in chunk], [p[1] for p in chunk],
                [p[2] for p in chunk], [p[3] for p in chunk],
                row_cap=self.max_window)
            deps, max_lanes = self._dispatch(batch, window.buffers)
            for i, (*_spec, fut) in enumerate(chunk):
                fut._fulfil((deps[i], max_lanes[i]))
                self.answered += 1

    # -- one-shot bridge (the resolver's immediate dense consults) -----------
    def consult_rows(self, q: np.ndarray, before: np.ndarray,
                     kind: np.ndarray):
        """Immediate batched consult for already-dense query rows (the
        resolver ``_consult`` bridge).  Uses the CURRENT index (refreshing
        incrementally), one ragged launch per capped chunk."""
        if q.shape[0] == 0:
            return (np.zeros((0, 1), dtype=bool),
                    np.zeros((0, TS_LANES), dtype=np.int64))
        rows = [tuple(np.nonzero(q[i])[0].tolist()) for i in range(q.shape[0])]
        self.oneshot_rows += len(rows)
        if self._jax_backed():
            self._refresh()
            buffers = self.index.front
        else:
            buffers = None
        deps_out = []
        lanes_out = []
        idxs = list(range(len(rows)))
        for chunk in split_rows(idxs, self.max_window):
            batch = build_batch([rows[i] for i in chunk],
                                [tuple(int(v) for v in before[i])
                                 for i in chunk],
                                [int(kind[i]) for i in chunk],
                                row_cap=self.max_window)
            deps, max_lanes = self._dispatch(batch, buffers)
            deps_out.append(deps)
            lanes_out.append(max_lanes)
        return np.concatenate(deps_out), np.concatenate(lanes_out)

    # -- dispatch -------------------------------------------------------------
    def _dispatch(self, batch: ConsultBatch, buffers):
        """One launch: ragged batch in, (deps [rows, T] bool, max_lanes
        [rows, 5]) out — counters incremented ONCE PER SUBMITTED CONSULT
        (batch.rows), never per launch (the r03 bookkeeping fix)."""
        if self._profiler is None:
            node = getattr(getattr(self.resolver, "store", None), "node", None)
            self._profiler = getattr(node, "profiler", None) or False
        t0 = time.perf_counter()
        compiled = False
        kt_shape = None
        if buffers is not None:
            k, t = buffers["live_T"].shape
            kt_shape = (t, k)
            n_shapes = len(self.jit_shapes)
            deps, max_lanes = self._dispatch_jax(batch, buffers)
            compiled = len(self.jit_shapes) > n_shapes
            self.resolver.device_consults += batch.rows
        else:
            h = self.resolver.host_index()
            q = batch.densify(h["key_inc"].shape[1])
            # the deterministic host fallback IS the resolver's own host tier
            # (it does its own per-consult counting)
            deps, max_lanes = self.resolver._consult_host(
                q, batch.before[:batch.rows].astype(np.int64),
                batch.kind[:batch.rows])
        dt = time.perf_counter() - t0
        self.batches += 1
        self.batch_size_hist[batch.rows] = \
            self.batch_size_hist.get(batch.rows, 0) + 1
        self.dispatch_seconds += dt
        self.dispatch_count += 1
        self.dispatch_max_seconds = max(self.dispatch_max_seconds, dt)
        self.occupancy_sum += batch.rows
        self._sample(0, batch.rows)
        if self._profiler and buffers is not None:
            # launch breakdown for the wall profiler: dispatch RTT, h2d
            # (the ragged batch arrays) and d2h (densified results) bytes,
            # and whether this launch compiled a new jit shape.  Wall-plane
            # only — nothing here feeds the deterministic registry.
            h2d = (batch.flat_cols.nbytes + batch.row_ids.nbytes
                   + batch.weights.nbytes + batch.before.nbytes
                   + batch.kind.nbytes)
            self._profiler.on_device_launch(
                batch.rows, dt, h2d, deps.nbytes + max_lanes.nbytes,
                compiled, shape=kt_shape)
        return deps[:batch.rows], max_lanes[:batch.rows]

    def _dispatch_jax(self, batch: ConsultBatch, buffers):
        from .kernel import consult_t
        import jax
        import jax.numpy as jnp
        k, t = buffers["live_T"].shape
        # bit-packing the result only pays when it crosses a real transfer
        # link; on the CPU backend it is pure extra compute
        packed = t >= 32768 and t % 8 == 0 \
            and jax.default_backend() != "cpu"
        self.jit_shapes.add(batch.shape_signature + (t, k, packed))
        out = consult_t()(
            buffers["live_T"], buffers["key_T"], buffers["ts"],
            buffers["txn_id"], buffers["kind"], buffers["status"],
            buffers["active"],
            jnp.asarray(batch.flat_cols), jnp.asarray(batch.row_ids),
            jnp.asarray(batch.weights), jnp.asarray(batch.before),
            jnp.asarray(batch.kind), packed=packed)
        deps, max_lanes = jax.device_get(out)
        if packed:
            deps = np.unpackbits(deps, axis=1, bitorder="little") \
                .astype(bool)[:, :t]
        return deps, max_lanes

    # -- telemetry ------------------------------------------------------------
    def _sample(self, queue_depth: int, batch_rows: int) -> None:
        # ring semantics: a long soak keeps the RECENT trajectory (the
        # windowed timeline and the Perfetto track both want the tail into a
        # stall, not the warm-up) — drop the OLDEST sample past the cap
        ts = self._now()
        if ts is None:
            ts = self.samples_dropped + len(self.samples)
        if len(self.samples) >= self._sample_cap:
            self.samples_dropped += 1    # deque(maxlen) evicts the oldest
        self.samples.append((ts, queue_depth, batch_rows))

    def stats(self) -> Dict[str, object]:
        occ = (self.occupancy_sum / (self.dispatch_count * self.max_window)
               if self.dispatch_count else 0.0)
        lat = (self.dispatch_seconds / self.dispatch_count
               if self.dispatch_count else 0.0)
        return {
            "submitted": self.submitted,
            "answered": self.answered,
            "oneshot_rows": self.oneshot_rows,
            "batches": self.batches,
            "dropped_windows": self.dropped_windows,
            "batch_size_hist": dict(sorted(self.batch_size_hist.items())),
            "mean_batch_rows": round(self.occupancy_sum
                                     / max(1, self.dispatch_count), 2),
            "window_occupancy": round(occ, 4),
            "dispatch_mean_s": round(lat, 6),
            "dispatch_max_s": round(self.dispatch_max_seconds, 6),
            "jit_shapes": len(self.jit_shapes | self.index.jit_shapes),
            "index_full_uploads": self.index.full_uploads,
            "index_incremental_refreshes": self.index.incremental_refreshes,
            "index_rows_uploaded": self.index.rows_uploaded,
            "samples_dropped": self.samples_dropped,
        }
