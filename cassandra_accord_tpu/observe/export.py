"""Chrome trace-event export: load a burn's flight recording in Perfetto.

Produces the JSON object format of the Trace Event spec
(``{"traceEvents": [...]}``, timestamps in MICROseconds — exactly the
simulator's native unit):

- pid = node id, tid 0 = that node's coordinator track, tid = store id + 1
  for its command-store tracks (``M`` metadata events name them);
- one ``X`` (complete) event per client txn on the coordinator track,
  spanning submit→resolve, args carrying path/outcome/recovery attribution;
- per-(node, store) ``X`` events for each status segment of a txn's
  lifecycle (PRE_ACCEPTED until ACCEPTED, ... until the next transition),
  with an ``i`` (instant) event for the terminal status;
- optional ``i`` events for raw message routing (SEND/DROP/RECV...), on the
  sending node's coordinator track;
- ``C`` (counter) events on the synthetic counters process (pid 0): in-flight
  client txns and cumulative recovery / invalidation attempts, sampled on
  uniform sim-time buckets — Perfetto renders them as counter tracks above
  the spans, so livelock shapes (the seed-6 probe storm) are visible at a
  glance.  Derived at EXPORT time from the recorded spans and attempt
  timestamps: no runtime sampling task, so the zero-observer-effect contract
  is untouched.

``validate_chrome_trace`` is the schema check the tier-1 tests run over
every export.
"""
from __future__ import annotations

import json
from typing import List

_VALID_PHASES = {"X", "i", "M", "B", "E", "C", "s", "t", "f"}
_FLOW_PHASES = {"s", "t", "f"}

# synthetic pid for cluster-wide counter tracks (real nodes are 1-based)
COUNTER_PID = 0
# synthetic pid for the wall-clock profiler tracks (observe/profiler.py):
# timestamps on this process are WALL micros since profiler start — a
# different time base from the sim tracks, linked per-txn by flow events
WALL_PID = 9999
_COUNTER_BUCKETS = 256


def counter_events(recorder, buckets: int = _COUNTER_BUCKETS) -> List[dict]:
    """Cluster-wide counter tracks sampled on uniform sim-time buckets:
    in-flight client txns (from span submit/resolve envelopes) and
    cumulative recovery / invalidation attempts (from the recorder's
    sim-timestamped attribution)."""
    spans = [s for s in recorder.spans.spans.values() if s.is_client_op]
    times = [s.submitted_us for s in spans] \
        + [s.resolved_us for s in spans if s.resolved_us is not None] \
        + list(recorder._recovery_times) + list(recorder._invalidate_times)
    if not times:
        return []
    lo, hi = min(times), max(times)
    width = max((hi - lo) // max(buckets, 1), 1)
    edges = list(range(lo, hi + width, width))

    def cumulative(points):
        pts = sorted(points)
        out, i = [], 0
        for edge in edges:
            while i < len(pts) and pts[i] <= edge:
                i += 1
            out.append(i)
        return out

    submitted = cumulative([s.submitted_us for s in spans])
    resolved = cumulative([s.resolved_us for s in spans
                           if s.resolved_us is not None])
    recoveries = cumulative(recorder._recovery_times)
    invalidates = cumulative(recorder._invalidate_times)
    events: List[dict] = []
    for i, edge in enumerate(edges):
        events.append({"name": "in_flight_txns", "cat": "counter", "ph": "C",
                       "ts": edge, "pid": COUNTER_PID, "tid": 0,
                       "args": {"in_flight": submitted[i] - resolved[i]}})
        if recoveries[-1] or invalidates[-1]:
            events.append({"name": "recovery_attempts", "cat": "counter",
                           "ph": "C", "ts": edge, "pid": COUNTER_PID,
                           "tid": 0,
                           "args": {"recoveries": recoveries[i],
                                    "invalidations": invalidates[i]}})
    return events


def service_counter_events(recorder,
                           buckets: int = _COUNTER_BUCKETS) -> List[dict]:
    """Consult-service counter track (pid 0, tid 1): batching-window queue
    depth and dispatched batch size over sim time, from the samples
    ``collect_cluster`` pulled out of every engaged DeviceConsultService.
    Bucketed to the same resolution as the cluster counter tracks."""
    samples = getattr(recorder, "_service_samples", None)
    if not samples:
        return []
    lo, hi = samples[0][0], samples[-1][0]
    width = max((hi - lo) // max(buckets, 1), 1)
    events: List[dict] = []
    bucket_ts = None
    depth_max = 0
    rows_max = 0
    for ts, depth, rows in samples:
        b = lo + ((ts - lo) // width) * width
        if bucket_ts is None:
            bucket_ts = b
        if b != bucket_ts:
            events.append({"name": "consult_service", "cat": "counter",
                           "ph": "C", "ts": bucket_ts, "pid": COUNTER_PID,
                           "tid": 1, "args": {"queue_depth": depth_max,
                                              "batch_rows": rows_max}})
            bucket_ts, depth_max, rows_max = b, 0, 0
        depth_max = max(depth_max, depth)
        rows_max = max(rows_max, rows)
    events.append({"name": "consult_service", "cat": "counter", "ph": "C",
                   "ts": bucket_ts, "pid": COUNTER_PID, "tid": 1,
                   "args": {"queue_depth": depth_max,
                            "batch_rows": rows_max}})
    return events


def timeline_counter_events(recorder) -> List[dict]:
    """Per-window telemetry counter track (pid 0, tid 2) from an attached
    ``observe/timeline.Timeline``: windowed commits/s, p99 commit latency
    (ms) and in-flight txns — the trajectory curves, natively in Perfetto,
    one ``C`` event per sim-time window.  Empty when no timeline rode the
    recorder."""
    timeline = getattr(recorder, "timeline", None)
    if timeline is None:
        return []
    from . import schema
    from .timeline import COMMIT_OUTCOMES
    commit_names = [schema.OUTCOME_METRICS[o] for o in COMMIT_OUTCOMES]
    events: List[dict] = []
    for rec in timeline.records(include_open=True):
        cluster = rec["scopes"].get("cluster", {})
        args: dict = {}
        rates = cluster.get("rates_per_s", {})
        # ALWAYS emitted, 0.0 included: Perfetto holds a counter at its last
        # sample until the next one, so skipping commit-less windows would
        # render a stall as a flat healthy line — the exact trajectory this
        # track exists to show is commits/s falling to zero
        args["commits_per_sec"] = round(
            sum(rates.get(n, 0.0) for n in commit_names), 3)
        pct = cluster.get("percentiles", {}).get(schema.LATENCY_METRIC)
        if pct and pct.get("p99") is not None:
            args["latency_p99_ms"] = round(pct["p99"] / 1000.0, 3)
        sample = cluster.get("samples", {}).get(schema.TIMELINE_IN_FLIGHT_METRIC)
        if sample is not None:
            args["in_flight"] = sample
        events.append({"name": "timeline", "cat": "counter", "ph": "C",
                       "ts": rec["start_us"], "pid": COUNTER_PID, "tid": 2,
                       "args": args})
    return events


def wall_profile_events(recorder, profiler) -> List[dict]:
    """Plane-2 tracks: one ``X`` slice per recorded handler invocation on
    the synthetic wall-clock process (pid ``WALL_PID``, tid = node id,
    timestamps in WALL micros since profiler start), plus FLOW events
    (``s``/``t``/``f``) linking each client txn's sim-time span (on its
    coordinator track) to the host handler slices that served it — the
    two-time-base bridge: click a txn, follow the flow to the wall plane."""
    if profiler is None or not profiler.slices:
        return []
    events: List[dict] = []
    # handler slices, and per-txn wall slices for flow binding
    by_txn: dict = {}
    for i, (type_name, node, tid_str, wall_us, dur_us, sim_us) in \
            enumerate(profiler.slices):
        events.append({"name": type_name, "cat": "wall_handler", "ph": "X",
                       "ts": wall_us, "dur": dur_us, "pid": WALL_PID,
                       "tid": node,
                       "args": {"txn_id": tid_str, "sim_us": sim_us}})
        if tid_str is not None:
            by_txn.setdefault(tid_str, []).append((wall_us, node))
    for span in recorder.spans.spans.values():
        if not span.is_client_op:
            continue
        slices = by_txn.get(str(span.txn_id))
        if not slices:
            continue
        flow_id = f"txnflow-{span.txn_id}"
        events.append({"name": "serves", "cat": "txnflow", "ph": "s",
                       "id": flow_id, "ts": span.submitted_us,
                       "pid": span.coordinator, "tid": 0,
                       "args": {"txn_id": str(span.txn_id)}})
        for j, (wall_us, node) in enumerate(slices):
            ph = "f" if j + 1 == len(slices) else "t"
            ev = {"name": "serves", "cat": "txnflow", "ph": ph,
                  "id": flow_id, "ts": wall_us, "pid": WALL_PID, "tid": node,
                  "args": {"txn_id": str(span.txn_id)}}
            if ph == "f":
                ev["bp"] = "e"   # bind to the enclosing handler slice
            events.append(ev)
    return events


def causal_flow_events(recorder) -> List[dict]:
    """Causal-edge flow tracks from an attached ``ProvenanceRecorder``:

    - one flow per message id chaining its wire lifecycle (send -> RECV ->
      reply -> RECV_RPLY) across the node tracks, so a delivery is clickable
      back to its send;
    - one flow per audit-violation causal slice (cap 8), threading the
      violation's ancestor cone through the handler/lifecycle tracks it
      touched — the cone IS the clickable path in the timeline UI.

    Derived entirely at export time from the provenance side table: zero
    runtime cost, nothing emitted when no recorder rode the run."""
    prov = getattr(recorder, "provenance", None)
    if prov is None:
        return []
    from .provenance import E_FRM, E_KIND, E_MSG, E_TO, E_US, K_MSG, \
        K_TRANSITION

    def track(ev):
        if ev[E_KIND] == K_MSG:
            return (ev[E_FRM] if ev[E_FRM] is not None else 0), 0
        if ev[E_KIND] == K_TRANSITION:
            # transition tuples carry store in the FRM slot (see provenance)
            return ev[E_TO], (ev[E_FRM] or 0) + 1
        return (ev[E_TO] if ev[E_TO] is not None else 0), 0

    events: List[dict] = []
    chains: dict = {}
    for ev in prov.events:
        if ev[E_KIND] == K_MSG and ev[E_MSG] is not None:
            chains.setdefault(ev[E_MSG], []).append(ev)
    for msg_id, chain in chains.items():
        if len(chain) < 2:
            continue
        flow_id = f"cause-msg-{msg_id}"
        for j, ev in enumerate(chain):
            ph = "s" if j == 0 else ("f" if j + 1 == len(chain) else "t")
            pid, tid = track(ev)
            e = {"name": "causal", "cat": "causal", "ph": ph, "id": flow_id,
                 "ts": ev[E_US], "pid": pid, "tid": tid,
                 "args": {"msg_id": msg_id}}
            if ph == "f":
                e["bp"] = "e"
            events.append(e)
    for k, violation in enumerate(getattr(recorder, "violations", ())[:8]):
        sl = getattr(violation, "causal_slice", None)
        if not sl or len(sl["events"]) < 2:
            continue
        flow_id = f"slice-{violation.rule}-{k}"
        cone = [prov.events[d["pid"]] for d in sl["events"]]
        for j, ev in enumerate(cone):
            ph = "s" if j == 0 else ("f" if j + 1 == len(cone) else "t")
            pid, tid = track(ev)
            e = {"name": "violation-slice", "cat": "causal", "ph": ph,
                 "id": flow_id, "ts": ev[E_US], "pid": pid, "tid": tid,
                 "args": {"rule": violation.rule}}
            if ph == "f":
                e["bp"] = "e"
            events.append(e)
    return events


def _span_events(span) -> List[dict]:
    events: List[dict] = []
    tid_str = str(span.txn_id)
    if span.is_client_op:
        end = span.resolved_us if span.resolved_us is not None \
            else span.submitted_us
        events.append({
            "name": f"txn {tid_str}", "cat": "txn", "ph": "X",
            "ts": span.submitted_us,
            "dur": max(end - span.submitted_us, 1),
            "pid": span.coordinator, "tid": 0,
            "args": {"txn_id": tid_str, "op_id": span.op_id,
                     "path": span.path, "outcome": span.outcome,
                     "recoveries": span.recoveries,
                     "invalidate_attempts": span.invalidate_attempts,
                     "timeouts": span.timeouts, "backoffs": span.backoffs},
        })
    for (node, store), transitions in sorted(span.transitions.items()):
        for i, (status, ts) in enumerate(transitions):
            args = {"txn_id": tid_str, "status": status}
            if i + 1 < len(transitions):
                dur = max(transitions[i + 1][1] - ts, 1)
                events.append({"name": status, "cat": "lifecycle", "ph": "X",
                               "ts": ts, "dur": dur, "pid": node,
                               "tid": store + 1, "args": args})
            else:
                events.append({"name": status, "cat": "lifecycle", "ph": "i",
                               "s": "t", "ts": ts, "pid": node,
                               "tid": store + 1, "args": args})
    return events


def chrome_trace(recorder, include_messages: bool = True,
                 profiler=None) -> dict:
    """Render a FlightRecorder as a Chrome trace-event JSON object.
    ``profiler`` (an ``observe.WallProfiler``) adds the wall-clock handler
    tracks + per-txn flow links (``wall_profile_events``)."""
    events: List[dict] = []
    pids = set()
    tids = set()        # (pid, tid)
    for span in recorder.spans.spans.values():
        for ev in _span_events(span):
            pids.add(ev["pid"])
            tids.add((ev["pid"], ev["tid"]))
            events.append(ev)
    for ev in wall_profile_events(recorder, profiler):
        pids.add(ev["pid"])
        tids.add((ev["pid"], ev["tid"]))
        events.append(ev)
    counters = counter_events(recorder)
    if counters:
        pids.add(COUNTER_PID)
        tids.add((COUNTER_PID, 0))
        events.extend(counters)
    svc_counters = service_counter_events(recorder)
    if svc_counters:
        pids.add(COUNTER_PID)
        tids.add((COUNTER_PID, 1))
        events.extend(svc_counters)
    tl_counters = timeline_counter_events(recorder)
    if tl_counters:
        pids.add(COUNTER_PID)
        tids.add((COUNTER_PID, 2))
        events.extend(tl_counters)
    for ev in causal_flow_events(recorder):
        pids.add(ev["pid"])
        tids.add((ev["pid"], ev["tid"]))
        events.append(ev)
    if include_messages:
        for seq, ts, event, frm, to, msg_id, brief in recorder.messages:
            pids.add(frm)
            tids.add((frm, 0))
            events.append({"name": f"{event} {brief}", "cat": "msg",
                           "ph": "i", "s": "t", "ts": ts, "pid": frm,
                           "tid": 0,
                           "args": {"seq": seq, "to": to, "event": event,
                                    "msg_id": msg_id}})
    meta: List[dict] = []
    for pid in sorted(pids):
        if pid == COUNTER_PID:
            pname = "cluster counters"
        elif pid == WALL_PID:
            pname = "host wall-clock (profiler)"
        else:
            pname = f"node {pid}"
        meta.append({"name": "process_name", "ph": "M", "ts": 0, "pid": pid,
                     "tid": 0, "args": {"name": pname}})
    for pid, tid in sorted(tids):
        if pid == COUNTER_PID:
            name = {0: "counters", 1: "consult service",
                    2: "timeline"}.get(tid, f"counters {tid}")
        elif pid == WALL_PID:
            name = f"node {tid} handlers (wall)"
        else:
            name = "coordinator" if tid == 0 else f"store {tid - 1}"
        meta.append({"name": "thread_name", "ph": "M", "ts": 0, "pid": pid,
                     "tid": tid, "args": {"name": name}})
    events.sort(key=lambda e: (e["ts"], e["pid"], e["tid"], e["name"]))
    return {"traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {"source": "cassandra_accord_tpu flight recorder",
                          "time_unit": "simulated_micros",
                          "dropped_messages": recorder.dropped_messages}}


def write_chrome_trace(path: str, recorder,
                       include_messages: bool = True,
                       profiler=None) -> None:
    with open(path, "w") as f:
        json.dump(chrome_trace(recorder, include_messages=include_messages,
                               profiler=profiler),
                  f, sort_keys=True)
        f.write("\n")


def validate_chrome_trace(doc) -> List[str]:
    """Schema check; returns a list of problems ([] = loadable)."""
    problems: List[str] = []
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return ["not an object with a traceEvents array"]
    events = doc["traceEvents"]
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    flow_starts = set()
    flow_ends: List[tuple] = []
    for i, ev in enumerate(events):
        ctx = f"event[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{ctx}: not an object")
            continue
        for field in ("name", "ph", "ts", "pid", "tid"):
            if field not in ev:
                problems.append(f"{ctx}: missing {field!r}")
        ph = ev.get("ph")
        if ph not in _VALID_PHASES:
            problems.append(f"{ctx}: bad phase {ph!r}")
        ts = ev.get("ts")
        if not isinstance(ts, int) or ts < 0:
            problems.append(f"{ctx}: ts must be a non-negative int, got {ts!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, int) or dur <= 0:
                problems.append(f"{ctx}: X event needs a positive int dur")
        if ph in _FLOW_PHASES:
            if not ev.get("id"):
                problems.append(f"{ctx}: flow event ({ph}) needs an id")
            elif ph == "s":
                flow_starts.add(ev["id"])
            elif ph == "f":
                flow_ends.append((i, ev["id"]))
        if ph == "C":
            args = ev.get("args")
            if not isinstance(args, dict) or not args:
                problems.append(f"{ctx}: C event needs a non-empty args dict")
            elif not all(isinstance(v, (int, float)) and not isinstance(v, bool)
                         for v in args.values()):
                problems.append(f"{ctx}: C event args must be numeric series")
        if "args" in ev:
            try:
                json.dumps(ev["args"])
            except TypeError:
                problems.append(f"{ctx}: args not JSON-serializable")
        if len(problems) > 20:
            problems.append("... (truncated)")
            break
    # flow pairing: a finish (f) with no start (s) of the same id renders as
    # a dangling arrow in Perfetto — an id alone is not enough
    for i, flow_id in flow_ends:
        if flow_id not in flow_starts:
            problems.append(f"event[{i}]: flow finish id {flow_id!r} has no "
                            f"matching start")
            if len(problems) > 24:
                problems.append("... (truncated)")
                break
    return problems
