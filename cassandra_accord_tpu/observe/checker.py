"""Elle-style strict-serializability checker over a client-visible history.

A second, protocol-blind oracle (the reference validates with Jepsen's Elle
and a Maelstrom adapter): given only what clients observed — list-append
writes with unique values and reads returning per-key version lists — decide
whether the history is strictly serializable, and if not, name the anomaly
with the full offending sub-history.

Model (Elle's list-append inference, specialized to our harness):

1. VERSION ORDER per key falls out of the data type: every observed read is
   a version (a list), and because appends are atomic list extensions, all
   observations of one key must be prefixes of one another.  The longest
   observation (or the final replica state when provided) IS the version
   order; a non-prefix pair of observations is itself an anomaly
   (``incompatible-order``).
2. WRITE ATTRIBUTION: write values are unique, so position ``p`` of key
   ``k``'s order names exactly one writer op.
3. DEPENDENCY GRAPH over ok ops (info-outcome writers whose values surface
   join as nodes — their effects provably executed):
   - ``ww``  writer of (k,p) -> writer of (k,p+1)
   - ``wr``  writer of (k,L-1) -> reader that observed length L
   - ``rw``  reader that observed length L -> writer of (k,L)   (anti-dep)
   - ``rt``  A -> B when A completed before B was invoked (real time); the
     quadratic pair set is encoded as a virtual chain over completion ranks
     (O(n) nodes/edges, same trick as harness/verifier.py).
4. Any cycle is a violation, classified by its edge kinds:
   - all ``ww``                    -> G0 (write cycle)
   - ``ww``/``wr`` only            -> G1c (circular information flow)
   - exactly one ``rw``            -> G-single (read skew); the 2-op
     wr+rw form is a fractured read, reported as non-repeatable-read
   - two+ ``rw``                   -> G2 (anti-dependency cycle)
   - any ``rt`` edge in the cycle  -> "-realtime" suffix: the cycle only
     closes through real time — a strict-serializability violation (e.g. a
     stale read of a completed write is G-single-realtime)
5. Direct (non-cycle) anomalies:
   - ``lost-update``        an acked write's value is missing from its key's
     authoritative final order
   - ``G1a-aborted-read``   an invalidated op's write value surfaced in a
     read or in the final state

Anomaly reports carry the offending sub-history (invoke/ok intervals, reads,
writes per implicated op) and, when a span recorder is supplied, the
flight-recorder timelines of the implicated txns — the "what was the
protocol doing" forensic attachment.

The checker knows nothing about Accord: no TxnIds ordering, no deps, no
epochs.  It can therefore disagree with the in-process verifier/auditor —
which is the point (ROADMAP item 4d).
"""
from __future__ import annotations

from bisect import bisect_left
from typing import Dict, List, Optional, Tuple


class HistoryAnomaly(AssertionError):
    """A named strict-serializability anomaly with its full report."""

    def __init__(self, report: dict):
        self.report = report
        super().__init__(format_report(report))


def format_report(report: dict, max_ops: int = 12) -> str:
    """Human-readable rendering of a checker report's first anomaly."""
    anomalies = report.get("anomalies") or []
    if not anomalies:
        return f"history clean: {report}"
    a = anomalies[0]
    lines = [f"history anomaly: {a['name']} — {a.get('detail', '')}".rstrip()]
    for e in a.get("edges", []):
        key = f" key={e['key']}" if e.get("key") is not None else ""
        lines.append(f"  op {e['from']} -{e['kind']}{key}-> op {e['to']}")
    subs = a.get("sub_history", [])
    for rec in subs[:max_ops]:
        lines.append(
            f"  op {rec['op_id']} [{rec['invoke_us']}..{rec['complete_us']}] "
            f"{rec['outcome']} reads={rec['reads']} writes={rec['writes']} "
            f"txn={rec['txn_id']}")
    if len(subs) > max_ops:
        lines.append(f"  ... {len(subs) - max_ops} more implicated ops")
    if a.get("timelines"):
        lines.append(f"  flight-recorder timelines attached for "
                     f"{sorted(a['timelines'])}")
    if a.get("causal_slices"):
        lines.append(f"  causal slices attached for "
                     f"{sorted(a['causal_slices'])}")
    more = len(anomalies) - 1
    if more:
        lines.append(f"  (+{more} further anomalies in report)")
    return "\n".join(lines)


def _classify(edges: List[dict]) -> Tuple[str, str]:
    """Name a cycle from its edge kinds; returns (name, detail)."""
    kinds = [e["kind"] for e in edges]
    data_kinds = [k for k in kinds if k != "rt"]
    has_rt = "rt" in kinds
    n_rw = data_kinds.count("rw")
    if not data_kinds:
        # cannot happen (the rt chain alone is acyclic) — defensive
        return "real-time", "cycle of pure real-time edges"
    if n_rw == 0 and set(data_kinds) == {"ww"}:
        base, detail = "G0", "write cycle: ww edges only"
    elif n_rw == 0:
        base, detail = "G1c", "circular information flow (ww/wr)"
    elif n_rw == 1:
        base, detail = "G-single", "single anti-dependency cycle (read skew)"
    else:
        base, detail = "G2", f"{n_rw} anti-dependency edges"
    real_ops = {e["from"] for e in edges} | {e["to"] for e in edges}
    if base == "G-single" and not has_rt and len(real_ops) == 2 \
            and set(data_kinds) == {"wr", "rw"}:
        return ("non-repeatable-read",
                "fractured read: observed part of one txn's atomic writes")
    if has_rt:
        if base == "G-single":
            detail = "stale read: op invoked after a completed write " \
                     "did not observe it (real-time violation)"
        else:
            detail += " closed through real time " \
                      "(strict-serializability violation)"
        return base + "-realtime", detail
    return base, detail


def check_history(ops, final_state: Optional[Dict] = None, spans=None,
                  raise_on_anomaly: bool = True,
                  max_anomalies: int = 8, provenance=None) -> dict:
    """Check a list of ``HistoryOp`` for strict serializability.

    ``final_state``: authoritative key -> version tuple (e.g. the burn's
    replica-agreement snapshot); enables lost-update detection and extends
    per-key orders beyond what reads observed.  ``spans``: a
    ``TxnSpanRecorder`` (or FlightRecorder ``.spans``) for timeline
    attachment.  ``provenance``: a ``ProvenanceRecorder`` — each anomaly
    then carries a bounded backward causal slice per implicated txn
    (``causal_slices``), the "how did the protocol get here" attachment.
    Returns the report; raises :class:`HistoryAnomaly` on the first anomaly
    unless ``raise_on_anomaly=False`` (then the report carries up to
    ``max_anomalies`` of them).
    """
    anomalies: List[dict] = []
    considered = [op for op in ops if op.outcome != "fail"]
    ok_ops = [op for op in considered if op.outcome == "ok"]

    def _attach(names, implicated, edges=None, detail=""):
        a = {"name": names, "detail": detail,
             "edges": edges or [],
             "sub_history": [op.to_record() for op in implicated]}
        if spans is not None:
            # accept a FlightRecorder, a TxnSpanRecorder, or a raw dict
            table = spans
            while not hasattr(table, "get"):
                table = getattr(table, "spans", {})
            tl = {}
            for op in implicated:
                span = table.get(op.txn_id)
                if span is not None and hasattr(span, "to_dict"):
                    tl[str(op.txn_id)] = span.to_dict()
            if tl:
                a["timelines"] = tl
        if provenance is not None:
            slices = {}
            for op in implicated:
                if op.txn_id is None:
                    continue
                sl = provenance.slice_for(txn_id=op.txn_id)
                if sl is not None:
                    slices[str(op.txn_id)] = sl
            if slices:
                a["causal_slices"] = slices
        anomalies.append(a)
        return a

    # -- 1. per-key version order from observations + final state ------------
    orders: Dict[object, tuple] = {}
    observers: Dict[object, object] = {}   # key -> op that gave the longest
    for op in ok_ops:
        for key, observed in op.reads.items():
            prev = orders.get(key, ())
            short, long_ = (observed, prev) if len(prev) >= len(observed) \
                else (prev, observed)
            if tuple(long_[:len(short)]) != tuple(short):
                prev_op = observers.get(key)
                _attach("incompatible-order",
                        [o for o in (prev_op, op) if o is not None],
                        detail=f"non-prefix observations of key {key}: "
                               f"{list(prev)} vs {list(observed)}")
                continue
            if len(observed) > len(prev):
                orders[key] = tuple(observed)
                observers[key] = op
    if final_state:
        for key, order in final_state.items():
            prev = orders.get(key, ())
            order = tuple(order)
            short, long_ = (order, prev) if len(prev) >= len(order) \
                else (prev, order)
            if tuple(long_[:len(short)]) != tuple(short):
                prev_op = observers.get(key)
                _attach("incompatible-order",
                        [o for o in (prev_op,) if o is not None],
                        detail=f"observation of key {key} is not a prefix of "
                               f"the final replica state: {list(prev)} vs "
                               f"final {list(order)}")
                continue
            if len(order) > len(prev):
                orders[key] = order

    # -- 2. unique write values name the writer of every position ------------
    value_pos: Dict[object, Dict[object, int]] = {
        key: {v: i for i, v in enumerate(order)}
        for key, order in orders.items()}
    writers: Dict[Tuple[object, int], object] = {}
    for op in considered:
        if op.outcome not in ("ok", "info", None):
            continue   # invalidated writers handled below (G1a)
        for key, vals in op.writes.items():
            positions = value_pos.get(key, {})
            for v in vals:
                pos = positions.get(v)
                if pos is not None:
                    writers[(key, pos)] = op

    # -- 3. direct anomalies: aborted read, lost update ----------------------
    for op in considered:
        if op.outcome != "invalidated":
            continue
        for key, vals in op.writes.items():
            surfaced = [v for v in vals if v in value_pos.get(key, {})]
            if surfaced:
                readers = [o for o in ok_ops
                           if any(v in o.reads.get(key, ()) for v in surfaced)]
                _attach("G1a-aborted-read", [op] + readers,
                        detail=f"invalidated write {surfaced} to key {key} "
                               f"surfaced in the version order")
    if final_state is not None:
        authoritative = set(final_state)
        for op in ok_ops:
            for key, vals in op.writes.items():
                if key not in authoritative:
                    # an acked write to a key entirely absent from the final
                    # state: every value of it was lost
                    _attach("lost-update", [op],
                            detail=f"acked write {list(vals)} to key {key}: "
                                   f"key absent from final replica state")
                    continue
                missing = [v for v in vals
                           if v not in value_pos.get(key, {})]
                if missing:
                    _attach("lost-update", [op],
                            detail=f"acked write {missing} to key {key} "
                                   f"missing from final order "
                                   f"{list(orders.get(key, ()))}")

    # -- 4. dependency graph -------------------------------------------------
    # nodes: ok ops + info ops that provably executed (their writes surfaced)
    graph_ops = list(ok_ops)
    seen = set(map(id, graph_ops))
    for w in writers.values():
        if id(w) not in seen:
            seen.add(id(w))
            graph_ops.append(w)
    adj: Dict[object, List[Tuple[object, str, object]]] = \
        {op: [] for op in graph_ops}
    edge_counts = {"ww": 0, "wr": 0, "rw": 0, "rt": 0}

    edge_seen = set()

    def _edge(a, b, kind, key):
        if a is b or (id(a), id(b), kind, key) in edge_seen:
            return
        edge_seen.add((id(a), id(b), kind, key))
        adj[a].append((b, kind, key))
        edge_counts[kind] += 1

    for key, order in orders.items():
        for pos in range(len(order) - 1):
            a, b = writers.get((key, pos)), writers.get((key, pos + 1))
            if a is not None and b is not None:
                _edge(a, b, "ww", key)
    # per-key committed writers: a read returns the ENTIRE list, so an ok
    # write NONE of whose values appear in an observed list must serialize
    # after that read — an rw edge the positional table alone cannot supply
    # when the write's value never surfaced in any observation or the final
    # state (its position is unknown, but its ordering vs the read is not).
    key_writers: Dict[object, List[object]] = {}
    for w in ok_ops:
        for key in w.writes:
            key_writers.setdefault(key, []).append(w)
    for op in ok_ops:
        for key, observed in op.reads.items():
            n = len(observed)
            if n:
                w = writers.get((key, n - 1))
                if w is not None:
                    _edge(w, op, "wr", key)
            if n < len(orders.get(key, ())):
                w = writers.get((key, n))
                if w is not None:
                    _edge(op, w, "rw", key)
            observed_set = set(observed)
            for w in key_writers.get(key, ()):
                if w is not op and \
                        not any(v in observed_set for v in w.writes[key]):
                    _edge(op, w, "rw", key)

    # real-time edges between ok ops, via a virtual chain over completion
    # ranks: rt_j means "completions of rank <= j have happened"; an op
    # invoked strictly after completion j is reachable from every op with
    # completion rank <= j in O(n) edges.  Strict (<) comparison: two
    # zero-duration ops sharing a sim-timestamp are concurrent, not ordered.
    by_completion = sorted(ok_ops, key=lambda o: o.complete_us)
    completes = [o.complete_us for o in by_completion]
    chain = [("rt", j) for j in range(len(by_completion))]
    for node in chain:
        adj[node] = []
    for j, op in enumerate(by_completion):
        adj[op].append((chain[j], "rt", None))
        if j + 1 < len(chain):
            adj[chain[j]].append((chain[j + 1], "rt", None))
    for op in ok_ops:
        # largest completion rank strictly before this op's invocation
        j = bisect_left(completes, op.invoke_us) - 1
        while j >= 0 and by_completion[j] is op:
            j -= 1
        if j >= 0:
            adj[chain[j]].append((op, "rt", None))
            edge_counts["rt"] += 1

    # -- 5. cycle detection (iterative 3-color DFS) --------------------------
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {node: WHITE for node in adj}

    def _find_cycle():
        for root in adj:
            if color[root] != WHITE:
                continue
            # stack of (node, edge iterator); path holds (node, via_edge)
            stack = [(root, iter(adj[root]))]
            color[root] = GRAY
            path = [(root, None)]
            while stack:
                node, it = stack[-1]
                advanced = False
                for nxt, kind, key in it:
                    if color.get(nxt, BLACK) == GRAY:
                        # back edge: slice the cycle out of the path
                        idx = next(i for i, (n, _e) in enumerate(path)
                                   if n is nxt)
                        cyc = path[idx:] + [(nxt, (node, kind, key))]
                        return cyc
                    if color.get(nxt, BLACK) == WHITE:
                        color[nxt] = GRAY
                        stack.append((nxt, iter(adj[nxt])))
                        path.append((nxt, (node, kind, key)))
                        advanced = True
                        break
                if not advanced:
                    color[node] = BLACK
                    stack.pop()
                    path.pop()
        return None

    cyc = _find_cycle()
    if cyc is not None:
        # the cycle as a closed edge walk: cyc[k][1] = (parent, kind, key) is
        # the edge cyc[k-1].node -> cyc[k].node; cyc[0].node == cyc[-1].node
        walk = []
        for k in range(1, len(cyc)):
            _parent, kind, key = cyc[k][1]
            walk.append((cyc[k - 1][0], cyc[k][0], kind, key))
        # rotate so the walk starts at a real op, then collapse virtual
        # rt-chain segments: a path a -> rt_i .. rt_j -> b is ONE rt edge
        def _real(node):
            return getattr(node, "op_id", None) is not None
        start = next(i for i, (s, _d, _k, _key) in enumerate(walk)
                     if _real(s))
        walk = walk[start:] + walk[:start]
        edges: List[dict] = []
        implicated: List[object] = [walk[0][0]]
        prev_real, pending_rt = walk[0][0], False
        for _src, dst, kind, key in walk:
            if not _real(dst):
                pending_rt = True
                continue
            edges.append({"from": prev_real.op_id, "to": dst.op_id,
                          "kind": "rt" if pending_rt else kind,
                          "key": None if (pending_rt or key is None)
                          else str(key)})
            pending_rt = False
            prev_real = dst
            if dst not in implicated:
                implicated.append(dst)
        name, detail = _classify(edges)
        _attach(name, implicated, edges=edges, detail=detail)

    report = {
        "ops": len(considered),
        "ok": len(ok_ops),
        "keys": len(orders),
        "edges": edge_counts,
        "anomalies": anomalies[:max_anomalies],
    }
    if anomalies and raise_on_anomaly:
        raise HistoryAnomaly(report)
    return report
