"""Plane 1 of the performance-observability layer: deterministic, sim-time
critical-path latency attribution ("where do the 23 ms per commit go").

The flight recorder already captures, per transaction, the client
submit/resolve envelope, the per-(node, store) ``SaveStatus`` transition
timeline, and (optionally) the full message event stream.  This module
reconstructs each committed txn's causal chain from those records —

    submit → PreAccept fan-out → quorum gather → decision → stable
    propagation → deps/execute wait → apply → ack —

and attributes every segment of the chain to one of a SMALL CLOSED class
set, then aggregates the per-txn budgets into a latency-budget report
(per-class totals/shares and exact p50/p95/p99, top-k classes by total
contribution).  The report is what ROADMAP item 2's columnar protocol-batch
refactor batches against: it names WHICH segment of a commit's life
dominates, instead of inferring it from end-to-end deltas.

Everything here is POST-HOC analysis over the recorder's already-captured
sim-time data: extraction runs after the burn, touches no RNG, no wall
clock, no scheduling — the zero-observer-effect contract is untouched by
construction (there are no runtime hooks at all).

Time plane: ALL durations in this module are simulated microseconds.  The
wall-clock plane (handler CPU, scheduler occupancy, device launch RTT) is
``observe/profiler.py`` — explicitly outside the determinism contract.

Class semantics (``SEGMENT_CLASSES``):

- ``message_wait``      network legs on the critical chain: fan-out,
                        quorum gather, decision/stable propagation, the
                        final apply-ack back to the client.
- ``replica_queue_wait``delivery → handler-run delay at a replica (store
                        executor queueing, request-coalescing windows,
                        pause parks).  Measured from the PreAccept RECV
                        event when the message timeline is recorded;
                        folded into the fan-out leg otherwise.
- ``handler_compute``   replica-side state-machine work (APPLYING→APPLIED
                        and zero-width handler segments).  Sim handlers
                        execute in zero sim time except for injected
                        executor delay, so this class is structurally tiny
                        in plane 1 — the WALL plane measures it honestly.
- ``device_consult_wait`` sim-time waits attributable to the device consult
                        tier (delivery-window batching).  Plane 1 cannot
                        separate this from replica queueing without a
                        per-message consult ledger, so it stays 0 here and
                        the wall plane reports dispatch RTT / kernel ms;
                        the class is declared so budgets from both planes
                        share one vocabulary.
- ``fence_bootstrap_wait`` stable→execute gaps on a store whose timeline
                        shows the txn landed via bootstrap/fetch paths
                        (first observation already decided: the store
                        never pre-accepted it).
- ``deps_wait``         stable→execute-ready on the critical (slowest
                        normally-participating) store: waiting for
                        dependency transactions to apply.
- ``recovery``          decision-phase and probe-resolution segments of
                        txns with recovery attempts attributed (or
                        resolved through client CheckStatus probes).
- ``unattributed``      residue the chain could not name (e.g. spans with
                        no replica transitions at all).  The acceptance bar
                        is ≥95% of mean commit latency attributed to the
                        NAMED classes above.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from . import schema

SEGMENT_CLASSES = ("message_wait", "replica_queue_wait", "handler_compute",
                   "device_consult_wait", "fence_bootstrap_wait", "deps_wait",
                   "recovery", "unattributed")

# span outcomes that count as a COMMIT for the latency budget (invalidated /
# lost / failed ops have no commit latency to attribute)
_COMMIT_OUTCOMES = schema.COMMIT_OUTCOMES

# SaveStatus names marking "the decision is known at this store"
_DECIDED = ("PRE_COMMITTED", "COMMITTED", "STABLE", "READY_TO_EXECUTE",
            "PRE_APPLIED", "APPLYING", "APPLIED")
_STABLE_PLUS = ("STABLE", "READY_TO_EXECUTE", "PRE_APPLIED", "APPLYING",
                "APPLIED")
_EXEC_READY = ("READY_TO_EXECUTE", "PRE_APPLIED", "APPLYING", "APPLIED")


class Segment:
    """One labeled span of a txn's critical chain."""
    __slots__ = ("phase", "cls", "start_us", "dur_us")

    def __init__(self, phase: str, cls: str, start_us: int, dur_us: int):
        assert cls in SEGMENT_CLASSES, cls
        self.phase = phase
        self.cls = cls
        self.start_us = start_us
        self.dur_us = dur_us

    def to_dict(self) -> dict:
        return {"phase": self.phase, "class": self.cls,
                "start_us": self.start_us, "dur_us": self.dur_us}


class TxnCriticalPath:
    """The reconstructed chain of one committed client txn; segment
    durations partition [submitted_us, resolved_us] exactly."""
    __slots__ = ("txn_id", "outcome", "total_us", "segments")

    def __init__(self, txn_id, outcome: str, total_us: int,
                 segments: List[Segment]):
        self.txn_id = txn_id
        self.outcome = outcome
        self.total_us = total_us
        self.segments = segments

    def by_class(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for seg in self.segments:
            out[seg.cls] = out.get(seg.cls, 0) + seg.dur_us
        return out

    def to_dict(self) -> dict:
        return {"txn_id": str(self.txn_id), "outcome": self.outcome,
                "total_us": self.total_us,
                "segments": [s.to_dict() for s in self.segments]}


def _preaccept_recv_index(recorder) -> Dict[str, int]:
    """txn-id string -> earliest sim-us a PreAccept REQUEST was delivered
    (RECV) anywhere.  Needs the recorder's message timeline; {} when
    messages were not recorded (or the ring dropped them) — extraction then
    folds replica queueing into the fan-out leg."""
    out: Dict[str, int] = {}
    for _seq, ts, event, _frm, _to, _msg_id, brief in recorder.messages:
        if event == "RECV" and brief.startswith("PreAccept("):
            tid = brief[len("PreAccept("):-1]
            if tid not in out:
                out[tid] = ts
    return out


def _first(transitions: List[Tuple[str, int]], names) -> Optional[int]:
    for status, ts in transitions:
        if status in names:
            return ts
    return None


def extract_txn_path(span, preaccept_recv_us: Optional[int] = None) \
        -> Optional[TxnCriticalPath]:
    """Reconstruct one client span's critical chain.  Returns None for spans
    that are not resolved commits (nothing to attribute)."""
    if not span.is_client_op or span.resolved_us is None \
            or span.outcome not in _COMMIT_OUTCOMES:
        return None
    t_submit, t_resolve = span.submitted_us, span.resolved_us
    total = t_resolve - t_submit

    # -- milestone extraction over the per-(node,store) timelines ------------
    preaccept_ts = []          # first PRE_ACCEPTED per participating store
    decided_ts = []            # first decided status per store
    stable_ts = []             # first STABLE+ per store
    apply_chains = []          # (first_applied, exec_ready, stable, bootstrap)
    for (_node, _store), transitions in span.transitions.items():
        pa = _first(transitions, ("PRE_ACCEPTED",))
        if pa is not None:
            preaccept_ts.append(pa)
        dec = _first(transitions, _DECIDED)
        if dec is not None:
            decided_ts.append(dec)
        st = _first(transitions, _STABLE_PLUS)
        if st is not None:
            stable_ts.append(st)
        applied = _first(transitions, ("APPLIED",))
        if applied is not None:
            # a store that never pre-accepted learned the txn already
            # decided (bootstrap / fetch / propagate): its execute wait is
            # fence/bootstrap-class, not deps-class
            apply_chains.append((applied, _first(transitions, _EXEC_READY),
                                 st, pa is None,
                                 _first(transitions, ("PRE_APPLIED",)),
                                 _first(transitions, ("READY_TO_EXECUTE",
                                                      "APPLYING"))))
    if not preaccept_ts and not apply_chains:
        # no replica evidence at all (e.g. probe-resolved after total loss):
        # recovery if probed, else unattributed
        cls = "recovery" if span.outcome == "recovered" else "unattributed"
        return TxnCriticalPath(span.txn_id, span.outcome, total,
                               [Segment("opaque", cls, t_submit, total)])

    recovering = span.recoveries > 0 or span.outcome == "recovered"
    segments: List[Segment] = []
    cursor = t_submit

    def emit(phase: str, cls: str, until: Optional[int]) -> None:
        nonlocal cursor
        if until is None:
            return
        until = min(max(until, cursor), t_resolve)
        if until > cursor:
            segments.append(Segment(phase, cls, cursor, until - cursor))
            cursor = until

    # 1) PreAccept fan-out: submit → first delivery (message) → first
    #    PRE_ACCEPTED (replica queue).  Without the message timeline the
    #    whole leg is the fan-out message wait.
    first_pa = min(preaccept_ts) if preaccept_ts else None
    if preaccept_recv_us is not None and first_pa is not None \
            and t_submit <= preaccept_recv_us <= first_pa:
        emit("preaccept_fanout", "message_wait", preaccept_recv_us)
        emit("preaccept_queue", "replica_queue_wait", first_pa)
    else:
        emit("preaccept_fanout", "message_wait", first_pa)
    # 2) quorum gather: replies trickle back until the fan-out's last
    #    pre-accept (the fast path waits on the full electorate)
    last_pa = max(preaccept_ts) if preaccept_ts else None
    emit("preaccept_quorum_gather", "message_wait", last_pa)
    # 3) decision: the coordinator's commit (+ Accept round on the slow
    #    path) landing at the first replica; recovery-class when a recovery
    #    round drove it
    emit("decision_wait", "recovery" if recovering else "message_wait",
         min(decided_ts) if decided_ts else None)
    # 4) stable propagation across the replica set
    emit("stable_propagation", "message_wait",
         min(stable_ts) if stable_ts else None)
    # 5) deps/execute wait + apply on the CRITICAL store: the one whose
    #    APPLIED lands last (the client ack waits for it).  The wait splits
    #    by WHICH plane was pending (round 12, so frontier-driven and
    #    event-driven runs compare class-for-class in one report):
    #    - deps_commit_wait   — the txn's own OUTCOME had not arrived (no
    #                           PRE_APPLIED yet): nothing local can apply it
    #                           regardless of deps;
    #    - deps_execute_wait  — outcome known, waiting for the local
    #                           dependency frontier to drain (and, in
    #                           frontier mode, for the device release tick).
    #    Both phases keep the deps_wait / fence_bootstrap_wait CLASS; the
    #    split is the phase axis.
    if apply_chains:
        # key on the APPLIED time only: the tuples carry Optionals that do
        # not order; ties break on list order (deterministic insertion order)
        applied, exec_ready, _stable, bootstrapped, outcome_at, drained_at = \
            max(apply_chains, key=lambda c: c[0])
        wait_cls = "fence_bootstrap_wait" if bootstrapped else "deps_wait"
        if exec_ready is not None:
            if outcome_at is not None and (drained_at is None
                                           or outcome_at < drained_at):
                # outcome arrived first: until then the commit/outcome plane
                # was the (or a) binding constraint
                emit("deps_commit_wait", wait_cls, outcome_at)
            emit("deps_execute_wait", wait_cls,
                 drained_at if drained_at is not None else applied)
            if outcome_at is not None and drained_at is not None \
                    and outcome_at > drained_at:
                # frontier drained before the outcome landed: that tail is
                # outcome wait, not apply compute
                emit("deps_commit_wait", wait_cls, outcome_at)
            emit("apply", "handler_compute", applied)
        else:
            emit("deps_execute_wait", wait_cls, applied)
    # 6) the ack back to the client (a probe round-trip when recovered)
    emit("ack", "recovery" if span.outcome == "recovered" else "message_wait",
         t_resolve)
    if cursor < t_resolve:
        segments.append(Segment("residue", "unattributed", cursor,
                                t_resolve - cursor))
    return TxnCriticalPath(span.txn_id, span.outcome, total, segments)


def extract_critical_paths(recorder) -> List[TxnCriticalPath]:
    """Every resolved committed client txn's critical chain, in submit
    order."""
    recv = _preaccept_recv_index(recorder)
    out: List[TxnCriticalPath] = []
    spans = sorted((s for s in recorder.spans.spans.values()
                    if s.is_client_op and s.submitted_us is not None),
                   key=lambda s: (s.submitted_us, str(s.txn_id)))
    for span in spans:
        path = extract_txn_path(span, recv.get(str(span.txn_id)))
        if path is not None:
            out.append(path)
    return out


def _percentile(sorted_vals: List[int], q: float) -> Optional[int]:
    """Exact nearest-rank percentile over a sorted list (deterministic;
    post-run analysis needs no bucketing)."""
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     int(q * len(sorted_vals) + 0.999999) - 1))
    return sorted_vals[idx]


def latency_budget(recorder, top_k: int = 6) -> dict:
    """The latency-budget report: per-class totals/shares over every
    committed txn's critical chain, exact p50/p95/p99 of per-txn class
    time, top-k classes by total contribution, and the attribution share
    (the ≥95% acceptance bar)."""
    paths = extract_critical_paths(recorder)
    per_class_vals: Dict[str, List[int]] = {c: [] for c in SEGMENT_CLASSES}
    per_phase: Dict[str, Dict[str, int]] = {}
    total_us = 0
    for path in paths:
        total_us += path.total_us
        budget = path.by_class()
        for cls in SEGMENT_CLASSES:
            per_class_vals[cls].append(budget.get(cls, 0))
        for seg in path.segments:
            row = per_phase.setdefault(
                seg.phase, {"total_us": 0, "count": 0, "class": seg.cls})
            row["total_us"] += seg.dur_us
            row["count"] += 1
    classes = {}
    for cls, vals in per_class_vals.items():
        cls_total = sum(vals)
        if not vals or (cls_total == 0 and cls != "unattributed"):
            continue
        ordered = sorted(vals)
        classes[cls] = {
            "total_us": cls_total,
            "share": round(cls_total / total_us, 4) if total_us else 0.0,
            "mean_us": round(cls_total / len(vals), 1),
            "p50_us": _percentile(ordered, 0.50),
            "p95_us": _percentile(ordered, 0.95),
            "p99_us": _percentile(ordered, 0.99),
        }
    totals = sorted(p.total_us for p in paths)
    unattributed = classes.get("unattributed", {}).get("total_us", 0)
    top = sorted(((c, v["total_us"]) for c, v in classes.items()
                  if c != "unattributed"),
                 key=lambda kv: (-kv[1], kv[0]))[:top_k]
    dominating = top[0][0] if top else None
    return {
        "time_plane": "sim_us",
        "txns": len(paths),
        "mean_commit_latency_us": round(total_us / len(paths), 1)
        if paths else None,
        "p50_us": _percentile(totals, 0.50),
        "p95_us": _percentile(totals, 0.95),
        "p99_us": _percentile(totals, 0.99),
        "total_us": total_us,
        "attributed_share": round(1.0 - (unattributed / total_us), 4)
        if total_us else None,
        "dominating_class": dominating,
        "dominating_share": classes[dominating]["share"] if dominating
        else None,
        "top": [{"class": c, "total_us": t,
                 "share": round(t / total_us, 4) if total_us else 0.0}
                for c, t in top],
        "classes": classes,
        "phases": {p: dict(v, share=round(v["total_us"] / total_us, 4)
                           if total_us else 0.0)
                   for p, v in sorted(per_phase.items())},
    }


def format_budget(report: dict, label: str = "") -> str:
    """Human-readable latency-budget table (the burn CLI's --profile
    output)."""
    if not report["txns"]:
        return f"latency budget{': ' + label if label else ''}: " \
               f"no committed txns recorded"
    lines = []
    head = f"latency budget{': ' + label if label else ''} — " \
           f"{report['txns']} commits, mean " \
           f"{report['mean_commit_latency_us'] / 1000.0:.2f} ms, " \
           f"{100.0 * report['attributed_share']:.1f}% attributed " \
           f"(sim time)"
    lines.append(head)
    lines.append(f"  {'class':<22}{'share':>7}{'mean_ms':>9}{'p50_ms':>8}"
                 f"{'p95_ms':>8}{'p99_ms':>8}")
    ranked = sorted(report["classes"].items(),
                    key=lambda kv: (-kv[1]["total_us"], kv[0]))
    for cls, row in ranked:
        lines.append(
            f"  {cls:<22}{100.0 * row['share']:>6.1f}%"
            f"{row['mean_us'] / 1000.0:>9.2f}"
            f"{(row['p50_us'] or 0) / 1000.0:>8.2f}"
            f"{(row['p95_us'] or 0) / 1000.0:>8.2f}"
            f"{(row['p99_us'] or 0) / 1000.0:>8.2f}")
    lines.append("  phases: " + ", ".join(
        f"{p} {100.0 * v['share']:.1f}%"
        for p, v in sorted(report["phases"].items(),
                           key=lambda kv: -kv[1]["total_us"])))
    return "\n".join(lines)
