"""Observability layer: deterministic metrics registry, txn lifecycle spans,
and the flight recorder (Chrome-trace export).

Design invariant — ZERO OBSERVER EFFECT: every hook in this package is a
passive, synchronous function call fed values the instrumented code already
computed (sim-timestamps, txn ids, status names).  No hook may

- allocate ids from any shared RNG (spans key on the txn's own id),
- read the wall clock (all timestamps are simulated micros handed in),
- schedule tasks, send messages, or otherwise alter the event loop.

``tests/test_observe.py::test_zero_observer_effect_hostile`` proves the
invariant in-tree: a same-seed hostile burn with the flight recorder on vs
off yields byte-identical full message traces (``harness.trace.diff_traces``)
and identical final-state outcome counters.
"""
from .registry import Counter, Gauge, Histogram, MetricsRegistry
from .spans import TxnSpan, TxnSpanRecorder
from .flight import FlightRecorder
from .audit import AuditViolation, InvariantAuditor
from .export import chrome_trace, validate_chrome_trace, write_chrome_trace
from .critical_path import (SEGMENT_CLASSES, extract_critical_paths,
                            format_budget, latency_budget)
from .profiler import WallProfiler, format_wall_profile
from .timeline import (Timeline, commits_per_sec_series, exact_percentile,
                       write_timeline_jsonl)
from .burnrate import BurnRateMonitor, SloSpec
from .history import HistoryOp, HistoryRecorder
from .checker import HistoryAnomaly, check_history, format_report
from .provenance import (ProvenanceRecorder, explain_divergence,
                         render_slice)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "TxnSpan", "TxnSpanRecorder", "FlightRecorder",
    "AuditViolation", "InvariantAuditor",
    "chrome_trace", "validate_chrome_trace", "write_chrome_trace",
    "SEGMENT_CLASSES", "extract_critical_paths", "format_budget",
    "latency_budget",
    "WallProfiler", "format_wall_profile",
    "Timeline", "commits_per_sec_series", "exact_percentile",
    "write_timeline_jsonl",
    "BurnRateMonitor", "SloSpec",
    "HistoryOp", "HistoryRecorder",
    "HistoryAnomaly", "check_history", "format_report",
    "ProvenanceRecorder", "explain_divergence", "render_slice",
]
