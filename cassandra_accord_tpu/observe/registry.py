"""Deterministic cluster metrics registry.

Counters, gauges and sim-time histograms with three scopes — cluster, per
node, per (node, store) — rendered as stable JSON (sorted keys, no floats
that depend on iteration order).  ``snapshot`` / ``delta`` / ``merge`` make
the registry diffable across runs and PRs the way the burn CLI's ``--json``
summaries are.

Everything here is plain host-side bookkeeping: no RNG, no wall clock, no
scheduling — the registry is safe to feed from inside the deterministic
simulation's hot paths (the zero-observer-effect contract, see
``observe/__init__``).
"""
from __future__ import annotations

import json
from typing import Dict, Optional, Tuple


class Counter:
    """Monotonic integer count."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Last-set value (pull-collected store/cluster state)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def set(self, value) -> None:
        self.value = value


# sim-time latency buckets (micros): 1ms .. 60s, exponential-ish
DEFAULT_BOUNDS_US = (1_000, 5_000, 10_000, 50_000, 100_000, 500_000,
                     1_000_000, 5_000_000, 10_000_000, 60_000_000)


class Histogram:
    """Fixed-bound histogram over simulated time (or any integer measure).

    The bounds are fixed at creation so snapshots of the same metric are
    always bucket-aligned and delta/merge are exact."""

    __slots__ = ("bounds", "counts", "count", "total")

    def __init__(self, bounds: Tuple[int, ...] = DEFAULT_BOUNDS_US):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)   # +1 = overflow bucket
        self.count = 0
        self.total = 0

    def record(self, value: int) -> None:
        self.record_many(value, 1)

    def record_many(self, value: int, n: int) -> None:
        """Record ``value`` n times in O(1) (bulk collectors: exact totals
        without a per-record loop)."""
        self.count += n
        self.total += value * n
        for i, bound in enumerate(self.bounds):
            if value <= bound:
                self.counts[i] += n
                return
        self.counts[-1] += n

    def to_snapshot(self) -> dict:
        return {"count": self.count, "total": self.total,
                "bounds": list(self.bounds), "buckets": list(self.counts)}

    @staticmethod
    def snapshot_percentile(snapshot: dict, q: float) -> Optional[int]:
        """Conservative percentile estimate from a snapshot dict: the upper
        bound of the bucket containing the q-quantile (None when empty or
        when the quantile lands in the overflow bucket).  One formula for
        bench.py's SLO stages and the perf-gate reports."""
        total = snapshot["count"]
        if not total:
            return None
        need = q * total
        acc = 0
        bounds = snapshot["bounds"]
        for i, n in enumerate(snapshot["buckets"]):
            acc += n
            if acc >= need:
                return bounds[i] if i < len(bounds) else None
        return None

    def percentile(self, q: float) -> Optional[int]:
        return self.snapshot_percentile(self.to_snapshot(), q)


class MetricsRegistry:
    """One flat registry; metrics are addressed by (scope, name).

    Scope strings: ``"cluster"``, ``"node/<id>"``, ``"store/<node>/<store>"``
    — chosen so the rendered snapshot sorts stably and a store's metrics sit
    under its node's prefix."""

    __slots__ = ("_metrics",)

    def __init__(self):
        self._metrics: Dict[Tuple[str, str], object] = {}

    @staticmethod
    def scope(node: Optional[int] = None, store: Optional[int] = None) -> str:
        if node is None:
            return "cluster"
        if store is None:
            return f"node/{node}"
        return f"store/{node}/{store}"

    def _get(self, kind, name: str, node, store, **kw):
        key = (self.scope(node, store), name)
        metric = self._metrics.get(key)
        if metric is None:
            metric = kind(**kw)
            self._metrics[key] = metric
        elif not isinstance(metric, kind):
            raise TypeError(f"metric {key} already registered as "
                            f"{type(metric).__name__}, not {kind.__name__}")
        elif isinstance(metric, Histogram) \
                and metric.bounds != tuple(kw["bounds"]):
            # loud, at the second call site — a silent first-caller-wins
            # would dump every later value in the wrong buckets and only
            # surface as a far-away delta/merge ValueError
            raise ValueError(f"histogram {key} already registered with "
                             f"bounds {metric.bounds}, not {kw['bounds']}")
        return metric

    def counter(self, name: str, node: Optional[int] = None,
                store: Optional[int] = None) -> Counter:
        return self._get(Counter, name, node, store)

    def gauge(self, name: str, node: Optional[int] = None,
              store: Optional[int] = None) -> Gauge:
        return self._get(Gauge, name, node, store)

    def histogram(self, name: str, node: Optional[int] = None,
                  store: Optional[int] = None,
                  bounds: Tuple[int, ...] = DEFAULT_BOUNDS_US) -> Histogram:
        return self._get(Histogram, name, node, store, bounds=bounds)

    # -- rendering -----------------------------------------------------------
    def snapshot(self) -> dict:
        """Nested plain-data snapshot: {scope: {name: value-or-hist-dict}}."""
        out: Dict[str, dict] = {}
        for (scope, name), metric in self._metrics.items():
            value = metric.to_snapshot() if isinstance(metric, Histogram) \
                else metric.value
            out.setdefault(scope, {})[name] = value
        return out

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True,
                          separators=(",", ":"))

    # -- snapshot algebra ----------------------------------------------------
    @staticmethod
    def _combine(a, b, sign: int):
        if isinstance(a, dict) or isinstance(b, dict):
            a = a if isinstance(a, dict) else \
                {"count": 0, "total": 0, "bounds": b["bounds"],
                 "buckets": [0] * len(b["buckets"])}
            b = b if isinstance(b, dict) else \
                {"count": 0, "total": 0, "bounds": a["bounds"],
                 "buckets": [0] * len(a["buckets"])}
            if a["bounds"] != b["bounds"]:
                raise ValueError("histogram bucket bounds differ")
            return {"count": a["count"] + sign * b["count"],
                    "total": a["total"] + sign * b["total"],
                    "bounds": list(a["bounds"]),
                    "buckets": [x + sign * y
                                for x, y in zip(a["buckets"], b["buckets"])]}
        return (a or 0) + sign * (b or 0)

    @classmethod
    def _fold(cls, a: dict, b: dict, sign: int) -> dict:
        out: Dict[str, dict] = {}
        for scope in sorted(set(a) | set(b)):
            sa, sb = a.get(scope, {}), b.get(scope, {})
            row = {}
            for name in sorted(set(sa) | set(sb)):
                row[name] = cls._combine(sa.get(name), sb.get(name), sign)
            out[scope] = row
        return out

    @classmethod
    def delta(cls, after: dict, before: dict) -> dict:
        """after - before, scope- and metric-wise (missing entries read 0)."""
        return cls._fold(after, before, -1)

    @classmethod
    def merge(cls, a: dict, b: dict) -> dict:
        """a + b (aggregating snapshots across seeds/runs)."""
        return cls._fold(a, b, +1)
